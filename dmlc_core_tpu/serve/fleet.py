"""Replica fleet supervision: launch, watch, restart N scoring processes.

:class:`ReplicaFleet` owns the *process* half of the multi-replica tier
(docs/serving.md "Multi-replica tier"): it launches N ``python -m
dmlc_core_tpu.serve`` replicas on **fixed ports** (allocated once, reused
across restarts — the router's replica URLs stay stable while processes
come and go), waits for ``/healthz`` readiness, and optionally supervises
them: a replica that exits (the SIGKILL chaos drill) is relaunched on its
own port and re-enters rotation through the router's half-open recovery.

Rolling restart = :meth:`ReplicaFleet.rolling_restart`: one replica at a
time, SIGTERM (the replica drains: finishes in-flight requests, answers
``/healthz`` with ``draining``, exits cleanly), relaunch, wait healthy,
move on.  Under an open-loop load storm this must record **zero**
``crashed`` client samples — the chaos gate ``bench_serving.py router``
enforces.

The fleet inherits the parent environment (so ``DMLC_TELEMETRY_DIR`` and
``DMLC_FAULT_PLAN`` flow through to replicas), prepends the repo root to
``PYTHONPATH``, and pins ``JAX_PLATFORMS`` to the parent's choice (cpu
default) — the same launch discipline the continuous-training ring uses.
"""

from __future__ import annotations

import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from dmlc_core_tpu.telemetry import clock
from dmlc_core_tpu.utils.logging import log_debug, log_info, log_warning

__all__ = ["ReplicaFleet"]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _free_port(host: str) -> int:
    """One ephemeral port the kernel considers free right now."""
    sock = socket.socket()
    try:
        sock.bind((host, 0))
        return sock.getsockname()[1]
    finally:
        sock.close()


def _probe_healthz(host: str, port: int,
                   timeout_s: float = 1.0) -> Optional[Dict[str, Any]]:
    """Parsed /healthz JSON, or None on any failure."""
    conn: Optional[http.client.HTTPConnection] = None
    try:
        conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        raw = resp.read()
        if resp.status != 200:
            return None
        parsed = json.loads(raw)
        return parsed if isinstance(parsed, dict) else None
    except (OSError, http.client.HTTPException, ValueError):
        return None
    finally:
        if conn is not None:
            conn.close()


class ReplicaFleet:
    """N supervised scoring replicas on fixed ports.

    ``per_replica_env``/``per_replica_args`` key on the replica index —
    how the chaos drill makes exactly one replica a straggler (its own
    ``DMLC_FAULT_PLAN``) without touching the others.  ``log_dir=None``
    sends replica output to the void; the drills always pass a directory
    so a failed gate has logs to read.
    """

    def __init__(self, count: int, *, model: str = "linear",
                 num_feature: int = 28, seed: int = 0,
                 host: str = "127.0.0.1",
                 ports: Optional[List[int]] = None,
                 max_batch: int = 64, max_delay_ms: float = 2.0,
                 max_queue_bytes: Optional[int] = None,
                 request_timeout_s: float = 10.0,
                 checkpoint: Optional[str] = None,
                 model_name: Optional[str] = None,
                 warmup: bool = True,
                 extra_args: Optional[List[str]] = None,
                 extra_env: Optional[Dict[str, str]] = None,
                 per_replica_env: Optional[Dict[int, Dict[str, str]]] = None,
                 per_replica_args: Optional[Dict[int, List[str]]] = None,
                 log_dir: Optional[str] = None,
                 auto_restart: bool = True):
        if count < 1:
            raise ValueError(f"fleet needs at least 1 replica, got {count}")
        self.count = int(count)
        self.host = host
        if ports is not None:
            if len(ports) != count:
                raise ValueError(f"got {len(ports)} ports for {count} "
                                 "replicas")
            self.ports = [int(p) for p in ports]
        else:
            self.ports = [_free_port(host) for _ in range(count)]
        if len(set(self.ports)) != count:
            raise ValueError(f"duplicate replica ports {self.ports}")
        self.model = model
        self.num_feature = int(num_feature)
        self.seed = int(seed)
        self.max_batch = int(max_batch)
        self.max_delay_ms = float(max_delay_ms)
        self.max_queue_bytes = max_queue_bytes
        self.request_timeout_s = float(request_timeout_s)
        self.checkpoint = checkpoint
        self.model_name = model_name
        self.warmup = warmup
        self.extra_args = list(extra_args or [])
        self.extra_env = dict(extra_env or {})
        self.per_replica_env = {int(k): dict(v) for k, v
                                in (per_replica_env or {}).items()}
        self.per_replica_args = {int(k): list(v) for k, v
                                 in (per_replica_args or {}).items()}
        self.log_dir = log_dir
        if log_dir is not None:
            os.makedirs(log_dir, exist_ok=True)
        self.auto_restart = bool(auto_restart)
        self._lock = threading.Lock()
        self._procs: List[Optional[subprocess.Popen]] = [None] * count
        self._launches = [0] * count   # per-slot process incarnations
        self._paused = [False] * count  # monitor hands off (restart path)
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None

    # -- addressing -----------------------------------------------------------

    def url(self, i: int) -> str:
        return f"http://{self.host}:{self.ports[i]}"

    @property
    def urls(self) -> List[str]:
        return [self.url(i) for i in range(self.count)]

    # -- launch / lifecycle ---------------------------------------------------

    def _argv(self, i: int) -> List[str]:
        argv = [sys.executable, "-m", "dmlc_core_tpu.serve",
                "--model", self.model,
                "--num-feature", str(self.num_feature),
                "--seed", str(self.seed),
                "--host", self.host, "--port", str(self.ports[i]),
                "--max-batch", str(self.max_batch),
                "--max-delay-ms", str(self.max_delay_ms),
                "--request-timeout-s", str(self.request_timeout_s)]
        if self.max_queue_bytes is not None:
            argv += ["--max-queue-bytes", str(self.max_queue_bytes)]
        if self.checkpoint:
            argv += ["--checkpoint", self.checkpoint]
        if self.model_name:
            argv += ["--model-name", self.model_name]
        if not self.warmup:
            argv.append("--no-warmup")
        argv += self.extra_args
        argv += self.per_replica_args.get(i, [])
        return argv

    def _launch(self, i: int) -> None:
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep \
            + env.get("PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.update(self.extra_env)
        env.update(self.per_replica_env.get(i, {}))
        if self.log_dir:
            # the child dups the descriptor at spawn; ours closes on exit
            with open(os.path.join(self.log_dir, f"replica-{i}.log"),
                      "ab") as log_fh:
                proc = subprocess.Popen(
                    self._argv(i), env=env,
                    stdout=log_fh, stderr=subprocess.STDOUT)
        else:
            proc = subprocess.Popen(
                self._argv(i), env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        with self._lock:
            self._procs[i] = proc
            self._launches[i] += 1
            incarnation = self._launches[i]
        log_info(f"fleet: replica {i} (incarnation {incarnation}) pid "
                 f"{proc.pid} on {self.url(i)}")

    def start(self, wait_healthy: bool = True,
              timeout_s: float = 90.0) -> "ReplicaFleet":
        for i in range(self.count):
            self._launch(i)
        if wait_healthy:
            self.wait_healthy(timeout_s=timeout_s)
        if self.auto_restart:
            self._monitor = threading.Thread(
                target=self._monitor_loop, name="fleet-monitor",
                daemon=True)
            self._monitor.start()
        return self

    def wait_healthy(self, indices: Optional[List[int]] = None,
                     timeout_s: float = 90.0) -> None:
        """Block until every (listed) replica answers /healthz "ok"."""
        pending = set(indices if indices is not None
                      else range(self.count))
        deadline = clock.monotonic() + timeout_s
        while pending:
            for i in sorted(pending):
                payload = _probe_healthz(self.host, self.ports[i])
                if payload is not None and payload.get("status") == "ok":
                    pending.discard(i)
            if not pending:
                return
            if clock.monotonic() >= deadline:
                raise RuntimeError(
                    f"replicas {sorted(pending)} not healthy after "
                    f"{timeout_s:g}s (ports "
                    f"{[self.ports[i] for i in sorted(pending)]})")
            time.sleep(0.1)

    def _monitor_loop(self) -> None:
        """Relaunch any replica whose process exits (unless its slot is
        paused for a supervised restart, or the fleet is closing)."""
        try:
            while not self._stop.is_set():
                for i in range(self.count):
                    with self._lock:
                        proc = self._procs[i]
                        paused = self._paused[i]
                    if proc is None or paused:
                        continue
                    code = proc.poll()
                    if code is None or self._stop.is_set():
                        continue
                    log_warning(f"fleet: replica {i} (pid {proc.pid}) "
                                f"exited rc={code}; relaunching")
                    self._launch(i)
                self._stop.wait(0.2)
        except Exception as exc:  # noqa: BLE001 — ferried, not swallowed
            log_warning(f"fleet: monitor exited abnormally: {exc!r}")

    def _set_paused(self, i: int, paused: bool) -> None:
        with self._lock:
            self._paused[i] = paused

    # -- chaos + restart surface ----------------------------------------------

    def pid(self, i: int) -> Optional[int]:
        with self._lock:
            proc = self._procs[i]
        return proc.pid if proc is not None else None

    def launches(self) -> List[int]:
        with self._lock:
            return list(self._launches)

    def kill(self, i: int) -> None:
        """SIGKILL replica ``i`` (the crash drill).  With auto_restart the
        monitor notices within ~200ms and relaunches on the same port."""
        with self._lock:
            proc = self._procs[i]
        if proc is not None and proc.poll() is None:
            log_info(f"fleet: SIGKILL replica {i} (pid {proc.pid})")
            proc.kill()

    def terminate(self, i: int, wait_s: float = 30.0) -> Optional[int]:
        """SIGTERM replica ``i`` and wait for its drain-and-exit.

        Pauses the monitor for the slot first (a drain is not a crash);
        the caller unpauses by relaunching via :meth:`restart` or
        resumes supervision itself.  Escalates to SIGKILL only if the
        drain deadline passes.
        """
        self._set_paused(i, True)
        with self._lock:
            proc = self._procs[i]
        if proc is None or proc.poll() is not None:
            return proc.poll() if proc is not None else None
        log_info(f"fleet: SIGTERM replica {i} (pid {proc.pid}) — draining")
        proc.send_signal(signal.SIGTERM)
        try:
            return proc.wait(timeout=wait_s)
        except subprocess.TimeoutExpired:
            log_warning(f"fleet: replica {i} did not drain within "
                        f"{wait_s:g}s; killing")
            proc.kill()
            try:
                return proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                return None

    def restart(self, i: int, wait_healthy: bool = True,
                timeout_s: float = 90.0) -> None:
        """Graceful single-replica restart: drain, relaunch, wait ready."""
        self.terminate(i)
        self._launch(i)
        if wait_healthy:
            self.wait_healthy([i], timeout_s=timeout_s)
        self._set_paused(i, False)

    def rolling_restart(self, settle_s: float = 0.5,
                        timeout_s: float = 90.0) -> None:
        """Restart every replica, one at a time, waiting for each to come
        back healthy (plus ``settle_s`` for the router's prober to
        re-admit it) before touching the next — at most one replica is
        ever out of rotation."""
        for i in range(self.count):
            log_info(f"fleet: rolling restart {i + 1}/{self.count}")
            self.restart(i, wait_healthy=True, timeout_s=timeout_s)
            time.sleep(settle_s)

    def poll(self) -> List[Optional[int]]:
        """Exit codes (None = running) without blocking."""
        out: List[Optional[int]] = []
        with self._lock:
            procs = list(self._procs)
        for proc in procs:
            out.append(None if proc is None else proc.poll())
        return out

    def close(self) -> None:
        """Stop supervision, drain every replica, reap everything."""
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(5.0)
            self._monitor = None
        with self._lock:
            procs = list(self._procs)
            for i in range(self.count):
                self._paused[i] = True
        for proc in procs:
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        deadline = clock.monotonic() + 30.0
        for proc in procs:
            if proc is None:
                continue
            remaining = max(deadline - clock.monotonic(), 0.1)
            try:
                proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                log_warning(f"fleet: pid {proc.pid} ignored SIGTERM; "
                            "killing")
                proc.kill()
                try:
                    proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    log_warning(f"fleet: pid {proc.pid} unreapable")
        log_debug(1, "fleet: closed")

    def __enter__(self) -> "ReplicaFleet":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.close()
