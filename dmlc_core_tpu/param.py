"""Reflected, self-documenting parameter structs.

Capability parity with the reference's ``dmlc::Parameter<PType>`` CRTP system
(include/dmlc/parameter.h:113-1008):

- declarative typed fields with defaults, range checks, enum values and
  docstrings (DMLC_DECLARE_FIELD chains, parameter.h:240-273, 638-659, 681-783),
- ``init(kwargs)`` with unknown-argument policy (RunInit parameter.h:370-410):
  strict by default, ``allow_unknown=True`` returns the unrecognized pairs
  (InitAllowUnknown), and double-underscore-wrapped "hidden" keys (``__foo__``)
  are always ignored,
- missing required fields raise :class:`ParamError` naming the field
  (parameter.h:562-571),
- reflection: :meth:`Parameter.get_field_info` and generated
  :meth:`Parameter.doc_string` (parameter.h:463-471),
- JSON and dict round-trip (Save/Load parameter.h:165-177, GetDict),
- typed environment reading :func:`get_env` (parameter.h:998-1008).

TPU-first design note: parameter structs are plain Python objects on the host;
they configure tracers/factories and never enter jit. Anything that must cross
into a compiled function should be pulled out as a static argument or pytree.

Usage::

    class LinearParam(Parameter):
        learning_rate = field(float, default=0.1, lower=0.0, help="step size")
        loss = field(str, default="logistic", enum=["logistic", "squared"],
                     help="objective")
        num_feature = field(int, help="feature dimension")   # required

    p = LinearParam()
    unknown = p.init({"num_feature": 100, "batch": 32}, allow_unknown=True)
"""

from __future__ import annotations

import json as _json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple, Type, Union

__all__ = ["Parameter", "ParamError", "field", "Field", "get_env"]


class ParamError(ValueError):
    """Raised on bad/missing parameter values (reference parameter.h:60-67)."""


_REQUIRED = object()


def _parse_bool(s: str) -> bool:
    t = s.strip().lower()
    if t in ("1", "true", "yes", "t"):
        return True
    if t in ("0", "false", "no", "f"):
        return False
    raise ValueError(f"invalid bool literal {s!r}")


class Field:
    """One declared parameter field (reference FieldEntry<T>, parameter.h:500-900).

    Acts as a data descriptor on :class:`Parameter` subclasses.
    """

    def __init__(
        self,
        dtype: type,
        default: Any = _REQUIRED,
        help: str = "",
        lower: Optional[float] = None,
        upper: Optional[float] = None,
        enum: Union[None, Sequence[str], Dict[str, Any]] = None,
        optional: bool = False,
    ):
        if dtype not in (int, float, str, bool):
            raise TypeError(f"unsupported field dtype {dtype!r}; use int/float/str/bool")
        self.dtype = dtype
        self.default = default
        self.help = help
        self.lower = lower
        self.upper = upper
        self.optional = optional
        # enum: sequence of allowed strings (str fields) or name->value map
        # (reference add_enum, parameter.h:681-783).
        self.enum_map: Optional[Dict[str, Any]] = None
        if enum is not None:
            if isinstance(enum, dict):
                self.enum_map = dict(enum)
            else:
                self.enum_map = {str(v): str(v) for v in enum}
        self.name: str = "<unbound>"
        if optional and default is _REQUIRED:
            self.default = None

    # -- descriptor protocol ------------------------------------------------
    def __set_name__(self, owner: type, name: str) -> None:
        self.name = name

    def __get__(self, obj: Any, objtype: Any = None) -> Any:
        if obj is None:
            return self
        try:
            return obj.__dict__[self.name]
        except KeyError:
            raise AttributeError(
                f"parameter field {self.name!r} accessed before init and has no default"
            ) from None

    def __set__(self, obj: Any, value: Any) -> None:
        obj.__dict__[self.name] = self.check(self.coerce(value))

    # -- value handling -----------------------------------------------------
    def coerce(self, value: Any) -> Any:
        """Parse/convert ``value`` to the field type (FieldEntryBase::Set, 518-539)."""
        if self.optional and (value is None or value == "None"):
            return None
        if self.enum_map is not None and isinstance(value, str):
            if value not in self.enum_map:
                raise ParamError(
                    f"Invalid value {value!r} for parameter {self.name!r}; "
                    f"expected one of {sorted(self.enum_map)}"
                )
            return self.enum_map[value]
        try:
            if isinstance(value, str) and self.dtype is bool:
                return _parse_bool(value)
            if isinstance(value, bool) and self.dtype in (int, float):
                return self.dtype(value)
            if self.dtype is int and isinstance(value, float) and value != int(value):
                raise ValueError(f"non-integral value {value!r}")
            return self.dtype(value)
        except (TypeError, ValueError) as exc:
            raise ParamError(
                f"Invalid value {value!r} for parameter {self.name!r} of type "
                f"{self.dtype.__name__}: {exc}"
            ) from None

    def check(self, value: Any) -> Any:
        """Range validation (FieldEntryNumeric::Check, parameter.h:638-659)."""
        if value is None and self.optional:
            return value
        if self.lower is not None and value < self.lower:
            raise ParamError(
                f"value {value!r} for parameter {self.name!r} exceeds bound: "
                f"expected {self.name} >= {self.lower}"
            )
        if self.upper is not None and value > self.upper:
            raise ParamError(
                f"value {value!r} for parameter {self.name!r} exceeds bound: "
                f"expected {self.name} <= {self.upper}"
            )
        if self.enum_map is not None and value not in self.enum_map.values():
            raise ParamError(
                f"value {value!r} for parameter {self.name!r} not among enum values "
                f"{sorted(map(repr, self.enum_map.values()))}"
            )
        return value

    def value_to_str(self, value: Any) -> str:
        if self.enum_map is not None:
            for k, v in self.enum_map.items():
                if v == value:
                    return k
        if value is None:
            return "None"
        if self.dtype is bool:
            return "1" if value else "0"
        return str(value)

    # -- reflection ---------------------------------------------------------
    def type_str(self) -> str:
        base = "optional[int]" if self.optional and self.dtype is int else self.dtype.__name__
        parts = [base]
        if self.enum_map is not None:
            parts = ["{" + ", ".join(sorted(map(repr, self.enum_map))) + "}"]
        if self.lower is not None or self.upper is not None:
            lo = self.lower if self.lower is not None else "-inf"
            hi = self.upper if self.upper is not None else "inf"
            parts.append(f"range [{lo}, {hi}]")
        if self.default is not _REQUIRED:
            parts.append(f"default={self.value_to_str(self.default)}")
        else:
            parts.append("required")
        return ", ".join(parts)


def field(
    dtype: type,
    default: Any = _REQUIRED,
    help: str = "",
    lower: Optional[float] = None,
    upper: Optional[float] = None,
    enum: Union[None, Sequence[str], Dict[str, Any]] = None,
    optional: bool = False,
) -> Field:
    """Declare a parameter field (reference DMLC_DECLARE_FIELD, parameter.h:240-250)."""
    return Field(dtype, default=default, help=help, lower=lower, upper=upper,
                 enum=enum, optional=optional)


class Parameter:
    """Base class for declarative parameter structs (reference Parameter<PType>)."""

    __fields__: Dict[str, Field] = {}

    def __init_subclass__(cls, **kwargs: Any) -> None:
        super().__init_subclass__(**kwargs)
        fields: Dict[str, Field] = {}
        for base in reversed(cls.__mro__[1:]):
            fields.update(getattr(base, "__fields__", {}))
        for name, value in list(vars(cls).items()):
            if isinstance(value, Field):
                fields[name] = value
        cls.__fields__ = fields

    def __init__(self, **kwargs: Any):
        for name, f in self.__fields__.items():
            if f.default is not _REQUIRED:
                self.__dict__[name] = f.check(f.coerce(f.default)) if f.default is not None else None
        if kwargs:
            self.init(kwargs)

    # -- init protocol ------------------------------------------------------
    def init(
        self,
        kwargs: Dict[str, Any],
        allow_unknown: bool = False,
    ) -> Dict[str, Any]:
        """Initialize fields from a kwargs dict (reference RunInit, parameter.h:370-410).

        Returns the dict of unknown key/value pairs when ``allow_unknown`` is
        True; raises :class:`ParamError` on unknown keys otherwise.  Keys of the
        form ``__x__`` are silently ignored (reference "hidden" args policy).
        Missing required fields raise :class:`ParamError`.
        """
        unknown: Dict[str, Any] = {}
        for key, value in kwargs.items():
            f = self.__fields__.get(key)
            if f is None:
                if len(key) > 4 and key.startswith("__") and key.endswith("__"):
                    continue
                if allow_unknown:
                    unknown[key] = value
                    continue
                raise ParamError(
                    f"Cannot find parameter {key!r} in {type(self).__name__}. "
                    f"Candidates: {sorted(self.__fields__)}"
                )
            setattr(self, key, value)
        missing = [n for n in self.__fields__ if n not in self.__dict__]
        if missing:
            raise ParamError(
                f"required parameter(s) {missing} of {type(self).__name__} not set"
            )
        return unknown

    def update(self, kwargs: Dict[str, Any]) -> None:
        """Update a subset of fields (reference UpdateDict semantics)."""
        for key, value in kwargs.items():
            if key in self.__fields__:
                setattr(self, key, value)

    # -- reflection / serialization -----------------------------------------
    def to_dict(self) -> Dict[str, str]:
        """All fields as a str->str dict (reference GetDict / __DICT__)."""
        return {
            name: f.value_to_str(self.__dict__[name])
            for name, f in self.__fields__.items()
            if name in self.__dict__
        }

    def to_json(self) -> str:
        """JSON text holding the str->str dict (reference Save, parameter.h:165-170)."""
        return _json.dumps(self.to_dict(), indent=2, sort_keys=True)

    def load_json(self, text: str) -> None:
        """Inverse of :meth:`to_json` (reference Load, parameter.h:172-177)."""
        data = _json.loads(text)
        if not isinstance(data, dict):
            raise ParamError("parameter JSON must hold an object of key/value pairs")
        self.init({str(k): v for k, v in data.items()})

    def save(self, stream: Any) -> None:
        """Write JSON to a binary stream (dmlc_core_tpu.io.Stream or file-like)."""
        stream.write(self.to_json().encode("utf-8"))

    @classmethod
    def get_field_info(cls) -> List[Tuple[str, str, str]]:
        """List of (name, type_str, description) (reference __FIELDS__, GetFieldInfo)."""
        return [(n, f.type_str(), f.help) for n, f in cls.__fields__.items()]

    @classmethod
    def doc_string(cls) -> str:
        """Generated human-readable doc (reference __DOC__, parameter.h:463-471)."""
        lines = []
        for name, f in cls.__fields__.items():
            lines.append(f"{name} : {f.type_str()}")
            if f.help:
                lines.append(f"    {f.help}")
        return "\n".join(lines)

    def __repr__(self) -> str:
        body = ", ".join(f"{k}={v!r}" for k, v in self.to_dict().items())
        return f"{type(self).__name__}({body})"

    def __eq__(self, other: Any) -> bool:
        return type(self) is type(other) and self.to_dict() == other.to_dict()

    def __hash__(self) -> int:  # params are config values; hash by content
        return hash((type(self), tuple(sorted(self.to_dict().items()))))


def get_env(key: str, dtype: Type, default: Any) -> Any:
    """Typed environment variable read (reference GetEnv, parameter.h:998-1008)."""
    raw = os.environ.get(key)
    if raw is None:
        return default
    if dtype is bool:
        return _parse_bool(raw)
    return dtype(raw)
