"""Pass 9 — jaxbound: host↔device boundary discipline.

PR 7 put the feed pipeline on a uint8 wire diet and routed every transfer
through ONE accounting wrapper (``_accounted_place``, bridge/loader.py),
so the trace CLI's critical path can split transfer from compute and the
``dmlc_transfer_bytes_total`` contract stays truthful.  Nothing enforced
that discipline until now — a stray ``jax.device_put`` in bridge code
ships bytes off the books, a float32 cast on the binned payload silently
re-inflates the wire 4x, and a ``jax.jit`` rebuilt per call retraces on
every request (the PR 5 knee-bench bug, found by hand then).

``jaxbound-unaccounted-transfer``
    A ``jax.device_put`` / ``jnp.asarray`` / ``jnp.array`` call inside
    ``dmlc_core_tpu/bridge/`` whose enclosing function is neither passed
    to ``_accounted_place`` (nor defined inside it) nor reachable from a
    traced root (where ``asarray`` of a tracer is free).  Every transfer
    the feed pipeline makes must go through the wrapper so the byte/span
    accounting cannot drift between paths.

``jaxbound-wide-wire``
    A value produced by the narrow-wire binning path (``.transform()`` /
    ``apply_bins`` / ``binned_batches``) that is cast to float32/float64
    (``.astype``, ``np.asarray(..., dtype=...)``, ``np.float32(...)``)
    and then flows into a transfer sink (``device_put`` or an accounted
    place function) within one function.  The wire dtype ladder exists so
    the tunnel ships uint8/uint16; widen ON DEVICE inside the jit
    (``models/gbdt.py _widen_bins``), never before the transfer.

``jaxbound-jit-in-hot-path``
    A ``jax.jit``/``pjit`` wrapper that is rebuilt per call: immediately
    invoked (``jax.jit(f)(x)``) or bound to a local that is only ever
    called, inside a function that is not an acknowledged
    construction-time context (module level, ``__init__``, an
    ``lru_cache``/``cache``/``cached_property``-decorated builder).  A
    fresh wrapper has an empty compile cache — every call of the
    enclosing function pays a full retrace; when the wrapped callable
    also closes over ``self`` the staleness is worse (trace-time state is
    baked in).  Store the wrapper on the instance/module, or build it
    under a memoizing decorator.

Scope: ``unaccounted-transfer`` and ``wide-wire`` apply to
``dmlc_core_tpu/bridge/`` (the feed pipeline owns the wire diet; models
legitimately take float input, and bench.py's staging keeps its own
labeled accounting).  ``jit-in-hot-path`` applies project-wide.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from dmlc_core_tpu.analysis.driver import (FileContext, Finding, dotted_name,
                                           keyword_arg)
from dmlc_core_tpu.analysis.graph import (ProjectGraph, resolve_callable,
                                          walk_in_scope)
from dmlc_core_tpu.analysis.purity import _reachable, _trace_roots

__all__ = ["run_project", "BRIDGE_PREFIX", "ACCOUNTED_WRAPPER"]

BRIDGE_PREFIX = "dmlc_core_tpu/bridge/"
ACCOUNTED_WRAPPER = "_accounted_place"

_TRANSFER_CALLS = {"device_put"}
_IMPLICIT_TRANSFER = {"asarray", "array"}  # on jnp/jax.numpy only
_JIT_NAMES = {"jit", "pjit"}
_WIDE_DTYPES = {"float32", "float64", "float_", "double"}
_NARROW_SOURCES = {"transform", "apply_bins", "binned_batches"}
_MEMO_DECORATORS = {"lru_cache", "cache", "cached_property"}


def _jnp_aliases(ctx: FileContext) -> Set[str]:
    """Local names bound to jax.numpy (``jnp``, ``jax.numpy``)."""
    out = {alias for alias, mod in ctx.module_aliases.items()
           if mod in ("jax.numpy", "jax")}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for alias in node.names:
                if alias.name == "numpy":
                    out.add(alias.asname or "numpy")
    return out


# -- accounted-function discovery ---------------------------------------------

def _accounted_functions(ctx: FileContext) -> Set[int]:
    """id()s of function nodes whose transfers are accounted: functions
    passed to ``_accounted_place`` and functions defined inside it."""
    out: Set[int] = set()
    defs = ctx.defs_by_name
    aliases = ctx.assign_aliases
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            if name.rsplit(".", 1)[-1] == ACCOUNTED_WRAPPER and node.args:
                for fn in resolve_callable(ctx, node.args[0], defs, aliases):
                    out.add(id(fn))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == ACCOUNTED_WRAPPER:
            for sub in ast.walk(node):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)) and sub is not node:
                    out.add(id(sub))
    return out


def _enclosing_chain(ctx: FileContext, node: ast.AST) -> Iterable[ast.AST]:
    cur = ctx.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            yield cur
        cur = ctx.parents.get(cur)


def _check_bridge_file(ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    accounted = _accounted_functions(ctx)
    traced = {id(fn) for fn in _reachable(ctx, _trace_roots(ctx))}
    jnp_names = _jnp_aliases(ctx)

    def is_exempt(node: ast.AST) -> bool:
        return any(id(fn) in accounted or id(fn) in traced
                   for fn in _enclosing_chain(ctx, node))

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func) or ""
        parts = name.split(".")
        short = parts[-1]
        hit = None
        if short in _TRANSFER_CALLS:
            hit = name
        elif short in _IMPLICIT_TRANSFER and len(parts) >= 2 \
                and parts[0] in jnp_names:
            hit = name
        if hit is None or is_exempt(node):
            continue
        findings.append(Finding(
            "jaxbound-unaccounted-transfer", ctx.relpath, node.lineno,
            ctx.qualname(node),
            f"{hit}() moves host bytes to device outside the "
            "_accounted_place wrapper (bridge/loader.py) — this transfer "
            "is invisible to dmlc_transfer_bytes_total and the trace "
            "critical path; route it through the wrapper"))
    findings += _check_wide_wire(ctx, accounted)
    return findings


# -- wide-wire def-use --------------------------------------------------------

def _dtype_token(node: Optional[ast.AST]) -> Optional[str]:
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    name = dotted_name(node)
    return name.rsplit(".", 1)[-1] if name else None


def _is_wide_cast(call: ast.Call) -> bool:
    name = dotted_name(call.func) or ""
    short = name.rsplit(".", 1)[-1]
    if short == "astype" and call.args:
        return _dtype_token(call.args[0]) in _WIDE_DTYPES
    if short in ("asarray", "array", "ascontiguousarray"):
        return _dtype_token(keyword_arg(call, "dtype")) in _WIDE_DTYPES
    return short in _WIDE_DTYPES  # np.float32(x) constructor cast
    # (bare float32 literals with no operand are dtype mentions, but they
    # only matter when the RESULT flows to a sink, which requires args)


def _check_wide_wire(ctx: FileContext,
                     accounted: Set[int]) -> List[Finding]:
    findings: List[Finding] = []
    accounted_names: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and id(node) in accounted:
            accounted_names.add(node.name)
    for fn in [n for n in ast.walk(ctx.tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
        narrow: Set[str] = set()
        widened: Set[str] = set()
        # two passes over the straight-line def-use so chains that span
        # assignments resolve regardless of walk order
        for _ in range(2):
            for node in walk_in_scope(fn):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    continue
                target = node.targets[0].id
                value = node.value
                if isinstance(value, ast.Call):
                    name = dotted_name(value.func) or ""
                    short = name.rsplit(".", 1)[-1]
                    operands = ([dotted_name(a) for a in value.args]
                                + ([dotted_name(value.func.value)]
                                   if isinstance(value.func, ast.Attribute)
                                   else []))
                    if short in _NARROW_SOURCES:
                        narrow.add(target)
                    elif _is_wide_cast(value) and any(
                            o and o.split(".")[0] in narrow
                            for o in operands):
                        widened.add(target)
                elif isinstance(value, ast.Name):
                    if value.id in narrow:
                        narrow.add(target)
                    if value.id in widened:
                        widened.add(target)
        if not widened:
            continue
        for node in walk_in_scope(fn):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            short = name.rsplit(".", 1)[-1]
            is_sink = (short in _TRANSFER_CALLS
                       or short in accounted_names)
            if not is_sink:
                continue
            for arg in node.args:
                aname = dotted_name(arg)
                if aname and aname.split(".")[0] in widened:
                    findings.append(Finding(
                        "jaxbound-wide-wire", ctx.relpath, node.lineno,
                        ctx.qualname(node),
                        f"{aname} carries binned (narrow-wire) data "
                        "widened to a float dtype before the transfer — "
                        "this re-inflates the wire ~4x; ship the narrow "
                        "dtype and widen on device inside the jit "
                        "(models/gbdt.py _widen_bins)"))
    return findings


# -- jit-in-hot-path ----------------------------------------------------------

def _decorator_names(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for dec in getattr(fn, "decorator_list", []):
        base = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted_name(base) or ""
        out.add(name.rsplit(".", 1)[-1])
    return out


def _jit_context_exempt(ctx: FileContext, call: ast.Call) -> bool:
    """Construction-time contexts where building a jit wrapper is fine."""
    chain = list(_enclosing_chain(ctx, call))
    if not chain:
        return True  # module level: runs once
    for fn in chain:
        if getattr(fn, "name", "") == "__init__":
            return True
        if _decorator_names(fn) & _MEMO_DECORATORS:
            return True
    return False


def _local_stored(fn: ast.AST, name: str, binding: ast.AST) -> bool:
    """Is the jit wrapper bound to ``name`` parked anywhere that outlives
    the call (returned / attr / subscript / container / passed on)?
    Merely CALLING it (``fn(x)``) parks nothing — that is exactly the
    rebuilt-per-call shape."""
    from dmlc_core_tpu.analysis.escape import _direct_owner

    def is_it(expr: ast.AST) -> bool:
        return isinstance(expr, ast.Name) and expr.id == name

    for node in walk_in_scope(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            if _direct_owner(node.value, is_it):
                return True
        elif isinstance(node, ast.Assign) and node is not binding:
            if any(isinstance(t, (ast.Attribute, ast.Subscript))
                   for t in node.targets) and \
                    _direct_owner(node.value, is_it):
                return True
        elif isinstance(node, ast.Call):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if is_it(arg):
                    return True
    return False


def _closes_over_self(arg: ast.AST, ctx: FileContext) -> bool:
    if isinstance(arg, ast.Attribute):
        return (isinstance(arg.value, ast.Name)
                and arg.value.id == "self")  # jit(self.method)
    if isinstance(arg, (ast.Lambda,)):
        return any(isinstance(n, ast.Name) and n.id == "self"
                   for n in ast.walk(arg.body))
    if isinstance(arg, ast.Name):
        fns = ctx.defs_by_name.get(arg.id, [])
        return any(any(isinstance(n, ast.Name) and n.id == "self"
                       for n in ast.walk(f))
                   for f in fns)
    return False


def _check_jit_hot_path(ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func) or ""
        if name.rsplit(".", 1)[-1] not in _JIT_NAMES:
            continue
        # only the real wrappers: jax.jit / pjit / bare jit import —
        # method calls like obj.jit() are not trace entry points
        root = name.split(".")[0]
        if root not in ("jax", "jit", "pjit") and name not in _JIT_NAMES:
            continue
        if _jit_context_exempt(ctx, node):
            continue
        parent = ctx.parents.get(node)
        rebuilt = None
        if isinstance(parent, ast.Call) and parent.func is node:
            rebuilt = "immediately invoked"
        elif (isinstance(parent, ast.Assign) and len(parent.targets) == 1
              and isinstance(parent.targets[0], ast.Name)):
            fn = ctx.enclosing(node, ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)
            if fn is not None and not _local_stored(
                    fn, parent.targets[0].id, parent):
                rebuilt = "bound to a local that is only called"
        if rebuilt is None:
            continue
        closure = (node.args and _closes_over_self(node.args[0], ctx))
        extra = (" — and the wrapped callable closes over self, so "
                 "trace-time instance state is baked into each rebuild"
                 if closure else "")
        findings.append(Finding(
            "jaxbound-jit-in-hot-path", ctx.relpath, node.lineno,
            ctx.qualname(node),
            f"{name}(...) is {rebuilt}: the wrapper is rebuilt on every "
            "call of the enclosing function, so its compile cache is "
            "always empty and every call retraces (the PR 5 knee-bench "
            "bug class); store the jitted fn on the instance/module or "
            f"build it under a memoizing decorator{extra}"))
    return findings


# -- the pass -----------------------------------------------------------------

def run_project(graph: ProjectGraph) -> List[Finding]:
    findings: List[Finding] = []
    for mod in graph.modules.values():
        ctx = mod.ctx
        if ctx.relpath.startswith(BRIDGE_PREFIX):
            findings += _check_bridge_file(ctx)
        findings += _check_jit_hot_path(ctx)
    return findings
