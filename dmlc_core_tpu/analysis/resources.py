"""Pass 3 — resources: handle lifetimes in the io layer + the style rules.

``resource-unclosed``
    A call that acquires an OS handle (``open``, ``socket.socket``,
    ``tempfile.TemporaryFile``...) whose result is neither (a) a ``with``
    context manager, (b) returned (ownership transfers to the caller — the
    filesystem-factory idiom), (c) handed to another call (wrapping, e.g.
    ``BufferedReader(open(...))``), (d) stored on ``self`` (class-owned
    lifecycle, closed by the owner's ``close``), nor (e) a local that the
    enclosing function visibly ``close``s / returns / hands off.  A bare
    ``open(p)`` expression or a never-closed local leaks the fd on any
    exception path.

``resource-tempdir``
    ``tempfile.mkdtemp()`` whose path never reaches ``shutil.rmtree`` inside
    a ``finally`` block of the enclosing function.  Cleanup in an ``except
    SomeError`` arm is exactly the bug this rule exists for: any *other*
    exception type leaks the dir (tracker/filecache.py shipped this).

``style-no-print``
    The original scripts/lint.py rule, migrated: library code logs through
    ``utils.logging``; ``print`` is reserved for the CLI-exempt modules.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from dmlc_core_tpu.analysis.driver import FileContext, Finding, dotted_name

__all__ = ["run", "OPENER_CALLS", "ACQUISITIONS", "RELEASE_METHODS",
           "RELEASE_FUNCS", "acquisition_kind"]

# -- the shared acquisition table ---------------------------------------------
#
# ONE extensible table of "this call acquires an OS resource" knowledge,
# consumed by two passes: this per-file pass checks the file/socket/temp
# subset with its lexical heuristics, and the interprocedural escape pass
# (pass 8, escape.py) tracks EVERY kind through def-use chains with
# exception edges.  Keys are dotted-name patterns matched against the
# full dotted call name and its one/two-component suffixes; values are
# the resource kind (drives per-kind release vocabulary and messages).
ACQUISITIONS = {
    "open": "file", "io.open": "file", "gzip.open": "file",
    "bz2.open": "file", "lzma.open": "file", "os.fdopen": "file",
    "tempfile.TemporaryFile": "file", "tempfile.NamedTemporaryFile": "file",
    "socket.socket": "socket", "socket.create_connection": "socket",
    "tempfile.mkdtemp": "tempdir", "mkdtemp": "tempdir",
    "os.open": "fd",
    "SharedMemory": "shm", "shared_memory.SharedMemory": "shm",
    "ThreadPoolExecutor": "executor", "ProcessPoolExecutor": "executor",
    "futures.ThreadPoolExecutor": "executor",
    "futures.ProcessPoolExecutor": "executor",
    "mmap.mmap": "mmap",
}

# method names that release a resource, by kind (None key = any kind)
RELEASE_METHODS = {
    None: {"close", "detach"},
    "socket": {"close"},
    "executor": {"shutdown"},
    "shm": {"close", "unlink"},
    "mmap": {"close"},
}

# function-style releases: shutil.rmtree(x) / os.close(fd) — matched on
# the call's last dotted component
RELEASE_FUNCS = {"rmtree", "rmdir"}


def acquisition_kind(name: str) -> "str | None":
    """Resource kind for a dotted call name, or None.  Matches the full
    name, then its two- and one-component suffixes, so both
    ``multiprocessing.shared_memory.SharedMemory`` and a bare
    ``SharedMemory`` import resolve."""
    if not name:
        return None
    if name in ACQUISITIONS:
        return ACQUISITIONS[name]
    parts = name.split(".")
    if len(parts) >= 2 and ".".join(parts[-2:]) in ACQUISITIONS:
        return ACQUISITIONS[".".join(parts[-2:])]
    # bare-suffix matches are restricted to unambiguous class names —
    # a one-component "open" suffix would match every `x.open()` method
    if parts[-1] in ("SharedMemory", "ThreadPoolExecutor",
                     "ProcessPoolExecutor", "mkdtemp"):
        return ACQUISITIONS[parts[-1]]
    return None


# the per-file rule keeps its historical scope: short-lifetime handle
# kinds whose "handed to a call / stored on self" heuristics are sound.
# The executor/shm/mmap kinds have ownership-structured lifetimes that
# only the escape pass's dataflow models without false positives.
OPENER_CALLS = {name for name, kind in ACQUISITIONS.items()
                if kind in ("file", "socket")}

_TEMPDIR_CALLS = {"tempfile.mkdtemp", "mkdtemp"}

_CLOSE_METHODS = {"close", "shutdown", "release", "detach"}


def run(ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name in OPENER_CALLS:
            findings.extend(_check_opener(ctx, node, name))
        elif name in _TEMPDIR_CALLS:
            findings.extend(_check_tempdir(ctx, node, name))
        elif name == "print" and ctx.is_library and not ctx.cli_exempt:
            findings.append(ctx.finding(
                "style-no-print", node,
                "use utils.logging, not print()"))
    return findings


# -- resource-unclosed --------------------------------------------------------

def _check_opener(ctx: FileContext, call: ast.Call,
                  name: str) -> Iterable[Finding]:
    parent = ctx.parents.get(call)
    # with open(...) as f:  — direct context manager
    if isinstance(parent, ast.withitem) and parent.context_expr is call:
        return
    # return open(...)  — ownership transfers to the caller
    if isinstance(parent, ast.Return):
        return
    # wrapped / handed straight to another call: Reader(open(...))
    if isinstance(parent, ast.Call):
        return
    if isinstance(parent, ast.keyword):
        return
    # self._f = open(...)  — class-owned lifecycle
    if isinstance(parent, ast.Assign):
        if all(isinstance(t, ast.Attribute) for t in parent.targets):
            return
        if (len(parent.targets) == 1
                and isinstance(parent.targets[0], ast.Name)):
            local = parent.targets[0].id
            func = ctx.enclosing(call, ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda) or ctx.tree
            if _name_released(func, local, parent):
                return
            yield ctx.finding(
                "resource-unclosed", call,
                f"{name}() result {local!r} is never closed, returned, or "
                "handed off in this function; use `with` or try/finally")
            return
    yield ctx.finding(
        "resource-unclosed", call,
        f"{name}() result is discarded without a `with` block; the handle "
        "leaks until GC (and immediately on exception paths)")


def _name_released(func: ast.AST, name: str, assign: ast.Assign) -> bool:
    """Does ``func`` visibly pass ownership of local ``name`` on: close it,
    return it, store it, use it as a context manager, or hand it to a call?"""
    for node in ast.walk(func):
        if isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                    and f.value.id == name and f.attr in _CLOSE_METHODS):
                return True
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id == name:
                    return True
        elif isinstance(node, ast.Return) and node.value is not None:
            if any(isinstance(n, ast.Name) and n.id == name
                   for n in ast.walk(node.value)):
                return True
        elif isinstance(node, ast.withitem):
            if any(isinstance(n, ast.Name) and n.id == name
                   for n in ast.walk(node.context_expr)):
                return True
        elif isinstance(node, ast.Assign) and node is not assign:
            if (any(isinstance(t, ast.Attribute) for t in node.targets)
                    and any(isinstance(n, ast.Name) and n.id == name
                            for n in ast.walk(node.value))):
                return True
    return False


# -- resource-tempdir ---------------------------------------------------------

def _check_tempdir(ctx: FileContext, call: ast.Call,
                   name: str) -> Iterable[Finding]:
    parent = ctx.parents.get(call)
    if isinstance(parent, (ast.Return, ast.Call, ast.keyword)):
        return  # ownership transferred
    if isinstance(parent, ast.Assign):
        if all(isinstance(t, ast.Attribute) for t in parent.targets):
            return  # class-owned
        if (len(parent.targets) == 1
                and isinstance(parent.targets[0], ast.Name)):
            local = parent.targets[0].id
            func = ctx.enclosing(call, ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda) or ctx.tree
            if _rmtree_in_finally(func, local) or _returned(func, local):
                return
            yield ctx.finding(
                "resource-tempdir", call,
                f"mkdtemp() dir {local!r} has no shutil.rmtree in a "
                "`finally`; cleanup in an `except <Type>` arm leaks the dir "
                "for every other exception type")
            return
    yield ctx.finding(
        "resource-tempdir", call,
        "mkdtemp() result is not bound to a cleanup path")


def _rmtree_in_finally(func: ast.AST, name: str) -> bool:
    for node in ast.walk(func):
        if not isinstance(node, ast.Try) or not node.finalbody:
            continue
        for stmt in node.finalbody:
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                called = dotted_name(sub.func) or ""
                if called.rsplit(".", 1)[-1] not in ("rmtree", "rmdir"):
                    continue
                for arg in sub.args:
                    if any(isinstance(n, ast.Name) and n.id == name
                           for n in ast.walk(arg)):
                        return True
    return False


def _returned(func: ast.AST, name: str) -> bool:
    for node in ast.walk(func):
        if (isinstance(node, ast.Return) and node.value is not None
                and any(isinstance(n, ast.Name) and n.id == name
                        for n in ast.walk(node.value))):
            return True
    return False
