"""The ratchet: committed findings are burn-down work, new findings fail.

``analysis_baseline.json`` maps finding keys (``<file>:<rule>:<symbol>``) to
one-line justifications.  Keys use symbols rather than line numbers so
unrelated edits above a finding don't invalidate the baseline; ``syntax``
findings are never baselineable.  Regenerate with
``python -m dmlc_core_tpu.analysis --write-baseline`` — existing
justifications survive the rewrite, new keys get a TODO placeholder that a
reviewer must replace before merging.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from dmlc_core_tpu.analysis.driver import Finding

__all__ = ["load", "save", "partition", "UNBASELINEABLE"]

UNBASELINEABLE = {"syntax"}

_PLACEHOLDER = "TODO: justify (why is this safe?) or fix"

_NOTE = ("dmlclint ratchet: every key here is a known finding being burned "
         "down, not an endorsement. New findings fail CI. Regenerate with "
         "`python -m dmlc_core_tpu.analysis --write-baseline`; justify every "
         "entry. See docs/analysis.md.")


def load(path: str) -> Dict[str, str]:
    """key -> justification; missing file means an empty baseline.
    A present-but-unparseable file raises ValueError: silently treating a
    truncated baseline as empty would report every finding as new."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        try:
            data = json.load(f)
        except json.JSONDecodeError as exc:
            raise ValueError(f"unreadable baseline {path}: {exc}") from exc
    if not isinstance(data, dict):
        raise ValueError(f"unreadable baseline {path}: expected an object, "
                         f"got {type(data).__name__}")
    findings = data.get("findings", {})
    if not isinstance(findings, dict):
        raise ValueError(f"unreadable baseline {path}: 'findings' must be "
                         f"an object, got {type(findings).__name__}")
    return {str(k): str(v) for k, v in findings.items()}


def save(path: str, findings: Sequence[Finding],
         previous: Dict[str, str],
         keep: Optional[Dict[str, str]] = None) -> None:
    """Write the baseline from ``findings``.  ``keep`` holds entries to
    carry over verbatim (files outside a path-scoped run — their findings
    were not recomputed, so their keys must survive the rewrite)."""
    entries: Dict[str, str] = dict(keep or {})
    counts: Dict[str, int] = {}
    for f in findings:
        if f.rule in UNBASELINEABLE:
            continue
        key = _instance_key(f.key, counts)
        entries.setdefault(key, previous.get(key, _PLACEHOLDER))
    data = {
        "version": 1,
        "tool": "dmlclint",
        "note": _NOTE,
        "findings": {k: entries[k] for k in sorted(entries)},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2)
        f.write("\n")


def _instance_key(key: str, counts: Dict[str, int]) -> str:
    """``key`` for the first finding with that key, ``key#2``/``key#3``...
    for repeats — so a SECOND violation of an already-baselined rule in
    the same symbol is a new key and still fails the ratchet."""
    counts[key] = counts.get(key, 0) + 1
    n = counts[key]
    return key if n == 1 else f"{key}#{n}"


def partition(findings: Sequence[Finding], baseline: Dict[str, str],
              ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """(new, baselined, stale-keys).  Stale keys are baseline entries no
    current finding matches — fixed (prune them) or renamed symbols."""
    new: List[Finding] = []
    baselined: List[Finding] = []
    hit: Set[str] = set()
    counts: Dict[str, int] = {}
    for f in findings:
        if f.rule in UNBASELINEABLE:
            new.append(f)
            continue
        key = _instance_key(f.key, counts)
        if key in baseline:
            baselined.append(f)
            hit.add(key)
        else:
            new.append(f)
    stale = sorted(set(baseline) - hit)
    return new, baselined, stale
