"""Pass 8 — escape: interprocedural resource-escape on exception paths.

The per-file resources pass asks "does a release exist *somewhere* in this
function"; it is path-blind and module-local by design.  This pass asks
the question that has actually cost review rounds (the PR 4 shm-lease,
PR 5 warmup-executor and PR 6 dump-lock fixes were all hand-found): does
**every** path from an acquisition to the function exit — including the
exception edge out of every statement between the acquire and the
``finally``/``with``/handler that releases — either release the resource
or transfer its ownership?

Built on the :mod:`.dataflow` CFG engine and the :mod:`.graph` call graph:

``escape-leak-on-raise``
    A path exists on which the last reference to an acquired resource
    (the shared acquisition table in ``resources.ACQUISITIONS``:
    SharedMemory segments, sockets, executors, mmaps, fds, temp dirs) is
    dropped: released/transferred on some paths but live on an exception
    edge (release only in ``except ValueError`` leaks every other type;
    cleanup in a nested ``def`` that may never run counts for nothing),
    live at the exceptional exit of ``__init__`` after a ``self.X =``
    acquisition (the caller never sees the instance, so its ``close`` is
    unreachable), or — for the ownership-structured kinds (shm/executor/
    mmap) and helper-returned resources the per-file pass cannot see —
    live on every path.  A ``self.X`` acquisition also creates a **class
    obligation**: some method of the class must visibly release the attr.

``escape-double-release``
    The inverse: a non-idempotent release (``unlink``/``rmtree``/
    ``os.close``/``rmdir``) reached on a path where the same release
    already happened (the close-in-except-and-finally shape).

Ownership model (how a resource stops being this function's problem):

- ``return x`` (incl. a tuple element) — the caller owns it, and callers
  of this function are analyzed as acquirers (**interprocedural
  acquire-through-return**);
- ``self.X = x`` — the instance owns it (checked per the class
  obligation above); assigning to a subscript/attribute/global or
  appending to a container parks it beyond tracking;
- passing ``x`` to a call: an **unresolved** callee is assumed to take
  ownership (the ``Reader(open(...))`` wrapping idiom); a
  **project-resolved** callee is consulted — if its parameter summary
  releases or stores the argument the resource is released/transferred,
  otherwise the caller still owns it (that is the "leak through helper"
  case the per-file pass calls a hand-off).

Soundness caveats (docs/analysis.md): no aliasing through containers or
attribute round-trips (simple ``y = x`` aliases are honored,
flow-insensitively); the raise model is syntactic (logging-family calls
are non-raising by contract); ``except Exception`` counts as a catch-all
(async exceptions between acquire and handler are out of scope); static
call resolution limits are inherited from the graph core.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from dmlc_core_tpu.analysis import dataflow
from dmlc_core_tpu.analysis.driver import Finding, dotted_name
from dmlc_core_tpu.analysis.graph import (FunctionInfo, ProjectGraph,
                                          walk_in_scope)
from dmlc_core_tpu.analysis.resources import (RELEASE_FUNCS, RELEASE_METHODS,
                                              acquisition_kind)

__all__ = ["run_project", "NON_IDEMPOTENT_RELEASES"]

# releases that blow up (or corrupt another handle) when repeated
NON_IDEMPOTENT_RELEASES = {"unlink", "rmtree", "rmdir", "os.close"}

# may-states of one acquisition; released states carry the method
# ("released:close") so close-then-unlink — the correct full shm release —
# is distinguishable from the same non-idempotent method repeating
_LIVE = "live"
_REL = "released"        # prefix; full form "released:<how>"
_XFER = "transferred"    # ownership moved (returned/stored/handed off)


def _released(how: str) -> str:
    return f"{_REL}:{how}"


def _is_done(status: str) -> bool:
    return status == _XFER or status.startswith(_REL)

_State = FrozenSet[Tuple[str, str]]  # {(acq_id, status), ...}


def _release_methods_for(kind: str) -> Set[str]:
    out = set(RELEASE_METHODS[None])
    out |= RELEASE_METHODS.get(kind, set())
    return out


# -- per-function acquisition discovery ---------------------------------------

@dataclasses.dataclass
class _Acq:
    acq_id: str            # unique per function: "name@lineno"
    name: str              # local variable name ("x") or "self.X"
    kind: str
    lineno: int
    stmt: ast.AST          # the acquiring statement
    self_attr: Optional[str]  # attr name when bound to self.X
    via_helper: bool       # acquired through a project helper's return


def _call_acquires(graph: ProjectGraph, fn: FunctionInfo, call: ast.Call,
                   summaries: "_Summaries") -> Optional[Tuple[str, Optional[int], bool]]:
    """(kind, tuple_index_of_resource, via_helper) when ``call`` acquires."""
    name = dotted_name(call.func) or ""
    kind = acquisition_kind(name)
    if kind is not None:
        return kind, None, False
    for callee in graph.resolve_call(fn, call.func):
        ret = summaries.returns_resource.get(callee.fq)
        if ret is not None:
            return ret[0], ret[1], True
    return None


def _binding_of(stmt: ast.AST, call: ast.Call,
                idx: Optional[int]) -> Optional[Tuple[str, Optional[str]]]:
    """(local name or 'self.X', self attr) the acquisition binds to, given
    the acquiring statement shapes this pass tracks."""
    if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
        return None
    target = stmt.targets[0]
    value = stmt.value
    # unwrap `x = ACQ() if cond else None` / `x = y or ACQ()`
    if isinstance(value, ast.IfExp):
        value = (value.body if _contains(value.body, call)
                 else value.orelse)
    if isinstance(value, ast.BoolOp):
        for operand in value.values:
            if _contains(operand, call):
                value = operand
                break
    if value is call and idx is None:
        return _target_name(target)
    # tuple unpack of a helper that returns the resource at a known index:
    # `sock, port = bind_free_port(...)`
    if (idx is not None and value is call
            and isinstance(target, ast.Tuple)
            and idx < len(target.elts)):
        return _target_name(target.elts[idx])
    return None


def _target_name(target: ast.AST) -> Optional[Tuple[str, Optional[str]]]:
    if isinstance(target, ast.Name):
        return target.id, None
    if (isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"):
        return f"self.{target.attr}", target.attr
    return None


def _contains(root: ast.AST, needle: ast.AST) -> bool:
    return any(n is needle for n in ast.walk(root))


def _direct_owner(value: ast.AST, is_res_name) -> bool:
    """Does ``value`` own the resource directly — the bare name, a tuple/
    list of names, a wrapper call taking it as a direct argument, or a
    conditional of those?  ``self._mm = mmap.mmap(self._fd.fileno(), 0)``
    merely READS ``_fd`` and must not count as storing it."""
    if is_res_name(value):
        return True
    if isinstance(value, (ast.Tuple, ast.List, ast.Set)):
        return any(_direct_owner(e, is_res_name) for e in value.elts)
    if isinstance(value, ast.Call):
        return any(is_res_name(a) for a in
                   list(value.args) + [kw.value for kw in value.keywords])
    if isinstance(value, ast.IfExp):
        return (_direct_owner(value.body, is_res_name)
                or _direct_owner(value.orelse, is_res_name))
    if isinstance(value, ast.BoolOp):
        return any(_direct_owner(v, is_res_name) for v in value.values)
    if isinstance(value, ast.Starred):
        return _direct_owner(value.value, is_res_name)
    return False


def _find_acquisitions(graph: ProjectGraph, fn: FunctionInfo,
                       summaries: "_Summaries") -> List[_Acq]:
    out: List[_Acq] = []
    stmts = _stmts_by_call(fn.node)
    for call, stmt in stmts:
        acq = _call_acquires(graph, fn, call, summaries)
        if acq is None:
            continue
        kind, idx, via_helper = acq
        # a `with ACQ() as x:` acquisition is safe by construction
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            continue
        # `return ACQ()` / `Reader(ACQ())` / bare-expression: ownership
        # transfers at birth (or is the per-file pass's business)
        binding = _binding_of(stmt, call, idx)
        if binding is None:
            continue
        name, self_attr = binding
        out.append(_Acq(f"{name}@{call.lineno}", name, kind, call.lineno,
                        stmt, self_attr, via_helper))
    return out


def _stmts_by_call(fn_node: ast.AST) -> List[Tuple[ast.Call, ast.AST]]:
    """(call, enclosing simple statement) for every in-scope call."""
    out: List[Tuple[ast.Call, ast.AST]] = []

    def visit(stmt: ast.AST) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(stmt, (ast.If, ast.While)):
            for sub in ast.walk(stmt.test):
                if isinstance(sub, ast.Call):
                    out.append((sub, stmt))
            for child in stmt.body + getattr(stmt, "orelse", []):
                visit(child)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            for sub in ast.walk(stmt.iter):
                if isinstance(sub, ast.Call):
                    out.append((sub, stmt))
            for child in stmt.body + stmt.orelse:
                visit(child)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                for sub in ast.walk(item.context_expr):
                    if isinstance(sub, ast.Call):
                        out.append((sub, stmt))
            for child in stmt.body:
                visit(child)
            return
        if isinstance(stmt, ast.Try):
            for child in (stmt.body + stmt.orelse + stmt.finalbody):
                visit(child)
            for handler in stmt.handlers:
                for child in handler.body:
                    visit(child)
            return
        for sub in walk_in_scope(stmt):
            if isinstance(sub, ast.Call):
                out.append((sub, stmt))

    body = fn_node.body if isinstance(fn_node.body, list) else [fn_node.body]
    for stmt in body:
        visit(stmt)
    return out


# -- interprocedural summaries ------------------------------------------------

class _Summaries:
    """Fixpoint summaries over the project graph.

    - ``returns_resource[fq] = (kind, tuple_index or None)`` — the
      function's return value is (or contains, at a fixed tuple index) a
      fresh acquisition;
    - ``param_effects[fq][i]`` in {"releases", "owns"} — what the callee
      does with its i-th positional parameter (absent = reads only);
    - ``attr_releases[fq]`` — ``self.X`` attrs this method (transitively
      through same-class calls) visibly releases.
    """

    def __init__(self, graph: ProjectGraph):
        self.graph = graph
        self.returns_resource: Dict[str, Tuple[str, Optional[int]]] = {}
        self.param_effects: Dict[str, Dict[int, str]] = {}
        self.attr_releases: Dict[str, Set[str]] = {}
        fns = graph.functions()
        for fn in fns:
            self.param_effects[fn.fq] = self._scan_params(fn)
            self.attr_releases[fn.fq] = self._scan_attr_releases(fn)
        # returns_resource + transitive attr releases need a fixpoint
        # (helper chains: `def a(): return b()`; `close()` calling
        # `self._teardown()`)
        changed = True
        while changed:
            changed = False
            for fn in fns:
                ret = self._scan_returns(fn)
                if ret is not None and self.returns_resource.get(fn.fq) != ret:
                    self.returns_resource[fn.fq] = ret
                    changed = True
                if fn.cls is not None:
                    mine = self.attr_releases[fn.fq]
                    before = len(mine)
                    for node in walk_in_scope(fn.node):
                        if not isinstance(node, ast.Call):
                            continue
                        name = dotted_name(node.func) or ""
                        if name.startswith("self.") and name.count(".") == 1:
                            callee = fn.cls.methods.get(name.split(".")[1])
                            if callee is not None:
                                mine |= self.attr_releases.get(callee.fq,
                                                               set())
                    if len(mine) != before:
                        changed = True

    # -- param effects --------------------------------------------------------

    def _scan_params(self, fn: FunctionInfo) -> Dict[int, str]:
        args = fn.node.args
        names = [a.arg for a in (list(args.posonlyargs) + list(args.args))]
        if fn.cls is not None and names and names[0] in ("self", "cls"):
            names = names[1:]
        effects: Dict[int, str] = {}
        for i, pname in enumerate(names):
            eff = self._param_effect(fn, pname)
            if eff is not None:
                effects[i] = eff
        return effects

    def _param_effect(self, fn: FunctionInfo, pname: str) -> Optional[str]:
        owns = False
        any_release = set().union(*RELEASE_METHODS.values())
        # a CamelCase call that is the operand of `raise` is an exception
        # constructor formatting the param into a message, not a wrapper
        # taking ownership of it
        raised_calls = {id(n.exc) for n in walk_in_scope(fn.node)
                        if isinstance(n, ast.Raise) and n.exc is not None}
        for node in walk_in_scope(fn.node):
            if isinstance(node, ast.Call):
                func = node.func
                if (isinstance(func, ast.Attribute)
                        and isinstance(func.value, ast.Name)
                        and func.value.id == pname
                        and func.attr in any_release):
                    return "releases"
                called = dotted_name(func) or ""
                short = called.rsplit(".", 1)[-1]
                for arg in list(node.args) + [kw.value for kw in
                                              node.keywords]:
                    if isinstance(arg, ast.Name) and arg.id == pname:
                        if short in RELEASE_FUNCS or called == "os.close":
                            return "releases"
                        # wrapper/ctor or re-owning container op
                        if (short[:1].isupper()
                                and id(node) not in raised_calls) \
                                or short in ("append", "add", "register"):
                            owns = True
            elif isinstance(node, ast.Return) and node.value is not None:
                if _direct_owner(node.value,
                                 lambda e: isinstance(e, ast.Name)
                                 and e.id == pname):
                    owns = True
            elif isinstance(node, ast.Assign):
                stores = any(isinstance(t, (ast.Attribute, ast.Subscript))
                             for t in node.targets)
                if stores and _direct_owner(
                        node.value, lambda e: isinstance(e, ast.Name)
                        and e.id == pname):
                    owns = True
            elif isinstance(node, ast.withitem):
                if (isinstance(node.context_expr, ast.Name)
                        and node.context_expr.id == pname):
                    return "releases"
        return "owns" if owns else None

    # -- attr releases --------------------------------------------------------

    def _scan_attr_releases(self, fn: FunctionInfo) -> Set[str]:
        if fn.cls is None:
            return set()
        out: Set[str] = set()
        any_release = set().union(*RELEASE_METHODS.values())
        for node in walk_in_scope(fn.node):
            if isinstance(node, ast.Call):
                func = node.func
                # self.X.close() / self.X.shutdown(...)
                if (isinstance(func, ast.Attribute)
                        and func.attr in any_release
                        and isinstance(func.value, ast.Attribute)
                        and isinstance(func.value.value, ast.Name)
                        and func.value.value.id == "self"):
                    out.add(func.value.attr)
                    continue
                called = dotted_name(func) or ""
                short = called.rsplit(".", 1)[-1]
                if short in RELEASE_FUNCS or called == "os.close":
                    for arg in node.args:
                        base = arg
                        # rmtree(self.X) / os.close(self.X)
                        if (isinstance(base, ast.Attribute)
                                and isinstance(base.value, ast.Name)
                                and base.value.id == "self"):
                            out.add(base.attr)
                # self.X handed to any call transfers the obligation
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    if (isinstance(arg, ast.Attribute)
                            and isinstance(arg.value, ast.Name)
                            and arg.value.id == "self"):
                        out.add(arg.attr)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        out.add(t.attr)
            elif isinstance(node, ast.withitem):
                ce = node.context_expr
                if (isinstance(ce, ast.Attribute)
                        and isinstance(ce.value, ast.Name)
                        and ce.value.id == "self"):
                    out.add(ce.attr)
        return out

    # -- returns --------------------------------------------------------------

    def _scan_returns(self, fn: FunctionInfo) -> Optional[Tuple[str,
                                                                Optional[int]]]:
        """Does ``fn`` return a fresh acquisition (directly, via a live
        local, or at a fixed tuple index)?"""
        local_kinds: Dict[str, str] = {}
        for node in walk_in_scope(fn.node):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                kind = self._expr_kind(fn, node.value)
                if kind:
                    local_kinds[node.targets[0].id] = kind
        for node in walk_in_scope(fn.node):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            value = node.value
            kind = self._expr_kind_or_local(fn, value, local_kinds)
            if kind:
                return kind, None
            if isinstance(value, ast.Tuple):
                for i, elt in enumerate(value.elts):
                    kind = self._expr_kind_or_local(fn, elt, local_kinds)
                    if kind:
                        return kind, i
        return None

    def _expr_kind(self, fn: FunctionInfo, expr: ast.AST) -> Optional[str]:
        if not isinstance(expr, ast.Call):
            return None
        kind = acquisition_kind(dotted_name(expr.func) or "")
        if kind:
            return kind
        for callee in self.graph.resolve_call(fn, expr.func):
            ret = self.returns_resource.get(callee.fq)
            if ret is not None and ret[1] is None:
                return ret[0]
        return None

    def _expr_kind_or_local(self, fn: FunctionInfo, expr: ast.AST,
                            local_kinds: Dict[str, str]) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return local_kinds.get(expr.id)
        return self._expr_kind(fn, expr)


# -- the per-function dataflow ------------------------------------------------

class _FnChecker:
    def __init__(self, graph: ProjectGraph, summaries: _Summaries,
                 fn: FunctionInfo, acqs: List[_Acq]):
        self.graph = graph
        self.summaries = summaries
        self.fn = fn
        self.acqs = {a.acq_id: a for a in acqs}
        self.is_init = fn.name == "__init__"
        # flow-insensitive alias sets: y = x makes y an alias of x's
        # resource (release via either name counts)
        self.aliases: Dict[str, Set[str]] = {a.acq_id: {a.name}
                                             for a in acqs}
        self._collect_aliases(acqs)
        self.findings: List[Finding] = []
        self._double_reported: Set[Tuple[str, int]] = set()
        # global names declared in this function body
        self.globals: Set[str] = set()
        for node in walk_in_scope(fn.node):
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                self.globals.update(node.names)

    def _collect_aliases(self, acqs: List[_Acq]) -> None:
        for node in walk_in_scope(self.fn.node):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Name)):
                for a in acqs:
                    if node.value.id in self.aliases[a.acq_id]:
                        self.aliases[a.acq_id].add(node.targets[0].id)

    # -- state helpers --------------------------------------------------------

    @staticmethod
    def _set(state: _State, acq_id: str, status: str) -> _State:
        return frozenset({(i, s) for i, s in state if i != acq_id}
                         | {(acq_id, status)})

    @staticmethod
    def _statuses(state: _State, acq_id: str) -> Set[str]:
        return {s for i, s in state if i == acq_id}

    # -- the transfer function ------------------------------------------------

    @staticmethod
    def _effect_nodes(stmt: ast.AST) -> Iterable[ast.AST]:
        """The AST region whose effects belong to this CFG node: compound
        statements contribute only their header expression (their bodies
        are separate CFG nodes)."""
        if isinstance(stmt, (ast.If, ast.While)):
            return ast.walk(stmt.test)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return ast.walk(stmt.iter)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            def gen():
                for item in stmt.items:
                    yield from ast.walk(item.context_expr)
            return gen()
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return ()  # a def/class statement only binds a name

        def simple():
            yield stmt
            yield from walk_in_scope(stmt)
        return simple()

    def transfer(self, node: dataflow.Node,
                 state: _State) -> Tuple[_State, _State]:
        stmt = node.stmt
        if stmt is None:
            return state, state
        if isinstance(stmt, tuple) and stmt[0] == dataflow.WITH_EXIT:
            out = state
            for item in stmt[1].items:
                out = self._apply_with_release(out, item.context_expr)
            return out, out
        pre = state
        out = state
        acquired_here: Set[str] = set()
        for a in self.acqs.values():
            if a.stmt is stmt:
                out = self._set(out, a.acq_id, _LIVE)
                acquired_here.add(a.acq_id)
        out = self._apply_effects(stmt, out)
        # exception edge: acquisitions have NOT happened (a failing
        # open() binds nothing) but releases count as done (a failing
        # close() is still a release attempt) — so the exc-state drops
        # this statement's acquisitions and keeps its releases
        exc = out
        for acq_id in acquired_here:
            prev = self._statuses(pre, acq_id)
            exc = frozenset({(i, s) for i, s in exc if i != acq_id}
                            | {(acq_id, s) for s in prev})
        return out, exc

    def _apply_with_release(self, state: _State, expr: ast.AST) -> _State:
        name = dotted_name(expr)
        if name is None and isinstance(expr, ast.Call):
            # contextlib.closing(x) / suppress(...)-style wrappers
            for arg in expr.args:
                state = self._apply_with_release(state, arg)
            return state
        if name is None:
            return state
        for acq_id, names in self.aliases.items():
            if name in names:
                state = self._set(state, acq_id, _released("exit"))
        return state

    def _apply_effects(self, stmt: ast.AST, state: _State) -> _State:
        for acq_id, acq in self.acqs.items():
            statuses = self._statuses(state, acq_id)
            if not statuses:
                continue  # not acquired on this path (or untracked)
            effect = self._stmt_effect(stmt, acq, self.aliases[acq_id])
            if effect is None:
                continue
            kind, method = effect
            if kind == "release":
                if (method in NON_IDEMPOTENT_RELEASES
                        and _released(method) in statuses
                        and (acq_id, stmt.lineno) not in
                        self._double_reported):
                    self._double_reported.add((acq_id, stmt.lineno))
                    self.findings.append(Finding(
                        "escape-double-release", self.fn.module.relpath,
                        stmt.lineno, self.fn.qualname,
                        f"{acq.name!r} ({acq.kind}, acquired at line "
                        f"{acq.lineno}) may already be released via "
                        f"{method} when this {method}() runs — a repeated "
                        f"{method} raises (or tears down a reused handle); "
                        "gate it or restructure the cleanup"))
                state = self._set(state, acq_id, _released(method))
            elif kind == "transfer":
                state = self._set(state, acq_id, _XFER)
            elif kind == "drop":
                state = frozenset((i, s) for i, s in state if i != acq_id)
        return state

    def _stmt_effect(self, stmt: ast.AST, acq: _Acq,
                     names: Set[str]) -> Optional[Tuple[str, str]]:
        """("release"|"transfer"|"drop", how) for one CFG node's effect
        on one resource, or None."""

        def is_res_name(expr: ast.AST) -> bool:
            if isinstance(expr, ast.Name) and expr.id in names:
                return True
            return (acq.self_attr is not None
                    and isinstance(expr, ast.Attribute)
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"
                    and expr.attr == acq.self_attr)

        release_methods = _release_methods_for(acq.kind)
        result: Optional[Tuple[str, str]] = None
        for node in self._effect_nodes(stmt):
            if isinstance(node, ast.Call):
                func = node.func
                if (isinstance(func, ast.Attribute)
                        and func.attr in release_methods
                        and is_res_name(func.value)):
                    return "release", func.attr
                called = dotted_name(func) or ""
                short = called.rsplit(".", 1)[-1]
                # self.m() where m transitively releases the tracked attr
                if (acq.self_attr is not None and self.fn.cls is not None
                        and called.startswith("self.")
                        and called.count(".") == 1):
                    meth = self.fn.cls.methods.get(short)
                    if meth is not None and acq.self_attr in \
                            self.summaries.attr_releases.get(meth.fq, set()):
                        return "release", short
                for pos, arg in enumerate(
                        list(node.args)
                        + [kw.value for kw in node.keywords]):
                    if not is_res_name(arg):
                        continue
                    if short in RELEASE_FUNCS or called == "os.close":
                        return ("release", short if short in RELEASE_FUNCS
                                else "os.close")
                    if self._call_takes_ownership(node, pos):
                        result = result or ("transfer", "arg")
                    # else: a resolved project callee that only READS the
                    # parameter — the caller still owns the resource
            elif isinstance(node, ast.Return) and node.value is not None:
                if _direct_owner(node.value, is_res_name):
                    return "transfer", "return"
            elif isinstance(node, ast.Assign) and node is not acq.stmt:
                # stored beyond this frame: attr/subscript target, or a
                # module-global rebound under a `global` declaration
                stores = any(
                    isinstance(t, (ast.Attribute, ast.Subscript))
                    or (isinstance(t, ast.Name) and t.id in self.globals)
                    or (isinstance(t, ast.Tuple)
                        and any(isinstance(e, (ast.Attribute, ast.Subscript))
                                or (isinstance(e, ast.Name)
                                    and e.id in self.globals)
                                for e in t.elts))
                    for t in node.targets)
                if stores and _direct_owner(node.value, is_res_name):
                    return "transfer", "store"
                # rebinding the tracked name drops tracking
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == acq.name:
                        return "drop", "rebind"
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id in names:
                        return "drop", "del"
            elif isinstance(node, ast.Expr) and isinstance(node.value,
                                                           ast.Yield):
                if node.value.value is not None and \
                        any(is_res_name(n)
                            for n in ast.walk(node.value.value)):
                    return "transfer", "yield"
        return result

    def _call_takes_ownership(self, call: ast.Call, arg_pos: int) -> bool:
        """Does passing the resource as this call's ``arg_pos``-th
        argument transfer ownership?  Unresolved callees: yes (the
        ``Reader(open(...))`` wrapping idiom).  Project-resolved callees:
        only if their parameter summary releases or stores that
        parameter — a helper that merely reads leaves the caller owning
        the resource (the leak-through-helper case)."""
        callees = self.graph.resolve_call(self.fn, call.func)
        if not callees:
            return True
        if arg_pos >= len(call.args):
            return True  # keyword-passed: positional summary can't see it
        for callee in callees:
            if self.summaries.param_effects.get(callee.fq,
                                                {}).get(arg_pos):
                return True
        return False

    # -- verdicts -------------------------------------------------------------

    def check(self) -> List[Finding]:
        if not self.acqs:
            return self.findings
        cfg = dataflow.build_cfg(self.fn.node)
        init: _State = frozenset()
        states = dataflow.run_forward(cfg, init, self.transfer,
                                      lambda a, b: a | b)
        normal = states.get(cfg.exit, frozenset())
        raised = states.get(cfg.raise_exit, frozenset())
        for acq_id, acq in self.acqs.items():
            self._verdict(acq, self._statuses(normal, acq_id),
                          self._statuses(raised, acq_id))
        return self.findings

    def _verdict(self, acq: _Acq, normal: Set[str],
                 raised: Set[str]) -> None:
        live_on_raise = _LIVE in raised
        live_on_normal = _LIVE in normal
        done_somewhere = any(_is_done(s) for s in (normal | raised))
        if acq.self_attr is not None:
            # instance ownership: the dataflow only checks the __init__
            # window (a failed constructor orphans the resource); outside
            # __init__ the instance owns it from birth
            if self.is_init and live_on_raise:
                self.findings.append(Finding(
                    "escape-leak-on-raise", self.fn.module.relpath,
                    acq.lineno, self.fn.qualname,
                    f"self.{acq.self_attr} ({acq.kind}) leaks when a later "
                    "statement in __init__ raises: the caller never "
                    "receives the instance, so no close() can reach it — "
                    "release it in a try/except around the rest of "
                    "__init__ (and re-raise)"))
            return
        if live_on_raise and done_somewhere:
            self.findings.append(Finding(
                "escape-leak-on-raise", self.fn.module.relpath,
                acq.lineno, self.fn.qualname,
                f"{acq.name!r} ({acq.kind}) is released on the normal "
                "path but stays live on an exception path out of this "
                "function — move the release into a finally/with (or a "
                "catch-all handler that re-raises)"))
            return
        if (live_on_normal or live_on_raise) and not done_somewhere:
            # live on EVERY path: the per-file resource pass owns the
            # direct file/socket/tempdir cases; report the kinds (and the
            # helper-returned acquisitions) it cannot see
            if acq.via_helper or acq.kind in ("shm", "executor", "mmap"):
                self.findings.append(Finding(
                    "escape-leak-on-raise", self.fn.module.relpath,
                    acq.lineno, self.fn.qualname,
                    f"{acq.name!r} ({acq.kind}"
                    + (", acquired through a helper's return"
                       if acq.via_helper else "")
                    + ") is never released or handed off on any path "
                    "through this function"))


# -- class-ownership obligations ----------------------------------------------

def _class_obligations(graph: ProjectGraph, summaries: _Summaries,
                       per_fn_acqs: Dict[str, List[_Acq]]) -> List[Finding]:
    findings: List[Finding] = []
    for fn in graph.functions():
        if fn.cls is None:
            continue
        for acq in per_fn_acqs.get(fn.fq, []):
            if acq.self_attr is None:
                continue
            released = set()
            for method in fn.cls.methods.values():
                released |= summaries.attr_releases.get(method.fq, set())
            if acq.self_attr not in released:
                findings.append(Finding(
                    "escape-leak-on-raise", fn.module.relpath, acq.lineno,
                    f"{fn.cls.name}.{acq.self_attr}",
                    f"self.{acq.self_attr} owns a {acq.kind} but no method "
                    f"of {fn.cls.name} ever releases it — add (or route "
                    "through) a close()/shutdown() so the owner has a "
                    "destroy path"))
    return findings


# -- the pass -----------------------------------------------------------------

def run_project(graph: ProjectGraph) -> List[Finding]:
    summaries = _Summaries(graph)
    findings: List[Finding] = []
    per_fn_acqs: Dict[str, List[_Acq]] = {}
    for fn in graph.functions():
        acqs = _find_acquisitions(graph, fn, summaries)
        if not acqs:
            continue
        per_fn_acqs[fn.fq] = acqs
        findings += _FnChecker(graph, summaries, fn, acqs).check()
    findings += _class_obligations(graph, summaries, per_fn_acqs)
    return findings
