"""dmlclint — project-specific multi-pass AST static analyzer.

The reference dmlc-core ships lint as a first-class subsystem
(scripts/lint.py driving cpplint+pylint over every layer).  This package is
that subsystem rebuilt for what *this* codebase actually gets wrong:

- :mod:`.lockset`   — threading discipline: per-attribute lock inference for
  lock-owning classes, exception ferrying out of thread targets, and
  join-on-destroy for non-daemon threads.
- :mod:`.purity`    — JAX tracing hygiene: host syncs (``.item()``,
  ``float()`` on traced values), impure calls (``random``/``time``/file I/O)
  and host-side branching inside functions reachable from ``jit`` /
  ``pjit`` / ``pallas_call`` / ``shard_map`` sites.
- :mod:`.resources` — unclosed file/socket/tempfile handles in the io layer,
  temp dirs without a ``finally`` cleanup, and the no-``print`` style rule.
- :mod:`.graph`     — the whole-repo module/call-graph core the project
  passes share: import resolution, symbol tables, cross-module call edges,
  partial/alias/annotation following.
- :mod:`.deadlock`  — interprocedural lock-order cycles and unbounded
  blocking calls made while holding a lock, over the project graph.
- :mod:`.contracts` — cross-artifact drift: every ``DMLC_*`` knob,
  ``dmlc_*`` metric, span name and fault site in code diffed against the
  docs catalog tables (knob/span catalogs are generated via
  ``--emit-knob-catalog`` / ``--emit-span-catalog``; the rule catalog
  via ``--emit-rule-catalog``).
- :mod:`.dataflow`  — the statement-level CFG (with exception edges) +
  forward may-analysis engine under the interprocedural passes.
- :mod:`.escape`    — exception-path resource escape: acquired shm
  segments / sockets / executors / mmaps / fds / temp dirs tracked along
  every path (including raise edges and failed ``__init__``s) with
  ownership-transfer modeling through the call graph.
- :mod:`.jaxbound`  — host↔device boundary discipline: transfers outside
  the ``_accounted_place`` wrapper, float casts re-inflating the narrow
  wire, and ``jax.jit`` wrappers rebuilt per call.
- :mod:`.baseline`  — the ratchet: findings are keyed
  ``<file>:<rule>:<symbol>`` against a committed ``analysis_baseline.json``;
  new findings fail, baselined ones are burn-down work.

Run with ``python -m dmlc_core_tpu.analysis``; see docs/analysis.md.
Stdlib-only by design so the CI gate needs no jax/numpy install.
"""

from dmlc_core_tpu.analysis.driver import (
    ALL_RULES, Finding, analyze_path, analyze_source, main)

# __all__ rather than a noqa comment: pyflakes (which gates CI via
# scripts/lint.py) honors __all__ but not flake8-style noqa
__all__ = ["ALL_RULES", "Finding", "analyze_path", "analyze_source", "main"]
