"""Pass 4 — protocol: wire-data validation discipline in the control plane.

``assert-in-protocol``
    A bare ``assert`` inside a function that reads from a peer or stream,
    in the network-facing layers (``dmlc_core_tpu/tracker/`` and
    ``dmlc_core_tpu/io/``).  Asserting on peer-supplied data is wrong
    twice: the check vanishes under ``python -O`` (the malformed frame
    then flows downstream unvalidated), and when it does fire it raises
    ``AssertionError`` through whatever daemon thread is serving the peer
    — crashing the service a hardened path would have kept alive by
    rejecting just that peer.  Validate with an explicit raise
    (:class:`dmlc_core_tpu.tracker.rendezvous.ProtocolError` in the
    tracker) or reject-log-and-continue instead.

    The pass is scoped to functions that visibly ingest external bytes (a
    call to one of :data:`WIRE_INGEST_CALLS` anywhere in the function):
    internal invariants asserted in pure topology/bookkeeping code are
    not protocol validation and stay allowed.
"""

from __future__ import annotations

import ast
from typing import List

from dmlc_core_tpu.analysis.driver import FileContext, Finding

__all__ = ["run", "PROTOCOL_PREFIXES", "WIRE_INGEST_CALLS"]

# the network-facing layers this discipline applies to (serve/ handles
# arbitrary HTTP clients: same hostile-peer posture as the tracker wire)
PROTOCOL_PREFIXES = ("dmlc_core_tpu/tracker/", "dmlc_core_tpu/io/",
                     "dmlc_core_tpu/serve/")

# method names whose presence marks a function as ingesting external bytes
WIRE_INGEST_CALLS = {
    "recv", "recvall", "recvint", "recvstr", "recvfrom", "recv_into",
    "accept", "read", "read_exact", "readline", "readinto", "getresponse",
}


def run(ctx: FileContext) -> List[Finding]:
    if not ctx.relpath.startswith(PROTOCOL_PREFIXES):
        return []
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assert):
            continue
        func = ctx.enclosing(node, ast.FunctionDef, ast.AsyncFunctionDef)
        if func is None or not _ingests_wire_data(func):
            continue
        findings.append(ctx.finding(
            "assert-in-protocol", node,
            "bare `assert` in a function that reads peer/stream data — "
            "vanishes under `python -O` and crashes the serving thread on "
            "a malformed peer; raise ProtocolError (or reject-log-continue)"))
    return findings


def _ingests_wire_data(func: ast.AST) -> bool:
    for node in ast.walk(func):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in WIRE_INGEST_CALLS):
            return True
    return False
