"""Pass 7 — contracts: code vs. docs cross-artifact drift checking.

Four name families form this project's operational contract surface, and
every one of them has a hand-maintained catalog that nothing verified
until now:

- ``DMLC_*`` **env knobs** read via ``os.environ``/``os.getenv``
  (documented in the knob tables of docs/robustness.md, observability.md,
  performance.md, serving.md and the generated knob catalog);
- ``dmlc_*`` **metric names** registered through the telemetry helpers
  (documented in the metric catalog tables of docs/observability.md and
  robustness.md);
- telemetry **span names** (documented in the span catalog table of
  docs/observability.md — generated, plus hand-kept wildcard rows for
  f-string names like ``collective.<op>``);
- fault **site names** (the ``fault.SITES`` registry — what
  ``python -m dmlc_core_tpu.fault list-sites`` prints — vs. the site
  table in docs/robustness.md, vs. the ``fault.inject(...)`` call sites).

The pass extracts each family from the AST (exact string-literal uses
only; f-strings can't be checked statically and are covered by wildcard
doc rows), parses every markdown table in ``docs/``, and diffs:

===============================  =============================================
rule                              meaning
===============================  =============================================
``contract-undocumented-knob``    env knob read in code, in no docs table
``contract-undocumented-metric``  metric name in code, in no docs table
``contract-undocumented-span``    span name in code, in no span-catalog table
``contract-undocumented-site``    fault site used but not registered in
                                  ``fault.SITES``, or registered but missing
                                  from the docs site table
``contract-stale-doc-entry``      a docs catalog row (first cell of a table)
                                  naming a knob/metric/span/site the code no
                                  longer has
===============================  =============================================

Doc-side convention: a **table row mention** (any cell) documents a name;
the **first cell** of a row creates the stale-check obligation.  Tables
are typed by their header: a table whose first header cell is ``site``
holds fault sites, ``span`` holds span names; knob/metric tokens are
recognized by shape anywhere.  Rows whose name contains ``<`` or ``*``
are wildcards: they satisfy prefix matches and are exempt from stale
checking (they exist precisely for dynamic names).

``--emit-knob-catalog`` / ``--emit-span-catalog`` on the analysis CLI
print the generated markdown tables this pass checks against, so the
committed catalogs are regenerated from code truth, never hand-drifted.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Mapping, Optional, Set, Tuple

from dmlc_core_tpu.analysis.driver import Finding, dotted_name
from dmlc_core_tpu.analysis.graph import ProjectGraph

__all__ = ["run_project", "load_docs", "render_knob_catalog",
           "render_span_catalog", "DOC_FILES"]

# the documentation surface the contract is checked against
DOC_FILES = ("docs/robustness.md", "docs/observability.md",
             "docs/performance.md", "docs/serving.md", "docs/analysis.md",
             "docs/guide.md", "docs/design.md", "docs/index.md",
             "docs/parameter.md")

KNOB_RE = re.compile(r"^DMLC_[A-Z0-9]+(?:_[A-Z0-9]+)*$")
METRIC_RE = re.compile(r"^dmlc_[a-z0-9_]+$")
# dotted names: fault sites in code (`tracker.framed.recv`)
SPAN_RE = re.compile(r"^[a-z0-9_]+(?:\.[a-z0-9_<>]+)+$")
# doc-side span/site rows: the dot is NOT required — a span may be named
# `startup`; anything name-shaped in a span/site-typed table documents it
# (path-like tokens with `/` stay excluded)
NAME_RE = re.compile(r"^[a-z0-9_]+(?:\.[a-z0-9_<>*]+)*$")

# telemetry call surfaces, by the callee's final attribute name
_METRIC_CALLS = {"count", "gauge_set", "gauge_add", "observe",
                 "counter", "gauge", "histogram"}
_SPAN_CALLS = {"span", "record_span", "record_complete", "record_instant",
               "event"}
_ENV_READ_CALLS = {"get", "getenv", "get_env", "setdefault", "pop"}
_FAULT_CALLS = {"inject", "truncate", "http_response"}

# names that look like metrics but are native ABI symbols, not series
_NOT_METRICS = {"dmlc_core_tpu", "dmlc_tpu_abi_version",
                "dmlc_tpu_parse_libsvm", "dmlc_tpu_parse_libfm",
                "dmlc_tpu_span_open", "dmlc_tpu_span_open2"}


@dataclasses.dataclass(frozen=True)
class _Occurrence:
    name: str
    relpath: str
    lineno: int


class CodeInventory:
    """Every contract-relevant name the code uses, with one witness site."""

    def __init__(self) -> None:
        self.knobs: Dict[str, List[_Occurrence]] = {}
        self.metrics: Dict[str, List[_Occurrence]] = {}
        self.spans: Dict[str, List[_Occurrence]] = {}
        self.sites_used: Dict[str, List[_Occurrence]] = {}
        # fault.SITES registry: site -> declaration occurrence
        self.sites_registered: Dict[str, _Occurrence] = {}

    @staticmethod
    def _add(store: Dict[str, List[_Occurrence]], occ: _Occurrence) -> None:
        store.setdefault(occ.name, []).append(occ)


def _is_environ_expr(expr: ast.AST) -> bool:
    name = dotted_name(expr) or ""
    return name in ("os.environ", "environ") or name.endswith(".environ")


def _const_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _module_str_constants(project: ProjectGraph) -> Dict[str,
                                                         Dict[str, str]]:
    """modname -> {NAME: "literal"} for module-level string assignments —
    the ``ENV_PROC = "DMLC_PARSE_PROC"`` idiom; reads through such
    constants are still static and must count as contract uses."""
    out: Dict[str, Dict[str, str]] = {}
    for modname, mod in project.modules.items():
        consts: Dict[str, str] = {}
        for stmt in mod.ctx.tree.body:
            value: Optional[ast.AST] = None
            targets: List[ast.AST] = []
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            text = _const_str(value)
            if text is None:
                continue
            for target in targets:
                if isinstance(target, ast.Name):
                    consts[target.id] = text
        out[modname] = consts
    return out


def extract_code(project: ProjectGraph) -> CodeInventory:
    inv = CodeInventory()
    constants = _module_str_constants(project)

    def resolve_str(mod, node: Optional[ast.AST]) -> Optional[str]:
        """A string argument: literal, module constant, or a constant
        imported from a sibling module (one hop)."""
        text = _const_str(node)
        if text is not None:
            return text
        if isinstance(node, ast.Name):
            local = constants.get(mod.modname, {})
            if node.id in local:
                return local[node.id]
            if node.id in mod.import_syms:
                tm, sym = mod.import_syms[node.id]
                return constants.get(tm, {}).get(sym)
        if isinstance(node, ast.Attribute):
            name = dotted_name(node)
            if name and "." in name:
                root, attr = name.split(".", 1)
                if "." not in attr and root in mod.import_mods:
                    return constants.get(mod.import_mods[root],
                                         {}).get(attr)
        return None

    for mod in project.modules.values():
        relpath = mod.relpath
        for node in ast.walk(mod.ctx.tree):
            if isinstance(node, ast.Subscript):
                # os.environ["DMLC_X"] (read or write)
                if _is_environ_expr(node.value):
                    key = resolve_str(mod, node.slice)
                    if key and KNOB_RE.match(key):
                        inv._add(inv.knobs,
                                 _Occurrence(key, relpath, node.lineno))
                continue
            if isinstance(node, ast.Compare):
                # "DMLC_X" in os.environ
                if (len(node.ops) == 1 and isinstance(node.ops[0], (ast.In,
                                                                    ast.NotIn))
                        and any(_is_environ_expr(c)
                                for c in node.comparators)):
                    key = resolve_str(mod, node.left)
                    if key and KNOB_RE.match(key):
                        inv._add(inv.knobs,
                                 _Occurrence(key, relpath, node.lineno))
                continue
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            last = (func.attr if isinstance(func, ast.Attribute)
                    else func.id if isinstance(func, ast.Name) else None)
            if last is None:
                continue
            arg0 = resolve_str(mod, node.args[0]) if node.args else None
            # env reads: os.environ.get("X") / os.getenv("X") /
            # param.get_env("X", ...) / environ.pop — the DMLC_* key shape
            # is the filter, not the receiver (env mappings travel under
            # local names: `(environ or os.environ).get(ENV_NPROC)`)
            if last in _ENV_READ_CALLS and arg0 and KNOB_RE.match(arg0):
                inv._add(inv.knobs, _Occurrence(arg0, relpath, node.lineno))
                continue
            if last in _METRIC_CALLS and arg0 and METRIC_RE.match(arg0) \
                    and arg0 not in _NOT_METRICS:
                inv._add(inv.metrics, _Occurrence(arg0, relpath, node.lineno))
                continue
            if last in _SPAN_CALLS and arg0:
                inv._add(inv.spans, _Occurrence(arg0, relpath, node.lineno))
                continue
            if last in _FAULT_CALLS and arg0:
                # only calls through the fault API surface (fault.inject /
                # plan-internal helpers share the names but not first-arg
                # site strings outside fault code)
                recv = (dotted_name(func.value)
                        if isinstance(func, ast.Attribute) else None)
                if recv and recv.split(".")[-1] == "fault" or \
                        relpath.startswith("dmlc_core_tpu/fault/"):
                    if SPAN_RE.match(arg0):
                        inv._add(inv.sites_used,
                                 _Occurrence(arg0, relpath, node.lineno))
        # the SITES registry itself (static parse; no runtime import)
        if relpath == "dmlc_core_tpu/fault/__init__.py":
            for stmt in mod.ctx.tree.body:
                targets = []
                if isinstance(stmt, ast.Assign):
                    targets = stmt.targets
                    value = stmt.value
                elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                    targets = [stmt.target]
                    value = stmt.value
                else:
                    continue
                if not any(isinstance(t, ast.Name) and t.id == "SITES"
                           for t in targets):
                    continue
                if isinstance(value, ast.Dict):
                    for key in value.keys:
                        site = _const_str(key)
                        if site:
                            inv.sites_registered[site] = _Occurrence(
                                site, relpath, key.lineno)
    return inv


# -- docs side ----------------------------------------------------------------

_BACKTICK_RE = re.compile(r"`([^`]+)`")


@dataclasses.dataclass(frozen=True)
class _DocEntry:
    name: str
    relpath: str
    lineno: int
    kind: str  # knob | metric | span | site

    @property
    def wildcard(self) -> bool:
        return "<" in self.name or "*" in self.name

    def prefix(self) -> str:
        cut = len(self.name)
        for ch in "<*":
            pos = self.name.find(ch)
            if pos != -1:
                cut = min(cut, pos)
        return self.name[:cut]


class DocInventory:
    def __init__(self) -> None:
        # names mentioned in ANY table cell (documentation credit)
        self.mentioned: Dict[str, Set[str]] = {
            "knob": set(), "metric": set(), "span": set(), "site": set()}
        self.wildcards: Dict[str, List[_DocEntry]] = {
            "span": [], "site": [], "metric": [], "knob": []}
        # first-cell entries (stale-check obligations)
        self.obligations: List[_DocEntry] = []

    def documents(self, kind: str, name: str) -> bool:
        if name in self.mentioned[kind]:
            return True
        return any(name.startswith(w.prefix())
                   for w in self.wildcards[kind] if w.prefix())


def _iter_tables(text: str):
    """Yield (header_cells, [(lineno, cells)]) for every markdown table."""
    lines = text.splitlines()
    i = 0
    while i < len(lines):
        if not lines[i].lstrip().startswith("|"):
            i += 1
            continue
        block: List[Tuple[int, str]] = []
        while i < len(lines) and lines[i].lstrip().startswith("|"):
            block.append((i + 1, lines[i]))
            i += 1
        if len(block) < 2:
            continue
        rows = []
        header: Optional[List[str]] = None
        for lineno, line in block:
            cells = [c.strip() for c in line.strip().strip("|").split("|")]
            if all(re.fullmatch(r":?-{2,}:?", c or "---") for c in cells):
                continue  # separator row
            if header is None:
                header = cells
            else:
                rows.append((lineno, cells))
        if header is not None:
            yield header, rows


def _strip_markup(token: str) -> str:
    # `DMLC_X` / `DMLC_X=1` / `DMLC_X=<dir>` / `dmlc_y_total{site,kind}` /
    # `knob (seconds)` usage forms all document the bare name
    for sep in ("{", "=", "(", " ", "["):
        token = token.split(sep)[0]
    return token.strip()


def extract_docs(docs: Mapping[str, str]) -> DocInventory:
    inv = DocInventory()
    for relpath, text in docs.items():
        for header, rows in _iter_tables(text):
            first = _BACKTICK_RE.sub(r"\1", header[0]).strip().lower() \
                if header else ""
            table_kind = {"site": "site", "span": "span"}.get(first)
            for lineno, cells in rows:
                for ci, cell in enumerate(cells):
                    for raw in _BACKTICK_RE.findall(cell):
                        token = _strip_markup(raw)
                        kinds = []
                        if KNOB_RE.match(token):
                            kinds.append("knob")
                        elif METRIC_RE.match(token) \
                                and token not in _NOT_METRICS:
                            kinds.append("metric")
                        elif table_kind and NAME_RE.match(token):
                            kinds.append(table_kind)
                        for kind in kinds:
                            entry = _DocEntry(token, relpath, lineno, kind)
                            if entry.wildcard:
                                inv.wildcards[kind].append(entry)
                            else:
                                inv.mentioned[kind].add(token)
                            if ci == 0 and not entry.wildcard:
                                inv.obligations.append(entry)
    return inv


def load_docs(root: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for rel in DOC_FILES:
        path = os.path.join(root, rel)
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                out[rel] = f.read()
    return out


# -- the diff -----------------------------------------------------------------

def run_project(project: ProjectGraph,
                docs: Mapping[str, str]) -> List[Finding]:
    code = extract_code(project)
    doc = extract_docs(docs)
    findings: List[Finding] = []

    def first(occs: List[_Occurrence]) -> _Occurrence:
        return min(occs, key=lambda o: (o.relpath, o.lineno))

    for name in sorted(code.knobs):
        if not doc.documents("knob", name):
            occ = first(code.knobs[name])
            findings.append(Finding(
                "contract-undocumented-knob", occ.relpath, occ.lineno,
                name,
                f"env knob {name} is read here but appears in no docs "
                "table — add it to the knob catalog (regenerate with "
                "--emit-knob-catalog) or delete the knob"))
    for name in sorted(code.metrics):
        if not doc.documents("metric", name):
            occ = first(code.metrics[name])
            findings.append(Finding(
                "contract-undocumented-metric", occ.relpath, occ.lineno,
                name,
                f"metric {name} is recorded here but appears in no docs "
                "table — add a row to the metric catalog "
                "(docs/observability.md) or drop the series"))
    for name in sorted(code.spans):
        if not doc.documents("span", name):
            occ = first(code.spans[name])
            findings.append(Finding(
                "contract-undocumented-span", occ.relpath, occ.lineno,
                name,
                f"span/event name {name} is recorded here but appears in "
                "no span-catalog table (docs/observability.md; regenerate "
                "with --emit-span-catalog)"))
    for name in sorted(code.sites_used):
        if name not in code.sites_registered:
            occ = first(code.sites_used[name])
            findings.append(Finding(
                "contract-undocumented-site", occ.relpath, occ.lineno,
                name,
                f"fault site {name} is injected here but is not registered "
                "in fault.SITES — `fault list-sites` and plan validation "
                "will not know it exists"))
    for name, occ in sorted(code.sites_registered.items()):
        if not doc.documents("site", name):
            findings.append(Finding(
                "contract-undocumented-site", occ.relpath, occ.lineno,
                name,
                f"fault site {name} is registered in fault.SITES but "
                "missing from the site table in docs/robustness.md"))

    # stale direction: docs first-cell entries with no code referent
    present = {
        "knob": set(code.knobs),
        "metric": set(code.metrics),
        "span": set(code.spans),
        "site": set(code.sites_registered) | set(code.sites_used),
    }
    seen_obligations: Set[Tuple[str, str]] = set()
    for entry in doc.obligations:
        key = (entry.kind, entry.name)
        if key in seen_obligations:
            continue
        seen_obligations.add(key)
        if entry.name not in present[entry.kind]:
            findings.append(Finding(
                "contract-stale-doc-entry", entry.relpath, entry.lineno,
                f"{entry.kind}:{entry.name}",
                f"docs table names {entry.kind} `{entry.name}` but the "
                "code no longer has it — prune the row or restore the "
                f"{entry.kind}"))
    return findings


# -- generated catalogs -------------------------------------------------------

def _where(occs: Iterable[_Occurrence], limit: int = 3) -> str:
    paths = sorted({o.relpath for o in occs})
    shown = ", ".join(f"`{p}`" for p in paths[:limit])
    if len(paths) > limit:
        shown += f" (+{len(paths) - limit} more)"
    return shown


def render_knob_catalog(project: ProjectGraph) -> str:
    """The generated knob catalog table (committed into
    docs/robustness.md; regenerating and diffing is the freshness check)."""
    inv = extract_code(project)
    lines = ["| knob | read at |", "| --- | --- |"]
    for name in sorted(inv.knobs):
        lines.append(f"| `{name}` | {_where(inv.knobs[name])} |")
    return "\n".join(lines)


def render_span_catalog(project: ProjectGraph) -> str:
    """The generated span catalog table (committed into
    docs/observability.md).  F-string span names cannot be extracted —
    cover those with hand-kept wildcard rows (`collective.<op>`)."""
    inv = extract_code(project)
    lines = ["| span | recorded at |", "| --- | --- |"]
    for name in sorted(inv.spans):
        lines.append(f"| `{name}` | {_where(inv.spans[name])} |")
    return "\n".join(lines)
