"""Pass 10 — races: Eraser-style data-race detection over thread roots.

The per-file lockset pass answers "is this class consistent with its own
lock?" — it is blind to classes that own *no* lock (the CheckpointWatcher
odometers), to attributes of one class written by another (the registry
stamping ``slot.version``), and to lock context inherited through the
call graph (a ``*_locked`` helper whose callers hold the Condition).
This pass runs the classic Eraser lockset algorithm over the
:class:`~dmlc_core_tpu.analysis.graph.ProjectGraph`:

1. **Thread-entry roots**: every ``threading.Thread(target=f)`` /
   ``executor.submit(f)`` whose target resolves statically, plus the
   ``do_*``/``handle*`` methods of HTTP/socketserver handler classes
   (each request runs on a server thread).
2. **Reachability**: functions reachable from a root run on that root's
   thread; public functions/methods (and everything they call) can run
   on the caller's ("main") thread.  One function can be both — a public
   ``poll_once`` that the watcher loop also drives IS the race.
3. **Locksets**: every attribute access site records the locks held
   lexically (``with`` statements, the deadlock pass's lock identity)
   PLUS the locks guaranteed at function entry — the intersection over
   all known call sites, iterated to fixpoint, which is how a private
   helper inherits the lock every caller wraps around it.
4. **Sharing + rules**: an attribute accessed from two distinct thread
   contexts (two roots, or a root and the main side) is *shared*.  If
   every write site's lockset is empty -> ``race-unlocked-shared-write``;
   if the sites hold locks but their intersection is empty ->
   ``race-inconsistent-lockset``.  Findings anchor at the offending
   WRITE site (thread-side preferred), never at the thread entry.

Eraser-style exemptions (the near-zero-noise contract):

- **init-before-start publication**: writes in ``__init__``/``__new__``,
  and writes lexically before the ``.start()`` call in the function that
  spawns the thread — the classic publish-then-start idiom.
- **read-only-after-publish**: an attribute never written outside
  construction has no write sites left and cannot fire.
- **queue/Future/Event-mediated handoff**: attributes whose inferred
  type is a synchronization object (Queue, Event, Lock, Thread, Future,
  executors) are lifecycle plumbing, not shared data — and values that
  travel *through* a queue arrive untyped, so the handoff pattern is
  structurally invisible to the sharing test.
- **join-mediated reads**: a read lexically after a ``.join(...)`` call
  in the same function observes a dead thread (the RabitTracker
  ``join()`` summary) and does not establish sharing.
- **per-request handler classes**: HTTP handler instances live for one
  request on one thread; their own attributes are thread-local.

Soundness caveats (docs/analysis.md): nested ``def`` thread targets are
invisible (launcher ferrying closures), module-level globals are out of
scope, attribute writes through untyped locals cannot be attributed, and
lock identity is per class attribute, not per instance — all shared with
the deadlock pass, all documented, all why the baseline/suppression
machinery backs this pass like every other.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from dmlc_core_tpu.analysis.deadlock import (LockDecl, _collect_locks,
                                             _lock_of_expr)
from dmlc_core_tpu.analysis.driver import Finding, dotted_name, keyword_arg
from dmlc_core_tpu.analysis.graph import (ClassInfo, FunctionInfo,
                                          ProjectGraph, _annotation_ref,
                                          walk_in_scope)

__all__ = ["run_project"]

_CONSTRUCTORS = {"__init__", "__new__"}

# attribute value types that ARE synchronization/handoff machinery:
# reassigning them is lifecycle management, not a shared-data write
_SYNC_TYPES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
               "Barrier", "Event", "Thread", "Timer", "Queue", "LifoQueue",
               "PriorityQueue", "SimpleQueue", "Future",
               "ThreadPoolExecutor", "ProcessPoolExecutor", "local"}

# stdlib bases whose subclasses run one instance per request/connection
# on a server thread: their methods are thread roots, their own
# attributes are per-request (thread-local)
_HANDLER_BASES = {"BaseHTTPRequestHandler", "SimpleHTTPRequestHandler",
                  "StreamRequestHandler", "BaseRequestHandler",
                  "ThreadingHTTPServer", "HTTPServer", "TCPServer",
                  "ThreadingMixIn"}

_HANDLER_METHOD_PREFIXES = ("do_", "handle")

# method calls that mutate their receiver container in place — a write
# to the attribute they are called on (Eraser tracks the memory, not
# just the binding)
_MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
             "add", "discard", "remove", "pop", "popleft", "popitem",
             "update", "setdefault", "clear", "sort", "reverse"}

# dunders that are public API despite the underscores (context managers,
# iteration, GC hooks — all driven by outside code); __init__/__new__
# stay listed: ctor self-writes are exempt anyway, but a ctor that pokes
# ANOTHER object's attributes runs on the constructing thread
_PUBLIC_DUNDERS = {"__call__", "__iter__", "__next__", "__enter__",
                   "__exit__", "__del__", "__len__", "__getitem__",
                   "__setitem__", "__contains__", "__bool__", "__repr__",
                   "__str__", "__eq__", "__hash__"}

_JOIN_NON_THREAD_ROOTS = {"os", "posixpath", "ntpath", "str"}


@dataclasses.dataclass(frozen=True)
class _Access:
    cls_key: str            # "modname:ClassName"
    attr: str
    fn_fq: str
    relpath: str
    lineno: int
    held: FrozenSet[str]    # lexical locks at the site
    is_write: bool
    self_base: bool         # via self./cls. (vs a typed local/param)


@dataclasses.dataclass
class _FnScan:
    fn: FunctionInfo
    accesses: List[_Access]
    calls: List[Tuple[str, FrozenSet[str]]]   # (callee fq, held at site)
    spawn_targets: List[str]                  # root fqs spawned here
    constructs: List[str]                     # cls_keys constructed here
    start_boundary: Optional[int]             # first thread .start() line
    join_line: Optional[int]                  # first thread .join() line


def _cls_key(cls: ClassInfo) -> str:
    return f"{cls.module.modname}:{cls.name}"


def _is_handler_class(cls: ClassInfo, graph: ProjectGraph,
                      hops: int = 0) -> bool:
    if hops > 4:
        return False
    for base in cls.bases:
        if base.rsplit(".", 1)[-1] in _HANDLER_BASES:
            return True
        resolved = graph.resolve_class(cls.module, base)
        if resolved is not None and resolved is not cls \
                and _is_handler_class(resolved, graph, hops + 1):
            return True
    return False


def _sync_attrs(cls: ClassInfo) -> Set[str]:
    """Attributes of ``cls`` whose value type is synchronization/handoff
    machinery (from ctor-call assignments and annotations)."""
    out: Set[str] = set()
    for attr, ref in cls.attr_types.items():
        if ref.rsplit(".", 1)[-1] in _SYNC_TYPES:
            out.add(attr)
    for node in ast.walk(cls.node):
        target = value = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign):
            target, value = node.target, node.value
            # Optional[threading.Thread] and friends: any sync type
            # named anywhere in the annotation marks the attribute
            for sub in ast.walk(node.annotation):
                name = None
                if isinstance(sub, ast.Name):
                    name = sub.id
                elif isinstance(sub, ast.Attribute):
                    name = sub.attr
                elif isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, str):
                    name = sub.value.rsplit(".", 1)[-1].rsplit("]", 1)[0]
                if name in _SYNC_TYPES and _self_attr(target):
                    out.add(target.attr)
        if target is not None and _self_attr(target) \
                and isinstance(value, ast.Call):
            name = dotted_name(value.func) or ""
            if name.rsplit(".", 1)[-1] in _SYNC_TYPES:
                out.add(target.attr)
    return out


def _self_attr(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id in ("self", "cls"))


def _is_property(fn_node: ast.AST) -> bool:
    for dec in getattr(fn_node, "decorator_list", ()):
        name = dotted_name(dec) or ""
        if name.rsplit(".", 1)[-1] in ("property", "cached_property"):
            return True
    return False


def _local_types(graph: ProjectGraph,
                 fn: FunctionInfo) -> Dict[str, ClassInfo]:
    """name -> project ClassInfo for typed locals visible inside ``fn``:
    annotated parameters, ``v = Cls(...)`` constructions, ``v = self.attr``
    through inferred attribute types / property return annotations, and
    ``v = obj.meth(...)`` through the callee's return annotation."""
    mod = fn.module
    out: Dict[str, ClassInfo] = {}
    for pname, ref in fn.param_types.items():
        cls = graph.resolve_class(mod, ref)
        if cls is not None:
            out[pname] = cls
    for node in walk_in_scope(fn.node):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        value = node.value
        cls: Optional[ClassInfo] = None
        if isinstance(value, ast.Call):
            ref = dotted_name(value.func)
            cls = graph.resolve_class(mod, ref)
            if cls is None:
                for callee in graph.resolve_call(fn, value.func):
                    ret = _annotation_ref(getattr(callee.node, "returns",
                                                  None))
                    cls = graph.resolve_class(callee.module, ret)
                    if cls is not None:
                        break
        elif _self_attr(value) and fn.cls is not None:
            ref = fn.cls.attr_types.get(value.attr)
            if ref is not None:
                cls = graph.resolve_class(mod, ref)
            else:
                prop = fn.cls.methods.get(value.attr)
                if prop is not None and _is_property(prop.node):
                    ret = _annotation_ref(getattr(prop.node, "returns",
                                                  None))
                    cls = graph.resolve_class(mod, ret)
        if cls is not None:
            out.setdefault(name, cls)
    return out


# -- per-function scan --------------------------------------------------------

def _scan_function(graph: ProjectGraph, fn: FunctionInfo,
                   decls: Dict[str, LockDecl]) -> _FnScan:
    locals_ = _local_types(graph, fn)
    accesses: List[_Access] = []
    calls: List[Tuple[str, FrozenSet[str]]] = []
    spawn_targets: List[str] = []
    constructs: List[str] = []
    state = {"boundary": None, "join": None}
    thread_locals: Set[str] = set()
    fresh_locals: Set[str] = set()
    relpath = fn.module.relpath

    def base_cls(node: ast.AST) -> Optional[Tuple[ClassInfo, bool]]:
        """(owning class, via-self) for an attribute base expression."""
        if isinstance(node, ast.Name):
            if node.id in ("self", "cls"):
                return (fn.cls, True) if fn.cls is not None else None
            if node.id in fresh_locals:
                # constructed in this very function: nobody else can see
                # it yet (init-before-publish, the URI.copy shape)
                return None
            cls = locals_.get(node.id)
            return (cls, False) if cls is not None else None
        return None

    def record(attr_node: ast.Attribute, is_write: bool,
               held: FrozenSet[str]) -> None:
        owner = base_cls(attr_node.value)
        if owner is None:
            return
        cls, via_self = owner
        accesses.append(_Access(_cls_key(cls), attr_node.attr, fn.fq,
                                relpath, attr_node.lineno,
                                held, is_write, via_self))

    def record_write_target(target: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(target, ast.Attribute):
            record(target, True, held)
        elif isinstance(target, ast.Subscript) \
                and isinstance(target.value, ast.Attribute):
            # self.X[k] = v mutates the container self.X holds
            record(target.value, True, held)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                record_write_target(elt, held)
        elif isinstance(target, ast.Starred):
            record_write_target(target.value, held)

    def threadish_receiver(recv: ast.AST) -> bool:
        if isinstance(recv, ast.Name):
            return recv.id in thread_locals
        if _self_attr(recv) and fn.cls is not None:
            return recv.attr in _sync_attrs_cached(fn.cls)
        if isinstance(recv, ast.Call):
            name = dotted_name(recv.func) or ""
            return name.rsplit(".", 1)[-1] == "Thread"
        return False

    def on_call(call: ast.Call, held: FrozenSet[str]) -> None:
        name = dotted_name(call.func) or ""
        short = name.rsplit(".", 1)[-1]
        if name:
            made = graph.resolve_class(fn.module, name)
            if made is not None:
                constructs.append(_cls_key(made))
        if short == "Thread" and name in ("Thread", "threading.Thread"):
            target = keyword_arg(call, "target")
            for root in graph.resolve_call(fn, target):
                spawn_targets.append(root.fq)
        if isinstance(call.func, ast.Attribute):
            meth = call.func.attr
            if meth == "submit" and call.args:
                for root in graph.resolve_call(fn, call.args[0]):
                    spawn_targets.append(root.fq)
            elif meth == "start" and not call.args \
                    and threadish_receiver(call.func.value):
                if state["boundary"] is None \
                        or call.lineno < state["boundary"]:
                    state["boundary"] = call.lineno
            elif meth == "join" and len(call.args) <= 1 \
                    and not isinstance(call.func.value, ast.Constant):
                recv = dotted_name(call.func.value) or ""
                if recv.split(".")[0] not in _JOIN_NON_THREAD_ROOTS:
                    if state["join"] is None \
                            or call.lineno < state["join"]:
                        state["join"] = call.lineno
            elif meth in _MUTATORS \
                    and isinstance(call.func.value, ast.Attribute):
                record(call.func.value, True, held)
        for callee in graph.resolve_call(fn, call.func):
            calls.append((callee.fq, held))

    def visit(node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return  # nested scope: runs at its own call time
        if isinstance(node, (ast.With, ast.AsyncWith)):
            newly: List[str] = []
            for item in node.items:
                entered = held.union(newly)
                visit(item.context_expr, entered)
                lock = _lock_of_expr(item.context_expr, fn, decls)
                if lock is not None:
                    newly.append(lock)
            inner = held.union(newly)
            for stmt in node.body:
                visit(stmt, inner)
            return
        if isinstance(node, ast.Assign):
            for target in node.targets:
                record_write_target(target, held)
            if len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                vname = dotted_name(node.value.func) or ""
                if vname.rsplit(".", 1)[-1] == "Thread":
                    thread_locals.add(node.targets[0].id)
                if graph.resolve_class(fn.module, vname) is not None:
                    fresh_locals.add(node.targets[0].id)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            record_write_target(node.target, held)
        elif isinstance(node, ast.Call):
            on_call(node, held)
        elif isinstance(node, ast.Attribute) \
                and isinstance(node.ctx, ast.Load):
            record(node, False, held)
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in ast.iter_child_nodes(fn.node):
        visit(stmt, frozenset())
    return _FnScan(fn, accesses, calls, spawn_targets, constructs,
                   state["boundary"], state["join"])


_SYNC_CACHE: Dict[int, Set[str]] = {}


def _sync_attrs_cached(cls: ClassInfo) -> Set[str]:
    key = id(cls)
    if key not in _SYNC_CACHE:
        _SYNC_CACHE[key] = _sync_attrs(cls)
    return _SYNC_CACHE[key]


# -- reachability -------------------------------------------------------------

def _discover_roots(graph: ProjectGraph,
                    scans: Dict[str, _FnScan]
                    ) -> Tuple[Set[str], Set[str]]:
    """(root fqs, handler class keys)."""
    roots: Set[str] = set()
    handler_classes: Set[str] = set()
    for scan in scans.values():
        roots.update(scan.spawn_targets)
    for mod in graph.modules.values():
        for cls in mod.classes.values():
            if not _is_handler_class(cls, graph):
                continue
            handler_classes.add(_cls_key(cls))
            for name, meth in cls.methods.items():
                if name.startswith(_HANDLER_METHOD_PREFIXES):
                    roots.add(meth.fq)
    return roots, handler_classes


def _propagate(seeds: Dict[str, FrozenSet[str]],
               scans: Dict[str, _FnScan]) -> Dict[str, FrozenSet[str]]:
    """Monotone label propagation over call edges, to fixpoint (the
    deadlock pass's iterate-until-stable discipline: memoized DFS is
    order-dependent under mutual recursion)."""
    labels: Dict[str, FrozenSet[str]] = dict(seeds)
    changed = True
    while changed:
        changed = False
        for fq, scan in scans.items():
            mine = labels.get(fq)
            if not mine:
                continue
            for callee_fq, _ in scan.calls:
                if callee_fq not in scans:
                    continue
                cur = labels.get(callee_fq, frozenset())
                new = cur | mine
                if new != cur:
                    labels[callee_fq] = new
                    changed = True
    return labels


def _is_public_entry(fn: FunctionInfo, handler_classes: Set[str]) -> bool:
    """Callable from user ("main-thread") code: public names and public
    dunders — excluding per-request handler methods, which only ever run
    on server threads."""
    if fn.cls is not None and _cls_key(fn.cls) in handler_classes:
        return False
    name = fn.name
    if name in _PUBLIC_DUNDERS:
        return True
    if name in _CONSTRUCTORS:
        return True
    return not name.startswith("_")


def _entry_held(scans: Dict[str, _FnScan],
                seeds: Set[str]) -> Dict[str, FrozenSet[str]]:
    """Locks guaranteed held at each function's entry: the intersection
    over all known call sites of (caller entry ∪ lexical held at the
    site); public entries and thread roots start at the empty set.
    Iterated to fixpoint (values only shrink once set)."""
    entry: Dict[str, Optional[FrozenSet[str]]] = {fq: None for fq in scans}
    for fq in seeds:
        if fq in entry:
            entry[fq] = frozenset()
    changed = True
    while changed:
        changed = False
        for fq, scan in scans.items():
            base = entry[fq]
            if base is None:
                continue  # context unknown (unreached so far)
            for callee_fq, held in scan.calls:
                if callee_fq not in entry:
                    continue
                eff = held | base
                cur = entry[callee_fq]
                new = eff if cur is None else (cur & eff)
                if new != cur:
                    entry[callee_fq] = new
                    changed = True
    return {fq: (held or frozenset()) for fq, held in entry.items()}


# -- the pass -----------------------------------------------------------------

def _short_lock(lock_id: str) -> str:
    return ".".join(lock_id.rsplit(".", 2)[-2:])


def run_project(graph: ProjectGraph) -> List[Finding]:
    _SYNC_CACHE.clear()
    decls = _collect_locks(graph)
    scans: Dict[str, _FnScan] = {}
    fns: Dict[str, FunctionInfo] = {}
    for fn in graph.functions():
        fns[fn.fq] = fn
        scans[fn.fq] = _scan_function(graph, fn, decls)
    roots, handler_classes = _discover_roots(graph, scans)
    if not roots:
        return []

    thread_side = _propagate({fq: frozenset([fq]) for fq in roots
                              if fq in scans}, scans)
    main_seeds = {fq for fq, fn in fns.items()
                  if _is_public_entry(fn, handler_classes)
                  and fq not in roots}
    main_side = _propagate({fq: frozenset(["<main>"]) for fq in main_seeds},
                           scans)
    entry = _entry_held(scans, roots | main_seeds)

    classes: Dict[str, ClassInfo] = {}
    for mod in graph.modules.values():
        for cls in mod.classes.values():
            classes[_cls_key(cls)] = cls

    # thread-confined classes: every known construction site runs only
    # on worker threads (the tracker's WorkerEntry) — instances never
    # escape to the main side, so their attributes are not shared data.
    # No known site -> NOT confined (conservative).
    ctor_sites: Dict[str, List[str]] = {}
    for fq, scan in scans.items():
        for cls_key in scan.constructs:
            ctor_sites.setdefault(cls_key, []).append(fq)
    confined = {cls_key for cls_key, sites in ctor_sites.items()
                if sites and all(thread_side.get(site)
                                 and not main_side.get(site)
                                 for site in sites)}

    # group accesses per (class, attr), applying site-level exemptions
    grouped: Dict[Tuple[str, str], List[_Access]] = {}
    for fq in sorted(scans):
        scan = scans[fq]
        fn = scan.fn
        is_ctor = fn.name in _CONSTRUCTORS
        for acc in scan.accesses:
            cls = classes.get(acc.cls_key)
            if cls is None or acc.cls_key in handler_classes \
                    or acc.cls_key in confined:
                continue
            if acc.attr in _sync_attrs_cached(cls):
                continue  # queue/Future/Event/Thread handoff machinery
            if is_ctor:
                # init-before-start publication: a constructor wires up
                # the instance AND the collaborators handed to it (the
                # ModelSlot ctor stamping runtime.version) before any
                # thread can observe either
                continue
            if acc.is_write and scan.start_boundary is not None \
                    and acc.lineno < scan.start_boundary:
                continue  # published before the thread starts
            if not acc.is_write and scan.join_line is not None \
                    and acc.lineno > scan.join_line:
                continue  # join-mediated handoff: the thread is dead
            grouped.setdefault((acc.cls_key, acc.attr), []).append(acc)

    findings: List[Finding] = []
    for (cls_key, attr) in sorted(grouped):
        accs = grouped[(cls_key, attr)]
        writes = [a for a in accs if a.is_write]
        if not writes:
            continue  # read-only-after-publish

        def eff(a: _Access) -> FrozenSet[str]:
            return a.held | entry.get(a.fn_fq, frozenset())

        # sharing: two distinct thread contexts must touch the attribute
        root_union: Set[str] = set()
        threaded_any = main_any = both_sided = False
        for a in accs:
            tr = thread_side.get(a.fn_fq, frozenset())
            mn = bool(main_side.get(a.fn_fq))
            root_union |= tr
            threaded_any = threaded_any or bool(tr)
            main_any = main_any or mn
            both_sided = both_sided or (bool(tr) and mn)
        shared = threaded_any and (main_any or len(root_union) >= 2
                                   or both_sided)
        if not shared:
            continue

        locksets = [eff(a) for a in writes]
        common = frozenset.intersection(*locksets)
        if common:
            continue  # a consistent lockset protects every write
        cls_name = cls_key.split(":", 1)[1]
        symbol = f"{cls_name}.{attr}"
        writes.sort(key=lambda a: (not thread_side.get(a.fn_fq),
                                   a.relpath, a.lineno))
        anchor = next((a for a in writes if not eff(a)), writes[0])
        anchor_fn = fns[anchor.fn_fq]
        roots_here = sorted(thread_side.get(anchor.fn_fq, frozenset()))
        where = (f"thread root {roots_here[0].split(':', 1)[1]}"
                 if roots_here else "the calling thread")
        others = sorted({f"{a.relpath}:{a.lineno}" for a in accs
                         if (a.relpath, a.lineno)
                         != (anchor.relpath, anchor.lineno)})
        context = f"; also accessed at {', '.join(others[:3])}" \
            if others else ""
        if any(locksets):
            held_desc = ", ".join(
                sorted({_short_lock(lk) for ls in locksets for lk in ls})
                ) or "nothing"
            findings.append(Finding(
                "race-inconsistent-lockset", anchor.relpath, anchor.lineno,
                symbol,
                f"{symbol} is written under inconsistent locksets (no "
                f"common lock; sites variously hold {held_desc}): this "
                f"write in {anchor_fn.qualname} runs on {where} holding "
                f"{{{', '.join(sorted(_short_lock(lk) for lk in eff(anchor))) or ''}}}"
                f"{context} — every write must hold one common lock"))
        else:
            findings.append(Finding(
                "race-unlocked-shared-write", anchor.relpath, anchor.lineno,
                symbol,
                f"{symbol} is shared across threads but written with no "
                f"lock held: this write in {anchor_fn.qualname} runs on "
                f"{where}{context} — guard every access with one lock, or "
                f"publish before start / hand off via a queue"))
    return findings
