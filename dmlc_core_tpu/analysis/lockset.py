"""Pass 1 — lockset: hand-rolled ``threading`` discipline.

Three rules over every class/function in a library module:

``lockset-unsync-write``
    For a class that owns a ``threading.Lock``/``RLock``/``Condition``/
    ``Semaphore`` attribute, every write to ``self.X`` is classified as
    under-lock (lexically inside ``with self._lock:``) or bare.  An
    attribute written both ways is a data race by the class's own
    convention: the lock announces that concurrent access is expected, so a
    bare write elsewhere bypasses it.  ``__init__``/``__new__`` writes are
    construction (no concurrency yet) and don't count as bare.

``lockset-thread-leak``
    A ``threading.Thread`` target whose body cannot ferry exceptions back to
    a consumer: no ``try`` anywhere in a locally-defined target, a lambda
    target (can't contain ``try``), or a library callable
    (``subprocess.check_call``) used directly as target.  Exceptions raised
    there die in ``Thread.run`` — the spawner's ``join()`` returns success.

``lockset-no-join``
    A non-daemon thread whose owning scope (the class, when stored on
    ``self``; the enclosing function otherwise) never calls ``.join()``:
    interpreter shutdown blocks on it and no destroy path exists.

Lexical lock tracking is deliberately unsound in both directions (a method
may be single-threaded by protocol; a lock can be taken by a caller) — the
baseline/suppression machinery exists precisely to record those verdicts.

This pass stays per-file by design; the *cross*-module half of threading
discipline (lock-order cycles, blocking calls made while holding a lock
through the call graph) lives in :mod:`.deadlock`, which reuses this
module's :data:`LOCK_TYPES` as the single definition of what constructs a
lock.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from dmlc_core_tpu.analysis.driver import (FileContext, Finding, dotted_name,
                                           keyword_arg)

__all__ = ["run", "LOCK_TYPES"]

LOCK_TYPES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}

_CONSTRUCTORS = {"__init__", "__new__"}


def run(ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef):
            findings += _check_class_lockset(ctx, node)
    findings += _check_threads(ctx)
    return findings


# -- lockset-unsync-write -----------------------------------------------------

def _is_lock_factory(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    name = dotted_name(value.func) or ""
    short = name.rsplit(".", 1)[-1]
    return short in LOCK_TYPES and (name == short
                                    or name == f"threading.{short}")


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attribute names holding locks: ``self.X = threading.Lock()`` in any
    method, or ``X = threading.Lock()`` at class level."""
    attrs: Set[str] = set()
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Assign) and _is_lock_factory(node.value)):
            continue
        for target in node.targets:
            if (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id in ("self", "cls")):
                attrs.add(target.attr)
            elif isinstance(target, ast.Name):
                attrs.add(target.id)
    return attrs


class _WriteCollector(ast.NodeVisitor):
    """Classify self-attribute writes in one method as locked or bare."""

    def __init__(self, lock_attrs: Set[str]):
        self.lock_attrs = lock_attrs
        self.depth = 0
        # attr -> [(lineno, under_lock)]
        self.writes: List[Tuple[str, int, bool]] = []

    def _is_lock_expr(self, expr: ast.AST) -> bool:
        name = dotted_name(expr)
        if not name or "." not in name:
            return False
        return name.rsplit(".", 1)[-1] in self.lock_attrs

    def visit_With(self, node: ast.With) -> None:
        locked = any(self._is_lock_expr(item.context_expr)
                     for item in node.items)
        self.depth += locked
        self.generic_visit(node)
        self.depth -= locked

    def _record(self, target: ast.AST) -> None:
        if (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
                and target.attr not in self.lock_attrs):
            self.writes.append((target.attr, target.lineno, self.depth > 0))

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            self._record(target)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record(node.target)
        self.generic_visit(node)


def _check_class_lockset(ctx: FileContext, cls: ast.ClassDef) -> List[Finding]:
    lock_attrs = _lock_attrs(cls)
    if not lock_attrs:
        return []
    locked_at: Dict[str, int] = {}
    bare_at: Dict[str, int] = {}
    for method in cls.body:
        if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        collector = _WriteCollector(lock_attrs)
        collector.visit(method)
        in_ctor = method.name in _CONSTRUCTORS
        for attr, lineno, under_lock in collector.writes:
            if under_lock:
                locked_at.setdefault(attr, lineno)
            elif not in_ctor:
                bare_at.setdefault(attr, lineno)
    findings = []
    for attr in sorted(set(locked_at) & set(bare_at)):
        findings.append(Finding(
            "lockset-unsync-write", ctx.relpath, bare_at[attr],
            f"{cls.name}.{attr}",
            f"self.{attr} is written under {cls.name}'s lock (line "
            f"{locked_at[attr]}) and without it (line {bare_at[attr]})"))
    return findings


# -- lockset-thread-leak / lockset-no-join ------------------------------------

def _resolve_target(ctx: FileContext, target: ast.AST,
                    defs: Dict[str, List[ast.AST]]) -> Optional[ast.AST]:
    """The local def a Thread target refers to, the Lambda node itself, or
    None for callables we can't see into (imported / bound elsewhere)."""
    if isinstance(target, ast.Lambda):
        return target
    name = dotted_name(target)
    if name is None:
        return None
    short = name.rsplit(".", 1)[-1]
    candidates = defs.get(short, [])
    if isinstance(target, ast.Name) or name.startswith(("self.", "cls.")):
        return candidates[0] if candidates else None
    return None


def _ferries(target_def: ast.AST) -> bool:
    """A target ferries exceptions iff it contains a try that isn't a bare
    swallow (``except: pass`` without re-raising or recording)."""
    for node in ast.walk(target_def):
        if isinstance(node, ast.Try):
            for handler in node.handlers:
                body = handler.body
                if not all(isinstance(stmt, (ast.Pass, ast.Continue))
                           for stmt in body):
                    return True
    return False


def _check_threads(ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    defs = ctx.defs_by_name
    for call in ast.walk(ctx.tree):
        if not isinstance(call, ast.Call):
            continue
        name = dotted_name(call.func)
        if name not in ("threading.Thread", "Thread"):
            continue
        symbol = ctx.qualname(call)
        target = keyword_arg(call, "target")
        if target is not None:
            target_def = _resolve_target(ctx, target, defs)
            target_name = dotted_name(target) or "<lambda>"
            if target_def is None and not isinstance(target, ast.Lambda):
                findings.append(ctx.finding(
                    "lockset-thread-leak", call,
                    f"thread target {target_name} is a non-local callable; "
                    "an exception it raises dies in Thread.run and join() "
                    "reports success — wrap it and ferry errors",
                    symbol=f"{symbol}.{target_name}"))
            elif target_def is not None and not _ferries(target_def):
                findings.append(ctx.finding(
                    "lockset-thread-leak", call,
                    f"thread target {target_name} has no exception "
                    "ferrying (no try/except, or only a bare swallow); "
                    "errors in the thread are lost",
                    symbol=f"{symbol}.{target_name}"))
        daemon = keyword_arg(call, "daemon")
        is_daemon = (isinstance(daemon, ast.Constant)
                     and daemon.value is True)
        if not is_daemon:
            scope = _join_scope(ctx, call)
            if scope is not None and not _has_join(scope):
                findings.append(ctx.finding(
                    "lockset-no-join", call,
                    "non-daemon thread is never join()ed in its owning "
                    "scope; give the owner a destroy/join path or make it "
                    "a ferried daemon",
                    symbol=symbol))
    return findings


def _join_scope(ctx: FileContext, call: ast.Call) -> Optional[ast.AST]:
    """Where a join() for this thread would have to live: the whole class
    when the Thread is stored on self, else the enclosing function."""
    parent = ctx.parents.get(call)
    stored_on_self = (isinstance(parent, ast.Assign) and any(
        isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
        and t.value.id == "self" for t in parent.targets))
    if stored_on_self:
        cls = ctx.enclosing(call, ast.ClassDef)
        if cls is not None:
            return cls
    return ctx.enclosing(call, ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda) or ctx.tree


# join() receivers that are never threads (string seps, path modules)
_NON_THREAD_JOIN = {"os.path", "posixpath", "ntpath", "str"}


def _has_join(scope: ast.AST) -> bool:
    for node in ast.walk(scope):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and not isinstance(node.func.value, ast.Constant)
                and dotted_name(node.func.value) not in _NON_THREAD_JOIN):
            return True
    return False
