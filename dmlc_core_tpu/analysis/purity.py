"""Pass 2 — JAX purity: host syncs and impurity inside traced code.

A single host sync inside a ``jit``/``pallas_call`` hot path silently
serializes the device pipeline (the 15.53M rows/s histogram figure dies on
one stray ``.item()``); an impure call (``random``/``time``/file I/O) bakes
a trace-time value into the compiled function and never runs again.  Neither
crashes, which is exactly why a static pass pays rent.

Mechanics, per module:

1. **Roots** — functions entering tracing: decorated with ``@jax.jit`` /
   ``@partial(jax.jit, ...)`` / ``@pl.when(...)``, or passed to a trace
   wrapper call site (``jit``/``pjit``/``vmap``/``pmap``/``grad``/
   ``shard_map``/``pallas_call``/``lax.scan``/``while_loop``/``fori_loop``/
   ``cond``/``switch``).  Lambdas are analyzed inline;
   ``functools.partial(f, ...)`` and simple ``name = f`` aliases are
   followed.
2. **Reachability** — from the roots, calls to same-module functions
   (bare names, ``self.``/``cls.`` methods) are walked transitively.  The
   walk is module-local by design: cross-module reachability would need
   import resolution, and the gate's baseline covers the remainder.
3. **Checks** inside reachable code:

   - ``purity-host-sync``: ``.item()`` / ``.tolist()`` /
     ``.block_until_ready()``; ``jax.device_get``; ``float()``/``int()``/
     ``bool()`` applied to a traced parameter.  Parameters annotated
     ``int``/``bool``/``str`` are treated as static (the idiom this package
     uses for static args) and exempt.
   - ``purity-host-branch``: an ``if``/``while`` test containing one of the
     syncs above — control flow on abstract values, the
     ``TracerBoolConversionError`` family caught before runtime.
   - ``purity-np-call``: a ``numpy`` (not ``jax.numpy``) call taking a
     traced parameter — executes on host, breaks the trace.  numpy on
     constants at trace time is legitimate and not flagged.
   - ``purity-impure-call``: ``random.*`` / ``np.random.*`` / ``time.*`` /
     ``open`` / ``print`` / ``input`` anywhere in traced code.
   - ``purity-telemetry-call``: a :mod:`dmlc_core_tpu.telemetry` helper
     (``span``/``count``/``gauge_set``/``gauge_add``/``observe``/
     ``record_span``, or ``io.fs_metrics.note_request``) inside traced
     code.  Telemetry is host-side only: under tracing the call fires once
     at trace time — the compiled function then records nothing (or that
     one stale sample) per execution, and the clock read is a host sync.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from dmlc_core_tpu.analysis.driver import FileContext, Finding, dotted_name
from dmlc_core_tpu.analysis.graph import resolve_callable as _resolve_callable

__all__ = ["run", "TRACE_WRAPPERS"]

# wrapper short-name -> indices of the traced-callable argument(s)
TRACE_WRAPPERS: Dict[str, Tuple[int, ...]] = {
    "jit": (0,), "pjit": (0,), "vmap": (0,), "pmap": (0,), "grad": (0,),
    "value_and_grad": (0,), "checkify": (0,),
    "shard_map": (0,), "shard_map_unchecked": (0,),
    "pallas_call": (0,), "custom_vjp": (0,),
    "scan": (0,), "while_loop": (0, 1), "fori_loop": (2,),
    "cond": (1, 2), "switch": (1, 2, 3, 4),
}

# decorators whose body runs under an enclosing trace (pallas predication)
TRACE_DECORATORS = {"when"} | set(TRACE_WRAPPERS)

_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_CAST_BUILTINS = {"float", "int", "bool", "complex"}
_STATIC_ANNOTATIONS = {"int", "bool", "str"}
_IMPURE_ROOTS = {"random", "time"}
_IMPURE_CALLS = {"open", "print", "input"}
_TELEMETRY_MODULES = {"dmlc_core_tpu.telemetry", "dmlc_core_tpu.io.fs_metrics"}
_TELEMETRY_HELPERS = {"span", "count", "gauge_set", "gauge_add", "observe",
                      "record_span", "note_request", "request_start"}

_FuncNode = ast.AST  # FunctionDef | AsyncFunctionDef | Lambda


def run(ctx: FileContext) -> List[Finding]:
    roots = _trace_roots(ctx)
    if not roots:
        return []
    traced = _reachable(ctx, roots)
    numpy_aliases = {alias for alias, mod in ctx.module_aliases.items()
                     if mod == "numpy" or mod.startswith("numpy.")}
    random_aliases = {alias for alias, mod in ctx.module_aliases.items()
                      if mod.split(".")[0] in _IMPURE_ROOTS}
    telemetry_names = _telemetry_names(ctx)
    findings: List[Finding] = []
    seen: Set[Tuple[str, int, str]] = set()
    for fn in traced:
        for f in _check_traced(ctx, fn, numpy_aliases, random_aliases,
                               telemetry_names):
            dedup = (f.rule, f.lineno, f.symbol)
            if dedup not in seen:
                seen.add(dedup)
                findings.append(f)
    return findings


# -- root discovery -----------------------------------------------------------

def _wrapper_name(expr: ast.AST) -> Optional[str]:
    name = dotted_name(expr)
    if name is None:
        return None
    short = name.rsplit(".", 1)[-1]
    return short if short in TRACE_DECORATORS else None


# module-local callable resolution is shared project infrastructure now:
# :func:`dmlc_core_tpu.analysis.graph.resolve_callable` (hoisted from here
# so the interprocedural passes and this one can never diverge on what an
# expression calls)


def _trace_roots(ctx: FileContext) -> List[_FuncNode]:
    defs = ctx.defs_by_name
    aliases = ctx.assign_aliases
    roots: List[_FuncNode] = []

    def add(expr: ast.AST) -> None:
        roots.extend(_resolve_callable(ctx, expr, defs, aliases))

    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                base = dec.func if isinstance(dec, ast.Call) else dec
                fname = dotted_name(base) or ""
                if fname.rsplit(".", 1)[-1] == "partial" and \
                        isinstance(dec, ast.Call) and dec.args:
                    base = dec.args[0]
                    fname = dotted_name(base) or ""
                if _wrapper_name(base):
                    roots.append(node)
                    break
        elif isinstance(node, ast.Call):
            wrapper = _wrapper_name(node.func)
            if wrapper is None:
                continue
            for idx in TRACE_WRAPPERS.get(wrapper, ()):
                if idx < len(node.args):
                    add(node.args[idx])
    return roots


def _reachable(ctx: FileContext, roots: List[_FuncNode]) -> List[_FuncNode]:
    defs = ctx.defs_by_name
    aliases = ctx.assign_aliases
    seen: Set[int] = set()
    out: List[_FuncNode] = []
    work = list(roots)
    while work:
        fn = work.pop()
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        out.append(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                work.extend(_resolve_callable(ctx, node.func, defs, aliases))
    return out


# -- checks inside traced code ------------------------------------------------

def _nonstatic_params(fn: _FuncNode) -> Set[str]:
    args = fn.args
    names: Set[str] = set()
    for arg in (list(args.posonlyargs) + list(args.args)
                + list(args.kwonlyargs)):
        ann = getattr(arg, "annotation", None)
        static = (isinstance(ann, ast.Name)
                  and ann.id in _STATIC_ANNOTATIONS)
        if arg.arg not in ("self", "cls") and not static:
            names.add(arg.arg)
    return names


def _sync_call(node: ast.AST, nonstatic: Set[str]) -> Optional[str]:
    """Message when ``node`` is a host-syncing call, else None."""
    if not isinstance(node, ast.Call):
        return None
    func = node.func
    if isinstance(func, ast.Attribute) and func.attr in _SYNC_METHODS:
        return (f".{func.attr}() forces a device->host sync inside traced "
                "code")
    name = dotted_name(func) or ""
    if name == "jax.device_get":
        return "jax.device_get inside traced code forces a host sync"
    if (name in _CAST_BUILTINS and len(node.args) == 1
            and isinstance(node.args[0], ast.Name)
            and node.args[0].id in nonstatic):
        return (f"{name}() on traced argument {node.args[0].id!r} forces "
                "concretization (host sync / TracerConversionError)")
    return None


def _np_call_on_param(node: ast.AST, nonstatic: Set[str],
                      numpy_aliases: Set[str]) -> Optional[str]:
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func)
    if not name:
        return None
    root = name.split(".")[0]
    if root not in numpy_aliases:
        return None
    for arg in list(node.args) + [kw.value for kw in node.keywords]:
        if isinstance(arg, ast.Name) and arg.id in nonstatic:
            return (f"{name}() on traced argument {arg.id!r} executes on "
                    "host and breaks tracing — use jax.numpy")
    return None


def _is_telemetry_module(path: str) -> bool:
    return (path in _TELEMETRY_MODULES
            or path.startswith("dmlc_core_tpu.telemetry."))


def _telemetry_names(ctx: FileContext) -> Tuple[Set[str], Set[str]]:
    """(module-alias names, directly-imported helper names) bound to the
    telemetry package in this file.  ``module_aliases`` only sees plain
    ``import X`` forms, but telemetry's documented idiom is
    ``from dmlc_core_tpu import telemetry`` — so scan ImportFrom here."""
    mods = {alias for alias, mod in ctx.module_aliases.items()
            if _is_telemetry_module(mod)}
    funcs: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ImportFrom) or node.module is None:
            continue
        for alias in node.names:
            bound = alias.asname or alias.name
            full = f"{node.module}.{alias.name}"
            if _is_telemetry_module(full):
                mods.add(bound)
            elif _is_telemetry_module(node.module) \
                    and alias.name in _TELEMETRY_HELPERS:
                funcs.add(bound)
    return mods, funcs


def _telemetry_call(node: ast.AST,
                    telemetry_names: Tuple[Set[str], Set[str]]
                    ) -> Optional[str]:
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func)
    if not name:
        return None
    mods, funcs = telemetry_names
    root = name.split(".")[0]
    hit = (root in mods or name in funcs
           or name.startswith("dmlc_core_tpu.telemetry."))
    if not hit:
        return None
    return (f"{name}() is host-side telemetry inside traced code — it runs "
            "once at trace time, not per execution; meter outside the "
            "jit/pallas boundary")


def _enabled_gated(ctx: FileContext, node: ast.AST,
                   telemetry_names: Tuple[Set[str], Set[str]]) -> bool:
    """Is ``node`` inside an ``if telemetry.enabled():`` block?  The
    PR 7 transfer-accounting idiom: host-side metering in bridge code is
    deliberately gated on the telemetry switch, which both documents the
    intent and makes the disabled mode a no-op — such calls don't need a
    suppression comment.  (The gate itself still evaluates at trace time;
    the rule's job is flagging *accidental* telemetry in traced code.)"""
    mods, funcs = telemetry_names
    cur = ctx.parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.If):
            for sub in ast.walk(cur.test):
                if not isinstance(sub, ast.Call):
                    continue
                name = dotted_name(sub.func) or ""
                if name.rsplit(".", 1)[-1] != "enabled":
                    continue
                root = name.split(".")[0]
                if (root in mods or "enabled" in funcs
                        or name.startswith("dmlc_core_tpu.telemetry.")):
                    return True
        cur = ctx.parents.get(cur)
    return False


def _impure_call(node: ast.AST, random_aliases: Set[str]) -> Optional[str]:
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func)
    if not name:
        return None
    root = name.split(".")[0]
    if root in random_aliases or name.startswith(("np.random.",
                                                  "numpy.random.")):
        return (f"{name}() in traced code bakes one trace-time value into "
                "the compiled function — thread jax.random keys instead")
    if name in _IMPURE_CALLS:
        return (f"{name}() is a side effect inside traced code (runs at "
                "trace time only, or not at all)")
    return None


def _check_traced(ctx: FileContext, fn: _FuncNode, numpy_aliases: Set[str],
                  random_aliases: Set[str],
                  telemetry_names: Tuple[Set[str], Set[str]]
                  ) -> Iterable[Finding]:
    nonstatic = _nonstatic_params(fn)
    # host-branch: syncs inside if/while tests get the escalated rule
    branch_tests: Set[int] = set()
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.If, ast.While)):
                for sub in ast.walk(node.test):
                    branch_tests.add(id(sub))
    for stmt in body:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            sync = _sync_call(node, nonstatic)
            if sync is not None:
                rule = ("purity-host-branch" if id(node) in branch_tests
                        else "purity-host-sync")
                msg = (sync if rule == "purity-host-sync" else
                       "Python control flow branches on a host-synced "
                       f"traced value ({sync.strip()})")
                yield ctx.finding(rule, node, msg)
                continue
            np_msg = _np_call_on_param(node, nonstatic, numpy_aliases)
            if np_msg is not None:
                yield ctx.finding("purity-np-call", node, np_msg)
                continue
            tel_msg = _telemetry_call(node, telemetry_names)
            if tel_msg is not None:
                if not _enabled_gated(ctx, node, telemetry_names):
                    yield ctx.finding("purity-telemetry-call", node, tel_msg)
                continue
            impure = _impure_call(node, random_aliases)
            if impure is not None:
                yield ctx.finding("purity-impure-call", node, impure)
