"""Project graph: the shared module/call-graph core for whole-repo passes.

The per-file passes (lockset, purity, resources...) deliberately stop at
the module boundary; the deadlock and contract passes cannot — a lock-order
inversion lives precisely in the interaction *between* modules (the
scheduler's RLock calling into admission's Lock calling into telemetry's),
and a config knob is read in one file and documented in another.  This
module builds, once per run:

- a **module table**: every analyzed file keyed by its dotted module name
  (``dmlc_core_tpu/serve/scheduler.py`` -> ``dmlc_core_tpu.serve.scheduler``),
  with import maps resolving local names to project modules/symbols
  (absolute and relative ``import``/``from`` forms);
- a **symbol table** per module: top-level functions, classes with their
  methods, and per-class attribute types inferred from ``self.X = Cls(...)``
  constructor assignments (so ``self.admission.release()`` resolves to
  ``AdmissionController.release``);
- **call resolution**: given a function and a call expression, the project
  function(s) it may invoke — bare names, ``self.``/``cls.`` methods,
  imported symbols, ``module.func`` attribute chains, ``Class.method``,
  typed ``self.attr.method``, with ``functools.partial(f, ...)`` and
  ``name = f`` aliases followed (the resolver hoisted out of ``purity.py``
  so every pass shares one notion of "what does this expression call").

Soundness caveats (documented in docs/analysis.md): resolution is static
and best-effort — dynamic dispatch through registries, monkey-patching,
and callables passed as arguments are invisible; nested ``def`` bodies
belong to their enclosing function's module scan, not the graph.  The
passes built on top inherit these caveats and pair with the baseline/
suppression machinery exactly like the per-file passes do.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from dmlc_core_tpu.analysis.driver import FileContext, dotted_name

__all__ = ["ProjectGraph", "ModuleInfo", "ClassInfo", "FunctionInfo",
           "resolve_callable", "module_name_of"]

_MAX_HOPS = 4


def resolve_callable(ctx: FileContext, expr: ast.AST,
                     defs: Dict[str, List[ast.AST]],
                     aliases: Dict[str, ast.AST],
                     hops: int = 0) -> List[ast.AST]:
    """Module-local callable resolution (shared with the purity pass).

    Returns the function defs / lambda nodes ``expr`` may refer to within
    one file: lambdas inline, ``functools.partial(f, ...)`` unwrapped,
    ``name = f`` assignment aliases followed, bare names and
    ``self.``/``cls.`` methods looked up in ``defs``.
    """
    if hops > _MAX_HOPS or expr is None:
        return []
    if isinstance(expr, ast.Lambda):
        return [expr]
    if isinstance(expr, ast.Call):  # functools.partial(f, ...) inline
        fname = dotted_name(expr.func) or ""
        if fname.rsplit(".", 1)[-1] == "partial" and expr.args:
            return resolve_callable(ctx, expr.args[0], defs, aliases,
                                    hops + 1)
        return []
    name = dotted_name(expr)
    if name is None:
        return []
    short = name.rsplit(".", 1)[-1]
    if isinstance(expr, ast.Name):
        alias = aliases.get(short)
        if alias is not None and alias is not expr:
            resolved = resolve_callable(ctx, alias, defs, aliases, hops + 1)
            if resolved:
                return resolved
        return defs.get(short, [])
    if name.startswith(("self.", "cls.")):
        return defs.get(short, [])
    return []


def module_name_of(relpath: str) -> str:
    """Dotted module name for a repo-relative path.

    ``dmlc_core_tpu/io/stream.py`` -> ``dmlc_core_tpu.io.stream``;
    a package ``__init__.py`` names the package itself; a top-level file
    (``bench.py``) names its stem.
    """
    path = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = path.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class FunctionInfo:
    """One module-level function or class method in the project."""

    __slots__ = ("node", "module", "cls", "name", "qualname", "fq",
                 "_param_types")

    def __init__(self, node: ast.AST, module: "ModuleInfo",
                 cls: Optional["ClassInfo"]):
        self.node = node
        self.module = module
        self.cls = cls
        self.name = node.name
        self.qualname = f"{cls.name}.{node.name}" if cls else node.name
        self.fq = f"{module.modname}:{self.qualname}"
        self._param_types: Optional[Dict[str, str]] = None

    @property
    def param_types(self) -> Dict[str, str]:
        """param name -> dotted class ref from its annotation (``x: Foo``,
        ``x: mod.Foo``, forward-ref strings; ``Optional[Foo]`` unwraps)."""
        if self._param_types is None:
            out: Dict[str, str] = {}
            args = self.node.args
            for arg in (list(args.posonlyargs) + list(args.args)
                        + list(args.kwonlyargs)):
                ref = _annotation_ref(arg.annotation)
                if ref:
                    out[arg.arg] = ref
            self._param_types = out
        return self._param_types

    def __repr__(self) -> str:  # debugging aid only
        return f"<fn {self.fq}>"


def _annotation_ref(ann: Optional[ast.AST]) -> Optional[str]:
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        ref = ann.value.strip().strip("'\"")
        return ref or None
    if isinstance(ann, ast.Subscript):  # Optional[Foo] / "Foo | None" parts
        return _annotation_ref(ann.slice)
    name = dotted_name(ann)
    return name


class ClassInfo:
    """A class: its methods plus inferred attribute types."""

    __slots__ = ("node", "module", "name", "methods", "bases", "attr_types")

    def __init__(self, node: ast.ClassDef, module: "ModuleInfo"):
        self.node = node
        self.module = module
        self.name = node.name
        self.methods: Dict[str, FunctionInfo] = {}
        self.bases: List[str] = [dotted_name(b) for b in node.bases
                                 if dotted_name(b)]
        # attr -> dotted constructor ref ("AdmissionController",
        # "mod.Cls"), from `self.X = Cls(...)` (incl. `self.X = x or
        # Cls()`); first assignment wins
        self.attr_types: Dict[str, str] = {}

    def _collect(self) -> None:
        for stmt in self.node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[stmt.name] = FunctionInfo(stmt, self.module,
                                                       self)
        for method in self.methods.values():
            for node in ast.walk(method.node):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Attribute)
                        and isinstance(node.targets[0].value, ast.Name)
                        and node.targets[0].value.id == "self"):
                    continue
                ref = _ctor_ref(node.value)
                if ref is None and isinstance(node.value, ast.Name):
                    # `self.registry = registry` with an annotated param
                    # (`registry: ModelRegistry`): the annotation is the
                    # ctor the caller ran
                    ref = method.param_types.get(node.value.id)
                if ref:
                    self.attr_types.setdefault(node.targets[0].attr, ref)


def _ctor_ref(value: ast.AST) -> Optional[str]:
    """Dotted class ref when ``value`` looks like a constructor call."""
    if isinstance(value, ast.BoolOp):  # x = arg or Default()
        for operand in value.values:
            ref = _ctor_ref(operand)
            if ref:
                return ref
        return None
    if isinstance(value, ast.Call):
        name = dotted_name(value.func)
        # heuristic: constructors are CamelCase in this codebase
        if name and name.rsplit(".", 1)[-1][:1].isupper():
            return name
    return None


class ModuleInfo:
    """One analyzed file: symbol tables + import maps."""

    __slots__ = ("ctx", "modname", "relpath", "top_defs", "classes",
                 "import_mods", "import_syms")

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.relpath = ctx.relpath
        self.modname = module_name_of(ctx.relpath)
        self.top_defs: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        # local name -> project module it is bound to
        self.import_mods: Dict[str, str] = {}
        # local name -> (module, symbol) for `from mod import f`
        self.import_syms: Dict[str, Tuple[str, str]] = {}
        for stmt in ctx.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.top_defs[stmt.name] = FunctionInfo(stmt, self, None)
            elif isinstance(stmt, ast.ClassDef):
                cls = ClassInfo(stmt, self)
                cls._collect()
                self.classes[stmt.name] = cls

    @property
    def package(self) -> str:
        """The package this module lives in (itself, for ``__init__``)."""
        if self.ctx.relpath.endswith("/__init__.py"):
            return self.modname
        return self.modname.rsplit(".", 1)[0] if "." in self.modname else ""

    def functions(self) -> List[FunctionInfo]:
        out = list(self.top_defs.values())
        for cls in self.classes.values():
            out.extend(cls.methods.values())
        return out

    def _resolve_import_base(self, node: ast.ImportFrom) -> Optional[str]:
        if node.level == 0:
            return node.module
        base = self.package
        for _ in range(node.level - 1):
            if "." not in base:
                base = ""
                break
            base = base.rsplit(".", 1)[0]
        if not base and node.level > 1:
            return None
        return f"{base}.{node.module}" if node.module else (base or None)

    def collect_imports(self, known_modules) -> None:
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        self.import_mods[alias.asname] = alias.name
                    else:
                        root = alias.name.split(".")[0]
                        self.import_mods.setdefault(root, root)
            elif isinstance(node, ast.ImportFrom):
                base = self._resolve_import_base(node)
                if base is None:
                    continue
                for alias in node.names:
                    local = alias.asname or alias.name
                    full = f"{base}.{alias.name}"
                    if full in known_modules:
                        self.import_mods[local] = full
                    else:
                        self.import_syms[local] = (base, alias.name)


class ProjectGraph:
    """All analyzed modules + cross-module call resolution."""

    def __init__(self, contexts) -> None:
        self.modules: Dict[str, ModuleInfo] = {}
        self.by_relpath: Dict[str, ModuleInfo] = {}
        for ctx in contexts:
            mod = ModuleInfo(ctx)
            self.modules[mod.modname] = mod
            self.by_relpath[mod.relpath] = mod
        for mod in self.modules.values():
            mod.collect_imports(self.modules)
        self._callee_cache: Dict[str, List[Tuple[ast.Call, FunctionInfo]]] = {}

    # -- lookup helpers -------------------------------------------------------

    def functions(self) -> List[FunctionInfo]:
        out: List[FunctionInfo] = []
        for mod in self.modules.values():
            out.extend(mod.functions())
        return out

    def _symbol_in(self, modname: str, symbol: str,
                   hops: int = 0) -> List[FunctionInfo]:
        """``symbol`` looked up in ``modname``: a function, a class (its
        constructor), or a package ``__init__`` re-export (one hop)."""
        mod = self.modules.get(modname)
        if mod is None or hops > _MAX_HOPS:
            return []
        if symbol in mod.top_defs:
            return [mod.top_defs[symbol]]
        if symbol in mod.classes:
            ctor = mod.classes[symbol].methods.get("__init__")
            return [ctor] if ctor else []
        if symbol in mod.import_syms:  # re-export chain
            tm, sym = mod.import_syms[symbol]
            return self._symbol_in(tm, sym, hops + 1)
        if symbol in mod.import_mods:
            return []  # a module object, not a callable
        return []

    def resolve_class(self, mod: ModuleInfo,
                      ref: Optional[str]) -> Optional[ClassInfo]:
        """A dotted class ref as seen from ``mod`` -> its ClassInfo."""
        if not ref:
            return None
        parts = ref.split(".")
        if len(parts) == 1:
            if parts[0] in mod.classes:
                return mod.classes[parts[0]]
            if parts[0] in mod.import_syms:
                tm, sym = mod.import_syms[parts[0]]
                target = self.modules.get(tm)
                if target:
                    return target.classes.get(sym)
            return None
        root, rest = parts[0], parts[1:]
        if root in mod.import_mods:
            target = self.modules.get(
                ".".join([mod.import_mods[root]] + rest[:-1]))
            if target:
                return target.classes.get(rest[-1])
        return None

    # -- call resolution ------------------------------------------------------

    def resolve_call(self, fn: FunctionInfo, expr: ast.AST,
                     hops: int = 0) -> List[FunctionInfo]:
        """Project functions a call expression may invoke, from inside
        ``fn``.  Best-effort static resolution; unknown -> []."""
        if hops > _MAX_HOPS or expr is None:
            return []
        mod = fn.module
        if isinstance(expr, ast.Call):  # functools.partial(f, ...) inline
            fname = dotted_name(expr.func) or ""
            if fname.rsplit(".", 1)[-1] == "partial" and expr.args:
                return self.resolve_call(fn, expr.args[0], hops + 1)
            return []
        name = dotted_name(expr)
        if name is None:
            return []
        parts = name.split(".")
        if len(parts) == 1:
            n = parts[0]
            alias = mod.ctx.assign_aliases.get(n)
            if alias is not None and alias is not expr:
                resolved = self.resolve_call(fn, alias, hops + 1)
                if resolved:
                    return resolved
            if n in mod.top_defs:
                return [mod.top_defs[n]]
            if n in mod.classes:
                ctor = mod.classes[n].methods.get("__init__")
                return [ctor] if ctor else []
            if n in mod.import_syms:
                return self._symbol_in(*mod.import_syms[n])
            return []
        root, rest = parts[0], parts[1:]
        if root in ("self", "cls") and fn.cls is not None:
            if len(rest) == 1:
                meth = self._method_of(fn.cls, rest[0])
                return [meth] if meth else []
            if len(rest) == 2:  # self.attr.method() via inferred attr type
                cls = self.resolve_class(mod, fn.cls.attr_types.get(rest[0]))
                if cls is not None:
                    meth = self._method_of(cls, rest[1])
                    return [meth] if meth else []
            return []
        if root in fn.param_types and len(rest) == 1:
            # annotated parameter: worker(batcher: MicroBatcher) ->
            # batcher.submit() resolves through the annotation
            cls = self.resolve_class(mod, fn.param_types[root])
            if cls is not None:
                meth = self._method_of(cls, rest[0])
                return [meth] if meth else []
            return []
        if root in mod.classes and len(rest) == 1:  # Class.method
            meth = mod.classes[root].methods.get(rest[0])
            return [meth] if meth else []
        if root in mod.import_syms and len(rest) == 1:
            # ImportedClass.method
            tm, sym = mod.import_syms[root]
            target = self.modules.get(tm)
            if target and sym in target.classes:
                meth = target.classes[sym].methods.get(rest[0])
                return [meth] if meth else []
            return []
        if root in mod.import_mods:
            base = mod.import_mods[root]
            # mod.func / pkg.sub.func: longest prefix naming a module wins
            for split in range(len(rest) - 1, -1, -1):
                cand = ".".join([base] + rest[:split])
                target = self.modules.get(cand)
                if target is None:
                    continue
                tail = rest[split:]
                if len(tail) == 1:
                    return self._symbol_in(cand, tail[0])
                if len(tail) == 2 and tail[0] in target.classes:
                    meth = target.classes[tail[0]].methods.get(tail[1])
                    return [meth] if meth else []
                return []
        return []

    def _method_of(self, cls: ClassInfo,
                   name: str, hops: int = 0) -> Optional[FunctionInfo]:
        """Method lookup in ``cls``, walking project-resolvable bases."""
        if name in cls.methods:
            return cls.methods[name]
        if hops > _MAX_HOPS:
            return None
        for base_ref in cls.bases:
            base = self.resolve_class(cls.module, base_ref)
            if base is not None and base is not cls:
                found = self._method_of(base, name, hops + 1)
                if found:
                    return found
        return None

    def callees(self, fn: FunctionInfo) -> List[Tuple[ast.Call, FunctionInfo]]:
        """(call node, resolved project function) pairs inside ``fn``,
        nested scopes excluded (they run at their own call time)."""
        cached = self._callee_cache.get(fn.fq)
        if cached is not None:
            return cached
        out: List[Tuple[ast.Call, FunctionInfo]] = []
        for node in walk_in_scope(fn.node):
            if isinstance(node, ast.Call):
                for callee in self.resolve_call(fn, node.func):
                    out.append((node, callee))
        self._callee_cache[fn.fq] = out
        return out


def walk_in_scope(fn_node: ast.AST):
    """Yield every AST node of a function body, excluding nested
    function/class scopes (their bodies execute at their own call time,
    not while the enclosing function runs)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))
