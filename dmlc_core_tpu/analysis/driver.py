"""dmlclint driver: file walking, shared AST infra, suppressions, CLI.

Findings are keyed ``<file>:<rule>:<symbol>`` and ratcheted against the
committed ``analysis_baseline.json`` (see :mod:`.baseline`): a finding whose
key is baselined is burn-down work and does not fail the run; a finding with
a new key does.  ``# dmlclint: disable=<rule>`` on (or on a comment line
immediately above) the offending line suppresses it at the source.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import os
import sys
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set

__all__ = ["Finding", "FileContext", "analyze_source", "analyze_path",
           "iter_python_files", "main", "ALL_RULES", "ROOT"]

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the same target set the old scripts/lint.py walked
TARGETS = ["dmlc_core_tpu", "tests", "examples", "bench.py",
           "__graft_entry__.py"]

# modules whose job is talking to a terminal: exempt from style-no-print
CLI_EXEMPT = {
    "dmlc_core_tpu/tracker/submit.py",
    "dmlc_core_tpu/tracker/launcher.py",
    "dmlc_core_tpu/io/__main__.py",
    "dmlc_core_tpu/analysis/driver.py",  # this CLI reports to stdout
    "dmlc_core_tpu/telemetry/report.py",  # `telemetry report` CLI table
    "dmlc_core_tpu/telemetry/traceview.py",  # `telemetry trace` CLI report
    "dmlc_core_tpu/telemetry/__main__.py",
    "dmlc_core_tpu/fault/__main__.py",  # `fault validate` CLI report
    "dmlc_core_tpu/serve/__main__.py",  # `python -m dmlc_core_tpu.serve` CLI
}

# the deep passes run on library code only; tests/examples get syntax checks
LIBRARY_PREFIX = "dmlc_core_tpu/"

ALL_RULES = {
    "syntax": "file does not parse (never baselineable)",
    "lockset-unsync-write": (
        "attribute of a lock-owning class is written both under and outside "
        "the lock"),
    "lockset-thread-leak": (
        "Thread target can die with an un-ferried exception (no try/except "
        "in the target, a bare swallow, a lambda, or a library callable)"),
    "lockset-no-join": (
        "non-daemon Thread with no .join() on any destroy/exit path in its "
        "owning scope"),
    "purity-host-sync": (
        "host synchronization inside traced code: .item()/.tolist()/"
        "block_until_ready, or float()/int()/bool() on a traced argument"),
    "purity-host-branch": (
        "Python if/while branches on a value synced from a traced "
        "computation"),
    "purity-np-call": (
        "numpy call on a traced argument inside traced code (executes on "
        "host, breaks tracing)"),
    "purity-impure-call": (
        "impure call inside traced code: random/time/open/print/input"),
    "purity-telemetry-call": (
        "telemetry helper (span/count/gauge/observe) inside traced code — "
        "host-side only: it fires once at trace time and records nothing "
        "(or one bogus sample) per compiled execution"),
    "resource-unclosed": (
        "open()/socket/TemporaryFile handle neither used as a context "
        "manager nor closed/returned/handed off in its function"),
    "resource-tempdir": (
        "tempfile.mkdtemp() result has no shutil.rmtree in a finally block "
        "(leaks the dir on non-anticipated exceptions)"),
    "assert-in-protocol": (
        "bare assert validating wire/peer-supplied data in tracker/ or io/ "
        "(vanishes under python -O; crashes the serving thread instead of "
        "rejecting the peer — raise ProtocolError)"),
    "shm-no-pickle": (
        "pickle/marshal on the shared-memory parse transport path "
        "(data/parse_proc.py): array payloads must cross process "
        "boundaries as raw shm bytes, never pickled objects"),
    "style-no-print": "library code must log via utils.logging, not print()",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    lineno: int
    symbol: str        # enclosing qualname / Class.attr — stable across moves
    message: str

    @property
    def key(self) -> str:
        return f"{self.path}:{self.rule}:{self.symbol}"

    def render(self) -> str:
        return f"{self.path}:{self.lineno}: {self.rule} [{self.symbol}] {self.message}"


# -- shared AST helpers -------------------------------------------------------

def build_parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def dotted_name(node: ast.AST) -> Optional[str]:
    """``jax.lax.scan`` for an Attribute/Name chain; None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    else:
        return None
    return ".".join(reversed(parts))


def keyword_arg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


class FileContext:
    """Everything a pass needs about one file, computed once."""

    def __init__(self, relpath: str, source: str, tree: ast.Module,
                 is_library: bool, cli_exempt: bool):
        self.relpath = relpath
        self.source = source
        self.tree = tree
        self.is_library = is_library
        self.cli_exempt = cli_exempt
        self.parents = build_parents(tree)
        self.module_aliases = self._collect_aliases(tree)
        self._defs_by_name: Optional[Dict[str, List[ast.AST]]] = None
        self._assign_aliases: Optional[Dict[str, ast.AST]] = None

    @staticmethod
    def _collect_aliases(tree: ast.Module) -> Dict[str, str]:
        """Local name -> imported module path (``np`` -> ``numpy``)."""
        out: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    out[alias.asname or alias.name.split(".")[0]] = alias.name
        return out

    @property
    def defs_by_name(self) -> Dict[str, List[ast.AST]]:
        """Module function defs by short name — shared by the lockset
        (thread-target resolution) and purity (root/callee resolution)
        passes; computed once per file."""
        if self._defs_by_name is None:
            defs: Dict[str, List[ast.AST]] = {}
            for node in ast.walk(self.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    defs.setdefault(node.name, []).append(node)
            self._defs_by_name = defs
        return self._defs_by_name

    @property
    def assign_aliases(self) -> Dict[str, ast.AST]:
        """``name = f`` / ``name = functools.partial(f, ...)`` bindings
        anywhere in the module, so ``kernel = partial(_kernel, ...);
        pallas_call(kernel)`` resolves.  Collisions across scopes keep the
        first binding — acceptable for a lint pass."""
        if self._assign_aliases is None:
            aliases: Dict[str, ast.AST] = {}
            for node in ast.walk(self.tree):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    continue
                value = node.value
                if isinstance(value, ast.Call):
                    fname = dotted_name(value.func) or ""
                    if fname.rsplit(".", 1)[-1] == "partial" and value.args:
                        value = value.args[0]
                    else:
                        continue
                if isinstance(value, (ast.Name, ast.Attribute, ast.Lambda)):
                    aliases.setdefault(node.targets[0].id, value)
            self._assign_aliases = aliases
        return self._assign_aliases

    def enclosing(self, node: ast.AST, *types) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, types):
                return cur
            cur = self.parents.get(cur)
        return None

    def qualname(self, node: ast.AST) -> str:
        """Dotted path of enclosing defs/classes, for stable finding keys."""
        parts: List[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            elif isinstance(cur, ast.Lambda):
                parts.append("<lambda>")
            cur = self.parents.get(cur)
        return ".".join(reversed(parts)) or "<module>"

    def finding(self, rule: str, node: ast.AST, message: str,
                symbol: Optional[str] = None) -> Finding:
        return Finding(rule, self.relpath, getattr(node, "lineno", 0),
                       symbol if symbol is not None else self.qualname(node),
                       message)


# -- suppression comments -----------------------------------------------------

_SUPPRESS_RE = re.compile(r"#\s*dmlclint:\s*disable=([A-Za-z0-9_,\- ]+)")


def suppressed_lines(source: str) -> Dict[int, Set[str]]:
    """line -> suppressed rule names.  A directive on a comment-only line
    also applies to the line below it, so rules can be silenced without
    pushing code past the line-length limit."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(source.splitlines(), 1):
        match = _SUPPRESS_RE.search(line)
        if not match:
            continue
        rules = {r.strip() for r in match.group(1).split(",") if r.strip()}
        out.setdefault(i, set()).update(rules)
        if line.lstrip().startswith("#"):
            out.setdefault(i + 1, set()).update(rules)
    return out


# -- per-file analysis --------------------------------------------------------

def analyze_source(source: str, relpath: str = "<string>",
                   is_library: Optional[bool] = None) -> List[Finding]:
    """Run every pass over one source blob; returns sorted, unsuppressed
    findings.  ``is_library`` defaults from the path (deep passes run on
    ``dmlc_core_tpu/`` files; everything else is syntax-checked only)."""
    relpath = relpath.replace(os.sep, "/")
    if is_library is None:
        is_library = relpath.startswith(LIBRARY_PREFIX)
    try:
        tree = ast.parse(source, relpath)
    except SyntaxError as exc:
        return [Finding("syntax", relpath, exc.lineno or 0, "<module>",
                        f"syntax error: {exc.msg}")]
    findings: List[Finding] = []
    if is_library:
        from dmlc_core_tpu.analysis import (lockset, protocol, purity,
                                            resources, transport)

        ctx = FileContext(relpath, source, tree, is_library,
                          cli_exempt=relpath in CLI_EXEMPT)
        findings += lockset.run(ctx)
        findings += purity.run(ctx)
        findings += resources.run(ctx)
        findings += protocol.run(ctx)
        findings += transport.run(ctx)
    supp = suppressed_lines(source)
    findings = [f for f in findings
                if not ({"all", f.rule} & supp.get(f.lineno, set()))]
    return sorted(findings, key=lambda f: (f.lineno, f.rule, f.symbol))


def repo_relpath(path: str, root: str = ROOT) -> str:
    """Repo-relative forward-slash path used in finding keys."""
    relpath = os.path.relpath(os.path.abspath(path), root)
    if relpath.startswith(".."):
        # out-of-tree file (e.g. a scratch checkout): anchor at the last
        # dmlc_core_tpu path component so library rules still apply
        parts = os.path.abspath(path).split(os.sep)
        if LIBRARY_PREFIX.rstrip("/") in parts:
            idx = len(parts) - 1 - parts[::-1].index(LIBRARY_PREFIX.rstrip("/"))
            relpath = os.sep.join(parts[idx:])
        else:
            relpath = os.path.basename(path)
    return relpath.replace(os.sep, "/")


def analyze_path(path: str, root: str = ROOT) -> List[Finding]:
    relpath = repo_relpath(path, root)
    try:
        # tokenize.open honors a PEP 263 `# -*- coding: ... -*-` line,
        # which plain utf-8 open would reject on legacy files
        with tokenize.open(path) as f:
            source = f.read()
    except (UnicodeDecodeError, LookupError, SyntaxError) as exc:
        # undecodable bytes / bogus coding cookie: one finding, not a
        # traceback that kills the whole gate
        return [Finding("syntax", relpath, 0, "<module>",
                        f"cannot decode source: {exc}")]
    return analyze_source(source, relpath)


def iter_python_files(paths: Optional[Sequence[str]] = None,
                      root: str = ROOT) -> Iterable[str]:
    targets = list(paths) if paths else [os.path.join(root, t)
                                         for t in TARGETS]
    for target in targets:
        if not os.path.exists(target):
            # a typo'd/renamed target must not pass the gate as
            # "0 files, 0 findings"
            raise FileNotFoundError(f"no such file or directory: {target}")
        if os.path.isfile(target):
            yield target
            continue
        for dirpath, _, files in os.walk(target):
            if "__pycache__" in dirpath:
                continue
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


# -- CLI ----------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    """The dmlclint argument parser — shared with scripts/lint.py so the
    shim's view of paths/flags can never diverge from the driver's (e.g.
    argparse prefix abbreviations like ``--base`` for ``--baseline``)."""
    parser = argparse.ArgumentParser(
        prog="python -m dmlc_core_tpu.analysis",
        description="dmlclint: lockset / JAX-purity / resource static "
                    "analysis with a ratcheted baseline (docs/analysis.md)")
    parser.add_argument("paths", nargs="*",
                        help="files or directories (default: repo targets)")
    parser.add_argument("--baseline",
                        default=os.path.join(ROOT, "analysis_baseline.json"),
                        help="baseline file (default: %(default)s)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding as new (ignore baseline)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline from current findings, "
                             "keeping existing justifications")
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="also print baselined findings")
    parser.add_argument("--list-rules", action="store_true")
    return parser


def main(argv: Optional[Sequence[str]] = None) -> int:
    from dmlc_core_tpu.analysis import baseline as baseline_mod

    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule in sorted(ALL_RULES):
            print(f"{rule:22s} {ALL_RULES[rule]}")
        return 0

    try:
        files = list(iter_python_files(args.paths or None))
    except FileNotFoundError as exc:
        print(f"dmlclint: {exc}", file=sys.stderr)
        return 2
    findings: List[Finding] = []
    for path in files:
        findings += analyze_path(path)

    try:
        # --no-baseline only changes *reporting*; a rewrite still loads the
        # file, else justifications (and out-of-scope keys in a path-scoped
        # run) would be silently destroyed
        load_it = args.write_baseline or not args.no_baseline
        previous = baseline_mod.load(args.baseline) if load_it else {}
    except ValueError as exc:
        print(f"dmlclint: {exc}", file=sys.stderr)
        return 2
    if args.write_baseline:
        # a path-scoped rewrite must not drop entries for files it never
        # analyzed — only the analyzed files' keys are regenerated
        keep = {}
        if args.paths:
            analyzed = {repo_relpath(p) for p in files}
            keep = {k: v for k, v in previous.items()
                    if k.split(":", 1)[0] not in analyzed}
        baseline_mod.save(args.baseline, findings, previous, keep=keep)
        print(f"dmlclint: baseline written to {args.baseline} "
              f"({len(findings)} finding(s), {len(keep)} out-of-scope "
              f"entries kept)")
        return 0

    new, baselined, stale = baseline_mod.partition(findings, previous)
    if args.paths:
        # a scoped run never recomputed out-of-scope files: their baseline
        # entries are not "fixed or moved", so don't advise pruning them
        analyzed = {repo_relpath(p) for p in files}
        stale = [k for k in stale if k.split(":", 1)[0] in analyzed]
    for f in new:
        print(f.render())
    if args.verbose:
        counts: Dict[str, int] = {}
        for f in baselined:
            key = baseline_mod._instance_key(f.key, counts)
            note = previous.get(key, previous.get(f.key, ""))
            print(f"{f.render()}  (baselined: {note})")
    if stale:
        print(f"dmlclint: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} (fixed or moved — prune "
              f"with --write-baseline):", file=sys.stderr)
        for key in stale:
            print(f"  {key}", file=sys.stderr)
    print(f"dmlclint: {len(files)} files, {len(new)} new finding(s), "
          f"{len(baselined)} baselined, {len(stale)} stale")
    return 1 if new else 0
