"""dmlclint driver: file walking, shared AST infra, suppressions, CLI.

Findings are keyed ``<file>:<rule>:<symbol>`` and ratcheted against the
committed ``analysis_baseline.json`` (see :mod:`.baseline`): a finding whose
key is baselined is burn-down work and does not fail the run; a finding with
a new key does.  ``# dmlclint: disable=<rule>`` on (or on a comment line
immediately above) the offending line suppresses it at the source.

Two kinds of passes run:

- **per-file passes** (lockset/purity/resources/protocol/transport) see one
  module at a time;
- **project passes** (deadlock/contracts/escape/jaxbound/races/wiretaint)
  see the whole repo at once through the :mod:`.graph` call-graph core —
  they run on the default (unscoped) gate invocation, or whenever
  ``--pass`` selects them explicitly.

``--jobs N`` fans the per-file stage out over a process pool (findings
are reassembled in file order, so output is byte-identical to a serial
run); project passes stay sequential — they need the whole graph.

``--format github`` renders new findings as GitHub workflow annotations;
``--format sarif`` emits a SARIF 2.1.0 document (``--output`` writes it to
a file for artifact upload).
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import sys
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = ["Finding", "FileContext", "analyze_source", "analyze_path",
           "iter_python_files", "main", "ALL_RULES", "ROOT",
           "PER_FILE_PASSES", "PROJECT_PASSES", "render_rule_catalog"]

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the same target set the old scripts/lint.py walked
TARGETS = ["dmlc_core_tpu", "tests", "examples", "bench.py",
           "__graft_entry__.py"]

PER_FILE_PASSES = ("lockset", "purity", "resources", "protocol", "transport")
PROJECT_PASSES = ("deadlock", "contracts", "escape", "jaxbound", "races",
                  "wiretaint")

# non-library files that still get threading-discipline passes (bench.py
# spawns watchdog/collector threads; its lock use is production code even
# though it lives at the repo root) and ride in the project graph for the
# whole-repo passes
EXTRA_DEEP: Dict[str, Tuple[str, ...]] = {"bench.py": ("lockset",)}

# modules whose job is talking to a terminal: exempt from style-no-print
CLI_EXEMPT = {
    "dmlc_core_tpu/tracker/submit.py",
    "dmlc_core_tpu/tracker/launcher.py",
    "dmlc_core_tpu/io/__main__.py",
    "dmlc_core_tpu/analysis/driver.py",  # this CLI reports to stdout
    "dmlc_core_tpu/telemetry/report.py",  # `telemetry report` CLI table
    "dmlc_core_tpu/telemetry/traceview.py",  # `telemetry trace` CLI report
    "dmlc_core_tpu/telemetry/__main__.py",
    "dmlc_core_tpu/fault/__main__.py",  # `fault validate` CLI report
    "dmlc_core_tpu/serve/__main__.py",  # `python -m dmlc_core_tpu.serve` CLI
}

# the deep passes run on library code only; tests/examples get syntax checks
LIBRARY_PREFIX = "dmlc_core_tpu/"

ALL_RULES = {
    "syntax": "file does not parse (never baselineable)",
    "lockset-unsync-write": (
        "attribute of a lock-owning class is written both under and outside "
        "the lock"),
    "lockset-thread-leak": (
        "Thread target can die with an un-ferried exception (no try/except "
        "in the target, a bare swallow, a lambda, or a library callable)"),
    "lockset-no-join": (
        "non-daemon Thread with no .join() on any destroy/exit path in its "
        "owning scope"),
    "purity-host-sync": (
        "host synchronization inside traced code: .item()/.tolist()/"
        "block_until_ready, or float()/int()/bool() on a traced argument"),
    "purity-host-branch": (
        "Python if/while branches on a value synced from a traced "
        "computation"),
    "purity-np-call": (
        "numpy call on a traced argument inside traced code (executes on "
        "host, breaks tracing)"),
    "purity-impure-call": (
        "impure call inside traced code: random/time/open/print/input"),
    "purity-telemetry-call": (
        "telemetry helper (span/count/gauge/observe) inside traced code — "
        "host-side only: it fires once at trace time and records nothing "
        "(or one bogus sample) per compiled execution"),
    "resource-unclosed": (
        "open()/socket/TemporaryFile handle neither used as a context "
        "manager nor closed/returned/handed off in its function"),
    "resource-tempdir": (
        "tempfile.mkdtemp() result has no shutil.rmtree in a finally block "
        "(leaks the dir on non-anticipated exceptions)"),
    "assert-in-protocol": (
        "bare assert validating wire/peer-supplied data in tracker/ or io/ "
        "(vanishes under python -O; crashes the serving thread instead of "
        "rejecting the peer — raise ProtocolError)"),
    "shm-no-pickle": (
        "pickle/marshal on the shared-memory parse transport path "
        "(data/parse_proc.py): array payloads must cross process "
        "boundaries as raw shm bytes, never pickled objects"),
    "style-no-print": "library code must log via utils.logging, not print()",
    "deadlock-lock-cycle": (
        "cycle in the global lock-order graph (interprocedural: holding A "
        "and calling code that takes B orders A before B) — two threads "
        "taking the locks in opposite order deadlock"),
    "deadlock-blocking-under-lock": (
        "unbounded blocking call (queue.get/.join()/.result()/.wait()/"
        "socket recv without timeout) while holding a lock, directly or "
        "through the call graph; every thread needing the lock wedges "
        "behind the wait"),
    "contract-undocumented-knob": (
        "DMLC_* env var read in code but absent from every docs table — "
        "regenerate the knob catalog (--emit-knob-catalog) or delete the "
        "knob"),
    "contract-undocumented-metric": (
        "dmlc_* metric recorded in code but absent from the docs metric "
        "catalogs"),
    "contract-undocumented-span": (
        "telemetry span/event name recorded in code but absent from the "
        "docs span catalog (--emit-span-catalog regenerates it)"),
    "contract-undocumented-site": (
        "fault site injected but not registered in fault.SITES, or "
        "registered but missing from the docs site table"),
    "contract-stale-doc-entry": (
        "a docs catalog row names a knob/metric/span/site the code no "
        "longer has — prune the row or restore the artifact"),
    "escape-leak-on-raise": (
        "a path from a resource acquisition (shm/socket/executor/mmap/fd/"
        "temp dir) to the function exit drops the last reference — "
        "typically the exception edge between the acquire and the "
        "finally/with that releases, a failed __init__ orphaning a "
        "self.-owned handle, or a class that never releases an attr it "
        "owns"),
    "escape-double-release": (
        "a non-idempotent release (unlink/rmtree/os.close) may run twice "
        "on one path — the second call raises or tears down a reused "
        "handle"),
    "jaxbound-unaccounted-transfer": (
        "jax.device_put / jnp.asarray in bridge/ outside the "
        "_accounted_place wrapper — bytes ship off the books of "
        "dmlc_transfer_bytes_total and the trace critical path"),
    "jaxbound-wide-wire": (
        "binned (narrow-wire) data cast to float32/float64 before a "
        "transfer — re-inflates the uint8 wire diet ~4x; widen on device "
        "inside the jit instead"),
    "jaxbound-jit-in-hot-path": (
        "jax.jit wrapper rebuilt per call (immediately invoked or bound "
        "to a call-only local): the compile cache is always empty, so "
        "every call retraces — store the jitted fn on the instance/"
        "module or memoize its builder"),
    "race-unlocked-shared-write": (
        "attribute reachable from a thread-entry root and another thread "
        "is written with no lock held at any write site (Eraser empty "
        "lockset) — guard every access with one lock, publish before "
        "thread start, or hand off via a queue"),
    "race-inconsistent-lockset": (
        "shared attribute's write sites hold locks, but no ONE lock is "
        "held at all of them (empty lockset intersection) — each site "
        "looks locked in isolation while the writes still race"),
    "taint-unbounded-wire-int": (
        "int decoded from the wire (FramedSocket recvint, struct.unpack, "
        "JSON off a received frame) sizes an allocation, range(), recv(n) "
        "or sequence repeat without a bounds guard — one hostile frame "
        "picks the allocation size"),
    "taint-wire-str-in-path": (
        "string decoded from the wire reaches open()/os.path.join()/"
        "Path()/remove() without an allowlist or basename() step — path "
        "traversal from a protocol frame"),
}

# which pass owns which rule (drives --pass filtering of stale-entry
# reporting and scoped baseline rewrites)
RULES_BY_PASS: Dict[str, Tuple[str, ...]] = {
    "lockset": ("lockset-unsync-write", "lockset-thread-leak",
                "lockset-no-join"),
    "purity": ("purity-host-sync", "purity-host-branch", "purity-np-call",
               "purity-impure-call", "purity-telemetry-call"),
    "resources": ("resource-unclosed", "resource-tempdir", "style-no-print"),
    "protocol": ("assert-in-protocol",),
    "transport": ("shm-no-pickle",),
    "deadlock": ("deadlock-lock-cycle", "deadlock-blocking-under-lock"),
    "contracts": ("contract-undocumented-knob", "contract-undocumented-metric",
                  "contract-undocumented-span", "contract-undocumented-site",
                  "contract-stale-doc-entry"),
    "escape": ("escape-leak-on-raise", "escape-double-release"),
    "jaxbound": ("jaxbound-unaccounted-transfer", "jaxbound-wide-wire",
                 "jaxbound-jit-in-hot-path"),
    "races": ("race-unlocked-shared-write", "race-inconsistent-lockset"),
    "wiretaint": ("taint-unbounded-wire-int", "taint-wire-str-in-path"),
}


def render_rule_catalog() -> str:
    """The generated rule-catalog table (committed into docs/analysis.md;
    ``--emit-rule-catalog`` regenerates it, and
    ``test_committed_catalogs_match_code`` pins freshness — the analyzer
    now eats its own cross-artifact dog food)."""
    lines = ["| pass | rule | what it flags |", "| --- | --- | --- |",
             "| driver | `syntax` | " + ALL_RULES["syntax"] + " |"]
    for pass_name in PER_FILE_PASSES + PROJECT_PASSES:
        for rule in RULES_BY_PASS[pass_name]:
            desc = " ".join(ALL_RULES[rule].split()).replace("|", "\\|")
            lines.append(f"| {pass_name} | `{rule}` | {desc} |")
    return "\n".join(lines)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    lineno: int
    symbol: str        # enclosing qualname / Class.attr — stable across moves
    message: str

    @property
    def key(self) -> str:
        return f"{self.path}:{self.rule}:{self.symbol}"

    def render(self) -> str:
        return f"{self.path}:{self.lineno}: {self.rule} [{self.symbol}] {self.message}"


# -- shared AST helpers -------------------------------------------------------

def build_parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def dotted_name(node: ast.AST) -> Optional[str]:
    """``jax.lax.scan`` for an Attribute/Name chain; None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    else:
        return None
    return ".".join(reversed(parts))


def keyword_arg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


class FileContext:
    """Everything a pass needs about one file, computed once."""

    def __init__(self, relpath: str, source: str, tree: ast.Module,
                 is_library: bool, cli_exempt: bool):
        self.relpath = relpath
        self.source = source
        self.tree = tree
        self.is_library = is_library
        self.cli_exempt = cli_exempt
        self.parents = build_parents(tree)
        self.module_aliases = self._collect_aliases(tree)
        self._defs_by_name: Optional[Dict[str, List[ast.AST]]] = None
        self._assign_aliases: Optional[Dict[str, ast.AST]] = None

    @staticmethod
    def _collect_aliases(tree: ast.Module) -> Dict[str, str]:
        """Local name -> imported module path (``np`` -> ``numpy``)."""
        out: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    out[alias.asname or alias.name.split(".")[0]] = alias.name
        return out

    @property
    def defs_by_name(self) -> Dict[str, List[ast.AST]]:
        """Module function defs by short name — shared by the lockset
        (thread-target resolution) and purity (root/callee resolution)
        passes; computed once per file."""
        if self._defs_by_name is None:
            defs: Dict[str, List[ast.AST]] = {}
            for node in ast.walk(self.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    defs.setdefault(node.name, []).append(node)
            self._defs_by_name = defs
        return self._defs_by_name

    @property
    def assign_aliases(self) -> Dict[str, ast.AST]:
        """``name = f`` / ``name = functools.partial(f, ...)`` bindings
        anywhere in the module, so ``kernel = partial(_kernel, ...);
        pallas_call(kernel)`` resolves.  Collisions across scopes keep the
        first binding — acceptable for a lint pass."""
        if self._assign_aliases is None:
            aliases: Dict[str, ast.AST] = {}
            for node in ast.walk(self.tree):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    continue
                value = node.value
                if isinstance(value, ast.Call):
                    fname = dotted_name(value.func) or ""
                    if fname.rsplit(".", 1)[-1] == "partial" and value.args:
                        value = value.args[0]
                    else:
                        continue
                if isinstance(value, (ast.Name, ast.Attribute, ast.Lambda)):
                    aliases.setdefault(node.targets[0].id, value)
            self._assign_aliases = aliases
        return self._assign_aliases

    def enclosing(self, node: ast.AST, *types) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, types):
                return cur
            cur = self.parents.get(cur)
        return None

    def qualname(self, node: ast.AST) -> str:
        """Dotted path of enclosing defs/classes, for stable finding keys."""
        parts: List[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            elif isinstance(cur, ast.Lambda):
                parts.append("<lambda>")
            cur = self.parents.get(cur)
        return ".".join(reversed(parts)) or "<module>"

    def finding(self, rule: str, node: ast.AST, message: str,
                symbol: Optional[str] = None) -> Finding:
        return Finding(rule, self.relpath, getattr(node, "lineno", 0),
                       symbol if symbol is not None else self.qualname(node),
                       message)


# -- suppression comments -----------------------------------------------------

_SUPPRESS_RE = re.compile(r"#\s*dmlclint:\s*disable=([A-Za-z0-9_,\- ]+)")


def suppressed_lines(source: str) -> Dict[int, Set[str]]:
    """line -> suppressed rule names.  A directive on a comment-only line
    also applies to the line below it, so rules can be silenced without
    pushing code past the line-length limit."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(source.splitlines(), 1):
        match = _SUPPRESS_RE.search(line)
        if not match:
            continue
        rules = {r.strip() for r in match.group(1).split(",") if r.strip()}
        out.setdefault(i, set()).update(rules)
        if line.lstrip().startswith("#"):
            out.setdefault(i + 1, set()).update(rules)
    return out


# -- per-file analysis --------------------------------------------------------

def default_passes(relpath: str) -> Tuple[str, ...]:
    """Per-file passes a path gets by default: the full set for library
    code, a named subset for EXTRA_DEEP files, syntax-only otherwise."""
    if relpath.startswith(LIBRARY_PREFIX):
        return PER_FILE_PASSES
    return EXTRA_DEEP.get(relpath, ())


def _pass_runners():
    from dmlc_core_tpu.analysis import (lockset, protocol, purity, resources,
                                        transport)

    return {"lockset": lockset.run, "purity": purity.run,
            "resources": resources.run, "protocol": protocol.run,
            "transport": transport.run}


def _parse_tree(source: str,
                relpath: str) -> Tuple[Optional[ast.Module],
                                       Optional[Finding]]:
    try:
        return ast.parse(source, relpath), None
    except SyntaxError as exc:
        return None, Finding("syntax", relpath, exc.lineno or 0, "<module>",
                             f"syntax error: {exc.msg}")


def _apply_suppressions(findings: List[Finding],
                        supp: Dict[int, Set[str]]) -> List[Finding]:
    return [f for f in findings
            if not ({"all", f.rule} & supp.get(f.lineno, set()))]


def _analyze_context(ctx: FileContext,
                     passes: Sequence[str]) -> List[Finding]:
    """Per-file passes over an already-parsed context."""
    findings: List[Finding] = []
    if passes:
        runners = _pass_runners()
        for name in passes:
            findings += runners[name](ctx)
    findings = _apply_suppressions(findings, suppressed_lines(ctx.source))
    return sorted(findings, key=lambda f: (f.lineno, f.rule, f.symbol))


def analyze_source(source: str, relpath: str = "<string>",
                   is_library: Optional[bool] = None,
                   passes: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run per-file passes over one source blob; returns sorted,
    unsuppressed findings.  ``passes`` selects a subset; ``is_library``
    keeps the historical override (True = every per-file pass, False =
    syntax only); by default the path decides (deep passes on
    ``dmlc_core_tpu/`` files and the EXTRA_DEEP subset on ``bench.py``)."""
    relpath = relpath.replace(os.sep, "/")
    if passes is None:
        if is_library is None:
            passes = default_passes(relpath)
        else:
            passes = PER_FILE_PASSES if is_library else ()
    tree, syntax = _parse_tree(source, relpath)
    if tree is None:
        return [syntax]
    lib = (is_library if is_library is not None
           else _project_scope(relpath))
    ctx = FileContext(relpath, source, tree, lib,
                      cli_exempt=relpath in CLI_EXEMPT)
    return _analyze_context(ctx, passes)


def repo_relpath(path: str, root: str = ROOT) -> str:
    """Repo-relative forward-slash path used in finding keys."""
    relpath = os.path.relpath(os.path.abspath(path), root)
    if relpath.startswith(".."):
        # out-of-tree file (e.g. a scratch checkout): anchor at the last
        # dmlc_core_tpu path component so library rules still apply
        parts = os.path.abspath(path).split(os.sep)
        if LIBRARY_PREFIX.rstrip("/") in parts:
            idx = len(parts) - 1 - parts[::-1].index(LIBRARY_PREFIX.rstrip("/"))
            relpath = os.sep.join(parts[idx:])
        else:
            relpath = os.path.basename(path)
    return relpath.replace(os.sep, "/")


def analyze_path(path: str, root: str = ROOT) -> List[Finding]:
    relpath = repo_relpath(path, root)
    source, err = _read_source(path, relpath)
    if source is None:
        return [err]
    return analyze_source(source, relpath)


def iter_python_files(paths: Optional[Sequence[str]] = None,
                      root: str = ROOT) -> Iterable[str]:
    targets = list(paths) if paths else [os.path.join(root, t)
                                         for t in TARGETS]
    for target in targets:
        if not os.path.exists(target):
            # a typo'd/renamed target must not pass the gate as
            # "0 files, 0 findings"
            raise FileNotFoundError(f"no such file or directory: {target}")
        if os.path.isfile(target):
            yield target
            continue
        for dirpath, _, files in os.walk(target):
            if "__pycache__" in dirpath:
                continue
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


# -- project passes -----------------------------------------------------------

def _project_scope(relpath: str) -> bool:
    """Files that ride in the project graph (library code + EXTRA_DEEP)."""
    return relpath.startswith(LIBRARY_PREFIX) or relpath in EXTRA_DEEP


def _read_source(path: str, relpath: str) -> Tuple[Optional[str],
                                                   Optional[Finding]]:
    try:
        # tokenize.open honors a PEP 263 `# -*- coding: ... -*-` line,
        # which plain utf-8 open would reject on legacy files
        with tokenize.open(path) as f:
            return f.read(), None
    except (UnicodeDecodeError, LookupError, SyntaxError) as exc:
        # undecodable bytes / bogus coding cookie: one finding, not a
        # traceback that kills the whole gate
        return None, Finding("syntax", relpath, 0, "<module>",
                             f"cannot decode source: {exc}")


def _project_contexts(extra: Optional[Dict[str, "FileContext"]] = None
                      ) -> List["FileContext"]:
    """Parse every project-scope file under the default targets into
    FileContexts, reusing already-parsed ones from ``extra``."""
    extra = extra or {}
    out: List[FileContext] = []
    seen: Set[str] = set()
    for path in iter_python_files(None):
        relpath = repo_relpath(path)
        if not _project_scope(relpath) or relpath in seen:
            continue
        seen.add(relpath)
        if relpath in extra:
            out.append(extra[relpath])
            continue
        source, err = _read_source(path, relpath)
        if source is None:
            continue  # the per-file sweep reports the decode error
        tree, syntax = _parse_tree(source, relpath)
        if tree is None:
            continue
        out.append(FileContext(relpath, source, tree, True,
                               cli_exempt=relpath in CLI_EXEMPT))
    return out


def _run_project_passes(selected: Set[str],
                        contexts: List["FileContext"]) -> List[Finding]:
    """Deadlock/contracts over the whole-project graph; suppression
    directives in the anchoring file apply exactly like per-file rules."""
    from dmlc_core_tpu.analysis import contracts as contracts_mod
    from dmlc_core_tpu.analysis import deadlock as deadlock_mod
    from dmlc_core_tpu.analysis import escape as escape_mod
    from dmlc_core_tpu.analysis import jaxbound as jaxbound_mod
    from dmlc_core_tpu.analysis import races as races_mod
    from dmlc_core_tpu.analysis import wiretaint as wiretaint_mod
    from dmlc_core_tpu.analysis.graph import ProjectGraph

    graph = ProjectGraph(contexts)
    findings: List[Finding] = []
    if "deadlock" in selected:
        findings += deadlock_mod.run_project(graph)
    if "contracts" in selected:
        findings += contracts_mod.run_project(
            graph, contracts_mod.load_docs(ROOT))
    if "escape" in selected:
        findings += escape_mod.run_project(graph)
    if "jaxbound" in selected:
        findings += jaxbound_mod.run_project(graph)
    if "races" in selected:
        findings += races_mod.run_project(graph)
    if "wiretaint" in selected:
        findings += wiretaint_mod.run_project(graph)
    supp_by_file: Dict[str, Dict[int, Set[str]]] = {}
    for ctx in contexts:
        supp_by_file[ctx.relpath] = suppressed_lines(ctx.source)
    out: List[Finding] = []
    for f in findings:
        supp = supp_by_file.get(f.path)
        if supp and ({"all", f.rule} & supp.get(f.lineno, set())):
            continue
        out.append(f)
    return sorted(out, key=lambda f: (f.path, f.lineno, f.rule, f.symbol))


# -- output formats -----------------------------------------------------------

def _github_annotation(f: Finding) -> str:
    # '::error' annotations render inline on the PR diff; commas/newlines
    # in properties must be %-escaped per the workflow-command grammar
    msg = f.message.replace("%", "%25").replace("\r", "%0D") \
        .replace("\n", "%0A")
    return (f"::error file={f.path},line={f.lineno},"
            f"title=dmlclint {f.rule} [{f.symbol}]::{msg}")


def _sarif_document(findings: Sequence[Finding]) -> Dict:
    rules = [{"id": rule,
              "shortDescription": {"text": ALL_RULES[rule]}}
             for rule in sorted(ALL_RULES)]
    results = [{
        "ruleId": f.rule,
        "level": "error",
        "message": {"text": f"[{f.symbol}] {f.message}"},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path,
                                     "uriBaseId": "SRCROOT"},
                "region": {"startLine": max(f.lineno, 1)},
            },
        }],
    } for f in findings]
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                    "master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "dmlclint",
                "informationUri": "docs/analysis.md",
                "rules": rules,
            }},
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": results,
        }],
    }


# -- CLI ----------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    """The dmlclint argument parser — shared with scripts/lint.py so the
    shim's view of paths/flags can never diverge from the driver's (e.g.
    argparse prefix abbreviations like ``--base`` for ``--baseline``)."""
    parser = argparse.ArgumentParser(
        prog="python -m dmlc_core_tpu.analysis",
        description="dmlclint: lockset / JAX-purity / resource / deadlock / "
                    "contract static analysis with a ratcheted baseline "
                    "(docs/analysis.md)")
    parser.add_argument("paths", nargs="*",
                        help="files or directories (default: repo targets)")
    parser.add_argument("--baseline",
                        default=os.path.join(ROOT, "analysis_baseline.json"),
                        help="baseline file (default: %(default)s)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding as new (ignore baseline)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline from current findings, "
                             "keeping existing justifications")
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="also print baselined findings")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--pass", dest="passes", action="append",
                        metavar="PASS",
                        help="run only the named pass(es) "
                             f"({', '.join(PER_FILE_PASSES + PROJECT_PASSES)}"
                             "; repeat or comma-separate; default: all). "
                             "Project passes always analyze the whole repo")
    parser.add_argument("--format", dest="fmt", default="text",
                        choices=("text", "github", "sarif"),
                        help="finding output format: text (default), github "
                             "workflow annotations, or a SARIF 2.1.0 "
                             "document")
    parser.add_argument("--output", metavar="FILE",
                        help="also write the SARIF document here (works "
                             "with any --format; with --format sarif it "
                             "replaces stdout output)")
    parser.add_argument("--jobs", type=int, default=1, metavar="N",
                        help="fan the per-file passes out over N worker "
                             "processes (default: 1 = serial); findings "
                             "are reassembled in file order, so output "
                             "is byte-identical to a serial run. Project "
                             "passes always run sequentially")
    parser.add_argument("--emit-knob-catalog", action="store_true",
                        help="print the generated DMLC_* knob catalog "
                             "markdown table and exit")
    parser.add_argument("--emit-span-catalog", action="store_true",
                        help="print the generated telemetry span catalog "
                             "markdown table and exit")
    parser.add_argument("--emit-rule-catalog", action="store_true",
                        help="print the generated rule catalog markdown "
                             "table (committed in docs/analysis.md) and "
                             "exit")
    return parser


def _selected_passes(args) -> Tuple[Set[str], bool]:
    """(selected pass names, was --pass given explicitly)."""
    every = set(PER_FILE_PASSES) | set(PROJECT_PASSES)
    if not args.passes:
        return every, False
    out: Set[str] = set()
    for spec in args.passes:
        for name in spec.split(","):
            name = name.strip()
            if not name:
                continue
            if name not in every:
                raise ValueError(
                    f"unknown pass {name!r} (choose from "
                    f"{', '.join(sorted(every))})")
            out.add(name)
    if not out:
        # `--pass ""` (an unset shell variable in CI) must not silently
        # disable every rule and green-light the gate
        raise ValueError("--pass given but names no pass (choose from "
                         f"{', '.join(sorted(every))})")
    return out, True


def _scan_file_job(job: Tuple[str, Set[str]]) -> Tuple[str, List[Finding]]:
    """One ``--jobs`` unit of work: read/parse a file and run its per-file
    passes.  Module-level (not a closure) so process pools can pickle it;
    Finding is a frozen dataclass of primitives, so results ship back
    cheaply.  ASTs never cross the process boundary — the project stage
    re-parses its own contexts."""
    path, selected = job
    relpath = repo_relpath(path)
    source, err = _read_source(path, relpath)
    if source is None:
        return relpath, [err]
    per_file = [p for p in default_passes(relpath) if p in selected]
    tree, syntax = _parse_tree(source, relpath)
    if tree is None:
        return relpath, [syntax]
    if not per_file:
        return relpath, []
    ctx = FileContext(relpath, source, tree, _project_scope(relpath),
                      cli_exempt=relpath in CLI_EXEMPT)
    return relpath, _analyze_context(ctx, per_file)


def main(argv: Optional[Sequence[str]] = None) -> int:
    from dmlc_core_tpu.analysis import baseline as baseline_mod

    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule in sorted(ALL_RULES):
            print(f"{rule:32s} {ALL_RULES[rule]}")
        return 0

    try:
        selected, explicit_passes = _selected_passes(args)
    except ValueError as exc:
        print(f"dmlclint: {exc}", file=sys.stderr)
        return 2

    if args.emit_rule_catalog:
        # no graph needed: the rule catalog is pure registry truth
        print(render_rule_catalog())
        return 0

    if args.emit_knob_catalog or args.emit_span_catalog:
        from dmlc_core_tpu.analysis import contracts as contracts_mod
        from dmlc_core_tpu.analysis.graph import ProjectGraph

        graph = ProjectGraph(_project_contexts())
        if args.emit_knob_catalog:
            print(contracts_mod.render_knob_catalog(graph))
        if args.emit_span_catalog:
            print(contracts_mod.render_span_catalog(graph))
        return 0

    try:
        files = list(iter_python_files(args.paths or None))
    except FileNotFoundError as exc:
        print(f"dmlclint: {exc}", file=sys.stderr)
        return 2
    # project passes: on by default for the unscoped gate run; a scoped
    # (path-argument) run skips them unless --pass asks — and then the
    # graph is still built over the whole repo, because a partial call
    # graph would under-approximate held-lock sets and doc obligations
    project_selected = selected & set(PROJECT_PASSES)
    project_ran = bool(project_selected
                       and (not args.paths or explicit_passes))

    findings: List[Finding] = []
    project_findings: List[Finding] = []
    jobs = max(1, args.jobs or 1)
    if jobs > 1 and len(files) > 1:
        # fan the per-file stage out.  pool.map submits every file up
        # front and preserves input order, so the parent can run the
        # (sequential, graph-bound) project passes WHILE workers chew
        # the per-file passes, then drain results — same findings, same
        # order, byte-identical output to a serial run
        import concurrent.futures

        with concurrent.futures.ProcessPoolExecutor(
                max_workers=jobs) as pool:
            per_file_results = pool.map(
                _scan_file_job, [(p, selected) for p in files],
                chunksize=max(1, len(files) // (jobs * 4)))
            if project_ran:
                project_findings = _run_project_passes(
                    project_selected, _project_contexts())
            for _relpath, batch in per_file_results:
                findings += batch
    else:
        parsed: Dict[str, FileContext] = {}
        for path in files:
            relpath = repo_relpath(path)
            source, err = _read_source(path, relpath)
            if source is None:
                findings.append(err)
                continue
            per_file = [p for p in default_passes(relpath)
                        if p in selected]
            tree, syntax = _parse_tree(source, relpath)
            if tree is None:
                findings.append(syntax)
                continue
            if per_file or _project_scope(relpath):
                # context built once: shared by the per-file passes here
                # and the project passes below (no re-parse)
                ctx = FileContext(relpath, source, tree,
                                  _project_scope(relpath),
                                  cli_exempt=relpath in CLI_EXEMPT)
                findings += _analyze_context(ctx, per_file)
                if _project_scope(relpath):
                    parsed[relpath] = ctx
        if project_ran:
            project_findings = _run_project_passes(
                project_selected, _project_contexts(extra=parsed))
    findings += project_findings

    try:
        # --no-baseline only changes *reporting*; a rewrite still loads the
        # file, else justifications (and out-of-scope keys in a path-scoped
        # run) would be silently destroyed
        load_it = args.write_baseline or not args.no_baseline
        previous = baseline_mod.load(args.baseline) if load_it else {}
    except ValueError as exc:
        print(f"dmlclint: {exc}", file=sys.stderr)
        return 2
    # rules whose passes actually RAN this invocation: per-file passes in
    # the selection always run; project-pass rules only count when the
    # project passes ran (a plain scoped run skips them, so their baseline
    # entries were never recomputed and must survive untouched).  Project
    # passes, when they DO run, always analyze the whole repo — so their
    # entries are recomputed regardless of any path scope.
    ran_passes = {name for name in selected
                  if name in PER_FILE_PASSES
                  or (name in PROJECT_PASSES and project_ran)}
    ran_rules = {rule for name in ran_passes
                 for rule in RULES_BY_PASS[name]} | {"syntax"}
    project_ran_rules = {rule for name in ran_passes
                         if name in PROJECT_PASSES
                         for rule in RULES_BY_PASS[name]}
    analyzed = {repo_relpath(p) for p in files} if args.paths else None

    def _rule_of_key(key: str) -> str:
        parts = key.split(":")
        return parts[1] if len(parts) >= 3 else ""

    def _recomputed(key: str) -> bool:
        """Was this baseline entry's finding recomputed by THIS run?  An
        entry whose rule no longer exists belongs to no pass and counts
        as recomputed: the rewrite is the prune path for dead-rule
        garbage, and the stale report must keep naming it."""
        rule = _rule_of_key(key)
        if rule not in ALL_RULES:
            return True
        if rule not in ran_rules:
            return False
        if rule in project_ran_rules:
            return True  # whole-repo pass: path scope does not shield it
        return analyzed is None or key.split(":", 1)[0] in analyzed

    if args.write_baseline:
        # a rewrite regenerates only recomputed keys; everything else is
        # kept verbatim (files a path-scoped run never analyzed, passes
        # that never ran)
        keep = {k: v for k, v in previous.items() if not _recomputed(k)}
        baseline_mod.save(args.baseline, findings, previous, keep=keep)
        print(f"dmlclint: baseline written to {args.baseline} "
              f"({len(findings)} finding(s), {len(keep)} out-of-scope "
              f"entries kept)")
        return 0

    new, baselined, stale = baseline_mod.partition(findings, previous)
    # a non-recomputed entry is not "fixed or moved" — don't advise
    # pruning it; recomputed ones (incl. dead-rule garbage) stay reported
    stale = [k for k in stale if _recomputed(k)]

    summary = (f"dmlclint: {len(files)} files, {len(new)} new finding(s), "
               f"{len(baselined)} baselined, {len(stale)} stale")
    if args.output:
        # the SARIF artifact is writable from ANY format mode, so one gate
        # run can render annotations AND produce the machine-readable
        # record (the CI analysis job relies on this)
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(json.dumps(_sarif_document(new), indent=2) + "\n")
        print(f"dmlclint: SARIF written to {args.output}")
    if args.fmt == "sarif":
        if not args.output:
            # the document owns stdout; keep it parseable
            print(json.dumps(_sarif_document(new), indent=2))
            print(summary, file=sys.stderr)
    else:
        for f in new:
            if args.fmt == "github":
                print(_github_annotation(f))
            print(f.render())
        if args.verbose:
            counts: Dict[str, int] = {}
            for f in baselined:
                key = baseline_mod._instance_key(f.key, counts)
                note = previous.get(key, previous.get(f.key, ""))
                print(f"{f.render()}  (baselined: {note})")
    if stale:
        print(f"dmlclint: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} (fixed or moved — prune "
              f"with --write-baseline):", file=sys.stderr)
        for key in stale:
            print(f"  {key}", file=sys.stderr)
    if args.fmt != "sarif" or args.output:
        print(summary)
    return 1 if new else 0
