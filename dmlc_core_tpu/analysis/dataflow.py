"""The dataflow engine under the interprocedural passes (pass 8+).

The PR 8 graph core answers "who calls whom"; this module answers "what
happens to a value along every path through one function" — including the
paths the per-file passes cannot see: the exception edge out of every
statement that can raise.  A resource acquired on line 10 and released on
line 14 is leak-free only if nothing between them can raise, or the raise
lands in a handler/``finally``/``with`` that still releases — exactly the
property a statement-level CFG with exception edges makes checkable.

What is built, per function:

- a **statement-level CFG**: one node per simple statement, with normal
  edges (sequence, branch, loop) and **exception edges** from every
  statement that can raise to the innermost handler — or to the
  function's exceptional exit when no catch-all handler encloses it;
- ``try``/``finally`` and ``with`` are modeled with their real edge
  semantics: a ``finally`` body is instantiated once per entry mode
  (normal fall-through, exception propagation, ``return``/``break``/
  ``continue`` jump), so states never smear between modes; a ``with``
  statement contributes a synthetic exit node on both the normal and the
  exception edge (that is what makes ``with`` safe by construction);
- a generic **forward may-analysis** (:func:`run_forward`): the client
  pass supplies per-statement transfer functions returning separate
  normal-edge and exception-edge output states; the engine iterates to a
  fixpoint and exposes the joined state at every node and at the three
  exits (normal return, exceptional, and each node's contribution).

Soundness caveats (inherited by every pass built on top; see
docs/analysis.md): the raise model is syntactic — a statement "can raise"
when it contains a call (logging-family calls exempt), subscript, raise,
or assert; ``except Exception``/``BaseException``/bare are treated as
catch-alls (an async ``KeyboardInterrupt`` between acquire and handler is
out of scope); aliasing through containers and attribute round-trips is
invisible.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from dmlc_core_tpu.analysis.driver import dotted_name

__all__ = ["CFG", "Node", "build_cfg", "run_forward", "stmt_can_raise",
           "WITH_EXIT"]

# marker object: a node whose ``stmt`` is (WITH_EXIT, with_node) runs the
# __exit__ of every context manager of ``with_node`` — the client's
# transfer function applies the releases there
WITH_EXIT = "with-exit"

# calls that are contractually non-raising for the purposes of the raise
# model: the logging family swallows handler errors by design, and
# treating every ``logger.info`` between acquire and release as a leak
# edge would drown the signal (documented soundness tradeoff)
_NONRAISING_ROOTS = {"logger", "logging", "warnings"}
_NONRAISING_PREFIXES = ("log_",)


class Node:
    """One CFG node.  ``stmt`` is the AST statement (or a (WITH_EXIT, n)
    pair, or None for entry/exit); ``succ`` are normal-edge successor ids,
    ``exc_succ`` exception-edge successor ids."""

    __slots__ = ("idx", "stmt", "succ", "exc_succ")

    def __init__(self, idx: int, stmt) -> None:
        self.idx = idx
        self.stmt = stmt
        self.succ: List[int] = []
        self.exc_succ: List[int] = []


class CFG:
    def __init__(self) -> None:
        self.nodes: List[Node] = []
        self.entry = self._new(None)
        self.exit = self._new(None)        # normal return / fall-off
        self.raise_exit = self._new(None)  # an exception leaves the function

    def _new(self, stmt) -> int:
        node = Node(len(self.nodes), stmt)
        self.nodes.append(node)
        return node.idx

    def add(self, stmt) -> int:
        return self._new(stmt)

    def edge(self, a: int, b: int) -> None:
        if b not in self.nodes[a].succ:
            self.nodes[a].succ.append(b)

    def exc_edge(self, a: int, b: int) -> None:
        if b not in self.nodes[a].exc_succ:
            self.nodes[a].exc_succ.append(b)


def _call_is_nonraising(call: ast.Call) -> bool:
    name = dotted_name(call.func) or ""
    root = name.split(".")[0]
    short = name.rsplit(".", 1)[-1]
    return (root in _NONRAISING_ROOTS
            or any(short.startswith(p) for p in _NONRAISING_PREFIXES))


def stmt_can_raise(stmt: ast.AST) -> bool:
    """Syntactic raise model: calls (minus the logging family), explicit
    raise/assert, and subscripts can raise; plain name/attribute moves and
    type annotations cannot.  Nested function/class bodies execute at
    their own call time — a ``def`` statement only evaluates its
    decorators and argument defaults."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        roots: List[ast.AST] = list(getattr(stmt, "decorator_list", []))
        args = getattr(stmt, "args", None)
        if args is not None:
            roots += list(args.defaults) + [d for d in args.kw_defaults
                                            if d is not None]
        roots += list(getattr(stmt, "bases", []))
        return any(stmt_can_raise(r) for r in roots)
    stack = [stmt]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)) and node is not stmt:
            continue
        if isinstance(node, ast.AnnAssign):
            # the annotation itself never runs user code worth modeling
            stack.append(node.target)
            if node.value is not None:
                stack.append(node.value)
            continue
        if isinstance(node, (ast.Raise, ast.Assert, ast.Subscript,
                             ast.Await)):
            return True
        if isinstance(node, ast.Call) and not _call_is_nonraising(node):
            return True
        stack.extend(ast.iter_child_nodes(node))
    return False


def _is_catch_all(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    names: List[str] = []
    if isinstance(handler.type, ast.Tuple):
        names = [dotted_name(e) or "" for e in handler.type.elts]
    else:
        names = [dotted_name(handler.type) or ""]
    return any(n.rsplit(".", 1)[-1] in ("Exception", "BaseException")
               for n in names)


class _Frame:
    """One enclosing ``try``-with-``finally`` or ``with`` an abrupt jump
    (return/break/continue) must run on its way out."""

    __slots__ = ("kind", "payload", "exc_target")

    def __init__(self, kind: str, payload, exc_target: int) -> None:
        self.kind = kind          # "finally" | "with"
        self.payload = payload    # stmt list | With node
        self.exc_target = exc_target  # exc target OUTSIDE this frame


class _Builder:
    def __init__(self, cfg: CFG, can_raise: Callable[[ast.AST], bool]):
        self.cfg = cfg
        self.can_raise = can_raise

    # -- plumbing -------------------------------------------------------------

    def _link(self, preds: Sequence[int], node: int) -> None:
        for p in preds:
            self.cfg.edge(p, node)

    def _unwind(self, preds: List[int], frames: List[_Frame],
                upto: int) -> List[int]:
        """Run the finally/with frames above depth ``upto`` (innermost
        first) for an abrupt jump; returns the preds after the unwind."""
        for frame in reversed(frames[upto:]):
            if frame.kind == "with":
                node = self.cfg.add((WITH_EXIT, frame.payload))
                self._link(preds, node)
                preds = [node]
            else:
                preds = self._emit_block(frame.payload, preds,
                                         frame.exc_target, None, [],
                                         frames_base=0)
        return preds

    # -- statement emission ---------------------------------------------------

    def _emit_stmt(self, stmt: ast.AST, preds: List[int], exc: int,
                   loop: Optional[Tuple[int, int, int]],
                   frames: List[_Frame], frames_base: int) -> List[int]:
        cfg = self.cfg
        if isinstance(stmt, ast.Return):
            node = cfg.add(stmt)
            self._link(preds, node)
            if self.can_raise(stmt):
                cfg.exc_edge(node, exc)
            out = self._unwind([node], frames, frames_base)
            self._link(out, cfg.exit)
            return []
        if isinstance(stmt, ast.Raise):
            node = cfg.add(stmt)
            self._link(preds, node)
            cfg.exc_edge(node, exc)
            return []
        if isinstance(stmt, (ast.Break, ast.Continue)):
            node = cfg.add(stmt)
            self._link(preds, node)
            if loop is not None:
                head, after, loop_base = loop
                out = self._unwind([node], frames, loop_base)
                self._link(out, after if isinstance(stmt, ast.Break)
                           else head)
            return []
        if isinstance(stmt, (ast.If,)):
            test = cfg.add(stmt)  # the test expression evaluates here
            self._link(preds, test)
            if self.can_raise(stmt.test):
                cfg.exc_edge(test, exc)
            out = self._emit_block(stmt.body, [test], exc, loop, frames,
                                   frames_base)
            if stmt.orelse:
                out += self._emit_block(stmt.orelse, [test], exc, loop,
                                        frames, frames_base)
            else:
                out += [test]
            return out
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            head = cfg.add(stmt)  # test / next(iter) evaluates here
            self._link(preds, head)
            header_raises = (self.can_raise(stmt.test)
                             if isinstance(stmt, ast.While)
                             else True)  # iteration can always raise
            if header_raises:
                cfg.exc_edge(head, exc)
            after = cfg.add(None)  # loop exit join point
            body_out = self._emit_block(
                stmt.body, [head], exc,
                (head, after, len(frames)), frames, frames_base)
            self._link(body_out, head)  # back edge
            self._link([head], after)   # loop condition false / exhausted
            if stmt.orelse:
                return self._emit_block(stmt.orelse, [after], exc, loop,
                                        frames, frames_base)
            return [after]
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            # context expressions evaluate unprotected, left to right
            node = cfg.add(stmt)
            self._link(preds, node)
            if any(self.can_raise(item.context_expr)
                   for item in stmt.items):
                cfg.exc_edge(node, exc)
            # exception inside the body runs __exit__ then propagates
            exc_exit = cfg.add((WITH_EXIT, stmt))
            cfg.edge(exc_exit, exc)
            frames.append(_Frame("with", stmt, exc))
            body_out = self._emit_block(stmt.body, [node], exc_exit, loop,
                                        frames, frames_base)
            frames.pop()
            norm_exit = cfg.add((WITH_EXIT, stmt))
            self._link(body_out, norm_exit)
            return [norm_exit]
        if isinstance(stmt, ast.Try):
            return self._emit_try(stmt, preds, exc, loop, frames,
                                  frames_base)
        # simple statement (incl. nested def/class: binding only)
        node = cfg.add(stmt)
        self._link(preds, node)
        if self.can_raise(stmt):
            cfg.exc_edge(node, exc)
        return [node]

    def _emit_try(self, stmt: ast.Try, preds: List[int], exc: int,
                  loop: Optional[Tuple[int, int, int]],
                  frames: List[_Frame], frames_base: int) -> List[int]:
        cfg = self.cfg
        has_finally = bool(stmt.finalbody)
        # where an exception that the handlers do not catch goes: through
        # the finally (exceptional instance) to the outer target
        if has_finally:
            fin_exc_entry = cfg.add(None)
            fin_exc_out = self._emit_block(stmt.finalbody, [fin_exc_entry],
                                           exc, None, [], 0)
            self._link(fin_exc_out, exc)
            unhandled = fin_exc_entry
            frames.append(_Frame("finally", stmt.finalbody, exc))
        else:
            unhandled = exc

        # exception dispatch point for the body: every handler may match,
        # and unless one is a catch-all the exception may also escape
        dispatch = cfg.add(None)
        if any(_is_catch_all(h) for h in stmt.handlers):
            pass
        else:
            cfg.edge(dispatch, unhandled)

        body_out = self._emit_block(stmt.body, preds, dispatch, loop,
                                    frames, frames_base)
        if stmt.orelse:
            body_out = self._emit_block(stmt.orelse, body_out, dispatch,
                                        loop, frames, frames_base)

        handler_outs: List[int] = []
        for handler in stmt.handlers:
            # the handler body's own exceptions go through the finally to
            # the OUTER target
            handler_outs += self._emit_block(handler.body, [dispatch],
                                             unhandled, loop, frames,
                                             frames_base)
        if has_finally:
            frames.pop()
            fin_entry = cfg.add(None)
            self._link(body_out, fin_entry)
            self._link(handler_outs, fin_entry)
            return self._emit_block(stmt.finalbody, [fin_entry], exc,
                                    loop, frames, frames_base)
        return body_out + handler_outs

    def _emit_block(self, stmts: Sequence[ast.AST], preds: List[int],
                    exc: int, loop, frames: List[_Frame],
                    frames_base: int) -> List[int]:
        for stmt in stmts:
            preds = self._emit_stmt(stmt, list(preds), exc, loop, frames,
                                    frames_base)
            if not preds:
                break  # return/raise/break/continue ended the block
        return preds


def build_cfg(fn_node: ast.AST,
              can_raise: Callable[[ast.AST], bool] = stmt_can_raise) -> CFG:
    """CFG for one function body.  ``can_raise`` is the raise model —
    override to tighten/loosen which statements get exception edges."""
    cfg = CFG()
    builder = _Builder(cfg, can_raise)
    body = fn_node.body if isinstance(fn_node.body, list) else [fn_node.body]
    out = builder._emit_block(body, [cfg.entry], cfg.raise_exit, None,
                              [], 0)
    builder._link(out, cfg.exit)
    return cfg


def run_forward(cfg: CFG, init, transfer, join):
    """Forward may-analysis to fixpoint.

    ``init`` is the entry state; ``transfer(node, state) -> (normal_out,
    exc_out)`` applies one node's effect (exc_out flows along exception
    edges — release statements report their post-state there, acquisitions
    their pre-state, so a failing ``close()`` still counts as released and
    a failing ``open()`` never counts as acquired); ``join(a, b) -> state``
    merges states at join points.  Returns ``{node_idx: in_state}``.
    """
    in_states: Dict[int, object] = {cfg.entry: init}
    work: List[int] = [cfg.entry]
    seen_order: Set[int] = {cfg.entry}
    while work:
        idx = work.pop(0)
        seen_order.discard(idx)
        node = cfg.nodes[idx]
        state = in_states.get(idx)
        if state is None:
            continue
        normal_out, exc_out = transfer(node, state)
        for succ, out in ([(s, normal_out) for s in node.succ]
                          + [(s, exc_out) for s in node.exc_succ]):
            prev = in_states.get(succ)
            merged = out if prev is None else join(prev, out)
            if prev is None or merged != prev:
                in_states[succ] = merged
                if succ not in seen_order:
                    seen_order.add(succ)
                    work.append(succ)
    return in_states
