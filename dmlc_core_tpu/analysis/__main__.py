import sys

from dmlc_core_tpu.analysis.driver import main

if __name__ == "__main__":
    sys.exit(main())
