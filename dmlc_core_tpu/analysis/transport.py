"""Transport pass: the shared-memory parse transport must never pickle.

The whole point of :mod:`dmlc_core_tpu.data.parse_proc` is that RowBlock
array payloads cross the process boundary as raw shared-memory bytes — a
``pickle.dumps`` (or any serializer cousin) on that path silently
reintroduces the copy+encode cost the backend exists to remove, and it
does so off the profiler's radar (the executor's own metadata pickling is
tiny and unavoidable; payload pickling is neither).

Rule ``shm-no-pickle`` flags, **only in the shm transport module(s)**:

- ``import pickle`` / ``from pickle import ...`` (and cPickle/_pickle,
  dill, cloudpickle, marshal);
- any call through those modules (``pickle.dumps(x)``, aliased or not);
- ``ForkingPickler`` usage (multiprocessing's payload pickler).
"""

from __future__ import annotations

import ast
from typing import List

from dmlc_core_tpu.analysis.driver import FileContext, Finding, dotted_name

__all__ = ["run", "SHM_TRANSPORT_PATHS"]

# modules whose array payloads are contractually shm-only
SHM_TRANSPORT_PATHS = {"dmlc_core_tpu/data/parse_proc.py"}

_BANNED_MODULES = {"pickle", "cPickle", "_pickle", "dill", "cloudpickle",
                   "marshal"}
_BANNED_NAMES = {"ForkingPickler"}

RULE = "shm-no-pickle"


def run(ctx: FileContext) -> List[Finding]:
    if ctx.relpath not in SHM_TRANSPORT_PATHS:
        return []
    findings: List[Finding] = []

    def flag(node: ast.AST, what: str) -> None:
        findings.append(ctx.finding(
            RULE, node,
            f"{what} on the shm transport path: array payloads must cross "
            "process boundaries as raw shared-memory bytes, not pickled "
            "objects"))

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.split(".")[0] in _BANNED_MODULES:
                    flag(node, f"import of {alias.name!r}")
        elif isinstance(node, ast.ImportFrom):
            root = (node.module or "").split(".")[0]
            if root in _BANNED_MODULES:
                flag(node, f"import from {node.module!r}")
            else:
                for alias in node.names:
                    if alias.name in _BANNED_NAMES:
                        flag(node, f"import of {alias.name!r}")
        elif isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            if not name:
                continue
            root = name.split(".")[0]
            resolved = ctx.module_aliases.get(root, root).split(".")[0]
            if resolved in _BANNED_MODULES:
                flag(node, f"call to {name!r}")
            elif name.rsplit(".", 1)[-1] in _BANNED_NAMES:
                flag(node, f"call to {name!r}")
    return findings
