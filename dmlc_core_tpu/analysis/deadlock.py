"""Pass 6 — deadlock: interprocedural lock-order + blocking-under-lock.

The per-file lockset pass checks that a class is *consistent* with its own
lock; it cannot see that the scheduler's RLock, the flight recorder's dump
lock and the parse pool's module lock form an order — or a cycle — because
the acquisitions live in different files connected only by calls.  This
pass walks the :class:`~dmlc_core_tpu.analysis.graph.ProjectGraph`:

``deadlock-lock-cycle``
    For every lock *declaration* (``self.X = threading.Lock()`` in a class,
    ``X = threading.Lock()`` at module level), every acquisition site
    (``with <lock>:``) records the set of locks already held there — both
    lexically and through the call graph (holding L and calling a function
    that transitively acquires M counts as an L→M ordering).  The global
    lock-order graph's cycles are deadlocks waiting for the right thread
    interleaving: thread 1 takes A then B, thread 2 takes B then A.  A
    single-lock cycle (re-acquiring a non-reentrant ``Lock`` you already
    hold) is the degenerate case and deadlocks *every* time; re-acquiring
    an ``RLock``/``Condition`` (reentrant by construction) is not flagged.

``deadlock-blocking-under-lock``
    An unbounded blocking call made while at least one lock is held — the
    other half of most real wedges: the lock holder parks forever, every
    other thread piles up behind the lock.  Flagged calls: ``queue.get()``
    / ``.join()`` / ``.result()`` / ``.wait()`` without a timeout, and
    socket-style ``.recv*()``/``.accept()``.  ``Condition.wait()`` under
    its *own* condition is the documented idiom (wait releases the lock it
    guards) and is exempt — but holding any *other* lock across the wait
    still blocks, and is flagged.  The check is interprocedural: holding a
    lock and calling a function whose transitive body blocks is the same
    bug one hop removed (`pool.submit(...).result()` under the pool lock
    was a live example in this repo).

Lock identity is **per class attribute / per module global**, not per
instance — the RacerX convention: two instances of one class map to one
order node.  That direction of unsoundness (a "cycle" between two distinct
instances cannot actually deadlock) is what the suppression machinery is
for; the converse (instance-blind analysis still catches every same-
instance inversion) is why it pays rent.  Acquisitions the pass can see
are ``with`` statements; bare ``.acquire()`` calls are out of scope (the
codebase uses ``with`` exclusively — keep it that way).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from dmlc_core_tpu.analysis.driver import (Finding, dotted_name, keyword_arg)
from dmlc_core_tpu.analysis.graph import (FunctionInfo, ModuleInfo,
                                          ProjectGraph, walk_in_scope)
from dmlc_core_tpu.analysis.lockset import LOCK_TYPES

__all__ = ["run_project", "BLOCKING_METHODS"]

# lock factories whose self-re-acquisition is NOT an unconditional
# deadlock, so self-edges in the order graph are skipped: RLock and
# Condition (default inner lock is an RLock) are reentrant for the holding
# thread; counting Semaphores legitimately acquire more than once while
# the count allows (the initial value is invisible statically).  Edges
# between *distinct* locks keep full cycle analysis for all kinds.
_REENTRANT_FACTORIES = {"RLock", "Condition", "Semaphore",
                        "BoundedSemaphore"}

# method name -> index of the positional timeout parameter (None = the call
# has no timeout form and is always unbounded)
BLOCKING_METHODS: Dict[str, Optional[int]] = {
    "get": 1,       # queue.Queue.get(block, timeout)
    "join": 0,      # Thread.join(timeout) / Process.join(timeout)
    "wait": 0,      # Condition/Event.wait(timeout), Popen.wait(timeout)
    "result": 0,    # Future.result(timeout)
    "recv": None, "recvall": None, "recvint": None, "recvstr": None,
    "recv_into": None, "accept": None,
}

# join() receivers that are never threads (mirrors lockset._has_join)
_NON_THREAD_RECEIVERS = {"os.path", "posixpath", "ntpath", "str"}


@dataclasses.dataclass(frozen=True)
class LockDecl:
    lock_id: str       # "mod.Class.attr" / "mod.name"
    relpath: str
    lineno: int
    reentrant: bool


@dataclasses.dataclass(frozen=True)
class _Acquire:
    lock: str
    held: FrozenSet[str]
    lineno: int


@dataclasses.dataclass(frozen=True)
class _Blocking:
    desc: str          # "queue.get() with no timeout" etc.
    relpath: str
    lineno: int
    qualname: str
    receiver_lock: Optional[str]  # lock id when the receiver IS a lock


@dataclasses.dataclass
class _Summary:
    fn: FunctionInfo
    acquires: List[_Acquire]
    blocking: List[Tuple[ast.Call, _Blocking, FrozenSet[str]]]
    calls: List[Tuple[ast.Call, FunctionInfo, FrozenSet[str]]]


# -- lock declaration / expression recognition --------------------------------

def _lock_factory_kind(value: ast.AST) -> Optional[str]:
    """``Lock``/``RLock``/... when ``value`` constructs a threading lock."""
    if not isinstance(value, ast.Call):
        return None
    name = dotted_name(value.func) or ""
    short = name.rsplit(".", 1)[-1]
    if short in LOCK_TYPES and (name == short or name == f"threading.{short}"):
        return short
    return None


def _collect_locks(project: ProjectGraph) -> Dict[str, LockDecl]:
    """Every lock declaration in the project, keyed by lock id."""
    decls: Dict[str, LockDecl] = {}

    def add(lock_id: str, mod: ModuleInfo, node: ast.AST,
            kind: str) -> None:
        decls.setdefault(lock_id, LockDecl(
            lock_id, mod.relpath, getattr(node, "lineno", 0),
            kind in _REENTRANT_FACTORIES))

    for mod in project.modules.values():
        for stmt in mod.ctx.tree.body:  # module-level locks
            if not isinstance(stmt, ast.Assign):
                continue
            kind = _lock_factory_kind(stmt.value)
            if kind is None:
                continue
            for target in stmt.targets:
                if isinstance(target, ast.Name):
                    add(f"{mod.modname}.{target.id}", mod, stmt, kind)
        for cls in mod.classes.values():  # self.X = threading.Lock()
            for node in ast.walk(cls.node):
                if not isinstance(node, ast.Assign):
                    continue
                kind = _lock_factory_kind(node.value)
                if kind is None:
                    continue
                for target in node.targets:
                    if (isinstance(target, ast.Attribute)
                            and isinstance(target.value, ast.Name)
                            and target.value.id in ("self", "cls")):
                        add(f"{mod.modname}.{cls.name}.{target.attr}",
                            mod, node, kind)
                    elif isinstance(target, ast.Name):
                        add(f"{mod.modname}.{cls.name}.{target.id}",
                            mod, node, kind)
    return decls


def _lock_of_expr(expr: ast.AST, fn: FunctionInfo,
                  decls: Dict[str, LockDecl]) -> Optional[str]:
    """Lock id an expression refers to, seen from inside ``fn``."""
    name = dotted_name(expr)
    if not name:
        return None
    mod = fn.module
    parts = name.split(".")
    if parts[0] in ("self", "cls") and len(parts) == 2 and fn.cls is not None:
        lock_id = f"{mod.modname}.{fn.cls.name}.{parts[1]}"
        return lock_id if lock_id in decls else None
    if len(parts) == 1:  # module-level lock by bare name
        lock_id = f"{mod.modname}.{parts[0]}"
        return lock_id if lock_id in decls else None
    # mod_alias._lock / pkg.mod._lock via imports
    if parts[0] in mod.import_mods:
        base = mod.import_mods[parts[0]]
        lock_id = ".".join([base] + parts[1:])
        return lock_id if lock_id in decls else None
    return None


# -- per-function scan --------------------------------------------------------

def _timeout_given(call: ast.Call, positional_idx: Optional[int]) -> bool:
    timeout = keyword_arg(call, "timeout")
    if timeout is not None:
        return not (isinstance(timeout, ast.Constant)
                    and timeout.value is None)
    if positional_idx is not None and len(call.args) > positional_idx:
        return True
    return False


def _classify_blocking(call: ast.Call, fn: FunctionInfo,
                       decls: Dict[str, LockDecl]) -> Optional[_Blocking]:
    func = call.func
    if not isinstance(func, ast.Attribute):
        return None
    method = func.attr
    if method not in BLOCKING_METHODS:
        return None
    receiver = dotted_name(func.value)
    if method == "join":
        # ",".join(xs) / os.path.join(...): an argument-taking join is the
        # string/path form, a thread join's only argument is a timeout
        if call.args or isinstance(func.value, ast.Constant):
            return None
        if receiver in _NON_THREAD_RECEIVERS:
            return None
    if method == "get" and (call.args or call.keywords):
        # dict.get(key[, default]) takes positionals; queue.get's only
        # useful arguments are block/timeout — treat any argument form
        # other than a bare timeout as bounded/not-a-queue
        if not _timeout_given(call, 1):
            return None
    if _timeout_given(call, BLOCKING_METHODS[method]):
        return None
    receiver_lock = (_lock_of_expr(func.value, fn, decls)
                     if method == "wait" else None)
    what = f".{method}()"
    if receiver:
        what = f"{receiver}.{method}()"
    return _Blocking(f"{what} with no timeout", fn.module.relpath,
                     call.lineno, fn.qualname, receiver_lock)


def _scan_function(project: ProjectGraph, fn: FunctionInfo,
                   decls: Dict[str, LockDecl]) -> _Summary:
    acquires: List[_Acquire] = []
    blocking: List[Tuple[ast.Call, _Blocking, FrozenSet[str]]] = []
    calls: List[Tuple[ast.Call, FunctionInfo, FrozenSet[str]]] = []

    def visit_expr(node: ast.AST, held: FrozenSet[str]) -> None:
        # walk_in_scope yields descendants only and treats the root as a
        # scope boundary, so check the root Call (the common context_expr
        # shape) and skip a root lambda outright
        if isinstance(node, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef, ast.ClassDef)):
            return
        if isinstance(node, ast.Call):
            on_call(node, held)
        for sub in walk_in_scope(node):
            if isinstance(sub, ast.Call):
                on_call(sub, held)

    def on_call(call: ast.Call, held: FrozenSet[str]) -> None:
        b = _classify_blocking(call, fn, decls)
        if b is not None:
            blocking.append((call, b, held))
        for callee in project.resolve_call(fn, call.func):
            calls.append((call, callee, held))

    def visit_stmt(node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return  # nested scope: runs at its own call time
        if isinstance(node, (ast.With, ast.AsyncWith)):
            # items acquire LEFT TO RIGHT: `with a, b:` orders a before b
            # exactly like the nested form, so each item's held-set
            # includes the items already entered in this same statement
            newly: List[str] = []
            for item in node.items:
                entered = held.union(newly)
                visit_expr(item.context_expr, entered)
                lock = _lock_of_expr(item.context_expr, fn, decls)
                if lock is not None:
                    acquires.append(_Acquire(lock, entered, node.lineno))
                    newly.append(lock)
            inner = held.union(newly)
            for stmt in node.body:
                visit_stmt(stmt, inner)
            return
        if isinstance(node, ast.Call):
            on_call(node, held)
        for child in ast.iter_child_nodes(node):
            visit_stmt(child, held)

    for stmt in ast.iter_child_nodes(fn.node):
        visit_stmt(stmt, frozenset())
    return _Summary(fn, acquires, blocking, calls)


# -- interprocedural summaries ------------------------------------------------

class _Propagator:
    """Transitive-effect computation over the call graph, by fixpoint.

    A memoized DFS is tempting but WRONG here: with mutual recursion
    (f <-> g), whichever function is reached first while its partner is
    on the recursion stack gets a partial result cached permanently —
    order-dependent false negatives.  The call graphs are small (a few
    hundred functions), so a plain iterate-until-stable propagation is
    both simple and exact for this monotone join."""

    def __init__(self, summaries: Dict[str, _Summary]):
        self.summaries = summaries
        # fq -> lock id -> (relpath, lineno) of one acquisition site
        self._acquired: Dict[str, Dict[str, Tuple[str, int]]] = {}
        # fq -> (relpath, lineno) -> _Blocking, insertion-ordered
        self._blocking: Dict[str, Dict[Tuple[str, int], _Blocking]] = {}
        for fq, summary in summaries.items():
            acq: Dict[str, Tuple[str, int]] = {}
            for a in summary.acquires:
                acq.setdefault(a.lock,
                               (summary.fn.module.relpath, a.lineno))
            self._acquired[fq] = acq
            blk: Dict[Tuple[str, int], _Blocking] = {}
            for _, b, _ in summary.blocking:
                blk.setdefault((b.relpath, b.lineno), b)
            self._blocking[fq] = blk
        changed = True
        while changed:
            changed = False
            for fq, summary in summaries.items():
                acq = self._acquired[fq]
                blk = self._blocking[fq]
                for _, callee, _ in summary.calls:
                    for lock, site in self._acquired.get(callee.fq,
                                                         {}).items():
                        if lock not in acq:
                            acq[lock] = site
                            changed = True
                    for key, b in self._blocking.get(callee.fq,
                                                     {}).items():
                        if key not in blk:
                            blk[key] = b
                            changed = True

    def acquired(self, fq: str) -> Dict[str, Tuple[str, int]]:
        """lock id -> (relpath, lineno) of one acquisition site reachable
        from ``fq`` (its own body or any transitive project callee)."""
        return self._acquired.get(fq, {})

    def blocking(self, fq: str) -> List[_Blocking]:
        """Unbounded blocking sites reachable from ``fq``."""
        return list(self._blocking.get(fq, {}).values())


# -- the pass -----------------------------------------------------------------

def run_project(project: ProjectGraph) -> List[Finding]:
    decls = _collect_locks(project)
    if not decls:
        return []
    summaries: Dict[str, _Summary] = {}
    for fn in project.functions():
        summaries[fn.fq] = _scan_function(project, fn, decls)
    prop = _Propagator(summaries)

    findings: List[Finding] = []
    # edge (held -> acquired) -> witness (relpath, lineno, description)
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def add_edge(held: str, lock: str, relpath: str, lineno: int,
                 how: str) -> None:
        if held == lock:
            if decls[lock].reentrant:
                return  # RLock/Condition re-entry is fine by construction
            edges.setdefault((held, lock), (relpath, lineno, how))
            return
        edges.setdefault((held, lock), (relpath, lineno, how))

    for summary in summaries.values():
        fn = summary.fn
        relpath = fn.module.relpath
        for acq in summary.acquires:
            for held in acq.held:
                add_edge(held, acq.lock, relpath, acq.lineno,
                         f"{fn.qualname} acquires {_short(acq.lock)} while "
                         f"holding {_short(held)}")
        for call, callee, held in summary.calls:
            if not held:
                continue
            for lock, site in prop.acquired(callee.fq).items():
                for h in held:
                    add_edge(h, lock, relpath, call.lineno,
                             f"{fn.qualname} calls {callee.qualname} "
                             f"(acquires {_short(lock)} at {site[0]}:"
                             f"{site[1]}) while holding {_short(h)}")
        # blocking-under-lock, local sites
        for call, b, held in summary.blocking:
            effective = held - ({b.receiver_lock} if b.receiver_lock else
                                set())
            if not effective:
                continue
            note = ("" if b.receiver_lock is None else
                    f" (the wait releases only {_short(b.receiver_lock)})")
            findings.append(Finding(
                "deadlock-blocking-under-lock", relpath, call.lineno,
                fn.qualname,
                f"{b.desc} while holding {_held_str(effective)}{note}; "
                "every thread needing the lock wedges behind this wait — "
                "bound it with a timeout or move it outside the lock"))
        # blocking-under-lock, one call-graph hop or more away
        reported: Set[int] = set()
        for call, callee, held in summary.calls:
            if not held or id(call) in reported:
                continue
            inherited = [b for b in prop.blocking(callee.fq)
                         if not (b.receiver_lock is not None
                                 and held == {b.receiver_lock})]
            if not inherited:
                continue
            reported.add(id(call))
            b = inherited[0]
            findings.append(Finding(
                "deadlock-blocking-under-lock", relpath, call.lineno,
                fn.qualname,
                f"call to {callee.qualname} while holding "
                f"{_held_str(held)} reaches {b.desc} "
                f"({b.relpath}:{b.lineno} in {b.qualname}); the lock is "
                "held across an unbounded wait"))

    findings.extend(_cycle_findings(edges, decls))
    return findings


def _short(lock_id: str) -> str:
    """Human form: the last two components (`Class.attr` / `mod._lock`)."""
    parts = lock_id.split(".")
    return ".".join(parts[-2:]) if len(parts) >= 2 else lock_id


def _held_str(held) -> str:
    return " + ".join(sorted(_short(h) for h in held))


def _cycle_findings(edges: Dict[Tuple[str, str], Tuple[str, int, str]],
                    decls: Dict[str, LockDecl]) -> List[Finding]:
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    findings: List[Finding] = []
    for cycle in _find_cycles(graph):
        # witness every edge of the cycle in the message; anchor the
        # finding at the first edge's site
        pairs = list(zip(cycle, cycle[1:] + cycle[:1]))
        witnesses = [edges[pair] for pair in pairs if pair in edges]
        if not witnesses:
            continue
        relpath, lineno, _ = witnesses[0]
        chain = " -> ".join(_short(l) for l in cycle + cycle[:1])
        detail = "; ".join(f"{w[2]} [{w[0]}:{w[1]}]" for w in witnesses)
        if len(cycle) == 1:
            msg = (f"non-reentrant lock {_short(cycle[0])} is re-acquired "
                   f"while already held — this deadlocks unconditionally: "
                   f"{detail}")
        else:
            msg = (f"lock-order cycle {chain}: two threads taking these "
                   f"locks in opposite order deadlock; {detail}")
        findings.append(Finding("deadlock-lock-cycle", relpath, lineno,
                                chain, msg))
    return findings


def _find_cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Cycles of the lock-order graph: one canonical simple cycle per
    strongly connected component with a cycle (plus self-loops)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)

    cycles: List[List[str]] = []
    for comp in sccs:
        comp_set = set(comp)
        if len(comp) == 1:
            v = comp[0]
            if v in graph.get(v, ()):  # self-loop
                cycles.append([v])
            continue
        cycles.append(_trace_cycle(graph, comp_set))
    return cycles


def _trace_cycle(graph: Dict[str, Set[str]],
                 comp: Set[str]) -> List[str]:
    """One simple cycle through an SCC, starting at its smallest node."""
    start = min(comp)
    path = [start]
    seen = {start}
    cur = start
    while True:
        nxt = None
        for cand in sorted(graph.get(cur, ())):
            if cand == start and len(path) > 1:
                return path
            if cand in comp and cand not in seen:
                nxt = cand
                break
        if nxt is None:
            # dead end inside the SCC (possible with the greedy walk):
            # back up; the SCC guarantees a cycle exists
            path.pop()
            if not path:
                return sorted(comp)  # defensive: report the whole SCC
            cur = path[-1]
            continue
        path.append(nxt)
        seen.add(nxt)
        cur = nxt
