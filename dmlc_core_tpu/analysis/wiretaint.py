"""Pass 11 — wiretaint: untrusted-wire values flowing into dangerous sinks.

The PR 3 protocol hardening bounded every length prefix the rendezvous
protocol reads (``MAX_FRAME``, ``MAX_PEERS``) — by hand, after the bugs
shipped.  This pass is the static twin: any int or string decoded from
the wire (``FramedSocket.recvint``/``recvstr``/``recv``/``recvall``,
``struct.unpack``, JSON parsed from a received frame) is *tainted*, and
a tainted value reaching a sink without an intervening bound or
allowlist guard is a finding:

- ``taint-unbounded-wire-int`` — a wire-decoded int used as an
  allocation or iteration size: ``range(n)``, ``bytearray(n)``/
  ``bytes(n)``, ``sock.recv(n)``/``recvall(n)``, list/str/bytes
  multiplication, ``np.zeros/empty/ones/full(n)``.  One hostile frame
  makes the peer allocate gigabytes or spin forever.
- ``taint-wire-str-in-path`` — a wire-decoded string used in a
  filesystem path operation (``open``, ``os.path.join``, ``Path(...)``,
  ``os.remove``/``makedirs``/``rmtree``) without sanitization: classic
  path traversal from a protocol frame.

Taint is killed by the guard shapes the hardened code actually uses:

- a bounds check that bails out — ``if n < 0 or n > MAX_FRAME: raise``
  (or ``return``/``continue``/``break``) lexically before the use;
- using the value *inside* an ``if`` whose test compares/allowlists it;
- wrapping in ``min(...)`` (upper bound), ``%``/``&`` (modulus/mask),
  or ``len(...)``;
- ``os.path.basename(...)`` for path strings (strips traversal).

Scope is deliberately function-local (the jaxbound def-use discipline):
taint does not cross function boundaries, attribute stores, or returns.
A parameter is trusted — callers are in-project and the coordinator side
of a protocol is not the attacker.  That keeps the lease/fleet clients
clean (their ``recvint`` results are only compared) and is documented as
a soundness caveat in docs/analysis.md; the seeded-bug tests pin down
what the pass *does* catch so the gate can ratchet from there.

Findings anchor at the sink line with the enclosing function's qualname
as the symbol — two sinks in one function share a key and exercise the
baseline's ``#2`` instance-key discipline.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from dmlc_core_tpu.analysis.driver import Finding, dotted_name
from dmlc_core_tpu.analysis.graph import ProjectGraph, walk_in_scope

__all__ = ["run_project"]

# receiver methods that read raw frames off a socket
_INT_SOURCES = {"recvint"}
_STR_SOURCES = {"recvstr"}
_BYTES_SOURCES = {"recv", "recvall", "recv_into", "recvframe"}

# calls whose result keeps the argument's taint (identity-ish wrappers)
_PASSTHROUGH = {"int", "float", "str", "bytes", "abs", "bool"}

# calls that bound/sanitize their argument
_INT_SANITIZERS = {"min", "len"}
_PATH_SANITIZERS = {"basename", "os.path.basename", "posixpath.basename",
                    "secure_filename"}

_INT_SINK_CALLS = {"range", "bytearray", "bytes", "memoryview"}
_INT_SINK_METHODS = {"recv", "recvall", "recv_into", "read"}
_NP_ALLOC = {"zeros", "empty", "ones", "full"}

_PATH_SINK_CALLS = {"open", "os.remove", "os.unlink", "os.rmdir",
                    "os.makedirs", "os.mkdir", "os.rename", "os.replace",
                    "shutil.rmtree", "pathlib.Path", "Path"}
_PATH_JOIN_CALLS = {"os.path.join", "posixpath.join", "ntpath.join"}

_INT = "int"
_STR = "str"
_ANY = "any"


def _short(name: str) -> str:
    return name.rsplit(".", 1)[-1]


class _FunctionTaint:
    """Two-pass def-use over one function body (nested scopes excluded),
    mirroring jaxbound's ``_check_wide_wire``: pass 1 computes the
    tainted-name environment to fixpoint; pass 2 walks statements in
    lexical order, retiring names as guards kill them and flagging
    sinks."""

    def __init__(self, relpath: str, qualname: str,
                 body: List[ast.stmt]) -> None:
        self.relpath = relpath
        self.qualname = qualname
        self.body = body
        self.tainted: Dict[str, str] = {}   # name -> _INT/_STR/_ANY
        self.guarded: Set[str] = set()      # names a bailout guard cleared
        self.findings: List[Finding] = []

    # -- taint classification -------------------------------------------------

    def _taint_of(self, node: ast.AST) -> Optional[str]:
        """Taint kind carried by an expression, or None."""
        if isinstance(node, ast.Name):
            if node.id in self.guarded:
                return None
            return self.tainted.get(node.id)
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            short = _short(name)
            if isinstance(node.func, ast.Attribute):
                meth = node.func.attr
                if meth in _INT_SOURCES:
                    return _INT
                if meth in _STR_SOURCES:
                    return _STR
                if meth in _BYTES_SOURCES:
                    return _ANY
                if meth == "decode":
                    inner = self._taint_of(node.func.value)
                    return _STR if inner else None
                if meth in ("strip", "lstrip", "rstrip", "lower", "upper",
                            "split", "rsplit", "partition", "format"):
                    inner = self._taint_of(node.func.value)
                    return _STR if inner else None
            if short == "unpack" or short == "unpack_from":
                return _ANY  # struct.unpack of wire bytes
            if short == "loads" and node.args \
                    and self._taint_of(node.args[0]):
                return _ANY  # json.loads of a received frame
            if short in _INT_SANITIZERS or name in _PATH_SANITIZERS \
                    or short in _PATH_SANITIZERS:
                return None
            if short in _PASSTHROUGH:
                kinds = [self._taint_of(a) for a in node.args]
                if any(kinds):
                    if short in ("int", "abs"):
                        return _INT
                    if short == "str":
                        return _STR
                    return _ANY
                return None
            if short == "max":
                # max() preserves the UPPER-unbounded hazard
                kinds = [self._taint_of(a) for a in node.args]
                return _INT if any(kinds) else None
            return None
        if isinstance(node, ast.Subscript):
            inner = self._taint_of(node.value)
            if inner:
                # element of a tainted tuple/dict/list: kind unknown
                return _ANY if inner == _ANY else inner
            return None
        if isinstance(node, ast.BinOp):
            if isinstance(node.op, (ast.Mod, ast.BitAnd)):
                return None  # modulus / mask bounds the value
            left = self._taint_of(node.left)
            right = self._taint_of(node.right)
            return left or right
        if isinstance(node, ast.UnaryOp):
            return self._taint_of(node.operand)
        if isinstance(node, ast.IfExp):
            return self._taint_of(node.body) or self._taint_of(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            for elt in node.elts:
                kind = self._taint_of(elt)
                if kind:
                    return kind
            return None
        if isinstance(node, ast.JoinedStr):
            for value in node.values:
                if isinstance(value, ast.FormattedValue) \
                        and self._taint_of(value.value):
                    return _STR
            return None
        return None

    # -- pass 1: propagate assignments to fixpoint ----------------------------

    def _propagate(self) -> None:
        for _ in range(8):  # bounded fixpoint; real chains are short
            changed = False
            for node in self._walk():
                targets: List[Tuple[ast.AST, ast.AST]] = []
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        targets.append((t, node.value))
                elif isinstance(node, ast.AnnAssign) \
                        and node.value is not None:
                    targets.append((node.target, node.value))
                elif isinstance(node, ast.AugAssign):
                    targets.append((node.target, node.value))
                for target, value in targets:
                    changed |= self._assign(target, value)
            if not changed:
                return

    def _assign(self, target: ast.AST, value: ast.AST) -> bool:
        if isinstance(target, (ast.Tuple, ast.List)):
            kind = self._taint_of(value)
            # a, b = unpack(...) / tainted tuple: every binding tainted
            changed = False
            if isinstance(value, (ast.Tuple, ast.List)) \
                    and len(value.elts) == len(target.elts):
                for t, v in zip(target.elts, value.elts):
                    changed |= self._assign(t, v)
                return changed
            if kind:
                for t in target.elts:
                    if isinstance(t, ast.Name):
                        changed |= self._mark(t.id, _ANY)
            return changed
        if isinstance(target, ast.Name):
            kind = self._taint_of(value)
            if kind:
                return self._mark(target.id, kind)
        return False

    def _mark(self, name: str, kind: str) -> bool:
        prev = self.tainted.get(name)
        new = kind if prev in (None, kind) else _ANY
        if prev != new:
            self.tainted[name] = new
            return True
        return False

    def _walk(self):
        for stmt in self.body:
            yield stmt
            yield from walk_in_scope(stmt)

    # -- pass 2: lexical walk, guards retire names, sinks flag ----------------

    def run(self) -> List[Finding]:
        self._propagate()
        if self.tainted:
            for stmt in self.body:
                self._visit(stmt)
        return self.findings

    def _guard_names(self, test: ast.AST) -> Set[str]:
        """Tainted names a comparison test bounds (Compare or BoolOp of
        Compares; membership counts as an allowlist check)."""
        names: Set[str] = set()
        for node in ast.walk(test):
            if isinstance(node, ast.Compare):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name) \
                            and sub.id in self.tainted:
                        names.add(sub.id)
        return names

    def _bails(self, body: List[ast.stmt]) -> bool:
        return any(isinstance(s, (ast.Raise, ast.Return, ast.Continue,
                                  ast.Break)) for s in body)

    def _visit(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, ast.If):
            bounded = self._guard_names(node.test)
            if bounded and self._bails(node.body):
                # if n < 0 or n > MAX: raise — n is clean afterwards
                for stmt in node.body:
                    self._visit(stmt)
                self.guarded |= bounded
                for stmt in node.orelse:
                    self._visit(stmt)
                return
            if bounded:
                # uses INSIDE `if 0 <= n <= MAX:` are bounded
                saved = set(self.guarded)
                self.guarded |= bounded
                for stmt in node.body:
                    self._visit(stmt)
                self.guarded = saved
                for stmt in node.orelse:
                    self._visit(stmt)
                return
        if isinstance(node, ast.Assert):
            bounded = self._guard_names(node.test)
            if bounded:
                self.guarded |= bounded
            return
        if isinstance(node, ast.Call):
            self._check_sink(node)
        elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
            self._check_multiply(node)
        for child in ast.iter_child_nodes(node):
            self._visit(child)

    def _is_live(self, node: ast.AST, kinds: Tuple[str, ...]) -> bool:
        kind = self._taint_of(node)
        return kind is not None and (kind == _ANY or kind in kinds)

    def _check_sink(self, call: ast.Call) -> None:
        name = dotted_name(call.func) or ""
        short = _short(name)
        args = call.args
        if not args:
            return
        # int sinks: allocation / iteration sized by the wire
        if (short in _INT_SINK_CALLS or name in _INT_SINK_CALLS
                or (short in _NP_ALLOC and "." in name)):
            for arg in args[:2]:
                if self._is_live(arg, (_INT,)):
                    self._flag_int(call, arg)
                    return
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in _INT_SINK_METHODS:
            if self._is_live(args[0], (_INT,)):
                self._flag_int(call, args[0])
                return
        # path sinks
        if name in _PATH_SINK_CALLS or short in ("Path",):
            if self._is_live(args[0], (_STR,)):
                self._flag_path(call, args[0])
                return
        if name in _PATH_JOIN_CALLS:
            for arg in args:
                if self._is_live(arg, (_STR,)):
                    self._flag_path(call, arg)
                    return

    def _check_multiply(self, binop: ast.BinOp) -> None:
        # [0] * n / b"\0" * n with a wire-sized n
        pairs = ((binop.left, binop.right), (binop.right, binop.left))
        for seq, count in pairs:
            literal_seq = isinstance(seq, (ast.List, ast.Tuple)) or (
                isinstance(seq, ast.Constant)
                and isinstance(seq.value, (str, bytes)))
            if literal_seq and self._is_live(count, (_INT,)):
                hint = _describe(count)
                self.findings.append(Finding(
                    "taint-unbounded-wire-int", self.relpath, binop.lineno,
                    self.qualname,
                    f"sequence repeat sized by unvalidated wire int "
                    f"{hint} in {self.qualname}: a hostile frame "
                    f"chooses the allocation size — bound it first "
                    f"(compare against a MAX_* cap and bail out)"))
                return

    def _flag_int(self, call: ast.Call, arg: ast.AST) -> None:
        sink = dotted_name(call.func) or "<call>"
        self.findings.append(Finding(
            "taint-unbounded-wire-int", self.relpath, call.lineno,
            self.qualname,
            f"{sink}({_describe(arg)}) sized by an unvalidated wire int "
            f"in {self.qualname}: a hostile frame chooses the "
            f"allocation/iteration size — bound it first (compare "
            f"against a MAX_* cap and bail out)"))

    def _flag_path(self, call: ast.Call, arg: ast.AST) -> None:
        sink = dotted_name(call.func) or "<call>"
        self.findings.append(Finding(
            "taint-wire-str-in-path", self.relpath, call.lineno,
            self.qualname,
            f"{sink}(...{_describe(arg)}...) builds a filesystem path "
            f"from an unvalidated wire string in {self.qualname}: a "
            f"hostile frame traverses the filesystem — allowlist or "
            f"os.path.basename() it first"))


def _describe(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    name = dotted_name(node)
    return name if name else "<expr>"


def run_project(graph: ProjectGraph) -> List[Finding]:
    findings: List[Finding] = []
    for fn in graph.functions():
        body = list(getattr(fn.node, "body", []))
        if not body:
            continue
        checker = _FunctionTaint(fn.module.relpath, fn.qualname, body)
        findings.extend(checker.run())
    return findings
