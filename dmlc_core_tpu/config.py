"""key=value config-file parser.

Capability parity with the reference's ``dmlc::Config`` (include/dmlc/config.h:40-186,
src/config.cc:19-279): parses ``key = value`` text with comments, quoted strings
with escape sequences, insertion-order iteration, an optional multi-value mode
(repeated keys accumulate instead of overwrite), and protobuf-text-style output
(``ToProtoString``, config.h:102).
"""

from __future__ import annotations

import io
from typing import Iterator, List, Tuple

from dmlc_core_tpu.utils.logging import CHECK

__all__ = ["Config"]

_ESCAPES = {"n": "\n", "t": "\t", "\\": "\\", '"': '"', "r": "\r"}
_REV_ESCAPES = {"\n": "\\n", "\t": "\\t", "\\": "\\\\", '"': '\\"', "\r": "\\r"}


def _tokenize(text: str) -> Iterator[str]:
    """Yield tokens: bare words, ``=``, and quoted strings with escapes resolved.

    Mirrors the reference tokenizer (src/config.cc:30-141): ``#`` starts a
    comment to end-of-line outside quotes; quoted tokens keep a leading marker
    so the writer can restore quoting.
    """
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == "#":
            while i < n and text[i] != "\n":
                i += 1
        elif c.isspace():
            i += 1
        elif c == "=":
            yield "="
            i += 1
        elif c == '"':
            i += 1
            out: List[str] = []
            closed = False
            while i < n:
                c = text[i]
                if c == "\\":
                    CHECK(i + 1 < n, "config: dangling escape at end of input")
                    esc = text[i + 1]
                    CHECK(esc in _ESCAPES, f"config: unsupported escape \\{esc}")
                    out.append(_ESCAPES[esc])
                    i += 2
                elif c == '"':
                    closed = True
                    i += 1
                    break
                else:
                    out.append(c)
                    i += 1
            CHECK(closed, "config: unterminated quoted string")
            yield '"' + "".join(out)
        else:
            j = i
            while j < n and not text[j].isspace() and text[j] not in ('=', '#', '"'):
                j += 1
            yield text[i:j]
            i = j


class Config:
    """Ordered key=value configuration (reference config.h:40-186)."""

    def __init__(self, text_or_stream: object = None, multi_value: bool = False):
        self._multi = multi_value
        self._order: List[Tuple[str, str, bool]] = []  # (key, value, was_quoted)
        self._map: dict = {}
        if text_or_stream is not None:
            self.load(text_or_stream)

    def load(self, text_or_stream: object) -> None:
        """Parse config text or a text stream (reference LoadFromStream, config.cc:143)."""
        if hasattr(text_or_stream, "read"):
            text = text_or_stream.read()
            if isinstance(text, bytes):
                text = text.decode("utf-8")
        else:
            text = str(text_or_stream)
        tokens = list(_tokenize(text))
        i = 0
        while i < len(tokens):
            key = tokens[i]
            CHECK(key != "=", "config: stray '=' without key")
            if key.startswith('"'):
                key = key[1:]
            CHECK(i + 2 < len(tokens) + 1 and i + 1 < len(tokens) and tokens[i + 1] == "=",
                  f"config: expected '=' after key {key!r}")
            CHECK(i + 2 < len(tokens), f"config: missing value for key {key!r}")
            raw = tokens[i + 2]
            quoted = raw.startswith('"')
            value = raw[1:] if quoted else raw
            self.set_param(key, value, quoted)
            i += 3

    def set_param(self, key: str, value: object, is_string: bool = False) -> None:
        """Set/append a key (reference SetParam config.h:84-92)."""
        value = str(value)
        if not self._multi and key in self._map:
            # overwrite in place, preserving original position
            for idx, (k, _, q) in enumerate(self._order):
                if k == key:
                    self._order[idx] = (key, value, is_string or q)
                    break
        else:
            self._order.append((key, value, is_string))
        self._map.setdefault(key, [])
        if self._multi:
            self._map[key].append(value)
        else:
            self._map[key] = [value]

    def get_param(self, key: str) -> str:
        """Latest value for key; raises KeyError when absent (config.h:77-82)."""
        return self._map[key][-1]

    def __contains__(self, key: str) -> bool:
        return key in self._map

    def items(self) -> Iterator[Tuple[str, str]]:
        """Iterate (key, value) in insertion order (reference begin/end iteration)."""
        for key, value, _ in self._order:
            yield key, value

    def __iter__(self) -> Iterator[Tuple[str, str]]:
        return self.items()

    def to_proto_string(self) -> str:
        """Protobuf-text-format rendering (reference ToProtoString, config.h:102)."""
        out = io.StringIO()
        for key, value, quoted in self._order:
            if quoted:
                escaped = "".join(_REV_ESCAPES.get(c, c) for c in value)
                out.write(f'{key} : "{escaped}"\n')
            else:
                out.write(f"{key} : {value}\n")
        return out.getvalue()
