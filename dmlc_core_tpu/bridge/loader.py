"""Mesh-placed batch loader: host parse pipeline -> device arrays, overlapped.

The reference's ThreadedIter idiom (prefetch thread + bounded queue,
threadediter.h) recast for TPU: a producer thread runs the parse/batch
pipeline and stages *host* batches; the consumer transfers them to the mesh
with the right NamedSharding while the device computes the previous step
(JAX's async dispatch gives compute/transfer overlap for free once batches
are prefetched).

Per-host data sharding reuses the InputSplit math unchanged: process p of N
reads shard ``(part_index=p, num_parts=N)`` (SURVEY.md §7 stage 4), and
``jax.make_array_from_process_local_data`` assembles the global batch.

:class:`DeviceFeedLoader` is the explicit double-buffered device-feed mode
(ROADMAP item 1): it keeps ``prefetch`` transfers *dispatched* ahead of the
consumer, so the host->device copy of batch k+1 overlaps compute on batch
k, and every transfer is accounted — ``loader.transfer`` spans plus the
``dmlc_transfer_{bytes,seconds}_total`` counters — so the trace CLI's
critical path splits transfer from compute.  Feed it
:func:`~dmlc_core_tpu.bridge.binning.binned_batches` and the wire carries
uint8 bin ids instead of float32 features (~1/12 the bytes for the
hist-GBDT shape; see ``bridge/binning.py``).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Iterator, Optional

import numpy as np

from dmlc_core_tpu import telemetry
from dmlc_core_tpu.bridge.batching import dense_batches, sparse_batches
from dmlc_core_tpu.data.parser import Parser
from dmlc_core_tpu.io.threadediter import ThreadedIter, IteratorProducer
from dmlc_core_tpu.telemetry import clock
from dmlc_core_tpu.utils.logging import CHECK

__all__ = ["MeshBatchLoader", "DeviceFeedLoader", "batch_nbytes"]


def batch_nbytes(batch: Any) -> int:
    """Total array-leaf bytes of a host batch pytree (what a transfer of
    it ships over the wire)."""
    import jax

    return sum(leaf.nbytes for leaf in jax.tree_util.tree_leaves(batch)
               if hasattr(leaf, "nbytes"))


def _record_transfer(path: str, nbytes: int, seconds: float,
                     phase: str) -> None:
    """One transfer accounting row: bytes move once (dispatch), seconds
    split by phase so dispatch cost and non-overlapped wait stay separate
    series (observability.md catalog)."""
    if phase == "dispatch":
        telemetry.count("dmlc_transfer_bytes_total", nbytes, path=path)
    telemetry.count("dmlc_transfer_seconds_total", seconds, path=path,
                    phase=phase)


def _accounted_place(inner: Callable[[Any], Any],
                     path: str) -> Callable[[Any], Any]:
    """Wrap a placement fn with the transfer accounting every feed path
    shares — ``loader.transfer`` span + byte/latency counters — so the
    mesh-shard and device-feed modes can never drift apart on how a
    transfer is recorded.  Zero-cost when telemetry is disabled."""

    def place(host_batch):
        if not telemetry.enabled():
            return inner(host_batch)
        nbytes = batch_nbytes(host_batch)
        start = clock.monotonic()
        with telemetry.span("loader.transfer", path=path, nbytes=nbytes):
            placed = inner(host_batch)
        _record_transfer(path, nbytes, clock.monotonic() - start,
                         "dispatch")
        return placed

    return place


class MeshBatchLoader:
    """Iterate device-placed batches over a mesh.

    Args:
      parser: host-side Parser (already sharded per process via part_index /
        num_parts at creation).
      mesh: jax Mesh; batch dim 0 is sharded over ``data_axis``.
      form: "dense" or "sparse".
      global_batch_size: rows per *global* step; this process stages
        ``global_batch_size / process_count`` rows.
      num_feature: required for dense form.
      nnz_bucket: optional fixed bucket for sparse form (else auto ladder —
        note each new bucket size triggers one recompile of the consumer).
      prefetch: host batches staged ahead (ThreadedIter capacity).
      device_prefetch: device transfers kept dispatched ahead of the
        consumer (0 = the legacy synchronous shard-on-demand path).  With
        N >= 1 the loader runs double-buffered: while the consumer
        computes on batch k, transfers of batches k+1..k+N are already in
        flight — the :class:`DeviceFeedLoader` discipline applied to the
        mesh path.
    """

    def __init__(
        self,
        parser: Parser,
        mesh: Any,
        form: str = "dense",
        global_batch_size: int = 1024,
        num_feature: Optional[int] = None,
        nnz_bucket: Optional[int] = None,
        data_axis: str = "data",
        prefetch: int = 2,
        drop_remainder: bool = True,
        device_prefetch: int = 0,
    ):
        import jax

        self._mesh = mesh
        self._axis = data_axis
        self._form = form
        nproc = jax.process_count()
        CHECK(global_batch_size % nproc == 0,
              "global_batch_size must divide evenly across processes")
        CHECK(device_prefetch >= 0, "device_prefetch must be >= 0")
        self._local_rows = global_batch_size // nproc
        self._global_batch = global_batch_size
        self._num_feature = num_feature
        self._device_prefetch = device_prefetch
        if form == "dense":
            CHECK(num_feature is not None, "dense form requires num_feature")
            factory = lambda: dense_batches(  # noqa: E731
                parser, self._local_rows, num_feature, drop_remainder)
        elif form == "sparse":
            factory = lambda: sparse_batches(  # noqa: E731
                parser, self._local_rows, nnz_bucket, drop_remainder)
        else:
            raise ValueError(f"unknown batch form {form!r}")
        self._parser = parser
        self._host_iter = ThreadedIter(_EpochProducer(parser, factory),
                                       max_capacity=prefetch, name="loader")
        # device-prefetch in-flight batches live on the LOADER, not in the
        # iterator: an abandoned mid-epoch iteration (break / islice) must
        # hand its already-dispatched batches to the next one, or they
        # silently vanish from the epoch (the sync path pulls lazily and
        # loses nothing — byte-identity demands the buffered path match)
        self._pending: deque = deque()

    def _shard(self, host_batch):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh, axis = self._mesh, self._axis

        def place(arr: np.ndarray):
            # batch-dim arrays shard over the data axis; nnz-dim arrays of the
            # sparse form shard likewise (each process's nonzeros stay local)
            sharding = NamedSharding(mesh, P(axis, *([None] * (arr.ndim - 1))))
            global_shape = (arr.shape[0] * jax.process_count(),) + arr.shape[1:]
            return jax.make_array_from_process_local_data(sharding, arr,
                                                          global_shape)

        # tree_map visits only array leaves: None fields are empty subtrees
        # and num_rows is static aux data (host-local, never device-placed)
        return jax.tree_util.tree_map(place, host_batch)

    def _host_batches(self) -> Iterator[Any]:
        while True:
            host_batch = self._host_iter.next()
            if host_batch is None:
                return
            yield host_batch

    def __iter__(self) -> Iterator[Any]:
        place = _accounted_place(self._shard, "mesh_shard")
        if not self._device_prefetch:
            for host_batch in self._host_batches():
                yield place(host_batch)
            return
        yield from _double_buffered(self._host_batches(), place,
                                    self._device_prefetch,
                                    path="mesh_shard",
                                    pending=self._pending)

    def before_first(self) -> None:
        self._pending.clear()
        self._host_iter.before_first()

    def bytes_read(self) -> int:
        return self._parser.bytes_read()

    def close(self) -> None:
        self._host_iter.destroy()
        if hasattr(self._parser, "close"):
            self._parser.close()


def _double_buffered(host_batches: Iterator[Any], place: Callable[[Any], Any],
                     prefetch: int, path: str,
                     pending: Optional[deque] = None) -> Iterator[Any]:
    """The double-buffer core: keep ``prefetch`` placed batches dispatched
    ahead, block for readiness only at hand-off.  JAX transfers are async
    once dispatched, so the wait measured here is exactly the
    non-overlapped transfer residue — when it is ~0, transfer fully hides
    behind compute (the trace-CLI critical-path signal).

    ``pending`` may be a caller-owned deque: dispatched-but-unconsumed
    batches then survive an abandoned iteration and are yielded first by
    the next one (MeshBatchLoader resumes mid-epoch; a local deque would
    silently drop up to ``prefetch`` batches on break/resume)."""
    import jax

    if pending is None:
        pending = deque()
    while True:
        while len(pending) < prefetch:
            try:
                host_batch = next(host_batches)
            except StopIteration:
                break
            pending.append(place(host_batch))
        if not pending:
            return
        batch = pending.popleft()
        if telemetry.enabled():
            start = clock.monotonic()
            with telemetry.span("loader.transfer.wait", path=path):
                jax.block_until_ready(batch)
            _record_transfer(path, 0, clock.monotonic() - start, "wait")
        yield batch


class DeviceFeedLoader:
    """Double-buffered async device feed over any restartable batch source.

    ``source`` is either a zero-arg factory returning one epoch's iterator
    of host batch pytrees (e.g. ``lambda: binned_batches(parser, binner,
    bs)``), or an object with ``before_first()`` + iteration (a
    :class:`MeshBatchLoader`-shaped host iterator).  Each ``__iter__``
    starts a fresh epoch; ``before_first()`` is the explicit restart for
    source objects that need it.

    ``place`` maps a host batch to its device form — default
    ``jax.device_put`` onto ``device`` (or ``sharding``); override it to
    fuse extra staging (e.g. a device-side widen).  The loader keeps
    ``prefetch`` transfers dispatched ahead of the consumer and records
    per-batch ``loader.transfer`` spans + ``dmlc_transfer_bytes_total`` /
    ``dmlc_transfer_seconds_total{phase=dispatch|wait}`` so the merged
    trace shows transfer vs compute (docs/observability.md).

    Determinism contract (tested): the batch sequence is byte-identical
    to placing the same host batches synchronously — buffering reorders
    *time*, never data — including across a full ``before_first()`` epoch
    restart.
    """

    def __init__(self, source: Any, device: Any = None, sharding: Any = None,
                 prefetch: int = 2,
                 place: Optional[Callable[[Any], Any]] = None):
        CHECK(prefetch >= 1, "prefetch must be >= 1")
        CHECK(device is None or sharding is None,
              "pass device= or sharding=, not both")
        self._source = source
        self._prefetch = prefetch
        self._device = device
        self._sharding = sharding
        self._place = place

    def _epoch(self) -> Iterator[Any]:
        if callable(self._source):
            return iter(self._source())
        if hasattr(self._source, "before_first"):
            self._source.before_first()
        return iter(self._source)

    def _placer(self) -> Callable[[Any], Any]:
        if self._place is not None:
            inner = self._place
        else:
            import jax

            target = self._sharding if self._sharding is not None \
                else self._device

            def inner(host_batch):
                if target is None:
                    return jax.device_put(host_batch)
                return jax.device_put(host_batch, target)

        return _accounted_place(inner, "device_feed")

    def __iter__(self) -> Iterator[Any]:
        yield from _double_buffered(self._epoch(), self._placer(),
                                    self._prefetch, path="device_feed")

    def before_first(self) -> None:
        """Restart the underlying source (factory sources restart per
        ``__iter__`` anyway; this forwards to object sources)."""
        if not callable(self._source) and hasattr(self._source,
                                                  "before_first"):
            self._source.before_first()


class _EpochProducer:
    """ThreadedIter producer over a restartable batch-iterator factory."""

    def __init__(self, parser: Parser, factory):
        self._parser = parser
        self._factory = factory
        self._it = None

    def before_first(self) -> None:
        self._parser.before_first()
        self._it = None

    def next(self, reuse):
        if self._it is None:
            self._it = iter(self._factory())
        try:
            return next(self._it)
        except StopIteration:
            self._it = None
            return None
        except BaseException:
            # a mid-epoch failure leaves the iterator a corpse: a later
            # next() would raise StopIteration off it and read as a clean
            # (silently truncated!) epoch end.  Drop it so the next pull
            # restarts the factory and before_first() recovers cleanly.
            self._it = None
            raise
