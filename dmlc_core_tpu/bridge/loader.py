"""Mesh-placed batch loader: host parse pipeline -> device arrays, overlapped.

The reference's ThreadedIter idiom (prefetch thread + bounded queue,
threadediter.h) recast for TPU: a producer thread runs the parse/batch
pipeline and stages *host* batches; the consumer transfers them to the mesh
with the right NamedSharding while the device computes the previous step
(JAX's async dispatch gives compute/transfer overlap for free once batches
are prefetched).

Per-host data sharding reuses the InputSplit math unchanged: process p of N
reads shard ``(part_index=p, num_parts=N)`` (SURVEY.md §7 stage 4), and
``jax.make_array_from_process_local_data`` assembles the global batch.
"""

from __future__ import annotations

from typing import Any, Iterator, Optional

import numpy as np

from dmlc_core_tpu.bridge.batching import dense_batches, sparse_batches
from dmlc_core_tpu.data.parser import Parser
from dmlc_core_tpu.io.threadediter import ThreadedIter, IteratorProducer
from dmlc_core_tpu.utils.logging import CHECK

__all__ = ["MeshBatchLoader"]


class MeshBatchLoader:
    """Iterate device-placed batches over a mesh.

    Args:
      parser: host-side Parser (already sharded per process via part_index /
        num_parts at creation).
      mesh: jax Mesh; batch dim 0 is sharded over ``data_axis``.
      form: "dense" or "sparse".
      global_batch_size: rows per *global* step; this process stages
        ``global_batch_size / process_count`` rows.
      num_feature: required for dense form.
      nnz_bucket: optional fixed bucket for sparse form (else auto ladder —
        note each new bucket size triggers one recompile of the consumer).
      prefetch: host batches staged ahead (ThreadedIter capacity).
    """

    def __init__(
        self,
        parser: Parser,
        mesh: Any,
        form: str = "dense",
        global_batch_size: int = 1024,
        num_feature: Optional[int] = None,
        nnz_bucket: Optional[int] = None,
        data_axis: str = "data",
        prefetch: int = 2,
        drop_remainder: bool = True,
    ):
        import jax

        self._mesh = mesh
        self._axis = data_axis
        self._form = form
        nproc = jax.process_count()
        CHECK(global_batch_size % nproc == 0,
              "global_batch_size must divide evenly across processes")
        self._local_rows = global_batch_size // nproc
        self._global_batch = global_batch_size
        self._num_feature = num_feature
        if form == "dense":
            CHECK(num_feature is not None, "dense form requires num_feature")
            factory = lambda: dense_batches(  # noqa: E731
                parser, self._local_rows, num_feature, drop_remainder)
        elif form == "sparse":
            factory = lambda: sparse_batches(  # noqa: E731
                parser, self._local_rows, nnz_bucket, drop_remainder)
        else:
            raise ValueError(f"unknown batch form {form!r}")
        self._parser = parser
        self._host_iter = ThreadedIter(_EpochProducer(parser, factory),
                                       max_capacity=prefetch, name="loader")

    def _shard(self, host_batch):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh, axis = self._mesh, self._axis

        def place(arr: np.ndarray):
            # batch-dim arrays shard over the data axis; nnz-dim arrays of the
            # sparse form shard likewise (each process's nonzeros stay local)
            sharding = NamedSharding(mesh, P(axis, *([None] * (arr.ndim - 1))))
            global_shape = (arr.shape[0] * jax.process_count(),) + arr.shape[1:]
            return jax.make_array_from_process_local_data(sharding, arr,
                                                          global_shape)

        # tree_map visits only array leaves: None fields are empty subtrees
        # and num_rows is static aux data (host-local, never device-placed)
        return jax.tree_util.tree_map(place, host_batch)

    def __iter__(self) -> Iterator[Any]:
        while True:
            host_batch = self._host_iter.next()
            if host_batch is None:
                return
            yield self._shard(host_batch)

    def before_first(self) -> None:
        self._host_iter.before_first()

    def bytes_read(self) -> int:
        return self._parser.bytes_read()

    def close(self) -> None:
        self._host_iter.destroy()
        if hasattr(self._parser, "close"):
            self._parser.close()


class _EpochProducer:
    """ThreadedIter producer over a restartable batch-iterator factory."""

    def __init__(self, parser: Parser, factory):
        self._parser = parser
        self._factory = factory
        self._it = None

    def before_first(self) -> None:
        self._parser.before_first()
        self._it = None

    def next(self, reuse):
        if self._it is None:
            self._it = iter(self._factory())
        try:
            return next(self._it)
        except StopIteration:
            self._it = None
            return None
