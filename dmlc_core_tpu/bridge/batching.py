"""Ragged CSR RowBlocks -> statically-shaped batches XLA can compile once.

The hard part SURVEY.md §7 calls out: RowBlock is ragged, XLA wants static
shapes.  Two TPU-friendly layouts:

- :class:`DenseBatch` — densified ``[batch, num_feature]`` features; right for
  low-dimensional dense data (csv/HIGGS) and MXU matmuls;
- :class:`SparseBatch` — flat COO-ish ``(value[N], index[N], row_id[N])`` with
  the nonzero count padded up to a *bucket* (power-of-two style) so the number
  of distinct compiled shapes stays logarithmic; padding rows carry
  ``row_id == batch_size`` and are dropped by ``segment_sum`` with
  ``num_segments = batch_size + 1``.

Both are pytrees, so they pass straight into jit'd steps.
"""

from __future__ import annotations

from typing import Iterator, NamedTuple, Optional

import numpy as np

from dmlc_core_tpu.data.parser import Parser
from dmlc_core_tpu.data.row_block import RowBlock, concat_blocks
from dmlc_core_tpu.utils.logging import CHECK, CHECK_LE

__all__ = [
    "DenseBatch",
    "SparseBatch",
    "block_to_dense",
    "block_to_sparse",
    "dense_batches",
    "sparse_batches",
    "bucket_size",
]


class DenseBatch(NamedTuple):
    x: np.ndarray        # [B, F] float32
    label: np.ndarray    # [B] float32
    weight: np.ndarray   # [B] float32 (1.0 where absent; 0.0 marks padding)
    # real (unpadded) row count; None from hand-built batches.  Consumers
    # must slice with this, NOT weight.sum(): explicit libsvm row weights
    # make the weight sum diverge from the row count
    num_rows: Optional[int] = None


class SparseBatch(NamedTuple):
    value: np.ndarray    # [N] float32
    index: np.ndarray    # [N] int32 feature ids (0 on padding)
    row_id: np.ndarray   # [N] int32 in [0, B]; B marks padding
    label: np.ndarray    # [B] float32
    weight: np.ndarray   # [B] float32 (0.0 marks padding rows)
    field: Optional[np.ndarray] = None  # [N] int32 (libfm)
    num_rows: Optional[int] = None      # real row count (see DenseBatch)


def _register_batch_pytree(cls, data_fields):
    """Register the batch type with ``num_rows`` as STATIC aux data, not a
    leaf: batches pass straight into jit'd steps (module docstring), where
    a leaf row count would be a tracer — unusable for the slicing the field
    exists for — and device loaders would have to special-case it.  As aux
    data it stays a host int (``batch.x[:batch.num_rows]`` works under
    jit; a changed count — e.g. the final partial batch — retraces, same
    as any static-shape change).
    """
    from jax import tree_util

    def flatten_with_keys(b):
        return ([(tree_util.GetAttrKey(f), getattr(b, f))
                 for f in data_fields], b.num_rows)

    def flatten(b):
        return [getattr(b, f) for f in data_fields], b.num_rows

    def unflatten(aux, children):
        return cls(*children, num_rows=aux)

    tree_util.register_pytree_with_keys(cls, flatten_with_keys, unflatten,
                                        flatten_func=flatten)


_register_batch_pytree(DenseBatch, ("x", "label", "weight"))
_register_batch_pytree(SparseBatch,
                       ("value", "index", "row_id", "label", "weight",
                        "field"))


def bucket_size(n: int, minimum: int = 256) -> int:
    """Round up to the bucket ladder: 1.5x-spaced powers-of-two-ish sizes so
    recompiles are O(log nnz) (static-shape discipline).

    From ``minimum=1`` the ladder runs 1, 2, 3, 4, 6, 8, 12, 16, ... — the
    serving scheduler uses it to bucket *batch* dimensions (serve/scheduler
    pads coalesced request batches to the next rung so jitted predict fns
    compile O(log max_batch) shapes, not one per arrival pattern).
    """
    b = minimum
    while b < n:
        # b=1 must step to 2 (1*3//2 would stick at 1 forever)
        b = b * 3 // 2 if (b & (b - 1)) == 0 and b > 1 else 1 << (b.bit_length())
    return b


def block_to_dense(block: RowBlock, num_feature: int,
                   batch_size: Optional[int] = None,
                   fill_value: float = 0.0) -> DenseBatch:
    """Densify a RowBlock into [B, num_feature] (B padded to batch_size).

    ``fill_value`` seeds features absent from a row: 0.0 by default
    (classic densification), ``np.nan`` for sparsity-aware GBDT training
    (GBDTParam.handle_missing) where absent means missing, not zero —
    XGBoost's sparse-libsvm semantics.  Padding rows are zeroed either way
    (they carry weight 0 and NaN would poison binning).
    """
    n = block.size
    b = batch_size or n
    CHECK_LE(n, b, "block larger than batch_size")
    x = np.full((b, num_feature), np.float32(fill_value), dtype=np.float32)
    if fill_value != 0.0:          # True for NaN too (NaN != 0.0)
        x[n:] = 0.0
    nnz = block.num_nonzero
    if nnz:
        rows = np.repeat(np.arange(n), np.diff(block.offset - block.offset[0]))
        idx = np.asarray(block.index, dtype=np.int64)
        CHECK(int(idx.max()) < num_feature, "feature index exceeds num_feature")
        vals = (block.value if block.value is not None
                else np.ones(nnz, dtype=np.float32))
        x[rows, idx] = vals
    label = np.zeros(b, dtype=np.float32)
    label[:n] = block.label
    weight = np.zeros(b, dtype=np.float32)
    weight[:n] = block.weight if block.weight is not None else 1.0
    return DenseBatch(x, label, weight, num_rows=n)


def block_to_sparse(block: RowBlock, nnz_bucket: Optional[int] = None,
                    batch_size: Optional[int] = None) -> SparseBatch:
    """Flatten a RowBlock into padded flat-COO (segment-sum ready)."""
    n = block.size
    b = batch_size or n
    CHECK_LE(n, b, "block larger than batch_size")
    nnz = block.num_nonzero
    cap = nnz_bucket or bucket_size(max(nnz, 1))
    CHECK_LE(nnz, cap, "nnz exceeds bucket")
    value = np.zeros(cap, dtype=np.float32)
    value[:nnz] = (block.value if block.value is not None
                   else np.ones(nnz, dtype=np.float32))
    index = np.zeros(cap, dtype=np.int32)
    index[:nnz] = block.index
    row_id = np.full(cap, b, dtype=np.int32)
    row_id[:nnz] = np.repeat(np.arange(n, dtype=np.int32),
                             np.diff(block.offset - block.offset[0]))
    label = np.zeros(b, dtype=np.float32)
    label[:n] = block.label
    weight = np.zeros(b, dtype=np.float32)
    weight[:n] = block.weight if block.weight is not None else 1.0
    field = None
    if block.field is not None:
        field = np.zeros(cap, dtype=np.int32)
        field[:nnz] = block.field
    return SparseBatch(value, index, row_id, label, weight, field,
                       num_rows=n)


class _Rebatcher:
    """Slice a stream of variable-size RowBlocks into fixed-size batches.

    Final-partial-batch contract: with ``drop_remainder=False`` (the
    default) the leftover ``0 < r < batch_size`` rows are emitted as one
    short block — the downstream ``block_to_dense`` / ``block_to_sparse``
    pad it back up to ``batch_size`` with **masked** rows (``weight == 0``,
    ``label == 0``, ``num_rows == r``), so consumers see only static
    shapes and slice/weight the padding away; an empty parser yields no
    batches at all (never an all-padding one).  With
    ``drop_remainder=True`` the short tail is dropped (equal step counts
    across data-parallel workers matter more than the last rows).
    """

    def __init__(self, parser: Parser, batch_size: int, drop_remainder: bool):
        self._parser = parser
        self._batch = batch_size
        self._drop = drop_remainder

    def __iter__(self) -> Iterator[RowBlock]:
        pending: list = []
        pending_rows = 0
        for block in self._parser:
            pending.append(block)
            pending_rows += block.size
            while pending_rows >= self._batch:
                merged = pending[0] if len(pending) == 1 else concat_blocks(pending)
                out = merged.slice(0, self._batch)
                rest = merged.slice(self._batch, merged.size)
                yield out
                pending = [rest] if rest.size else []
                pending_rows = rest.size
        if pending_rows and not self._drop:
            merged = pending[0] if len(pending) == 1 else concat_blocks(pending)
            yield merged


def dense_batches(parser: Parser, batch_size: int, num_feature: int,
                  drop_remainder: bool = False,
                  fill_value: float = 0.0) -> Iterator[DenseBatch]:
    """Fixed-size dense batches from a parser.

    Every yielded batch is exactly ``[batch_size, num_feature]``; the
    final partial batch (``drop_remainder=False``) arrives zero-padded
    with the mask in ``weight`` (0.0 on padding rows — explicit row
    weights are preserved on real rows) and the true row count in
    ``num_rows`` (see :class:`_Rebatcher` for the full contract).

    ``fill_value=np.nan`` marks absent features as missing for
    sparsity-aware GBDT training (see :func:`block_to_dense`).
    """
    for block in _Rebatcher(parser, batch_size, drop_remainder):
        yield block_to_dense(block, num_feature, batch_size,
                             fill_value=fill_value)


def sparse_batches(parser: Parser, batch_size: int,
                   nnz_bucket: Optional[int] = None,
                   drop_remainder: bool = False) -> Iterator[SparseBatch]:
    """Fixed-size flat-COO batches; nnz padded to a bucket ladder.

    The final partial batch (``drop_remainder=False``) keeps the static
    ``[batch_size]`` row axis: padding rows carry ``weight == 0`` and
    padding nnz slots carry ``row_id == batch_size`` (the segment-sum
    drop segment), with the true row count in ``num_rows``.
    """
    for block in _Rebatcher(parser, batch_size, drop_remainder):
        cap = nnz_bucket or bucket_size(block.num_nonzero or 1)
        yield block_to_sparse(block, cap, batch_size)
