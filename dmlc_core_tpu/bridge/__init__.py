"""JAX bridge: RowBlocks -> mesh-placed jax.Array batches + URI checkpoints.

This is the genuinely new, TPU-native layer (SURVEY.md §7 stage 4): the
reference's ThreadedIter feeding a training binary becomes a double-buffered
host loader emitting statically-shaped device arrays against an explicit
mesh/sharding, with per-host input sharding riding the same InputSplit math.
"""

from dmlc_core_tpu.bridge.batching import (  # noqa: F401
    DenseBatch,
    SparseBatch,
    dense_batches,
    sparse_batches,
    block_to_dense,
    block_to_sparse,
)
from dmlc_core_tpu.bridge.binning import (  # noqa: F401
    BinnedBatch,
    HostBinner,
    binned_batches,
    fit_binner,
)
from dmlc_core_tpu.bridge.loader import (MeshBatchLoader,  # noqa: F401
                                         DeviceFeedLoader)
from dmlc_core_tpu.bridge.checkpoint import (save_checkpoint,  # noqa: F401
                                             load_checkpoint,
                                             AsyncCheckpointer,
                                             CheckpointManager)
