"""Checkpoint pytrees to any URI-dispatched stream.

The reference's checkpoint mechanism is "Serializable::Save to any URI"
(io.h:112-126 + remote write streams, SURVEY.md §5.4).  The TPU equivalent:
flatten a jax/numpy pytree, write each leaf as a typed array onto a
:func:`dmlc_core_tpu.io.create_stream` (local/S3/GCS/... decided by URI), with
a JSON header describing the tree structure — so a checkpoint written on a
pod restores anywhere the URI resolves.

Format: magic "DMLCTPU1" | u64 header_len | header JSON | leaf blobs in order.
Header: {"leaves": [{"path": str, "dtype": str, "shape": [...]}, ...]}.

**Manifests** (the serving hot-swap contract, docs/serving.md "Model
lifecycle"): :class:`CheckpointManager` publishes a tiny JSON manifest
beside each step — ``ckpt-XXXXXXXX.manifest.json`` with the step number,
the blob's byte count, a CRC-32 over every blob byte, and the wall time —
written only *after* the checkpoint bytes are durable.  A reader that goes
manifest-first therefore never opens a partially written checkpoint on a
store without atomic rename, and :func:`verify_checkpoint` re-hashes the
blob against its manifest so corrupt/truncated bytes are rejected before
any jax work touches them.
"""

from __future__ import annotations

import glob
import json
import os
import re
import struct
import threading
import time
import zlib
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from dmlc_core_tpu.io.stream import create_stream, create_stream_for_read
from dmlc_core_tpu.utils.logging import CHECK, CHECK_EQ, log_info, log_warning

__all__ = ["save_checkpoint", "load_checkpoint", "AsyncCheckpointer",
           "CheckpointManager", "CheckpointCorruptError", "verify_checkpoint"]

_MAGIC = b"DMLCTPU1"

MANIFEST_SUFFIX = ".manifest.json"
MANIFEST_VERSION = 1

_VERIFY_CHUNK = 1 << 20


class CheckpointCorruptError(RuntimeError):
    """A checkpoint whose bytes disagree with its manifest (or that is not
    a checkpoint at all) — the one error a hot-swap validator must turn
    into "previous-good keeps serving", never into a crash."""


def _flatten(tree: Any):
    import jax

    leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    paths = [jax.tree_util.keystr(path) for path, _ in leaves]
    values = [leaf for _, leaf in leaves]
    return paths, values, treedef


def _is_local_uri(uri: str) -> bool:
    return "://" not in uri or uri.startswith("file://")


def _strip_file_scheme(uri: str) -> str:
    return uri[len("file://"):] if uri.startswith("file://") else uri


def _temp_suffix() -> str:
    """Host+pid writer tag for temp names: pid liveness is only decidable on
    the writing host, so the host must be part of the name."""
    import socket

    return f"{socket.gethostname()}.{os.getpid()}"


def _sweep_orphan_temps(base_path: str) -> None:
    """Remove ``{base_path}.tmp.<host>.<pid>`` files whose writer is dead.

    Liveness (``kill(pid, 0)``) is only meaningful for temps written on THIS
    host; another host's in-flight temp on a shared filesystem must never be
    classified dead by a local pid probe, so foreign-host temps are left
    alone (they are cleaned by their own host's next save/retention pass).
    """
    import socket

    host = socket.gethostname()
    prefix = base_path + ".tmp."
    for stale in glob.glob(prefix + "*"):
        rest = stale[len(prefix):]          # "<host>.<pid>" (legacy: "<pid>")
        tmp_host, _, pid_s = rest.rpartition(".")
        if tmp_host and tmp_host != host:
            continue                        # foreign host: cannot test pid
        try:
            pid = int(pid_s)
        except ValueError:
            continue                        # unrecognized name: leave it
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            pass                            # dead writer: sweep
        except OSError:
            continue                        # e.g. EPERM: pid exists
        else:
            continue                        # live writer, leave it
        try:
            os.remove(stale)
        except OSError:
            pass


def save_checkpoint(uri: str, tree: Any) -> Dict[str, Any]:
    """Write a pytree of arrays/scalars to ``uri``; returns a digest summary
    ``{"nbytes", "crc32", "num_leaves"}`` over the exact bytes written (what
    :class:`CheckpointManager` publishes as the step's manifest).

    Local writes are atomic (temp file + rename), so a crash mid-write never
    leaves a truncated checkpoint at the final path.  Remote stores already
    commit object writes atomically at close (e.g. S3 complete-multipart).
    """
    paths, values, _ = _flatten(tree)
    arrays = [np.asarray(v) for v in values]
    header = json.dumps({
        "leaves": [
            {"path": p, "dtype": str(a.dtype), "shape": list(a.shape)}
            for p, a in zip(paths, arrays)
        ]
    }).encode("utf-8")
    target = uri
    local = _is_local_uri(uri)
    if local:
        # host+pid-unique temp name: concurrent savers to the same URI (even
        # across hosts on a shared filesystem) must not interleave writes
        # into one temp file and rename a torn mix
        target = f"{uri}.tmp.{_temp_suffix()}"
    crc = 0
    nbytes = 0

    def _put(fo, chunk: bytes) -> None:
        nonlocal crc, nbytes
        fo.write(chunk)
        crc = zlib.crc32(chunk, crc)
        nbytes += len(chunk)

    with create_stream(target, "w") as fo:
        _put(fo, _MAGIC)
        fo.write_u64(len(header))
        crc = zlib.crc32(struct.pack("<Q", len(header)), crc)
        nbytes += 8
        _put(fo, header)
        for a in arrays:
            _put(fo, np.ascontiguousarray(a).tobytes())
    if local:
        os.replace(_strip_file_scheme(target), _strip_file_scheme(uri))
    return {"nbytes": nbytes, "crc32": crc, "num_leaves": len(arrays)}


def verify_checkpoint(uri: str, manifest: Dict[str, Any]) -> None:
    """Re-hash the blob at ``uri`` against its manifest — magic, byte
    count, CRC-32 — raising :class:`CheckpointCorruptError` on any
    disagreement.  Pure byte IO: no numpy reshaping, no jax, so a hot-swap
    validator can reject a torn or bit-rotted candidate before any model
    work starts.
    """
    want_nbytes = int(manifest.get("nbytes", -1))
    want_crc = int(manifest.get("crc32", -1))
    crc = 0
    nbytes = 0
    first = b""
    with (create_stream_for_read(uri) or create_stream(uri, "r")) as fi:
        while True:
            chunk = fi.read(_VERIFY_CHUNK)
            if not chunk:
                break
            if nbytes < len(_MAGIC):
                first += chunk[:len(_MAGIC) - nbytes]
            crc = zlib.crc32(chunk, crc)
            nbytes += len(chunk)
    if first != _MAGIC:
        raise CheckpointCorruptError(
            f"{uri!r}: not a dmlc_core_tpu checkpoint (bad magic)")
    if nbytes != want_nbytes:
        raise CheckpointCorruptError(
            f"{uri!r}: {nbytes} bytes on store, manifest says "
            f"{want_nbytes} (truncated or torn write)")
    if crc != want_crc:
        raise CheckpointCorruptError(
            f"{uri!r}: CRC-32 mismatch (got {crc:#010x}, manifest says "
            f"{want_crc:#010x}) — corrupt checkpoint")


def load_checkpoint(uri: str, template: Any = None) -> Any:
    """Read a checkpoint back.

    With ``template`` (a pytree of matching structure), returns the template's
    structure filled with loaded leaves.  Without, returns a flat
    ``{path: array}`` dict.
    """
    import jax

    with (create_stream_for_read(uri) or create_stream(uri, "r")) as fi:
        CHECK_EQ(fi.read_exact(8), _MAGIC, "not a dmlc_core_tpu checkpoint")
        header = json.loads(fi.read_exact(fi.read_u64()).decode("utf-8"))
        loaded = {}
        for leaf in header["leaves"]:
            dtype = np.dtype(leaf["dtype"])
            shape = tuple(leaf["shape"])
            nbytes = dtype.itemsize * int(np.prod(shape)) if shape else dtype.itemsize
            data = fi.read_exact(int(nbytes))
            loaded[leaf["path"]] = np.frombuffer(data, dtype=dtype).reshape(shape)
    if template is None:
        return loaded
    paths, values, treedef = _flatten(template)
    CHECK_EQ(len(paths), len(loaded), "checkpoint/template structure mismatch")
    new_values = []
    for p, v in zip(paths, values):
        CHECK(p in loaded, f"checkpoint missing leaf {p!r}")
        arr = loaded[p]
        CHECK_EQ(tuple(arr.shape), tuple(np.shape(v)),
                 f"shape mismatch for leaf {p!r}")
        new_values.append(arr)
    return jax.tree_util.tree_unflatten(treedef, new_values)


class AsyncCheckpointer:
    """Orbax-style async checkpoint writes (SURVEY.md §5.4).

    ``save`` synchronously snapshots device arrays to host memory (so the
    training step can immediately mutate state) and hands the byte writing —
    typically the slow part on a remote store — to a background thread.  At
    most one write is in flight; a second ``save`` first waits for the
    previous one.  Errors from the background write surface on the next
    ``save``/``wait_until_finished`` call, carrying the failed URI.
    """

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._error_uri: Optional[str] = None

    def save(self, uri: str, tree: Any, on_durable=None) -> None:
        """Snapshot ``tree`` and write it in the background.

        ``on_durable`` (optional) runs on the writer thread only after the
        checkpoint bytes are fully committed, receiving the
        :func:`save_checkpoint` digest summary — the hook manifest
        publication and retention use, so older steps are never deleted
        (and the manifest never appears) while the write is in flight.
        """
        self.wait_until_finished()
        # snapshot on the caller's thread: device->host transfer completes
        # here, so the step loop may overwrite the arrays right away
        snapshot = _host_snapshot(tree)

        def _write():
            try:
                summary = save_checkpoint(uri, snapshot)
            except BaseException as e:  # ferried to the caller's thread
                self._error = e
                self._error_uri = uri
                return
            if on_durable is not None:
                try:
                    on_durable(summary)
                except BaseException as e:
                    # the checkpoint IS durable — a retention/hook failure
                    # must not masquerade as a write failure and block restore
                    log_warning(f"post-checkpoint hook for {uri!r} "
                                f"failed: {e}")

        # non-daemon: interpreter shutdown joins the writer, so a script that
        # exits right after save() still gets a complete final checkpoint
        self._thread = threading.Thread(target=_write,
                                        name="dmlc-ckpt-writer", daemon=False)
        self._thread.start()

    def wait_until_finished(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, uri = self._error, self._error_uri
            self._error = self._error_uri = None
            raise RuntimeError(f"async checkpoint to {uri!r} failed") from err


def _host_snapshot(tree: Any) -> Any:
    import jax

    # np.array(copy=True): device arrays transfer, host arrays genuinely
    # copy — np.asarray would alias a numpy input and let the caller's next
    # step race the background write
    return jax.tree_util.tree_map(lambda v: np.array(v, copy=True), tree)


class CheckpointManager:
    """Step-numbered checkpoints with retention and latest-step resume.

    Directory layout: ``{directory}/ckpt-{step:08d}`` over any URI-dispatched
    store.  ``keep`` bounds how many past steps are retained (retention
    deletes only on local paths; remote stores are expected to carry their
    own lifecycle rules — a warning is logged once).  This is the
    slice-granular resume story of SURVEY §5.3/§5.4: every process restarts,
    finds ``latest_step()``, restores, continues.
    """

    _STEP_RE = re.compile(r"ckpt-(\d{8,})$")

    def __init__(self, directory: str, keep: int = 3):
        CHECK(keep >= 1, "keep must be >= 1")
        self.directory = directory.rstrip("/")
        self.keep = keep
        self._async = AsyncCheckpointer()
        # _retain runs on whatever thread made the step durable (the async
        # writer thread, a trainer's publish clock) — the once-only
        # retention warning flag needs a lock like any shared write
        self._warn_lock = threading.Lock()
        self._warned_retention = False
        self._is_local = "://" not in directory or \
            directory.startswith("file://")

    def step_uri(self, step: int) -> str:
        return f"{self.directory}/ckpt-{step:08d}"

    # internal alias kept for call-site brevity
    _step_uri = step_uri

    def manifest_uri(self, step: int) -> str:
        return self.step_uri(step) + MANIFEST_SUFFIX

    def all_steps(self) -> List[int]:
        from dmlc_core_tpu.io.filesys import URI, get_filesystem

        base = URI(self.directory)
        try:
            infos = get_filesystem(base).list_directory(base)
        except FileNotFoundError:
            return []          # directory not created yet = no checkpoints;
                               # other listing errors (auth, transient remote
                               # failures) must propagate, not masquerade as
                               # "start fresh"
        steps = []
        for info in infos:
            m = self._STEP_RE.search(str(info.path))
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def latest_valid(self, *, above: int = -1,
                     known_bad: Iterable[Tuple[int, Any]] = (),
                     verify: bool = False,
                     skip_unpublished: bool = False) \
            -> Tuple[Optional[int], Optional[Dict[str, Any]]]:
        """The newest trustworthy step, manifest-first: ``(step, manifest)``
        or ``(None, None)``.

        One scan, two callers (the fallback-past-bad-steps logic must exist
        exactly once): the serving :class:`~dmlc_core_tpu.serve.lifecycle.
        CheckpointWatcher` candidate pick and the continuous trainer's
        crash-resume.  Newest first, skipping every ``(step, crc32)`` pair
        in ``known_bad`` (the watcher's rejected-candidate ledger), and
        stopping at ``above`` exclusive.

        A step without a parseable manifest stops the scan by default —
        its write may still be in flight, and falling back to an older
        step would just churn a watcher (watch semantics).  With
        ``skip_unpublished=True`` it is skipped instead: a resuming
        trainer KNOWS the previous writer is dead, so a manifest-less
        newest step is an abandoned publish, not an in-flight one.

        ``verify=True`` additionally re-hashes each candidate's blob
        against its manifest (:func:`verify_checkpoint`) and falls back
        past corrupt/truncated steps — resume must never restore bytes
        the serving validate stage would reject.
        """
        bad = set(known_bad)
        for step in reversed(self.all_steps()):
            if step <= above:
                return None, None
            manifest = self.read_manifest(step)
            if manifest is None:
                if skip_unpublished:
                    continue
                return None, None
            if (step, manifest.get("crc32")) in bad:
                continue
            if verify:
                try:
                    verify_checkpoint(self.step_uri(step), manifest)
                except Exception as e:
                    log_warning(f"checkpoint step {step} fails its "
                                f"manifest ({e}); falling back past it")
                    continue
            return step, manifest
        return None, None

    def prepare_step(self, step: int) -> str:
        """Make the step's URI writable and return it: ensure the local
        directory exists and sweep temp orphans a crashed previous writer
        of this step left behind (pid-unique temp names would otherwise
        accumulate); live writers' temps are skipped.  No-op on remote
        stores.  External publishers (the continuous trainer's
        temp+verify+manifest-last sequence) call this before their own
        :func:`save_checkpoint`."""
        uri = self.step_uri(step)
        if self._is_local:
            os.makedirs(_strip_file_scheme(self.directory), exist_ok=True)
            _sweep_orphan_temps(_strip_file_scheme(uri))
        return uri

    def save(self, step: int, tree: Any, async_: bool = True) -> None:
        uri = self.prepare_step(step)
        if async_:
            # manifest + retention run on the writer thread only once the
            # new step is durable — publishing the manifest earlier would
            # point readers at in-flight bytes, and deleting older steps
            # before durability could leave zero restorable checkpoints
            self._async.save(uri, tree,
                             on_durable=lambda summary:
                             self._publish(step, summary))
        else:
            summary = save_checkpoint(uri, tree)
            self._publish(step, summary)
        log_info(f"checkpoint step {step} -> {uri}")

    def _publish(self, step: int, summary: Dict[str, Any]) -> None:
        """Write the step's manifest (the durable blob's digest), then run
        retention.  Ordering is the whole point: a manifest-first reader
        (the serving checkpoint watcher) never opens a checkpoint whose
        bytes are still in flight."""
        self.write_manifest(step, summary)
        self._retain(step)

    def publish(self, step: int, summary: Dict[str, Any]) -> None:
        """Publish a step an external writer already made durable (and
        verified): manifest-last + retention.  The tail of the continuous
        trainer's temp+verify+manifest-last publish."""
        self._publish(step, summary)

    def write_manifest(self, step: int, summary: Dict[str, Any]) -> None:
        manifest = {
            "manifest_version": MANIFEST_VERSION,
            "step": int(step),
            "nbytes": int(summary["nbytes"]),
            "crc32": int(summary["crc32"]),
            "num_leaves": int(summary.get("num_leaves", 0)),
            # current wall time, NOT clock.wall_epoch() (that is the
            # constant process-start anchor — every manifest a long
            # trainer publishes would carry the same timestamp)
            "written_at": time.time(),
        }
        uri = self.manifest_uri(step)
        payload = json.dumps(manifest, sort_keys=True).encode("utf-8")
        target = uri
        if self._is_local:
            # same atomic temp+rename discipline as the blob: a torn
            # manifest must never validate (or invalidate) a good blob
            target = f"{uri}.tmp.{_temp_suffix()}"
        with create_stream(target, "w") as fo:
            fo.write(payload)
        if self._is_local:
            os.replace(_strip_file_scheme(target), _strip_file_scheme(uri))

    def read_manifest(self, step: int) -> Optional[Dict[str, Any]]:
        """The step's manifest dict, or ``None`` when it is absent or
        unparseable — both mean "do not trust this checkpoint yet" to a
        manifest-first reader (absent = the blob may still be writing)."""
        uri = self.manifest_uri(step)
        try:
            with (create_stream_for_read(uri) or create_stream(uri, "r")) as fi:
                chunks = []
                while True:
                    chunk = fi.read(1 << 16)
                    if not chunk:
                        break
                    chunks.append(chunk)
                raw = b"".join(chunks)
        except Exception:
            return None
        try:
            manifest = json.loads(raw.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            log_warning(f"checkpoint manifest {uri!r} unparseable ({e}); "
                        "treating the step as unpublished")
            return None
        if not isinstance(manifest, dict):
            log_warning(f"checkpoint manifest {uri!r} is not an object; "
                        "treating the step as unpublished")
            return None
        return manifest

    def restore(self, step: Optional[int] = None,
                template: Any = None) -> Any:
        self.wait_until_finished()
        if step is not None:
            return load_checkpoint(self._step_uri(step), template)
        steps = self.all_steps()
        CHECK(bool(steps), f"no checkpoints under {self.directory!r}")
        # newest first, falling back past corrupt/truncated files (a remote
        # store without atomic rename can expose a partial newest step)
        last_err: Optional[BaseException] = None
        for s in reversed(steps):
            try:
                return load_checkpoint(self._step_uri(s), template)
            except Exception as e:
                log_warning(f"checkpoint step {s} unreadable ({e}); "
                            "falling back to previous step")
                last_err = e
        raise RuntimeError(
            f"all checkpoints under {self.directory!r} are unreadable"
        ) from last_err

    def wait_until_finished(self) -> None:
        self._async.wait_until_finished()

    def _retain(self, current_step: int) -> None:
        if not self._is_local:
            # retention only deletes local checkpoints; skip the (remote)
            # listing round-trip entirely on the hot save path
            with self._warn_lock:
                warn, self._warned_retention = \
                    not self._warned_retention, True
            if warn:
                log_warning("CheckpointManager retention only deletes local "
                            "checkpoints; remote steps are left in place")
            return
        # current_step is durable by the time retention runs (sync path, or
        # the writer thread's on_durable hook); the union guards against a
        # lagging directory listing — only strictly older steps are deleted
        steps = sorted(set(self.all_steps()) | {current_step})
        excess = [s for s in steps[:-self.keep] if s != current_step]
        for s in excess:
            path = _strip_file_scheme(self._step_uri(s))
            # manifest first: a step must never look published (manifest
            # present) after its blob is gone
            try:
                os.remove(path + MANIFEST_SUFFIX)
            except OSError:
                pass
            try:
                os.remove(path)
            except OSError:
                pass
            _sweep_orphan_temps(path)
