"""Checkpoint pytrees to any URI-dispatched stream.

The reference's checkpoint mechanism is "Serializable::Save to any URI"
(io.h:112-126 + remote write streams, SURVEY.md §5.4).  The TPU equivalent:
flatten a jax/numpy pytree, write each leaf as a typed array onto a
:func:`dmlc_core_tpu.io.create_stream` (local/S3/GCS/... decided by URI), with
a JSON header describing the tree structure — so a checkpoint written on a
pod restores anywhere the URI resolves.

Format: magic "DMLCTPU1" | u64 header_len | header JSON | leaf blobs in order.
Header: {"leaves": [{"path": str, "dtype": str, "shape": [...]}, ...]}.
"""

from __future__ import annotations

import json
from typing import Any

import numpy as np

from dmlc_core_tpu.io.stream import create_stream, create_stream_for_read
from dmlc_core_tpu.utils.logging import CHECK, CHECK_EQ

__all__ = ["save_checkpoint", "load_checkpoint"]

_MAGIC = b"DMLCTPU1"


def _flatten(tree: Any):
    import jax

    leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    paths = [jax.tree_util.keystr(path) for path, _ in leaves]
    values = [leaf for _, leaf in leaves]
    return paths, values, treedef


def save_checkpoint(uri: str, tree: Any) -> None:
    """Write a pytree of arrays/scalars to ``uri``."""
    import jax

    paths, values, _ = _flatten(tree)
    arrays = [np.asarray(v) for v in values]
    header = json.dumps({
        "leaves": [
            {"path": p, "dtype": str(a.dtype), "shape": list(a.shape)}
            for p, a in zip(paths, arrays)
        ]
    }).encode("utf-8")
    with create_stream(uri, "w") as fo:
        fo.write(_MAGIC)
        fo.write_u64(len(header))
        fo.write(header)
        for a in arrays:
            fo.write(np.ascontiguousarray(a).tobytes())


def load_checkpoint(uri: str, template: Any = None) -> Any:
    """Read a checkpoint back.

    With ``template`` (a pytree of matching structure), returns the template's
    structure filled with loaded leaves.  Without, returns a flat
    ``{path: array}`` dict.
    """
    import jax

    with (create_stream_for_read(uri) or create_stream(uri, "r")) as fi:
        CHECK_EQ(fi.read_exact(8), _MAGIC, "not a dmlc_core_tpu checkpoint")
        header = json.loads(fi.read_exact(fi.read_u64()).decode("utf-8"))
        loaded = {}
        for leaf in header["leaves"]:
            dtype = np.dtype(leaf["dtype"])
            shape = tuple(leaf["shape"])
            nbytes = dtype.itemsize * int(np.prod(shape)) if shape else dtype.itemsize
            data = fi.read_exact(int(nbytes))
            loaded[leaf["path"]] = np.frombuffer(data, dtype=dtype).reshape(shape)
    if template is None:
        return loaded
    paths, values, treedef = _flatten(template)
    CHECK_EQ(len(paths), len(loaded), "checkpoint/template structure mismatch")
    new_values = []
    for p, v in zip(paths, values):
        CHECK(p in loaded, f"checkpoint missing leaf {p!r}")
        arr = loaded[p]
        CHECK_EQ(tuple(arr.shape), tuple(np.shape(v)),
                 f"shape mismatch for leaf {p!r}")
        new_values.append(arr)
    return jax.tree_util.tree_unflatten(treedef, new_values)
