"""Host-side quantile binning: compute edges once, ship uint8 over the wire.

The device-feed bottleneck (VERDICT.md, ROADMAP item 1): a 2M x 28
float32 hist-training feed moves ~670 MB host<->device (x f32 up, bins
i32 back, bins up again) through a ~10-15 MB/s tunnel, while the hist
algorithm only ever reads the 256-bin ids — the same 8-bit representation
LightGBM/XGBoost histogram training computes on.  This module moves the
binning to the host so the wire carries the **uint8 bins** instead:

- :func:`fit_binner` streams quantile bin edges over any row source — a
  raw ``[n, F]`` array, an iterable of arrays, a parser / RowBlock
  iterator, or :class:`~dmlc_core_tpu.data.page_cache.PageCacheReader`'s
  zero-copy mmap'd views — using the same mergeable per-chunk summaries
  as the distributed sketch (:mod:`dmlc_core_tpu.ops.histogram`), so the
  edges are computed in one pass without materialising the dataset;
- :class:`HostBinner` applies those edges with numpy ``searchsorted``
  exactly as the on-device :func:`~dmlc_core_tpu.ops.histogram.apply_bins`
  does (``side="right"``, same NaN handling), emitting the narrowest wire
  dtype that holds ``num_bins`` ids (uint8 through 256 bins) — split
  decisions are bitwise-identical to the float path by construction
  (asserted in ``tests/test_device_feed.py``);
- :class:`BinnedBatch` + :func:`binned_batches` adapt the existing dense
  batch pipeline to the binned wire format for the device-feed loader.

Wire-format size math (the reason this module exists): ``n x F`` rows cost
``n*F`` bytes binned-uint8 vs ``3 * n*F * 4`` on the old
device-side-binning path — a 12x wire reduction (2M x 28: 56 MB vs
~670 MB), plus ``8n`` bytes of labels+weights either way.
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator, NamedTuple, Optional

import numpy as np

from dmlc_core_tpu.bridge.batching import (DenseBatch, _register_batch_pytree,
                                           dense_batches)
from dmlc_core_tpu.data.row_block import RowBlock
from dmlc_core_tpu.ops.histogram import (local_quantile_summary,
                                         merged_quantile_boundaries)
from dmlc_core_tpu.utils.logging import CHECK

__all__ = ["HostBinner", "BinnedBatch", "fit_binner",
           "fit_binner_from_summaries", "default_summary_points",
           "binned_batches", "wire_dtype"]


def default_summary_points(num_bins: int) -> int:
    """Per-chunk summary resolution K for ``num_bins`` target bins — the
    single formula both :func:`fit_binner` and any external summary
    producer (the fleet-ingest workers) must share for their summaries to
    merge into identical edges."""
    return max(64, 8 * num_bins)


def wire_dtype(num_bins: int) -> np.dtype:
    """The narrowest unsigned dtype that holds ``num_bins`` bin ids."""
    CHECK(num_bins >= 2, f"num_bins must be >= 2, got {num_bins}")
    if num_bins <= 256:
        return np.dtype(np.uint8)
    if num_bins <= 65536:
        return np.dtype(np.uint16)
    return np.dtype(np.int32)


class BinnedBatch(NamedTuple):
    """A :class:`~dmlc_core_tpu.bridge.batching.DenseBatch` whose features
    are pre-binned ids in the wire dtype — what the device feed ships.

    Same padding/masking contract as DenseBatch: padding rows carry
    ``weight == 0`` and the true row count rides in ``num_rows`` (static
    aux data, host-side)."""

    bins: np.ndarray     # [B, F] wire dtype (uint8 for <=256 bins)
    label: np.ndarray    # [B] float32
    weight: np.ndarray   # [B] float32 (0.0 marks padding)
    num_rows: Optional[int] = None


_register_batch_pytree(BinnedBatch, ("bins", "label", "weight"))


class HostBinner:
    """Apply fixed quantile edges on the host; emit wire-dtype bin ids.

    ``boundaries`` is ``[F, eff_bins - 1]`` float32 exactly as
    :meth:`GBDT.make_bins` / :func:`fit_binner` produce it, where
    ``eff_bins = num_bins - 1`` when ``handle_missing`` reserves the last
    id for NaNs (the GBDT sparsity-aware contract), else ``num_bins``.

    :meth:`transform` is the host twin of the on-device
    :func:`~dmlc_core_tpu.ops.histogram.apply_bins`: identical ids for
    identical float32 inputs (both are ``searchsorted(side="right")`` over
    the same edges), so a model trained on these bins makes bitwise-equal
    split decisions to one that binned on device.
    """

    def __init__(self, boundaries: np.ndarray, num_bins: int,
                 handle_missing: bool = False):
        boundaries = np.asarray(boundaries, dtype=np.float32)
        CHECK(boundaries.ndim == 2,
              f"boundaries must be [F, bins-1], got {boundaries.shape}")
        eff = num_bins - 1 if handle_missing else num_bins
        CHECK(boundaries.shape[1] == eff - 1,
              f"boundaries have {boundaries.shape[1] + 1} bins; expected "
              f"{eff} (num_bins={num_bins}, handle_missing={handle_missing})")
        self.boundaries = boundaries
        self.num_bins = num_bins
        self.handle_missing = handle_missing
        self.dtype = wire_dtype(num_bins)

    @property
    def num_feature(self) -> int:
        return self.boundaries.shape[0]

    def transform(self, x: np.ndarray) -> np.ndarray:
        """Bin ``x [B, F]`` float -> ``[B, F]`` wire-dtype ids.

        NaNs take the reserved missing id under ``handle_missing``;
        without it they land in the last bin (numpy and jax searchsorted
        agree: NaN compares false against every edge probe, so the binary
        search walks right) — both match ``apply_bins`` exactly.
        """
        x = np.asarray(x)
        CHECK(x.ndim == 2 and x.shape[1] == self.num_feature,
              f"x must be [B, {self.num_feature}], got {x.shape}")
        x32 = np.ascontiguousarray(x, dtype=np.float32)
        out = np.empty(x32.shape, dtype=self.dtype)
        for f in range(self.num_feature):
            out[:, f] = np.searchsorted(self.boundaries[f], x32[:, f],
                                        side="right")
        if self.handle_missing:
            out[np.isnan(x32)] = self.num_bins - 1
        return out

    def transform_batch(self, batch: DenseBatch) -> BinnedBatch:
        """Bin one dense batch into the wire format (labels/weights/count
        pass through untouched)."""
        return BinnedBatch(self.transform(batch.x), batch.label,
                           batch.weight, num_rows=batch.num_rows)

    def wire_nbytes(self, n_rows: int) -> int:
        """Bytes one ``[n_rows, F]`` binned feed ships (features only)."""
        return n_rows * self.num_feature * self.dtype.itemsize


def _dense_chunks(source: Any, num_feature: Optional[int],
                  handle_missing: bool) -> Iterator[np.ndarray]:
    """Normalize any supported row source into ``[n, F]`` float chunks.

    RowBlock sources densify chunk-by-chunk (absent features become NaN
    under ``handle_missing`` — the XGBoost sparse-means-missing
    semantics — else 0.0, matching ``block_to_dense``); ndarray sources
    stream through untouched, so page-cache views and parser output both
    feed the same summary math.
    """
    from dmlc_core_tpu.bridge.batching import block_to_dense

    fill = np.nan if handle_missing else 0.0

    def one(item):
        if isinstance(item, RowBlock):
            CHECK(num_feature is not None,
                  "RowBlock sources need num_feature= to densify")
            return block_to_dense(item, num_feature, fill_value=fill).x
        arr = np.asarray(item)
        CHECK(arr.ndim == 2, f"chunks must be [n, F], got {arr.shape}")
        return arr

    if isinstance(source, np.ndarray):
        yield one(source)
        return
    if isinstance(source, RowBlock):
        yield one(source)
        return
    for item in source:
        chunk = one(item)
        if chunk.shape[0]:
            yield chunk


def _resummarize(points: np.ndarray, counts: np.ndarray,
                 num_points: int) -> np.ndarray:
    """Collapse pooled per-chunk summaries to one fixed [F, num_points]
    summary (weighted quantiles of the pooled points) so a streamed fit
    can still allgather a fixed-size block per rank."""
    return merged_quantile_boundaries(points, counts, num_points + 1)


def fit_binner(source: Any, num_bins: int,
               num_feature: Optional[int] = None,
               handle_missing: bool = False, comm=None,
               num_points: Optional[int] = None) -> HostBinner:
    """Stream quantile bin edges over ``source``; return a ready binner.

    ``source`` may be a ``[n, F]`` array, an iterable of arrays, a
    parser / RowBlock iterable (``num_feature`` required to densify), or
    a :class:`~dmlc_core_tpu.data.page_cache.PageCacheReader` (pass
    ``reader.blocks``) — the mmap'd views are read in place, never
    copied whole.  Each chunk contributes a fixed-size mergeable summary
    (:func:`~dmlc_core_tpu.ops.histogram.local_quantile_summary`) and the
    deterministic weighted merge produces the edges in one pass: memory
    is O(chunks x F x num_points), not O(rows).

    ``comm`` (rabit-shaped allgather, e.g. ``dmlc_core_tpu.collective``)
    makes edges consistent across data-parallel workers: the local stream
    is re-summarised to one fixed block per rank and merged globally, so
    every rank returns identical boundaries — same discipline as
    :func:`~dmlc_core_tpu.ops.histogram.distributed_quantile_boundaries`.

    ``handle_missing`` reserves the last bin id for NaN (GBDT
    sparsity-aware contract): edges then cover ``num_bins - 1`` real bins.
    """
    K = num_points or default_summary_points(num_bins)
    all_points, all_counts = [], []
    n_feat = None
    for chunk in _dense_chunks(source, num_feature, handle_missing):
        if n_feat is None:
            n_feat = chunk.shape[1]
        CHECK(chunk.shape[1] == n_feat,
              f"chunk feature dim {chunk.shape[1]} != {n_feat}")
        pts, cnt = local_quantile_summary(chunk, K)
        all_points.append(pts)
        all_counts.append(cnt)
    CHECK(all_points, "fit_binner: empty source (no rows to summarise)")
    return fit_binner_from_summaries(
        np.stack(all_points), np.stack(all_counts), num_bins,
        handle_missing=handle_missing, comm=comm, num_points=K)


def fit_binner_from_summaries(points: np.ndarray, counts: np.ndarray,
                              num_bins: int, *,
                              handle_missing: bool = False, comm=None,
                              num_points: Optional[int] = None) -> HostBinner:
    """The allgather-merge tail of :func:`fit_binner`, callable on
    pre-accumulated ``local_quantile_summary`` stacks.

    ``points [C, F, K]`` / ``counts [C, F]`` are this rank's per-chunk
    summaries (K must be :func:`default_summary_points` of ``num_bins``
    unless ``num_points`` overrides it, and every participating rank must
    use the same K).  With ``comm`` the local stack is re-summarised to one
    fixed ``[F, K]`` block, allgathered, and merged globally — every rank
    returns bitwise-identical boundaries.  This is how the fleet-ingest
    workers (:mod:`dmlc_core_tpu.parallel.fleet_ingest`) fit one
    cross-rank-consistent binner over dynamically-assigned unit sets:
    summaries accumulate per unit during ingest, and the rank's final
    merge goes through exactly this path.
    """
    eff_bins = num_bins - 1 if handle_missing else num_bins
    K = num_points or default_summary_points(num_bins)
    points = np.asarray(points, dtype=np.float32)
    counts = np.asarray(counts, dtype=np.float32)
    if comm is not None:
        local = _resummarize(points, counts, K)          # [F, K]
        local_mass = counts.sum(axis=0).astype(np.float32)
        points = comm.allgather(local.astype(np.float32))    # [W, F, K]
        counts = comm.allgather(local_mass)                  # [W, F]
    boundaries = merged_quantile_boundaries(points, counts, eff_bins)
    return HostBinner(boundaries, num_bins, handle_missing=handle_missing)


def binned_batches(parser, binner: HostBinner, batch_size: int,
                   drop_remainder: bool = False) -> Iterable[BinnedBatch]:
    """Fixed-size :class:`BinnedBatch` stream from a parser: the dense
    batch pipeline with host binning fused in, so downstream transfers
    ship wire-dtype ids instead of float32 features.

    Under ``binner.handle_missing`` absent features densify to NaN and
    bin to the reserved missing id (padding rows stay zero-binned with
    ``weight == 0``, exactly like the float pipeline's contract).
    """
    fill = np.nan if binner.handle_missing else 0.0
    for batch in dense_batches(parser, batch_size, binner.num_feature,
                               drop_remainder=drop_remainder,
                               fill_value=fill):
        yield binner.transform_batch(batch)
