"""Small common helpers (reference: include/dmlc/common.h:20-45)."""

from __future__ import annotations

from typing import List

__all__ = ["split_string", "hash_combine"]


def split_string(s: str, delim: str) -> List[str]:
    """Split a string by a single-char delimiter, dropping empty tokens.

    Matches the reference's ``dmlc::Split`` (common.h:20-32), which is built on
    istream getline and therefore never yields empty fields.
    """
    return [t for t in s.split(delim) if t != ""]


def hash_combine(seed: int, value: int) -> int:
    """Combine hash values boost-style (reference common.h:38-44), mod 2**64."""
    seed ^= (hash(value) + 0x9E3779B9 + ((seed << 6) & 0xFFFFFFFFFFFFFFFF) + (seed >> 2)) & 0xFFFFFFFFFFFFFFFF
    return seed & 0xFFFFFFFFFFFFFFFF
