"""Make ``JAX_PLATFORMS`` authoritative even under plugin-pinning images.

Some TPU images register their PJRT plugin from ``sitecustomize`` and pin
``jax_platforms`` via ``jax.config`` at import time, which silently overrides
the ``JAX_PLATFORMS`` environment variable.  CLIs that must honor an explicit
platform request (tests on a virtual CPU mesh, examples run off-accelerator)
call :func:`sync_platform_from_env` right after importing jax.
"""

from __future__ import annotations

import os

__all__ = ["sync_platform_from_env"]


def sync_platform_from_env() -> None:
    """Re-assert ``JAX_PLATFORMS`` from the environment onto jax.config.

    No-op when the variable is unset or jax already agrees.
    """
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    try:
        if jax.config.jax_platforms != want:
            jax.config.update("jax_platforms", want)
    except Exception:
        pass
