"""Threading primitives (reference include/dmlc/concurrency.h, thread_local.h,
memory.h).

Python-side parity notes:
- :class:`ConcurrentBlockingQueue` — bounded FIFO/priority queue with the
  reference's SignalForKill semantics (concurrency.h:62-122);
- :class:`ThreadLocalStore` — per-thread singleton registry
  (thread_local.h:34-79);
- :class:`BufferPool` — fixed-size buffer recycling (memory.h:21-76); in the
  rebuild the hot path recycles via ThreadedIter, but the pool is exposed for
  host-staging buffers (e.g. pinned batch arrays reused across steps);
- a Spinlock (concurrency.h:23-49) is deliberately *not* provided: under the
  GIL a spinlock is strictly worse than threading.Lock, and the C++ native
  core uses std::mutex.
"""

from __future__ import annotations

import heapq
import threading
from collections import deque
from typing import Any, Callable, Dict, Generic, List, Optional, TypeVar

T = TypeVar("T")

__all__ = ["ConcurrentBlockingQueue", "ThreadLocalStore", "BufferPool"]


class ConcurrentBlockingQueue(Generic[T]):
    """Bounded blocking queue, FIFO or priority ordering."""

    def __init__(self, max_size: int = 0, priority: bool = False):
        self._max = max_size
        self._priority = priority
        self._fifo: deque = deque()
        self._heap: List = []
        self._count = 0
        self._killed = False
        self._cond = threading.Condition()

    def push(self, value: T, priority: int = 0) -> None:
        with self._cond:
            while (self._max and self._size() >= self._max
                   and not self._killed):
                self._cond.wait()
            if self._killed:
                return
            if self._priority:
                self._count += 1
                heapq.heappush(self._heap, (-priority, self._count, value))
            else:
                self._fifo.append(value)
            self._cond.notify_all()

    def pop(self) -> Optional[T]:
        """Blocking pop; None after signal_for_kill (reference Pop returning
        false on kill)."""
        with self._cond:
            while self._size() == 0 and not self._killed:
                self._cond.wait()
            if self._size() == 0:
                return None
            if self._priority:
                value = heapq.heappop(self._heap)[2]
            else:
                value = self._fifo.popleft()
            self._cond.notify_all()
            return value

    def signal_for_kill(self) -> None:
        with self._cond:
            self._killed = True
            self._cond.notify_all()

    def size(self) -> int:
        with self._cond:
            return self._size()

    def _size(self) -> int:
        return len(self._heap) if self._priority else len(self._fifo)


class ThreadLocalStore:
    """Per-thread singletons keyed by factory (reference ThreadLocalStore)."""

    _local = threading.local()

    @classmethod
    def get(cls, factory: Callable[[], Any]) -> Any:
        store: Dict = getattr(cls._local, "store", None)
        if store is None:
            store = {}
            cls._local.store = store
        key = factory
        if key not in store:
            store[key] = factory()
        return store[key]


class BufferPool:
    """Recycle fixed-size bytearray/numpy buffers (reference MemoryPool)."""

    def __init__(self, nbytes: int, max_cached: int = 16):
        self._nbytes = nbytes
        self._max = max_cached
        self._free: List[bytearray] = []
        self._lock = threading.Lock()

    def alloc(self) -> bytearray:
        with self._lock:
            if self._free:
                return self._free.pop()
        return bytearray(self._nbytes)

    def free(self, buf: bytearray) -> None:
        if len(buf) != self._nbytes:
            return
        with self._lock:
            if len(self._free) < self._max:
                self._free.append(buf)
