"""Core utility substrate (reference: include/dmlc/{logging,timer,common}.h)."""

from dmlc_core_tpu.utils.logging import (  # noqa: F401
    Error,
    LOG,
    CHECK,
    CHECK_EQ,
    CHECK_NE,
    CHECK_LT,
    CHECK_GT,
    CHECK_LE,
    CHECK_GE,
    CHECK_NOTNULL,
    set_log_sink,
)
from dmlc_core_tpu.utils.common import split_string, hash_combine  # noqa: F401
from dmlc_core_tpu.utils.timer import get_time  # noqa: F401
