"""glog-style logging + CHECK substrate.

Capability parity with the reference's include/dmlc/logging.h:26-331:
- severity-leveled logging (``LOG(INFO/WARNING/ERROR/FATAL)``) with timestamps,
- ``CHECK``/``CHECK_EQ``/... assertion macros whose fatal path *throws* a
  structured :class:`Error` (the reference's DMLC_LOG_FATAL_THROW default,
  logging.h:282-318) carrying a traceback,
- an application-redirectable sink (the reference's DMLC_LOG_CUSTOMIZE hook,
  logging.h:233-252) via :func:`set_log_sink`,
- ``VLOG``-style debug verbosity gated by the ``DMLC_LOG_DEBUG`` env var.

Design note: in the reference these are C preprocessor macros that capture
file:line; here the Python ``LOG(...)`` callable walks one stack frame for the
same file:line prefix.  The hot data path never logs per-record, so this is not
performance-relevant.
"""

from __future__ import annotations

import os
import sys
import time
import traceback
from typing import Any, Callable, Optional

__all__ = [
    "Error",
    "LOG",
    "LogMessage",
    "CHECK",
    "CHECK_EQ",
    "CHECK_NE",
    "CHECK_LT",
    "CHECK_GT",
    "CHECK_LE",
    "CHECK_GE",
    "CHECK_NOTNULL",
    "DCHECK",
    "set_log_sink",
    "log_info",
    "log_warning",
    "log_error",
    "log_fatal",
]

INFO = "INFO"
WARNING = "WARNING"
ERROR = "ERROR"
FATAL = "FATAL"
_SEVERITY_ORDER = {INFO: 0, WARNING: 1, ERROR: 2, FATAL: 3}


class Error(RuntimeError):
    """Exception thrown by the fatal logging path (reference logging.h:26-32)."""


# Application-redirected sink; when None, write to stderr
# (reference: CustomLogMessage::Log, logging.h:233-252).
_log_sink: Optional[Callable[[str, str], None]] = None
# Minimum severity actually emitted (stderr logger always emits in the
# reference; we add a filter knob for bench runs).
_min_severity = INFO


def set_log_sink(sink: Optional[Callable[[str, str], None]]) -> None:
    """Redirect log output. ``sink(severity, formatted_line)``; None restores stderr."""
    global _log_sink
    _log_sink = sink


def set_min_severity(severity: str) -> None:
    global _min_severity
    if severity not in _SEVERITY_ORDER:
        raise ValueError(f"unknown severity {severity!r}")
    _min_severity = severity


def _caller(depth: int = 2) -> str:
    try:
        frame = sys._getframe(depth)
        return f"{os.path.basename(frame.f_code.co_filename)}:{frame.f_lineno}"
    except Exception:  # pragma: no cover - _getframe always available in CPython
        return "?:?"


def _emit(severity: str, msg: str, where: str) -> None:
    stamp = time.strftime("%H:%M:%S", time.localtime())
    line = f"[{stamp}] {where}: {msg}"
    if _log_sink is not None:
        _log_sink(severity, line)
        return
    if _SEVERITY_ORDER[severity] >= _SEVERITY_ORDER[_min_severity]:
        sys.stderr.write(f"{severity[0]} {line}\n")
        sys.stderr.flush()


def LOG(severity: str, msg: Any = "") -> None:
    """LOG(severity, message). FATAL raises :class:`Error` after logging.

    Mirrors the reference's LOG(severity) << msg stream macros
    (logging.h:152-205) with the throw-on-fatal default.
    """
    where = _caller()
    text = str(msg)
    if severity == FATAL:
        stack = "".join(traceback.format_stack(sys._getframe(1), limit=8))
        _emit(FATAL, text, where)
        raise Error(f"[{where}] {text}\nStack trace:\n{stack}")
    _emit(severity, text, where)


def log_info(msg: Any) -> None:
    _emit(INFO, str(msg), _caller())


def log_warning(msg: Any) -> None:
    _emit(WARNING, str(msg), _caller())


def log_error(msg: Any) -> None:
    _emit(ERROR, str(msg), _caller())


def log_fatal(msg: Any) -> None:
    LOG(FATAL, msg)


def log_debug(verbosity: int, msg: Any) -> None:
    """VLOG-equivalent, gated by DMLC_LOG_DEBUG (reference logging.h:152-158)."""
    if int(os.environ.get("DMLC_LOG_DEBUG", "0")) >= verbosity:
        _emit(INFO, str(msg), _caller())


class LogMessage:
    """Stream-style log builder: ``LogMessage(INFO) << "x=" << x`` then emits on del.

    Provided for API familiarity (reference logging.h:207-230); the functional
    :func:`LOG` is the idiomatic entry point.
    """

    def __init__(self, severity: str = INFO):
        self._severity = severity
        self._parts: list = []
        self._where = _caller()

    def __lshift__(self, other: Any) -> "LogMessage":
        self._parts.append(str(other))
        return self

    def flush(self) -> None:
        msg = "".join(self._parts)
        self._parts = []
        if self._severity == FATAL:
            _emit(FATAL, msg, self._where)
            raise Error(f"[{self._where}] {msg}")
        _emit(self._severity, msg, self._where)

    def __del__(self):
        if self._parts and self._severity != FATAL:
            try:
                self.flush()
            except Exception:
                pass


def _fail(op: str, x: Any, y: Any, msg: Any) -> None:
    detail = f"Check failed: {x!r} {op} {y!r}" if op else f"Check failed: {x!r}"
    if msg:
        detail += f" {msg}"
    where = _caller(3)
    _emit(FATAL, detail, where)
    raise Error(f"[{where}] {detail}")


def CHECK(cond: Any, msg: Any = "") -> None:
    """CHECK(cond): raise Error when cond is falsy (reference logging.h:104-115)."""
    if not cond:
        _fail("", cond, None, msg)


def CHECK_EQ(x: Any, y: Any, msg: Any = "") -> None:
    if not (x == y):
        _fail("==", x, y, msg)


def CHECK_NE(x: Any, y: Any, msg: Any = "") -> None:
    if not (x != y):
        _fail("!=", x, y, msg)


def CHECK_LT(x: Any, y: Any, msg: Any = "") -> None:
    if not (x < y):
        _fail("<", x, y, msg)


def CHECK_GT(x: Any, y: Any, msg: Any = "") -> None:
    if not (x > y):
        _fail(">", x, y, msg)


def CHECK_LE(x: Any, y: Any, msg: Any = "") -> None:
    if not (x <= y):
        _fail("<=", x, y, msg)


def CHECK_GE(x: Any, y: Any, msg: Any = "") -> None:
    if not (x >= y):
        _fail(">=", x, y, msg)


def CHECK_NOTNULL(x: Any, msg: Any = "") -> Any:
    """Returns x; raises when x is None (reference logging.h:125-128)."""
    if x is None:
        _fail("is not", x, None, msg or "CHECK_NOTNULL")
    return x


# DCHECK*: compiled out in NDEBUG builds in the reference (logging.h:130-140);
# here gated on PYTHONOPTIMIZE / __debug__.
if __debug__:
    DCHECK = CHECK
    DCHECK_EQ = CHECK_EQ
    DCHECK_NE = CHECK_NE
    DCHECK_LT = CHECK_LT
    DCHECK_GT = CHECK_GT
    DCHECK_LE = CHECK_LE
    DCHECK_GE = CHECK_GE
else:  # pragma: no cover
    def _noop(*a: Any, **k: Any) -> None:
        return None

    DCHECK = DCHECK_EQ = DCHECK_NE = DCHECK_LT = DCHECK_GT = DCHECK_LE = DCHECK_GE = _noop
