"""Tracing / profiling helpers (reference §5.1: timer.h + inline MB/s logs).

The reference's observability is GetTime() + throughput prints; the TPU-native
equivalents here:

- :class:`ThroughputMeter` — the input-pipeline "N MB read, X MB/sec" meter
  (reference src/data/basic_row_iter.h:70-75), reusable by any byte stage;
- :func:`trace` — context manager around ``jax.profiler`` producing a
  TensorBoard-loadable trace directory (device timelines, XLA ops);
- :func:`annotate` — named TraceAnnotation spans visible in those traces;
- :func:`device_timer` — ``block_until_ready``-bracketed wall timing for
  honest device measurements (async dispatch otherwise lies).
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Iterator, Optional, Tuple

from dmlc_core_tpu import telemetry
from dmlc_core_tpu.telemetry import clock
from dmlc_core_tpu.utils.logging import log_info

__all__ = ["ThroughputMeter", "trace", "annotate", "device_timer"]


class ThroughputMeter:
    """Incremental byte/row throughput with periodic logging.

    A thin facade over the telemetry registry: the rolling state here only
    feeds :meth:`summary` / the periodic log line; when telemetry is enabled
    every :meth:`add` also lands in the ``dmlc_pipeline_bytes_total`` /
    ``dmlc_pipeline_rows_total`` counters (labeled ``meter=<name>``), so
    there is exactly one metering path and exporters see what the log says.
    """

    def __init__(self, name: str = "pipeline", log_every_bytes: int = 10 << 20):
        self.name = name
        self._log_every = log_every_bytes
        self.reset()

    def reset(self) -> None:
        self._start = clock.monotonic()
        self._bytes = 0
        self._rows = 0
        self._next_log = self._log_every

    def add(self, nbytes: int, nrows: int = 0) -> None:
        self._bytes += nbytes
        self._rows += nrows
        if telemetry.enabled():
            if nbytes:
                telemetry.count("dmlc_pipeline_bytes_total", nbytes,
                                meter=self.name)
            if nrows:
                telemetry.count("dmlc_pipeline_rows_total", nrows,
                                meter=self.name)
        if self._bytes >= self._next_log:
            self._next_log += self._log_every
            log_info(f"{self.name}: {self.mb:.0f} MB read, "
                     f"{self.mb_per_sec:.2f} MB/sec")

    @property
    def elapsed(self) -> float:
        return max(clock.elapsed(self._start), 1e-9)

    @property
    def mb(self) -> float:
        return self._bytes / (1 << 20)

    @property
    def mb_per_sec(self) -> float:
        return self.mb / self.elapsed

    @property
    def rows_per_sec(self) -> float:
        return self._rows / self.elapsed

    def summary(self) -> str:
        return (f"{self.name}: {self.mb:.2f} MB in {self.elapsed:.2f}s "
                f"({self.mb_per_sec:.2f} MB/sec, "
                f"{self.rows_per_sec:.0f} rows/sec)")


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler trace (view with TensorBoard's profile plugin)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named span inside a profiler trace."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


def device_timer(fn: Callable, *args: Any, iters: int = 1,
                 warmup: int = 1) -> Tuple[Any, float]:
    """(result, seconds-per-iter) with compile warmup and async-safe timing."""
    import jax

    out = None
    for _ in range(max(warmup, 0)):
        out = jax.block_until_ready(fn(*args))
    start = clock.monotonic()
    for _ in range(iters):
        out = fn(*args)
    out = jax.block_until_ready(out)
    return out, clock.elapsed(start) / max(iters, 1)
