"""Wall-clock timer (reference: include/dmlc/timer.h:27-46).

On TPU, timing device work additionally requires ``jax.block_until_ready`` —
see :func:`device_time` — because dispatch is asynchronous.
"""

from __future__ import annotations

import time

__all__ = ["get_time", "device_time"]


def get_time() -> float:
    """Seconds since epoch at the highest available resolution."""
    return time.perf_counter()


def device_time(fn, *args, **kwargs):
    """Run ``fn`` and block on its jax outputs; return (result, elapsed_seconds)."""
    import jax

    start = time.perf_counter()
    out = fn(*args, **kwargs)
    out = jax.block_until_ready(out)
    return out, time.perf_counter() - start
