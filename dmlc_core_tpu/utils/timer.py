"""Wall-clock timer (reference: include/dmlc/timer.h:27-46).

The actual clock lives in :mod:`dmlc_core_tpu.telemetry.clock` — the single
monotonic-clock helper every meter in this package shares (this module used
to hand-roll ``time.perf_counter`` alongside profiler.py; now there is one
metering path).

On TPU, timing device work additionally requires ``jax.block_until_ready`` —
see :func:`device_time` — because dispatch is asynchronous.
"""

from __future__ import annotations

from dmlc_core_tpu.telemetry import clock

__all__ = ["get_time", "device_time"]


def get_time() -> float:
    """Seconds on a monotonic clock at the highest available resolution."""
    return clock.monotonic()


def device_time(fn, *args, **kwargs):
    """Run ``fn`` and block on its jax outputs; return (result, elapsed_seconds)."""
    import jax

    start = clock.monotonic()
    out = fn(*args, **kwargs)
    out = jax.block_until_ready(out)
    return out, clock.elapsed(start)
