"""dmlc_core_tpu — a TPU-native framework with the capabilities of dmlc-core.

The reference (/root/reference, cjolivier01/dmlc-core) is the C++11 common-support
library under XGBoost/MXNet: a parameter/registry/config/logging substrate, a
URI-dispatched virtual filesystem + streaming serialization layer, a sharded
threaded record-input pipeline with text/binary parsers, and a Python tracker
for distributed job launch and rank rendezvous.

This package provides the same surface, redesigned TPU-first:

- ``dmlc_core_tpu.utils``     — logging/CHECK substrate, timers, small helpers
  (reference: include/dmlc/logging.h, timer.h, common.h).
- ``dmlc_core_tpu.param``     — reflected parameter structs with
  declare/default/range/enum/doc/JSON semantics (reference: include/dmlc/parameter.h).
- ``dmlc_core_tpu.registry``  — name->factory registries with aliases
  (reference: include/dmlc/registry.h).
- ``dmlc_core_tpu.config``    — key=value config files (reference: include/dmlc/config.h).
- ``dmlc_core_tpu.serializer``— typed binary serialization onto streams
  (reference: include/dmlc/serializer.h).
- ``dmlc_core_tpu.io``        — Stream/SeekStream, URI-dispatched filesystems,
  RecordIO, InputSplit sharding engine, ThreadedIter
  (reference: include/dmlc/io.h, src/io/).
- ``dmlc_core_tpu.data``      — RowBlock CSR batches, libsvm/libfm/csv parsers,
  row iterators (reference: include/dmlc/data.h, src/data/).
- ``dmlc_core_tpu.bridge``    — RowBlock -> mesh-placed jax.Array batches
  (the TPU-native recast of ThreadedIter feeding device infeed).
- ``dmlc_core_tpu.collective``— Rabit-shaped allreduce/broadcast implemented as
  jax.lax collectives over ICI/DCN (replaces tracker-brokered TCP trees).
- ``dmlc_core_tpu.parallel``  — device-mesh construction and sharding helpers.
- ``dmlc_core_tpu.ops``/``models`` — TPU compute: histogram/sketch ops, linear
  models, hist-GBDT (the XGBoost-hist-on-TPU north star).
- ``dmlc_core_tpu.tracker``   — dmlc-submit-compatible launcher + rendezvous
  (reference: tracker/dmlc_tracker/).

JAX is imported lazily (only by bridge/collective/parallel/ops/models) so the
pure host-side layers work in minimal environments.
"""

__version__ = "0.1.0"

from dmlc_core_tpu.utils.logging import Error, CHECK, CHECK_EQ, LOG  # noqa: F401
from dmlc_core_tpu.param import Parameter, ParamError, field, get_env  # noqa: F401
from dmlc_core_tpu.registry import Registry  # noqa: F401
from dmlc_core_tpu.json_io import (  # noqa: F401
    JSONReader, JSONWriter, JSONObjectReadHelper, JSONError, register_any_type)
