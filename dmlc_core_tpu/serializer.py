"""Schema-directed typed binary serialization onto streams.

Capability parity with the reference's compile-time serializer
(include/dmlc/serializer.h:35-381): POD scalars, strings, vectors of POD
(bulk-copied, serializer.h:104+), nested STL composites (vector/map/pair of
anything), and user classes implementing ``Serializable``
(SaveLoadClassHandler, serializer.h:80-88).  Unsupported types raise at
save/load time (the reference fails at compile time, serializer.h:96-98).

Layout (matching the reference so C++/Python blobs interoperate):
- POD scalar: raw little-endian fixed width;
- string / vector<T>: ``uint64`` element count then payload;
- map<K,V>: ``uint64`` count then (key, value) pairs;
- pair<A,B>: A then B.

The schema is a *spec* value::

    POD(np.float32)                 # one scalar
    Str                             # byte/unicode string
    Vector(POD(np.int64))           # bulk numpy fast path
    Vector(Str)                     # element-wise
    Map(Str, Vector(POD(np.f4)))    # dict
    Pair(POD(np.i4), Str)           # 2-tuple
    Obj(MyClass)                    # MyClass() constructed then .load(stream)

``save(stream, value, spec)`` / ``load(stream, spec)`` are the entry points.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from dmlc_core_tpu.io.stream import Stream
from dmlc_core_tpu.utils.logging import CHECK

__all__ = ["POD", "Str", "Vector", "Map", "Pair", "Obj", "save", "load"]


class _Spec:
    def save(self, stream: Stream, value: Any) -> None:
        raise NotImplementedError

    def load(self, stream: Stream) -> Any:
        raise NotImplementedError


class POD(_Spec):
    """Fixed-width scalar (reference PODHandler, serializer.h:69-77)."""

    def __init__(self, dtype: Any):
        # pin little-endian regardless of host order (the reference guards
        # byte order the same way, include/dmlc/endian.h:10-17); on LE
        # hosts this is the native dtype, so no conversion cost
        self.dtype = np.dtype(dtype).newbyteorder("<")
        CHECK(self.dtype.kind in "iufb", f"POD spec requires numeric dtype, got {self.dtype}")

    def save(self, stream: Stream, value: Any) -> None:
        stream.write(np.asarray(value, dtype=self.dtype).tobytes())

    def load(self, stream: Stream) -> Any:
        data = stream.read_exact(self.dtype.itemsize)
        return np.frombuffer(data, dtype=self.dtype)[0].item()


class _StrSpec(_Spec):
    """Length-prefixed byte string; decodes to str when valid UTF-8 was written."""

    def save(self, stream: Stream, value: Any) -> None:
        stream.write_string(value)

    def load(self, stream: Stream) -> str:
        return stream.read_string().decode("utf-8")


Str = _StrSpec()


class Vector(_Spec):
    """uint64 count + elements (reference PODVectorHandler/ComposeVectorHandler)."""

    def __init__(self, elem: _Spec):
        self.elem = elem

    def save(self, stream: Stream, value: Any) -> None:
        if isinstance(self.elem, POD):
            arr = np.asarray(value, dtype=self.elem.dtype)
            CHECK(arr.ndim <= 1, "Vector(POD) expects a 1-d sequence")
            stream.write_array(arr.reshape(-1))
            return
        value = list(value)
        stream.write_u64(len(value))
        for item in value:
            self.elem.save(stream, item)

    def load(self, stream: Stream) -> Any:
        if isinstance(self.elem, POD):
            return stream.read_array(self.elem.dtype)
        n = stream.read_u64()
        return [self.elem.load(stream) for _ in range(n)]


class Map(_Spec):
    """uint64 count + key/value pairs (reference map handlers)."""

    def __init__(self, key: _Spec, value: _Spec):
        self.key = key
        self.value = value

    def save(self, stream: Stream, value: Dict) -> None:
        stream.write_u64(len(value))
        for k, v in value.items():
            self.key.save(stream, k)
            self.value.save(stream, v)

    def load(self, stream: Stream) -> Dict:
        n = stream.read_u64()
        out = {}
        for _ in range(n):
            k = self.key.load(stream)
            out[k] = self.value.load(stream)
        return out


class Pair(_Spec):
    """A then B (reference PairHandler)."""

    def __init__(self, first: _Spec, second: _Spec):
        self.first = first
        self.second = second

    def save(self, stream: Stream, value: Tuple) -> None:
        self.first.save(stream, value[0])
        self.second.save(stream, value[1])

    def load(self, stream: Stream) -> Tuple:
        a = self.first.load(stream)
        b = self.second.load(stream)
        return (a, b)


class Obj(_Spec):
    """A class with save(stream)/load(stream) (reference SaveLoadClassHandler)."""

    def __init__(self, cls: type):
        self.cls = cls

    def save(self, stream: Stream, value: Any) -> None:
        value.save(stream)

    def load(self, stream: Stream) -> Any:
        obj = self.cls()
        obj.load(stream)
        return obj


def _infer_spec(value: Any) -> _Spec:
    """Best-effort spec inference for convenience saves (numpy arrays, str, ...)."""
    if isinstance(value, np.ndarray):
        return Vector(POD(value.dtype))
    if isinstance(value, (bytes, str)):
        return Str
    if isinstance(value, bool):
        return POD(np.uint8)
    if isinstance(value, int):
        return POD(np.int64)
    if isinstance(value, float):
        return POD(np.float64)
    raise TypeError(
        f"cannot infer serialization spec for {type(value).__name__}; pass spec= "
        f"(the reference rejects undefined types at compile time, serializer.h:96-98)"
    )


def save(stream: Stream, value: Any, spec: _Spec | None = None) -> None:
    (spec or _infer_spec(value)).save(stream, value)


def load(stream: Stream, spec: _Spec) -> Any:
    return spec.load(stream)
