"""Deterministic fault injection for the control plane and IO paths.

The distributed story rests on the tracker rendezvous and the remote-FS
streaming layer; this package exists to *prove*, continuously, that one
misbehaving peer, one flaky link, or one throttling endpoint cannot hang or
kill the system.  A JSON **fault plan** (:mod:`.plan`) names injection
sites, fault kinds, and a deterministic firing discipline; the hardened
subsystems consult this module at named sites and the chaos suite
(``pytest -m chaos``, docs/robustness.md) drives plans through them.

Injection sites (see :data:`SITES`):

- ``tracker.framed.recv`` / ``tracker.framed.send`` — every framed wire op
  in :class:`dmlc_core_tpu.tracker.rendezvous.FramedSocket`;
- ``tracker.accept``       — the tracker accept loop, per connection;
- ``net.request``          — :func:`dmlc_core_tpu.io.net_retry.request_with_retries`
  (``http_status`` rules replace the request; act rules fire before it);
- ``io.stream.open``       — URI stream factory open;
- ``io.stream.read``       — :meth:`Stream.read_exact` (``truncate`` rules);
- ``io.cache.fetch``       — remote page-cache ranged reads
  (:func:`dmlc_core_tpu.data.page_cache.fetch_remote_cache`);
- ``threadediter.produce`` — the producer thread, per item;
- ``data.parse_worker``    — process-pool parse workers, per sub-range
  (``exit`` = kill a worker mid-chunk);
- ``io.fleet.lease``       — fleet-ingest lease client, per wire op
  (``exit`` with ``match {"op": "commit"}`` = kill a worker mid-unit,
  after processing but before its commit lands — the reassignment drill);
- ``serve.request`` / ``serve.queue`` / ``serve.predict`` — the scoring
  service's ingress, batch assembly, and model call (docs/serving.md);
- ``serve.swap``           — the model-lifecycle watcher's
  watch/validate/warmup/swap stages (hot-swap chaos: a rejected candidate
  must leave previous-good serving);
- ``serve.router.forward`` — the multi-replica router's forward path, per
  attempt (replica-death, slow-link, and injected-response chaos);
- ``train.ingest`` / ``train.round`` / ``train.publish`` — the continuous
  trainer daemon's batch fetch, boosting round, and checkpoint publish
  (kill-mid-round and torn-publish chaos: docs/training.md).

**Disabled is the default and costs one attribute load + branch**: every
helper returns immediately while no plan is configured, and the instrumented
call sites additionally guard on :func:`enabled` so disabled-mode wire
conversations are byte-identical to the un-instrumented code
(tests/test_tracker_conformance.py).

Enable via :func:`configure` (tests) or the environment::

    DMLC_FAULT_PLAN='{"rules": [{"site": "net.request", "kind": "http_status"}]}'
    DMLC_FAULT_PLAN=@/path/to/plan.json

Every fired fault is logged, appended to the in-process ledger
(:func:`fires`), and counted as ``dmlc_fault_injected_total{site,kind}``
through the telemetry stack — a chaos run with ``DMLC_TELEMETRY_DIR`` set
leaves an auditable record of exactly which faults fired where.

Validate or inspect a plan without running anything:
``python -m dmlc_core_tpu.fault validate plan.json`` and
``python -m dmlc_core_tpu.fault list-sites``.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from dmlc_core_tpu import telemetry
from dmlc_core_tpu.fault.plan import (ACT_KINDS, FaultPlan, FaultPlanError,
                                      FaultRule, KINDS)

__all__ = [
    "SITES", "KINDS",
    "enabled", "configure", "clear", "get_plan", "fires",
    "inject", "truncate", "http_response",
    "FaultPlan", "FaultRule", "FaultPlanError",
]

logger = logging.getLogger("dmlc_core_tpu.fault")

# the named sites the codebase is instrumented with -> what faults mean there
SITES: Dict[str, str] = {
    "tracker.framed.recv": (
        "FramedSocket receive path; 'truncate' simulates a peer closing "
        "mid-frame, act kinds fire before the read"),
    "tracker.framed.send": "FramedSocket send path",
    "tracker.accept": (
        "tracker accept loop, once per accepted connection (before the "
        "handshake)"),
    "net.request": (
        "remote-FS HTTP request; 'http_status' replaces the round-trip "
        "with an injected response, act kinds fire instead of connecting"),
    "io.stream.open": "URI stream factory open (create_stream[_for_read])",
    "io.stream.read": (
        "Stream.read_exact; 'truncate' cuts the stream short, modeling a "
        "truncated object/dropped connection"),
    "io.cache.fetch": (
        "remote page-cache ranged reads (ctx: uri=<uri>, offset=<byte "
        "offset>), once per header/TOC/page fetch; 'truncate' cuts a page "
        "short and 'reset'/'error' kill the transfer — every outcome must "
        "end in a loud stream-parse fallback, never a served bad page"),
    "threadediter.produce": (
        "producer thread, once per produced item (ctx: name=<iterator>)"),
    "data.parse_worker": (
        "process-pool parse worker, once per sub-range before parsing "
        "(ctx: parser=<class>); 'exit' kills the worker mid-chunk.  "
        "Workers read DMLC_FAULT_PLAN at start: the shared pool must be "
        "(re)started after setting the plan (data.parse_proc.shutdown())"),
    "io.fleet.lease": (
        "fleet-ingest shard-lease client, once per wire op before it runs "
        "(ctx: op=acquire|renew|commit, worker=<id>); 'delay' models a "
        "straggling rank, 'reset' a flaky control link (the client "
        "retries), and 'exit' with match op=commit kills a worker mid-unit "
        "— the lease expires and the unit must be reassigned with "
        "exactly-once coverage (docs/performance.md \"Fleet ingest\")"),
    "serve.request": (
        "scoring HTTP ingress, once per POST /v1/score before parsing; "
        "'http_status' REPLACES the response (the chaos 503 storm), "
        "delay/stall model a slow handler thread, 'reset' kills the "
        "connection mid-request (the one outcome a client sees as a "
        "crash)"),
    "serve.queue": (
        "micro-batch assembly loop, once per batch (ctx: depth=<queue "
        "depth>); 'stall' models a stuck consumer — the queue backs up "
        "and admission control starts shedding (503 + Retry-After)"),
    "serve.predict": (
        "once per assembled batch before the model call (ctx: "
        "model=<family>, slot=<slot name>, rows=<n>); 'error' models a "
        "killed predict worker — that batch's requests fail with a "
        "structured 503 predict_failed and the batcher continues; a "
        "'delay' holds the batch's admission bytes, so queues genuinely "
        "back up (the router chaos drill saturates replicas this way)"),
    "serve.swap": (
        "model-lifecycle watcher, once per stage of each hot-swap cycle "
        "(ctx: model=<slot>, stage=watch|validate|warmup|swap); "
        "'error'/'reset' during validate or warmup reject the candidate "
        "— previous-good keeps serving; 'stall' during swap delays the "
        "pointer flip but can never tear it (docs/serving.md \"Model "
        "lifecycle\")"),
    "serve.router.forward": (
        "multi-replica router, once per forward attempt before the replica "
        "connection is opened (ctx: replica=<name>, attempt=<n>, "
        "tag=primary|hedge); 'reset' models a replica dying at connect "
        "time (zero response bytes read — the router retries on another "
        "replica), 'stall'/'delay' model a slow replica link (hedging "
        "territory), 'error' a router-side forwarding bug (structured "
        "503 replica_failed, never a dropped connection), and "
        "'http_status' REPLACES the replica round-trip with an injected "
        "response (docs/serving.md \"Multi-replica tier\")"),
    "train.ingest": (
        "continuous trainer, once per batch fetch before the source is "
        "read (ctx: cursor=<position>, incarnation=<n>); 'error'/'reset' "
        "model a flaky source — the fetch is retried next tick, the "
        "cursor does not advance (docs/training.md)"),
    "train.round": (
        "continuous trainer, once per boosting round before it computes "
        "(ctx: round=<odometer>, incarnation=<n>); 'exit' kills the "
        "trainer mid-round — restart must resume from the last valid "
        "manifest with the rounds since it retrained, never a torn "
        "checkpoint (the continuous chaos drill)"),
    "train.publish": (
        "continuous trainer checkpoint publish (ctx: step=<n>, "
        "phase=begin|durable, incarnation=<n>); 'exit' at phase=durable "
        "kills between blob and manifest — the step must never become a "
        "swap candidate; 'truncate' at phase=durable tears the durable "
        "blob before the publish-side verify, which must reject the step "
        "and re-publish it idempotently"),
}

_plan: Optional[FaultPlan] = None
_TRUNCATE_KINDS = frozenset({"truncate"})
_HTTP_KINDS = frozenset({"http_status"})


def enabled() -> bool:
    """True when a fault plan is configured (call sites guard on this)."""
    return _plan is not None


def configure(spec: Any) -> FaultPlan:
    """Install a plan (dict, JSON text, or FaultPlan); returns it."""
    global _plan
    plan = spec if isinstance(spec, FaultPlan) else FaultPlan(spec)
    _plan = plan
    logger.info("fault plan configured: %d rule(s), seed=%r",
                len(plan.rules), plan.seed)
    return plan


def clear() -> None:
    """Remove the plan; every helper becomes a no-op again."""
    global _plan
    _plan = None


def get_plan() -> Optional[FaultPlan]:
    return _plan


def fires() -> List[Tuple[str, str, int]]:
    """(site, kind, rule index) for every fault fired so far, in order."""
    plan = _plan
    if plan is None:
        return []
    with plan._lock:
        return list(plan.fired_log)


def _note(site: str, kind: str) -> None:
    logger.warning("fault injected: site=%s kind=%s", site, kind)
    telemetry.count("dmlc_fault_injected_total", site=site, kind=kind)
    # the fire lands ON the span that was running when it hit: an instant
    # event carrying the thread's active trace context, so an assembled
    # trace shows exactly which request/chunk ate the injected fault —
    # and the flight ring keeps it even if the process dies right after
    telemetry.event("fault.injected", site=site, kind=kind)
    if not telemetry.enabled():
        telemetry.flight.note("fault.injected", site=site, kind=kind)


def inject(site: str, **ctx: Any) -> None:
    """Fire any eligible act rule at ``site``: sleep, raise, or exit.

    No-op without a plan.  ``delay``/``stall`` sleep and return; ``reset``
    raises ConnectionResetError; ``error`` raises the rule's whitelisted
    exception; ``exit`` calls ``os._exit`` (worker kill-at-site).
    """
    plan = _plan
    if plan is None:
        return
    rule = plan.select(site, ACT_KINDS, ctx)
    if rule is None:
        return
    _note(site, rule.kind)
    if rule.kind in ("delay", "stall"):
        time.sleep(rule.seconds)
        return
    if rule.kind == "reset":
        raise ConnectionResetError(rule.message)
    if rule.kind == "exit":
        # flush the fault ledger to telemetry before dying, so a killed
        # worker's chaos run still shows WHERE it was killed; the flight
        # dump marks the process as crashed (reason names the site) so the
        # trace assembler reports it instead of showing silence
        try:
            telemetry.flight.dump(f"fault_exit:{site}")
            if telemetry.enabled():
                telemetry._atexit_flush()
        except Exception:
            pass
        os._exit(rule.code)
    raise rule.exception(rule.message)


def truncate(site: str, nbytes: int, **ctx: Any) -> int:
    """Possibly reduced byte budget for a read at ``site``.

    Returns ``nbytes`` untouched without a plan or when no truncate rule
    fires; otherwise the injected shorter length (``keep`` bytes, or
    ``fraction`` of the request).
    """
    plan = _plan
    if plan is None:
        return nbytes
    rule = plan.select(site, _TRUNCATE_KINDS, ctx)
    if rule is None:
        return nbytes
    _note(site, rule.kind)
    if rule.fraction is not None:
        return min(nbytes, int(nbytes * rule.fraction))
    return min(nbytes, rule.keep)


def http_response(site: str, **ctx: Any) \
        -> Optional[Tuple[int, Dict[str, str], bytes]]:
    """Injected (status, headers, body) replacing a request, or None."""
    plan = _plan
    if plan is None:
        return None
    rule = plan.select(site, _HTTP_KINDS, ctx)
    if rule is None:
        return None
    _note(site, rule.kind)
    return rule.status, dict(rule.headers), rule.body


# -- env-driven bring-up ------------------------------------------------------

def _init_from_env() -> None:
    spec = os.environ.get("DMLC_FAULT_PLAN", "").strip()
    if not spec:
        return
    if spec.startswith("@"):
        # a plan file: the form long plans and k8s configmaps use
        with open(spec[1:], encoding="utf-8") as f:
            spec = f.read()
    # a malformed plan raises here, at import: a chaos run that silently
    # injects nothing must fail loudly, not pass greenly
    configure(spec)


_init_from_env()
