"""Fault-plan CLI: validate a plan and list the instrumented sites.

Usage::

    python -m dmlc_core_tpu.fault list-sites
    python -m dmlc_core_tpu.fault validate plan.json      # or - for stdin

``validate`` exits 0 on a well-formed plan (printing each parsed rule) and
2 on a malformed one — wire it before a chaos run so a typo'd plan fails
the job instead of silently injecting nothing.
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional, Sequence

from dmlc_core_tpu.fault import SITES, FaultPlan, FaultPlanError


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m dmlc_core_tpu.fault",
        description="fault-injection plan tools (docs/robustness.md)")
    sub = parser.add_subparsers(dest="cmd", required=True)
    sub.add_parser("list-sites", help="print the instrumented sites")
    val = sub.add_parser("validate", help="parse a plan; exit 0/2")
    val.add_argument("plan", help="plan file path, or - for stdin")
    return parser


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "list-sites":
        width = max(len(s) for s in SITES)
        for site in sorted(SITES):
            print(f"{site:<{width}}  {SITES[site]}")
        return 0
    # validate
    try:
        if args.plan == "-":
            text = sys.stdin.read()
        else:
            with open(args.plan, encoding="utf-8") as f:
                text = f.read()
        plan = FaultPlan(text)
    except OSError as exc:
        print(f"fault: cannot read plan: {exc}", file=sys.stderr)
        return 2
    except FaultPlanError as exc:
        print(f"fault: invalid plan: {exc}", file=sys.stderr)
        return 2
    known = set(SITES)
    print(f"fault: plan ok — {len(plan.rules)} rule(s), seed={plan.seed!r}")
    for rule in plan.rules:
        print(f"  {rule.describe()}")
        # wildcard sites can't be checked statically; exact ones can
        if not any(ch in rule.site for ch in "*?[") and rule.site not in known:
            print(f"  warning: site {rule.site!r} is not an instrumented "
                  "site (list-sites)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
