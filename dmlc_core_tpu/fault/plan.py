"""Fault plan: the declarative, deterministic description of what to break.

A plan is JSON (or an equivalent dict) with an optional ``seed`` and a list
of ``rules``.  Each rule names an injection **site** (see
:data:`dmlc_core_tpu.fault.SITES`; ``fnmatch`` wildcards allowed), a fault
**kind**, and firing discipline::

    {
      "seed": 7,
      "rules": [
        {"site": "tracker.framed.recv", "kind": "reset", "after": 2},
        {"site": "net.request", "kind": "http_status", "status": 503,
         "headers": {"retry-after": "1"}, "times": 3},
        {"site": "threadediter.produce", "kind": "delay", "seconds": 0.05,
         "probability": 0.5, "times": null, "match": {"name": "parse"}}
      ]
    }

Firing discipline per rule:

- ``after``: skip the first N matching hits (default 0);
- ``times``: maximum fires (default 1; ``null``/``"inf"`` = unlimited);
- ``probability``: fire chance per eligible hit, decided by a PRNG seeded
  from ``(plan seed, rule index, site, kind)`` — the same plan replays the
  same decisions, which is what makes chaos runs debuggable;
- ``match``: context filters compared as strings against the keyword
  context the injection site provides (e.g. ``{"name": "parse"}`` on the
  threadediter site, ``{"mode": "r"}`` on stream open).

Kinds and their parameters:

=============  =============================================================
``delay``      sleep ``seconds`` (default 0.05) and continue
``stall``      alias of ``delay`` for long hangs (semantically: a peer that
               stops responding rather than a slow one)
``reset``      raise ``ConnectionResetError`` at the site
``error``      raise ``exception`` (whitelisted name, default
               ``ConnectionError``) with ``message``
``exit``       ``os._exit(code)`` (default 1) — worker kill-at-site for
               subprocess chaos tests
``truncate``   value-transforming: cut a read to ``keep`` bytes (default 0)
               or ``fraction`` of the request (sites that read peer bytes)
``http_status``value-producing: replace the request with an injected
               (``status`` default 503, ``headers``, ``body``) response
=============  =============================================================

Unknown keys, kinds, sites-typed-wrong, negative counts and out-of-range
probabilities all raise :class:`FaultPlanError` at configure time: a chaos
plan that silently injects nothing is worse than no plan at all.
"""

from __future__ import annotations

import fnmatch
import json
import random
import socket
import threading
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["FaultPlan", "FaultRule", "FaultPlanError", "KINDS",
           "ACT_KINDS"]


class FaultPlanError(ValueError):
    """A fault plan that cannot mean what its author intended."""


# kinds consulted by fault.inject() (side effects: sleep / raise / exit)
ACT_KINDS = frozenset({"delay", "stall", "reset", "error", "exit"})
# value kinds consulted by their dedicated helpers
KINDS = ACT_KINDS | {"truncate", "http_status"}

# the only exceptions an "error" rule may raise: everything an injection
# site's hardened caller is expected to survive
_EXCEPTIONS: Dict[str, type] = {
    "ConnectionError": ConnectionError,
    "ConnectionResetError": ConnectionResetError,
    "ConnectionAbortedError": ConnectionAbortedError,
    "BrokenPipeError": BrokenPipeError,
    "TimeoutError": TimeoutError,
    "socket.timeout": socket.timeout,
    "OSError": OSError,
    "IOError": IOError,
    "ValueError": ValueError,
    "RuntimeError": RuntimeError,
}

_RULE_KEYS = {
    "site", "kind", "after", "times", "probability", "match",
    "seconds", "exception", "message", "code", "keep", "fraction",
    "status", "headers", "body",
}


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise FaultPlanError(msg)


def _coerce(fn, spec: Dict[str, Any], key: str, default: Any, index: int):
    """Typed field read that fails as a plan error, not a raw traceback —
    the validate CLI's exit-0/2 contract depends on every malformed value
    surfacing as FaultPlanError."""
    try:
        return fn(spec.get(key, default))
    except (TypeError, ValueError) as exc:
        raise FaultPlanError(
            f"rule #{index}: invalid {key!r}: {exc}") from None


class FaultRule:
    """One parsed rule plus its firing state (hits/fired/PRNG)."""

    def __init__(self, spec: Dict[str, Any], index: int, seed: Any):
        _require(isinstance(spec, dict),
                 f"rule #{index}: expected an object, got {type(spec).__name__}")
        unknown = set(spec) - _RULE_KEYS
        _require(not unknown,
                 f"rule #{index}: unknown key(s) {sorted(unknown)}")
        self.index = index
        self.site = spec.get("site")
        _require(isinstance(self.site, str) and self.site,
                 f"rule #{index}: 'site' must be a non-empty string")
        self.kind = spec.get("kind")
        _require(self.kind in KINDS,
                 f"rule #{index}: unknown kind {self.kind!r} "
                 f"(one of {sorted(KINDS)})")

        self.after = _coerce(int, spec, "after", 0, index)
        _require(self.after >= 0, f"rule #{index}: 'after' must be >= 0")
        times = spec.get("times", 1)
        if times in (None, "inf"):
            self.times: Optional[int] = None
        else:
            self.times = _coerce(int, spec, "times", 1, index)
            _require(self.times >= 1,
                     f"rule #{index}: 'times' must be >= 1 (or null for "
                     "unlimited)")
        self.probability = _coerce(float, spec, "probability", 1.0, index)
        _require(0.0 < self.probability <= 1.0,
                 f"rule #{index}: 'probability' must be in (0, 1]")
        match = spec.get("match", {})
        _require(isinstance(match, dict),
                 f"rule #{index}: 'match' must be an object")
        self.match = {str(k): str(v) for k, v in match.items()}

        # per-kind parameters
        self.seconds = _coerce(float, spec, "seconds", 0.05, index)
        _require(self.seconds >= 0, f"rule #{index}: 'seconds' must be >= 0")
        exc_name = spec.get("exception", "ConnectionError")
        _require(exc_name in _EXCEPTIONS,
                 f"rule #{index}: 'exception' must be one of "
                 f"{sorted(_EXCEPTIONS)}")
        self.exception = _EXCEPTIONS[exc_name]
        self.message = str(spec.get(
            "message", f"injected fault (site={self.site}, kind={self.kind})"))
        self.code = _coerce(int, spec, "code", 1, index)
        self.keep = _coerce(int, spec, "keep", 0, index)
        _require(self.keep >= 0, f"rule #{index}: 'keep' must be >= 0")
        self.fraction = spec.get("fraction")
        if self.fraction is not None:
            self.fraction = _coerce(float, spec, "fraction", None, index)
            _require(0.0 <= self.fraction < 1.0,
                     f"rule #{index}: 'fraction' must be in [0, 1)")
        self.status = _coerce(int, spec, "status", 503, index)
        headers = spec.get("headers", {})
        _require(isinstance(headers, dict),
                 f"rule #{index}: 'headers' must be an object")
        self.headers = {str(k).lower(): str(v) for k, v in headers.items()}
        body = spec.get("body", "")
        _require(isinstance(body, (str, bytes)),
                 f"rule #{index}: 'body' must be a string")
        self.body = body.encode() if isinstance(body, str) else bytes(body)

        # deterministic per-rule decision stream: same plan -> same chaos
        self._rng = random.Random(f"{seed}:{index}:{self.site}:{self.kind}")
        self.hits = 0
        self.fired = 0

    def matches(self, site: str, ctx: Dict[str, Any]) -> bool:
        if not fnmatch.fnmatchcase(site, self.site):
            return False
        return all(str(ctx.get(k)) == v for k, v in self.match.items())

    def describe(self) -> str:
        extra = {
            "delay": f" seconds={self.seconds}",
            "stall": f" seconds={self.seconds}",
            "error": f" exception={self.exception.__name__}",
            "exit": f" code={self.code}",
            "truncate": (f" fraction={self.fraction}"
                         if self.fraction is not None else f" keep={self.keep}"),
            "http_status": f" status={self.status}",
        }.get(self.kind, "")
        times = "inf" if self.times is None else self.times
        return (f"#{self.index} site={self.site} kind={self.kind}{extra} "
                f"after={self.after} times={times} p={self.probability}"
                + (f" match={self.match}" if self.match else ""))


class FaultPlan:
    """Parsed plan + thread-safe firing state."""

    def __init__(self, spec: Any):
        if isinstance(spec, (str, bytes)):
            try:
                spec = json.loads(spec)
            except json.JSONDecodeError as exc:
                raise FaultPlanError(f"fault plan is not valid JSON: {exc}") \
                    from None
        _require(isinstance(spec, dict),
                 f"fault plan must be a JSON object, got {type(spec).__name__}")
        unknown = set(spec) - {"seed", "rules"}
        _require(not unknown,
                 f"fault plan: unknown top-level key(s) {sorted(unknown)}")
        self.seed = spec.get("seed", 0)
        rules = spec.get("rules", [])
        _require(isinstance(rules, list), "fault plan: 'rules' must be a list")
        self.rules: List[FaultRule] = [FaultRule(r, i, self.seed)
                                       for i, r in enumerate(rules)]
        self._lock = threading.Lock()
        # every fire, in order: (site, kind, rule index) — the in-process
        # ledger tests assert on (telemetry is the cross-process one)
        self.fired_log: List[Tuple[str, str, int]] = []

    def select(self, site: str, kinds: frozenset,
               ctx: Dict[str, Any]) -> Optional[FaultRule]:
        """First eligible matching rule, or None.  Every matching rule's hit
        counter advances (so ``after`` counts real traffic at the site even
        when an earlier rule fires for the same hit)."""
        chosen: Optional[FaultRule] = None
        with self._lock:
            for rule in self.rules:
                if rule.kind not in kinds or not rule.matches(site, ctx):
                    continue
                rule.hits += 1
                if chosen is not None:
                    continue
                if rule.hits <= rule.after:
                    continue
                if rule.times is not None and rule.fired >= rule.times:
                    continue
                if (rule.probability < 1.0
                        and rule._rng.random() >= rule.probability):
                    continue
                rule.fired += 1
                self.fired_log.append((site, rule.kind, rule.index))
                chosen = rule
        return chosen
