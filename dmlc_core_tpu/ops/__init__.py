"""TPU compute ops: quantile binning, gradient histograms, sparse segment ops.

These are the device-side kernels the DMLC ecosystem runs on top of this
library (XGBoost's hist algorithm, linear learners).  The reference contains
no device code — SURVEY.md §6's north star is "XGBoost hist on TPU", and these
ops are its core: binning + scatter-add gradient histograms + segment
reductions, all static-shape and jit-compiled.
"""

from dmlc_core_tpu.ops.histogram import (  # noqa: F401
    quantile_boundaries,
    apply_bins,
    grad_histogram,
)
from dmlc_core_tpu.ops.sparse import segment_matvec, sparse_logit  # noqa: F401
