"""Quantile binning + gradient histograms (the XGBoost-hist core on TPU).

Design notes (TPU-first):
- binning is a one-time ``searchsorted`` per feature (vmapped, compiled once);
  bins are uint8/int32 — HBM-friendly, 4x smaller than raw floats at 256 bins;
- the per-round gradient histogram is one flat ``segment_sum`` (XLA scatter-
  add) over ``node*F*nbins + f*nbins + bin`` ids — a single fused kernel, no
  per-feature loops;
- everything is static-shape: ``num_bins``, ``num_features``, and the level's
  node count are compile-time constants, so XLA tiles the scatter efficiently
  and the whole boosting round stays inside one jit.

Under a sharded batch (rows split over the "data" mesh axis) GSPMD turns the
segment_sum into per-shard partial histograms + an all-reduce over ICI —
exactly the distributed-hist aggregation XGBoost does over Rabit
(SURVEY.md §2.9), but compiler-scheduled.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["quantile_boundaries", "apply_bins", "grad_histogram"]


def quantile_boundaries(sample: np.ndarray, num_bins: int) -> np.ndarray:
    """Per-feature quantile split points from a host-side sample.

    Returns boundaries [F, num_bins-1]; feature value v lands in bin
    ``searchsorted(boundaries[f], v)`` in [0, num_bins).  (The reference
    ecosystem's quantile sketch; a host numpy quantile is exact for the
    sampled rows and runs once per training job.)
    """
    sample = np.asarray(sample, dtype=np.float32)
    qs = np.linspace(0, 1, num_bins + 1)[1:-1]
    bounds = np.quantile(sample, qs, axis=0).T.astype(np.float32)  # [F, nb-1]
    # strictly increasing boundaries keep searchsorted stable on ties
    eps = np.float32(1e-6)
    bounds = np.maximum.accumulate(bounds +
                                   eps * np.arange(bounds.shape[1],
                                                   dtype=np.float32), axis=1)
    return bounds


def apply_bins(x, boundaries):
    """Bin dense features: x [B, F] float -> bins [B, F] int32 in [0, num_bins).

    jit-safe; vmapped searchsorted over the feature axis.
    """
    import jax
    import jax.numpy as jnp

    x = jnp.asarray(x)
    boundaries = jnp.asarray(boundaries)

    def one_feature(col, bounds):
        return jnp.searchsorted(bounds, col, side="right").astype(jnp.int32)

    return jax.vmap(one_feature, in_axes=(1, 0), out_axes=1)(x, boundaries)


def grad_histogram(bins, node_ids, grad, hess, num_nodes: int, num_bins: int,
                   model_axis: Optional[str] = None):
    """Per-(node, feature, bin) gradient/hessian sums.

    Args:
      bins:     [B, F] int32 binned features.
      node_ids: [B] int32 current tree-node of each row (in [0, num_nodes)).
      grad/hess: [B] float32 (pre-multiplied by instance weight; padding rows
        must carry 0 weight so they vanish from every bin).
      num_nodes, num_bins: static.
      model_axis: optional mesh axis name — when set, the histogram output is
        sharding-constrained to split the feature dim over that axis
        (tensor-parallel hist for very wide feature spaces).

    Returns (G, H): each [num_nodes, F, num_bins] float32.
    """
    import jax
    import jax.numpy as jnp

    bins = jnp.asarray(bins)
    B, F = bins.shape
    ids = (node_ids[:, None] * (F * num_bins)
           + jnp.arange(F, dtype=jnp.int32)[None, :] * num_bins
           + bins)                                    # [B, F]
    flat_ids = ids.reshape(-1)
    nseg = num_nodes * F * num_bins
    g_flat = jnp.broadcast_to(grad[:, None], (B, F)).reshape(-1)
    h_flat = jnp.broadcast_to(hess[:, None], (B, F)).reshape(-1)
    G = jax.ops.segment_sum(g_flat, flat_ids, num_segments=nseg)
    H = jax.ops.segment_sum(h_flat, flat_ids, num_segments=nseg)
    G = G.reshape(num_nodes, F, num_bins)
    H = H.reshape(num_nodes, F, num_bins)
    if model_axis is not None:
        from jax.sharding import PartitionSpec as P

        constraint = P(None, model_axis, None)
        G = jax.lax.with_sharding_constraint(G, constraint)
        H = jax.lax.with_sharding_constraint(H, constraint)
    return G, H
