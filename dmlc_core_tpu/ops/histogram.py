"""Quantile binning + gradient histograms (the XGBoost-hist core on TPU).

Design notes (TPU-first):
- binning is a one-time ``searchsorted`` per feature (vmapped, compiled once);
  bins are uint8/int32 — HBM-friendly, 4x smaller than raw floats at 256 bins;
- TWO histogram algorithms, chosen per backend:

  * ``"onehot"`` (TPU): the histogram is a **matmul on the MXU**.
    ``G[n,f,b] = sum_i nodehot[i,n] * g_i * binhot[i,f,b]`` — contract the
    row axis with ``dot_general``:  ``[2n, B] @ [B, F*nbins]``.  The bin
    one-hot depends only on the (static) binned features, so a full ``fit``
    materialises it ONCE in bf16 and every level of every round is a pure
    matmul read — systolic-array work instead of scatter.  TPU scatter-adds
    serialise (measured: the flat segment_sum below is >1000x slower than
    this on v5e); the one-hot matmul is the idiomatic recast.
  * ``"scatter"`` (CPU): one flat ``segment_sum`` over
    ``node*F*nbins + f*nbins + bin`` ids — cache-friendly scalar scatter,
    the fastest CPU formulation (and the exact-f32 reference in tests).

- everything is static-shape: ``num_bins``, ``num_features``, and the level's
  node count are compile-time constants, so XLA tiles the matmul/scatter
  efficiently and the whole boosting round stays inside one jit.

Under a sharded batch (rows split over the "data" mesh axis) GSPMD turns
either formulation into per-shard partial histograms + an all-reduce over
ICI — exactly the distributed-hist aggregation XGBoost does over Rabit
(SURVEY.md §2.9), but compiler-scheduled (the contracted row axis of the
dot_general is the sharded one, so the psum falls out of SPMD partitioning).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from dmlc_core_tpu.utils.logging import CHECK

__all__ = ["quantile_boundaries", "apply_bins", "grad_histogram",
           "bin_onehot", "resolve_hist_method", "local_quantile_summary",
           "merged_quantile_boundaries", "distributed_quantile_boundaries"]


def resolve_hist_method(method: str, *arrays) -> str:
    """Resolve ``"auto"`` to a concrete histogram algorithm.

    Prefers the committed platform of any input jax.Array, falling back to
    ``jax.default_backend()``: on TPU/GPU the VMEM-resident Pallas kernel
    when available (else the plain one-hot MXU matmul), scatter segment-sums
    on CPU.
    """
    if method != "auto":
        return method
    import jax

    platform = None
    for a in arrays:
        devs = getattr(a, "devices", None)
        if callable(devs):
            try:
                platform = next(iter(a.devices())).platform
                break
            except Exception:
                continue
    if platform is None:
        platform = jax.default_backend()
    if platform == "cpu":
        return "scatter"
    from dmlc_core_tpu.ops.hist_pallas import pallas_supported

    return "pallas" if pallas_supported() else "onehot"


def bin_onehot(bins, num_bins: int, dtype=None):
    """One-hot encode binned features: [B, F] int -> [B, F*num_bins].

    This is the matmul RHS of the one-hot histogram.  It depends only on the
    binned features, so callers training many rounds materialise it once
    (bf16: 0/1 exactly representable) and amortise across every level/round.
    """
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.bfloat16
    bins = jnp.asarray(bins).astype(jnp.int32)  # narrow dtypes must not wrap
    B, F = bins.shape
    iota = jnp.arange(num_bins, dtype=jnp.int32)
    return (bins[:, :, None] == iota).astype(dtype).reshape(B, F * num_bins)


def _strictly_increasing(bounds: np.ndarray) -> np.ndarray:
    """Make per-feature boundaries strictly increasing so searchsorted is
    stable on ties (repeated quantiles from heavy-tailed or constant
    features collapse otherwise).

    The nudge is magnitude-relative: an absolute epsilon is absorbed by
    float32 once |bound| exceeds ~1e1 (ulp(1e7) ≈ 1), which would let
    duplicate boundaries survive on large-valued features.
    """
    eps = np.float32(1e-6)
    scale = np.maximum(np.abs(bounds), np.float32(1.0))
    return np.maximum.accumulate(
        bounds + eps * scale * np.arange(bounds.shape[1], dtype=np.float32),
        axis=1)


def quantile_boundaries(sample: np.ndarray, num_bins: int) -> np.ndarray:
    """Per-feature quantile split points from a host-side sample.

    Returns boundaries [F, num_bins-1]; feature value v lands in bin
    ``searchsorted(boundaries[f], v)`` in [0, num_bins).  (The reference
    ecosystem's quantile sketch; a host numpy quantile is exact for the
    sampled rows and runs once per training job.)
    """
    sample = np.asarray(sample, dtype=np.float32)
    qs = np.linspace(0, 1, num_bins + 1)[1:-1]
    bounds = _nan_aware_quantile(sample, qs)             # [F, nb-1]
    return _strictly_increasing(bounds)


def _nan_aware_quantile(sample: np.ndarray, qs: np.ndarray) -> np.ndarray:
    """Per-feature quantiles, transposed to [F, len(qs)]; NaNs (missing
    values under GBDTParam.handle_missing) are excluded from the ranks.
    All-NaN features get zero boundaries (no real value to separate)."""
    if np.isnan(sample).any():
        import warnings

        with warnings.catch_warnings():
            warnings.filterwarnings("ignore", message="All-NaN slice")
            out = np.nanquantile(sample, qs, axis=0).T.astype(np.float32)
        return np.nan_to_num(out, nan=0.0)
    return np.quantile(sample, qs, axis=0).T.astype(np.float32)


def local_quantile_summary(sample: np.ndarray, num_points: int):
    """Fixed-size mergeable quantile summary of one data shard.

    Returns ``(points [F, num_points] float32, counts [F] float32)``: the
    shard's per-feature equi-rank quantiles plus its per-feature FINITE
    value counts.  Every point of feature f carries mass
    ``counts[f] / num_points``, which is all
    :func:`merged_quantile_boundaries` needs to take weighted quantiles of
    a union of shards — the fixed shape makes the summary allgather-able
    (every rank contributes the same [F, K] block regardless of shard
    size).

    Counts are per-feature because NaNs (missing values) carry no rank
    mass: a feature that is entirely missing on this shard contributes
    zero mass (its zero-filled points vanish in the merge) instead of K
    fabricated zeros at full shard weight.  An empty shard likewise
    returns zero points with zero counts and still participates in the
    collective without skewing the result.
    """
    sample = np.asarray(sample, dtype=np.float32)
    n, F = sample.shape
    if n == 0:
        return (np.zeros((F, num_points), np.float32),
                np.zeros((F,), np.float32))
    qs = np.linspace(0, 1, num_points)
    points = _nan_aware_quantile(sample, qs)
    counts = np.sum(np.isfinite(sample), axis=0).astype(np.float32)
    return points, counts


def merged_quantile_boundaries(points: np.ndarray, counts,
                               num_bins: int) -> np.ndarray:
    """Merge per-shard quantile summaries into one set of bin boundaries.

    Args:
      points: [W, F, K] stacked :func:`local_quantile_summary` points from
        all W shards (e.g. straight from ``collective.allgather``).
      counts: [W, F] per-shard per-feature finite counts (or [W] uniform
        per-shard row counts when no values are missing).
      num_bins: target bin count.

    Returns boundaries [F, num_bins-1], bit-identical on every rank that
    sees the same (points, counts) — which allgather guarantees — so
    data-parallel workers bin consistently without shipping raw rows.  This
    is the distributed-quantile-sketch step of XGBoost-hist (reference:
    SURVEY.md §2.9 — the hist aggregation consumer of rabit allreduce),
    done as one fixed-size allgather + a deterministic host merge: each
    point of shard w's feature f carries mass ``counts[w, f] / K`` and the
    merged boundary_j is the pooled weighted ``(j+1)/num_bins`` quantile
    per feature (inverted-CDF rule).  A feature with zero total mass (all
    shards all-missing) gets zero boundaries — there are no real values to
    separate.
    """
    points = np.asarray(points, dtype=np.float32)
    CHECK(points.ndim == 3, f"points must be [W, F, K], got {points.shape}")
    W, F, K = points.shape
    counts = np.asarray(counts, dtype=np.float64)
    if counts.ndim == 1:
        counts = np.broadcast_to(counts[:, None], (W, F))
    CHECK(counts.shape == (W, F),
          f"counts must be [W]={W} or [W, F]={(W, F)}, got {counts.shape}")
    CHECK(counts.sum() > 0, "merged_quantile_boundaries: all shards empty")
    # pooled points [F, W*K], per-point mass [F, W*K] (per-feature shard mass)
    pooled = np.swapaxes(points, 0, 1).reshape(F, W * K)
    mass = np.repeat(counts.T, K, axis=1) / K            # [F, W*K]
    order = np.argsort(pooled, axis=1, kind="stable")
    v_sorted = np.take_along_axis(pooled, order, axis=1)
    cum = np.cumsum(np.take_along_axis(mass, order, axis=1), axis=1)
    total = counts.sum(axis=0)                           # [F]
    out = np.empty((F, num_bins - 1), np.float32)
    for j in range(num_bins - 1):
        target = total * (j + 1) / num_bins              # [F]
        idx = np.minimum((cum < target[:, None]).sum(axis=1), W * K - 1)
        out[:, j] = v_sorted[np.arange(F), idx]
    out[total == 0] = 0.0
    return _strictly_increasing(out)


def distributed_quantile_boundaries(sample: np.ndarray, num_bins: int,
                                    comm=None,
                                    num_points: Optional[int] = None,
                                    count: Optional[int] = None
                                    ) -> np.ndarray:
    """Quantile bin boundaries consistent across data-parallel workers.

    Each worker summarises its local ``sample`` (:func:`local_quantile_
    summary`), allgathers the fixed-size summaries through ``comm`` (any
    object with rabit-shaped ``allgather`` — e.g. ``dmlc_core_tpu.
    collective``), and merges deterministically: all ranks return identical
    boundaries.  With ``comm=None`` (single process) this degrades to the
    plain :func:`quantile_boundaries`.

    ``num_points`` controls summary resolution (default ``8 * num_bins``,
    min 64): per-shard rank error is O(1/num_points), far below bin width.

    ``count`` overrides the shard mass this rank contributes to the merge.
    Pass the TRUE shard row count when ``sample`` is a capped subsample —
    otherwise imbalanced shards are mis-weighted (a 10M-row shard sampled
    to 100k would count the same as a full 100k shard).
    """
    if comm is None:
        return quantile_boundaries(sample, num_bins)
    K = num_points or max(64, 8 * num_bins)
    points, fc = local_quantile_summary(sample, K)       # fc: [F] finite
    n = np.asarray(sample).shape[0]
    if count is not None:
        CHECK(count >= 0, f"count must be non-negative, got {count}")
        CHECK(n > 0 or count == 0,
              f"count={count} with an empty sample contributes unsampled "
              f"mass; pass the shard's rows (or a subsample) too")
        if n > 0:
            # scale per-feature finite mass from the subsample up to the
            # shard's true size (assumes missingness rates survive sampling)
            fc = fc * (count / n)
    all_points = comm.allgather(points.astype(np.float32))   # [W, F, K]
    all_counts = comm.allgather(fc.astype(np.float32))       # [W, F]
    return merged_quantile_boundaries(all_points, all_counts, num_bins)


def apply_bins(x, boundaries, missing_bin: Optional[int] = None):
    """Bin dense features: x [B, F] float -> bins [B, F] int32 in [0, num_bins).

    jit-safe; vmapped searchsorted over the feature axis.  With
    ``missing_bin`` set, NaN entries take that reserved id (sparsity-aware
    GBDT: boundaries then cover one fewer bin, ``[F, num_bins - 2]``).
    """
    import jax
    import jax.numpy as jnp

    x = jnp.asarray(x)
    boundaries = jnp.asarray(boundaries)

    def one_feature(col, bounds):
        return jnp.searchsorted(bounds, col, side="right").astype(jnp.int32)

    ids = jax.vmap(one_feature, in_axes=(1, 0), out_axes=1)(x, boundaries)
    if missing_bin is not None:
        ids = jnp.where(jnp.isnan(x), jnp.int32(missing_bin), ids)
    return ids


def grad_histogram(bins, node_ids, grad, hess, num_nodes: int, num_bins: int,
                   model_axis: Optional[str] = None, method: str = "scatter",
                   onehot=None):
    """Per-(node, feature, bin) gradient/hessian sums.

    Args:
      bins:     [B, F] int32 binned features.
      node_ids: [B] int32 current tree-node of each row (in [0, num_nodes)).
      grad/hess: [B] float32 (pre-multiplied by instance weight; padding rows
        must carry 0 weight so they vanish from every bin).
      num_nodes, num_bins: static.
      model_axis: optional mesh axis name — when set, the histogram output is
        sharding-constrained to split the feature dim over that axis
        (tensor-parallel hist for very wide feature spaces).
      method: "scatter" (default: segment_sum, exact f32 — the reference
        formulation and the fast CPU one) | "onehot" (bf16 MXU matmul, the
        fast TPU one) | "auto" (resolve by platform).  The exact path stays
        the default so existing callers keep f32 semantics.
      onehot: optional precomputed :func:`bin_onehot` (amortised across
        levels/rounds by callers; only used by the onehot method).

    Returns (G, H): each [num_nodes, F, num_bins] float32.
    """
    import jax
    import jax.numpy as jnp

    bins = jnp.asarray(bins)
    B, F = bins.shape
    method = resolve_hist_method(method, bins, grad)
    if method == "pallas_fused":
        from dmlc_core_tpu.ops.hist_pallas import (pallas_fused_supported,
                                                   pallas_supported)

        if not pallas_fused_supported():
            # the fused kernel can fail to lower on real Mosaic where the
            # plain kernel still compiles (sub-16-sublane concat)
            method = "pallas" if pallas_supported() else "onehot"
    sharded_mesh = None
    if method in ("pallas", "pallas_fused"):
        from dmlc_core_tpu.ops.hist_pallas import (hist_node_block,
                                                   sharded_hist_plan)

        if model_axis is None:
            # the kernel keeps a [2n, F*nbins] accumulator resident in
            # VMEM; deeper levels run in node blocks (plain kernel only —
            # the blocked sweep has no fused variant), and only when even
            # an 8-node block overflows does the matmul take over
            block = hist_node_block(num_nodes, F, num_bins)
            if block is None:
                method = "onehot"
            elif block < num_nodes and method == "pallas_fused":
                method = "pallas"
        else:
            # model-sharded: pallas_call is not GSPMD-partitionable, but the
            # kernel stays on via shard_map — each model shard runs it (node-
            # blocked when deep) on its own F/mp feature slice
            sharded_mesh = sharded_hist_plan(model_axis, F, num_nodes,
                                             num_bins, batch=B)
            if sharded_mesh is None:
                method = "onehot"
            elif method == "pallas_fused":
                mp = sharded_mesh.shape[model_axis]
                if hist_node_block(num_nodes, F // mp,
                                   num_bins) < num_nodes:
                    method = "pallas"   # blocked sweeps have no fused variant

    if method in ("pallas", "pallas_fused") and sharded_mesh is not None:
        from dmlc_core_tpu.ops.hist_pallas import grad_hist_pallas_sharded

        G, H = grad_hist_pallas_sharded(
            bins, node_ids, grad, hess, num_nodes, num_bins, sharded_mesh,
            model_axis, fused=(method == "pallas_fused"))
    elif method == "pallas":
        from dmlc_core_tpu.ops.hist_pallas import grad_hist_pallas

        G, H = grad_hist_pallas(bins, node_ids, grad, hess, num_nodes,
                                num_bins)
    elif method == "pallas_fused":
        from dmlc_core_tpu.ops.hist_pallas import grad_hist_pallas_fused

        G, H = grad_hist_pallas_fused(bins, node_ids, grad, hess, num_nodes,
                                      num_bins)
    elif method == "onehot":
        if onehot is None:
            onehot = bin_onehot(bins, num_bins)
        dt = onehot.dtype
        nodehot = (node_ids.astype(jnp.int32)[:, None]
                   == jnp.arange(num_nodes, dtype=jnp.int32)).astype(dt)
        # [B, 2n]: per-row node one-hot weighted by g (first n cols) and h
        W = jnp.concatenate([nodehot * grad[:, None].astype(dt),
                             nodehot * hess[:, None].astype(dt)], axis=1)
        GH = jax.lax.dot_general(
            W, onehot, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # [2n, F*nbins] f32 acc
        GH = GH.reshape(2, num_nodes, F, num_bins)
        G, H = GH[0], GH[1]
    else:
        ids = (node_ids[:, None] * (F * num_bins)
               + jnp.arange(F, dtype=jnp.int32)[None, :] * num_bins
               + bins)                                    # [B, F]
        flat_ids = ids.reshape(-1)
        nseg = num_nodes * F * num_bins
        g_flat = jnp.broadcast_to(grad[:, None], (B, F)).reshape(-1)
        h_flat = jnp.broadcast_to(hess[:, None], (B, F)).reshape(-1)
        G = jax.ops.segment_sum(g_flat, flat_ids, num_segments=nseg)
        H = jax.ops.segment_sum(h_flat, flat_ids, num_segments=nseg)
        G = G.reshape(num_nodes, F, num_bins)
        H = H.reshape(num_nodes, F, num_bins)
    if model_axis is not None:
        from jax.sharding import PartitionSpec as P

        constraint = P(None, model_axis, None)
        G = jax.lax.with_sharding_constraint(G, constraint)
        H = jax.lax.with_sharding_constraint(H, constraint)
    return G, H
