"""Pallas TPU kernel: gradient histograms without materialising the one-hot.

The ``"onehot"`` method in :mod:`.histogram` casts the XGBoost-hist kernel
(reference workload: src/data + Rabit hist aggregation consumers) as an MXU
matmul ``W[M, B] @ onehot[B, F*nbins]``.  That is compute-shaped right, but
HBM-bound: the materialised one-hot is ``F*nbins/8`` times larger than the
binned features (28 feat x 256 bins -> 14 KB/row in bf16 vs 112 B/row of
int32 bins), and every tree level of every boosting round re-reads all of it.

This kernel keeps the matmul but builds the one-hot **tile-by-tile in VMEM**:

- grid = row tiles (1-D, sequential on TPU);
- the ``[M, F*nbins]`` f32 accumulator lives in one VMEM output block whose
  index map is constant, so it persists across grid steps (zeroed at step 0);
- per step: DMA ``W`` tile ``[M, TB]`` (bf16) + bins tile ``[TB, F]``
  (int32), then for each feature compare-to-iota -> ``[TB, nbins]`` one-hot
  in VMEM and issue one MXU dot, accumulating in f32.

HBM traffic per level falls from ``B*F*nbins*2`` bytes to
``B*(4F + 2M + 12)`` — ~100x for the flagship shapes — turning the histogram
from bandwidth- to compute-bound.  Numerics match the ``"onehot"`` method
exactly (same bf16 one-hot / bf16 W / f32 accumulate).

Used automatically on TPU via ``resolve_hist_method("auto")`` when the
histogram block fits VMEM; falls back to the plain one-hot matmul otherwise.
"""

from __future__ import annotations

import functools

import numpy as np

__all__ = ["hist_matmul_pallas", "grad_hist_pallas",
           "grad_hist_pallas_fused", "pallas_supported",
           "pallas_fused_supported", "hist_fits_vmem",
           "BLOCK_ROWS"]

# interpreter mode: runs the kernels on CPU for tests/debugging (flipped by
# tests, or set DMLC_TPU_PALLAS_INTERPRET=1 to debug without a chip)
import os as _os

_INTERPRET = _os.environ.get("DMLC_TPU_PALLAS_INTERPRET",
                             "").strip().lower() in ("1", "true", "yes")

# row-tile size: callers that want the wrapper's internal padding to no-op
# (e.g. GBDT's fit-level padding) must pad to a multiple of this
BLOCK_ROWS = 1024

# VMEM budget for the resident accumulator block (bytes); above this
# callers fall back to the plain one-hot matmul.
_ACC_BYTES_LIMIT = 8 * 1024 * 1024


def _pad_nodes(num_nodes: int) -> int:
    """Node-slot padding so M = 2*n_pad is a multiple of the bf16 tile (16)."""
    return -(-max(8, num_nodes) // 8) * 8


def hist_fits_vmem(num_nodes: int, num_feature: int, num_bins: int) -> bool:
    """Whether the resident [2*n_pad, F*nbins] f32 accumulator fits VMEM."""
    return 2 * _pad_nodes(num_nodes) * num_feature * num_bins * 4 \
        <= _ACC_BYTES_LIMIT


def _accumulate_tile(w, bins_ref, out_ref, num_feature: int, num_bins: int):
    """Shared tile body: zero-init at step 0, then per-feature one-hot dots
    of ``w`` [M, TB] accumulated into the resident ``out_ref``."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _zero():
        out_ref[:] = jnp.zeros_like(out_ref)

    iota = jax.lax.broadcasted_iota(jnp.int32, (1, num_bins), 1)
    for f in range(num_feature):
        onehot = (bins_ref[:, f:f + 1] == iota).astype(w.dtype)  # [TB, nbins]
        out_ref[:, f * num_bins:(f + 1) * num_bins] += jax.lax.dot_general(
            w, onehot, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)


def _split_gh(out, n_pad: int, num_nodes: int, num_feature: int,
              num_bins: int):
    """Shared epilogue: [2*n_pad, F*nbins] -> (G, H) trimmed to num_nodes."""
    out = out.reshape(2, n_pad, num_feature, num_bins)
    return out[0, :num_nodes], out[1, :num_nodes]


def _kernel(w_ref, bins_ref, out_ref, *, num_feature: int, num_bins: int):
    _accumulate_tile(w_ref[:], bins_ref, out_ref, num_feature, num_bins)


def hist_matmul_pallas(w, bins, num_bins: int, block_rows: int = BLOCK_ROWS):
    """``out[m, f*nbins + b] = sum_i w[m, i] * (bins[i, f] == b)``.

    Args:
      w: [M, B] bf16 per-row weights (M multiple of 16; rows beyond the live
        node count must be zero).
      bins: [B, F] int32 binned features in [0, num_bins).
      num_bins: static bin count.
      block_rows: row-tile size (B is padded up to a multiple internally).

    Returns [M, F*num_bins] float32.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, b = w.shape
    bf = bins.shape[1]
    if b % block_rows:
        pad = block_rows - b % block_rows
        w = jnp.pad(w, ((0, 0), (0, pad)))         # zero W => zero contribution
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        b += pad
    kernel = functools.partial(_kernel, num_feature=bf, num_bins=num_bins)
    return pl.pallas_call(
        kernel,
        grid=(b // block_rows,),
        in_specs=[
            pl.BlockSpec((m, block_rows), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_rows, bf), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((m, bf * num_bins), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, bf * num_bins), jnp.float32),
        interpret=_INTERPRET,
    )(w, bins)


def grad_hist_pallas(bins, node_ids, grad, hess, num_nodes: int,
                     num_bins: int):
    """Per-(node, feature, bin) gradient/hessian sums via the VMEM kernel.

    Same contract as :func:`.histogram.grad_histogram`; returns (G, H) each
    [num_nodes, F, num_bins] float32.  Rows with out-of-range (e.g. negative)
    node ids contribute nothing.
    """
    import jax.numpy as jnp

    bins = jnp.asarray(bins).astype(jnp.int32)
    bf = bins.shape[1]
    n_pad = _pad_nodes(num_nodes)
    iota_n = jnp.arange(n_pad, dtype=jnp.int32)
    nodehot = node_ids.astype(jnp.int32)[None, :] == iota_n[:, None]  # [n, B]
    w = jnp.concatenate([
        jnp.where(nodehot, grad[None, :], 0.0),
        jnp.where(nodehot, hess[None, :], 0.0),
    ], axis=0).astype(jnp.bfloat16)                # [2*n_pad, B]
    out = hist_matmul_pallas(w, bins, num_bins)
    return _split_gh(out, n_pad, num_nodes, bf, num_bins)


def _fused_kernel(node_ref, g_ref, h_ref, bins_ref, out_ref, *,
                  n_pad: int, num_feature: int, num_bins: int):
    import jax
    import jax.numpy as jnp

    # W tile [2*n_pad, TB] built in VMEM from node/g/h (12 B/row of HBM
    # traffic instead of 4*n_pad B/row for a materialised W)
    iota_n = jax.lax.broadcasted_iota(jnp.int32, (n_pad, 1), 0)
    nodehot = (iota_n == node_ref[:]).astype(jnp.bfloat16)   # [n_pad, TB]
    w = jnp.concatenate([nodehot * g_ref[:].astype(jnp.bfloat16),
                         nodehot * h_ref[:].astype(jnp.bfloat16)], axis=0)
    _accumulate_tile(w, bins_ref, out_ref, num_feature, num_bins)


def grad_hist_pallas_fused(bins, node_ids, grad, hess, num_nodes: int,
                           num_bins: int, block_rows: int = BLOCK_ROWS):
    """Like :func:`grad_hist_pallas`, with the weight matrix built in-kernel.

    Skips the XLA-side [2n, B] W materialisation entirely: the kernel reads
    node/g/h row tiles and bins, and builds both one-hots in VMEM.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bins = jnp.asarray(bins).astype(jnp.int32)
    b, bf = bins.shape
    n_pad = _pad_nodes(num_nodes)
    node = node_ids.astype(jnp.int32).reshape(1, b)
    g = grad.astype(jnp.float32).reshape(1, b)
    h = hess.astype(jnp.float32).reshape(1, b)
    if b % block_rows:
        pad = block_rows - b % block_rows
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        node = jnp.pad(node, ((0, 0), (0, pad)), constant_values=-1)
        g = jnp.pad(g, ((0, 0), (0, pad)))
        h = jnp.pad(h, ((0, 0), (0, pad)))
        b += pad
    m = 2 * n_pad
    kernel = functools.partial(_fused_kernel, n_pad=n_pad, num_feature=bf,
                               num_bins=num_bins)
    row_spec = pl.BlockSpec((1, block_rows), lambda i: (0, i),
                            memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        kernel,
        grid=(b // block_rows,),
        in_specs=[row_spec, row_spec, row_spec,
                  pl.BlockSpec((block_rows, bf), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((m, bf * num_bins), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, bf * num_bins), jnp.float32),
        interpret=_INTERPRET,
    )(node, g, h, bins)
    return _split_gh(out, n_pad, num_nodes, bf, num_bins)


@functools.lru_cache(maxsize=None)
def pallas_supported() -> bool:
    """Probe once whether the Pallas TPU path compiles+runs on this backend."""
    import jax

    if jax.default_backend() == "cpu" and not _INTERPRET:
        return False
    try:
        import jax.numpy as jnp

        w = jnp.zeros((16, 128), jnp.bfloat16).at[0, 0].set(1.0)
        bins = jnp.zeros((128, 2), jnp.int32)
        out = jax.jit(lambda w, b: hist_matmul_pallas(w, b, 8,
                                                      block_rows=128))(w, bins)
        return bool(np.asarray(out)[0, 0] == 1.0)
    except Exception:
        return False


@functools.lru_cache(maxsize=None)
def pallas_fused_supported() -> bool:
    """Probe the fused-W kernel separately from the plain one.

    The fused kernel's in-VMEM bf16 concat at the n_pad=8 boundary (below the
    16-sublane tile) can fail to lower on real Mosaic even when
    :func:`hist_matmul_pallas` compiles — probing only the plain kernel would
    let a user-selected ``pallas_fused`` crash at first use.
    """
    if not pallas_supported():
        return False
    try:
        import jax
        import jax.numpy as jnp

        bins = jnp.zeros((128, 2), jnp.int32)
        node = jnp.zeros((128,), jnp.int32)
        one = jnp.ones((128,), jnp.float32)
        G, _ = jax.jit(lambda b, n, g, h: grad_hist_pallas_fused(
            b, n, g, h, num_nodes=4, num_bins=8, block_rows=128))(
                bins, node, one, one)
        return bool(np.asarray(G)[0, 0, 0] == 128.0)
    except Exception:
        return False
