"""Pallas TPU kernel: gradient histograms without materialising the one-hot.

The ``"onehot"`` method in :mod:`.histogram` casts the XGBoost-hist kernel
(reference workload: src/data + Rabit hist aggregation consumers) as an MXU
matmul ``W[M, B] @ onehot[B, F*nbins]``.  That is compute-shaped right, but
HBM-bound: the materialised one-hot is ``F*nbins/8`` times larger than the
binned features (28 feat x 256 bins -> 14 KB/row in bf16 vs 112 B/row of
int32 bins), and every tree level of every boosting round re-reads all of it.

This kernel keeps the matmul but builds the one-hot **tile-by-tile in VMEM**:

- grid = row tiles (1-D, sequential on TPU);
- the ``[M, F*nbins]`` f32 accumulator lives in one VMEM output block whose
  index map is constant, so it persists across grid steps (zeroed at step 0);
- per step: DMA ``W`` tile ``[M, TB]`` (bf16) + bins tile ``[TB, F]``
  (int32), then for each feature compare-to-iota -> ``[TB, nbins]`` one-hot
  in VMEM and issue one MXU dot, accumulating in f32.

HBM traffic per level falls from ``B*F*nbins*2`` bytes to
``B*(4F + 2M + 12)`` — ~100x for the flagship shapes — turning the histogram
from bandwidth- to compute-bound.  Numerics match the ``"onehot"`` method
exactly (same bf16 one-hot / bf16 W / f32 accumulate).

Used automatically on TPU via ``resolve_hist_method("auto")`` when the
histogram block fits VMEM; falls back to the plain one-hot matmul otherwise.
"""

from __future__ import annotations

import functools

import numpy as np

__all__ = ["hist_matmul_pallas", "grad_hist_pallas",
           "grad_hist_pallas_fused", "grad_hist_pallas_sharded",
           "ambient_mesh", "sharded_hist_plan", "pallas_supported",
           "pallas_fused_supported", "pallas_i8_supported", "hist_fits_vmem",
           "hist_node_block", "BLOCK_ROWS", "DATA_AXIS"]

# interpreter mode: runs the kernels on CPU for tests/debugging (flipped by
# tests, or set DMLC_TPU_PALLAS_INTERPRET=1 to debug without a chip)
import os as _os

_INTERPRET = _os.environ.get("DMLC_TPU_PALLAS_INTERPRET",
                             "").strip().lower() in ("1", "true", "yes")

# row-tile size: callers that want the wrapper's internal padding to no-op
# (e.g. GBDT's fit-level padding) must pad to a multiple of this.
# DMLC_TPU_HIST_BLOCK_ROWS overrides for on-chip tuning sweeps; 1024 is the
# measured-best default on v5e (see BASELINE.md round-3 block_rows sweep).
try:
    BLOCK_ROWS = int(_os.environ.get("DMLC_TPU_HIST_BLOCK_ROWS", "") or 1024)
except ValueError:
    raise ValueError(
        "DMLC_TPU_HIST_BLOCK_ROWS must be an integer multiple of the 128 "
        f"lane width, got {_os.environ['DMLC_TPU_HIST_BLOCK_ROWS']!r}"
    ) from None
if BLOCK_ROWS < 128 or BLOCK_ROWS % 128:
    raise ValueError(
        f"DMLC_TPU_HIST_BLOCK_ROWS must be a positive multiple of the 128 "
        f"lane width, got {BLOCK_ROWS}")


def _bins_compare_dtype(num_bins: int):
    """dtype bins are compared in inside the kernel: int8 when the bin ids
    fit (<=256 with wraparound) AND the backend lowers it, else int32."""
    import jax.numpy as jnp

    if num_bins <= 256 and pallas_i8_supported():
        return jnp.int8
    return jnp.int32

# VMEM budget for the resident accumulator block (bytes); above this
# callers fall back to the plain one-hot matmul.
_ACC_BYTES_LIMIT = 8 * 1024 * 1024


def _pad_nodes(num_nodes: int) -> int:
    """Node-slot padding so M = 2*n_pad is a multiple of the bf16 tile (16)."""
    return -(-max(8, num_nodes) // 8) * 8


def hist_fits_vmem(num_nodes: int, num_feature: int, num_bins: int) -> bool:
    """Whether the resident [2*n_pad, F*nbins] f32 accumulator fits VMEM."""
    return 2 * _pad_nodes(num_nodes) * num_feature * num_bins * 4 \
        <= _ACC_BYTES_LIMIT


def hist_node_block(num_nodes: int, num_feature: int, num_bins: int):
    """Nodes per kernel sweep, or None when even 8 node slots overflow VMEM.

    Deep tree levels whose full [2n, F*nbins] accumulator exceeds VMEM run
    the kernel in node blocks: each sweep re-reads the bins tile and
    re-builds the one-hot, but kernel cost is VPU-bound and m-independent
    (measured — BASELINE.md r3 profile), so #sweeps scales the cost, while
    the one-hot-matmul fallback's MXU work scales with the FULL node count
    AND re-reads the 2n x B x F*nbins problem from HBM.  Blocking keeps the
    kernel the fastest choice for every depth the GBDT allows.
    """
    if hist_fits_vmem(num_nodes, num_feature, num_bins):
        return num_nodes
    block = 1 << (num_nodes - 1).bit_length()
    while block >= 8:
        if hist_fits_vmem(block, num_feature, num_bins):
            return block
        block //= 2
    return None


def _accumulate_tile(w, bins_ref, out_ref, num_feature: int, num_bins: int):
    """Shared tile body: zero-init at step 0, then per-feature one-hot dots
    of ``w`` [M, TB] accumulated into the resident ``out_ref``.

    The iota matches the bins dtype: callers may pass bins as int8 (the
    profiled v5e bottleneck is this in-VMEM one-hot build, not the MXU dots
    — kernel time is m-independent — and int8 compares run 4 lanes/cycle
    wider on the VPU).  num_bins=256 still fits: both sides wrap through
    int8 identically, so equality is preserved.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _zero():
        out_ref[:] = jnp.zeros_like(out_ref)

    iota = jax.lax.broadcasted_iota(jnp.int32, (1, num_bins), 1)
    iota = iota.astype(bins_ref.dtype)
    for f in range(num_feature):
        onehot = (bins_ref[:, f:f + 1] == iota).astype(w.dtype)  # [TB, nbins]
        out_ref[:, f * num_bins:(f + 1) * num_bins] += jax.lax.dot_general(
            w, onehot, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)


def _split_gh(out, n_pad: int, num_nodes: int, num_feature: int,
              num_bins: int):
    """Shared epilogue: [2*n_pad, F*nbins] -> (G, H) trimmed to num_nodes."""
    out = out.reshape(2, n_pad, num_feature, num_bins)
    return out[0, :num_nodes], out[1, :num_nodes]


def _kernel(w_ref, bins_ref, out_ref, *, num_feature: int, num_bins: int):
    _accumulate_tile(w_ref[:], bins_ref, out_ref, num_feature, num_bins)


def hist_matmul_pallas(w, bins, num_bins: int, block_rows: int = BLOCK_ROWS):
    """``out[m, f*nbins + b] = sum_i w[m, i] * (bins[i, f] == b)``.

    Args:
      w: [M, B] bf16 per-row weights (M multiple of 16; rows beyond the live
        node count must be zero).
      bins: [B, F] int32 binned features in [0, num_bins).
      num_bins: static bin count.
      block_rows: row-tile size (B is padded up to a multiple internally).

    Returns [M, F*num_bins] float32.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, b = w.shape
    bf = bins.shape[1]
    bins = bins.astype(_bins_compare_dtype(num_bins))
    if b % block_rows:
        pad = block_rows - b % block_rows
        w = jnp.pad(w, ((0, 0), (0, pad)))         # zero W => zero contribution
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        b += pad
    kernel = functools.partial(_kernel, num_feature=bf, num_bins=num_bins)
    return pl.pallas_call(
        kernel,
        grid=(b // block_rows,),
        in_specs=[
            pl.BlockSpec((m, block_rows), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_rows, bf), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((m, bf * num_bins), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, bf * num_bins), jnp.float32),
        interpret=_INTERPRET,
    )(w, bins)


def grad_hist_pallas(bins, node_ids, grad, hess, num_nodes: int,
                     num_bins: int):
    """Per-(node, feature, bin) gradient/hessian sums via the VMEM kernel.

    Same contract as :func:`.histogram.grad_histogram`; returns (G, H) each
    [num_nodes, F, num_bins] float32.  Rows with out-of-range (e.g. negative)
    node ids contribute nothing.

    Levels too deep for one resident accumulator run in node blocks (see
    :func:`hist_node_block`): shifting node ids by the block base makes the
    kernel's own out-of-range drop do the partitioning.
    """
    import jax.numpy as jnp

    block = hist_node_block(num_nodes, bins.shape[1], num_bins)
    assert block is not None, "caller must gate on hist_node_block"
    if block < num_nodes:
        node_ids = node_ids.astype(jnp.int32)
        parts = [
            _grad_hist_pallas_block(bins, node_ids - b0, grad, hess,
                                    min(block, num_nodes - b0), num_bins)
            for b0 in range(0, num_nodes, block)
        ]
        return (jnp.concatenate([p[0] for p in parts]),
                jnp.concatenate([p[1] for p in parts]))
    return _grad_hist_pallas_block(bins, node_ids, grad, hess, num_nodes,
                                   num_bins)


def _grad_hist_pallas_block(bins, node_ids, grad, hess, num_nodes: int,
                            num_bins: int):
    import jax.numpy as jnp

    bins = jnp.asarray(bins).astype(jnp.int32)
    bf = bins.shape[1]
    n_pad = _pad_nodes(num_nodes)
    iota_n = jnp.arange(n_pad, dtype=jnp.int32)
    nodehot = node_ids.astype(jnp.int32)[None, :] == iota_n[:, None]  # [n, B]
    w = jnp.concatenate([
        jnp.where(nodehot, grad[None, :], 0.0),
        jnp.where(nodehot, hess[None, :], 0.0),
    ], axis=0).astype(jnp.bfloat16)                # [2*n_pad, B]
    out = hist_matmul_pallas(w, bins, num_bins)
    return _split_gh(out, n_pad, num_nodes, bf, num_bins)


def _fused_kernel(node_ref, g_ref, h_ref, bins_ref, out_ref, *,
                  n_pad: int, num_feature: int, num_bins: int):
    import jax
    import jax.numpy as jnp

    # W tile [2*n_pad, TB] built in VMEM from node/g/h (12 B/row of HBM
    # traffic instead of 4*n_pad B/row for a materialised W)
    iota_n = jax.lax.broadcasted_iota(jnp.int32, (n_pad, 1), 0)
    nodehot = (iota_n == node_ref[:]).astype(jnp.bfloat16)   # [n_pad, TB]
    w = jnp.concatenate([nodehot * g_ref[:].astype(jnp.bfloat16),
                         nodehot * h_ref[:].astype(jnp.bfloat16)], axis=0)
    _accumulate_tile(w, bins_ref, out_ref, num_feature, num_bins)


def grad_hist_pallas_fused(bins, node_ids, grad, hess, num_nodes: int,
                           num_bins: int, block_rows: int = BLOCK_ROWS):
    """Like :func:`grad_hist_pallas`, with the weight matrix built in-kernel.

    Skips the XLA-side [2n, B] W materialisation entirely: the kernel reads
    node/g/h row tiles and bins, and builds both one-hots in VMEM.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bins = jnp.asarray(bins).astype(_bins_compare_dtype(num_bins))
    b, bf = bins.shape
    n_pad = _pad_nodes(num_nodes)
    node = node_ids.astype(jnp.int32).reshape(1, b)
    g = grad.astype(jnp.float32).reshape(1, b)
    h = hess.astype(jnp.float32).reshape(1, b)
    if b % block_rows:
        pad = block_rows - b % block_rows
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        node = jnp.pad(node, ((0, 0), (0, pad)), constant_values=-1)
        g = jnp.pad(g, ((0, 0), (0, pad)))
        h = jnp.pad(h, ((0, 0), (0, pad)))
        b += pad
    m = 2 * n_pad
    kernel = functools.partial(_fused_kernel, n_pad=n_pad, num_feature=bf,
                               num_bins=num_bins)
    row_spec = pl.BlockSpec((1, block_rows), lambda i: (0, i),
                            memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        kernel,
        grid=(b // block_rows,),
        in_specs=[row_spec, row_spec, row_spec,
                  pl.BlockSpec((block_rows, bf), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((m, bf * num_bins), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, bf * num_bins), jnp.float32),
        interpret=_INTERPRET,
    )(node, g, h, bins)
    return _split_gh(out, n_pad, num_nodes, bf, num_bins)


# mesh axis name the whole package shards batch rows over (parallel/mesh.py
# data_sharding default); the sharded hist uses it for its psum axis
DATA_AXIS = "data"


def ambient_mesh():
    """The Mesh of an enclosing ``with mesh:`` block, or None.

    grad_histogram reads this at trace time to shard_map the kernel for
    model-parallel runs; callers opt in simply by tracing under their mesh
    (the convention every sharded path in this package already follows).
    Guarded: if a jax upgrade moves the thread-resources accessor, model-
    sharded callers degrade to the onehot fallback instead of crashing.
    """
    try:
        from jax._src import mesh as mesh_lib

        m = mesh_lib.thread_resources.env.physical_mesh
    except Exception:
        try:
            from jax.interpreters import pxla

            m = pxla.thread_resources.env.physical_mesh
        except Exception:
            return None
    return None if m.empty else m


def sharded_hist_plan(model_axis, num_feature: int, num_nodes: int,
                      num_bins: int, batch=None, mesh=None):
    """The mesh to shard_map the hist kernel over, or None to fall back.

    Single source of truth for the model-sharded-pallas gate (used by both
    ``grad_histogram`` and ``GBDT._method`` so the two can't drift): requires
    an ambient (or given) mesh carrying ``model_axis``, features dividing
    evenly across it, rows dividing across the data axis (``batch=None``
    skips that check for callers that pad rows later), and the per-shard
    ``F/mp`` slice supporting at least a node-blocked accumulator (deep
    levels sweep node blocks inside each shard, same as unsharded).
    """
    if model_axis is None:
        return None
    if mesh is None:
        mesh = ambient_mesh()
    if mesh is None:
        return None
    mp = mesh.shape.get(model_axis)
    dp = mesh.shape.get(DATA_AXIS, 1)
    if (mp is None or num_feature % mp != 0
            or (batch is not None and batch % dp != 0)
            or hist_node_block(num_nodes, num_feature // mp, num_bins)
            is None):
        return None
    return mesh


def grad_hist_pallas_sharded(bins, node_ids, grad, hess, num_nodes: int,
                             num_bins: int, mesh, model_axis: str,
                             data_axis: str = DATA_AXIS,
                             fused: bool = False):
    """shard_map-wrapped VMEM hist: rows dp-sharded, features model-sharded.

    Keeps the Pallas kernel under tensor parallelism (SURVEY §2.9) instead of
    falling back to the HBM-tiled one-hot matmul: each model shard slices its
    own ``F/mp`` feature columns (bins arrive feature-replicated), runs the
    VMEM kernel on its local row shard, and psums partial histograms over the
    data axis.  Output is ``P(None, model_axis, None)`` — exactly the
    constraint the GSPMD path advertises, so split-finding code downstream is
    unchanged.

    Requires ``F % mesh.shape[model_axis] == 0``; callers check this (and the
    per-shard VMEM fit) before dispatching here.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from dmlc_core_tpu.parallel.compat import shard_map_unchecked

    F = bins.shape[1]
    mp = mesh.shape[model_axis]
    f_local = F // mp
    row_axis = data_axis if data_axis in mesh.shape else None
    inner = grad_hist_pallas_fused if fused else grad_hist_pallas

    def local_hist(b, n, g, h):
        idx = jax.lax.axis_index(model_axis)
        b_local = jax.lax.dynamic_slice_in_dim(b, idx * f_local, f_local,
                                               axis=1)
        G, H = inner(b_local, n.astype(jnp.int32), g, h, num_nodes, num_bins)
        if row_axis is not None:
            G = jax.lax.psum(G, row_axis)
            H = jax.lax.psum(H, row_axis)
        return G, H

    out_spec = P(None, model_axis, None)
    # unchecked variant: pallas_call's out_shape carries no vma annotation;
    # the psum above already makes the outputs data-axis-invariant
    return shard_map_unchecked(
        local_hist, mesh,
        in_specs=(P(row_axis, None), P(row_axis), P(row_axis), P(row_axis)),
        out_specs=(out_spec, out_spec),
    )(bins, node_ids, grad, hess)


@functools.lru_cache(maxsize=None)
def pallas_supported() -> bool:
    """Probe once whether the Pallas TPU path compiles+runs on this backend."""
    import jax

    if jax.default_backend() == "cpu" and not _INTERPRET:
        return False
    try:
        import jax.numpy as jnp

        w = jnp.zeros((16, 128), jnp.bfloat16).at[0, 0].set(1.0)
        bins = jnp.zeros((128, 2), jnp.int32)
        out = jax.jit(lambda w, b: hist_matmul_pallas(w, b, 8,
                                                      block_rows=128))(w, bins)
        return bool(np.asarray(out)[0, 0] == 1.0)
    except Exception:
        return False


@functools.lru_cache(maxsize=None)
def pallas_i8_supported() -> bool:
    """Probe whether int8 bins compare+select lowers in the kernel.

    Probed with a direct pallas_call (not through the wrappers, which would
    recurse into this gate): an int8 bins tile against the shared tile body.
    Falls back to int32 bins when Mosaic rejects the int8 vector ops, and is
    disabled outright by DMLC_TPU_HIST_I8=0 for A/B benchmarking.
    """
    if _os.environ.get("DMLC_TPU_HIST_I8", "").strip() == "0":
        return False
    import jax

    if jax.default_backend() == "cpu" and not _INTERPRET:
        return False
    try:
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        kernel = functools.partial(_kernel, num_feature=2, num_bins=8)
        w = jnp.zeros((16, 128), jnp.bfloat16).at[0, 0].set(1.0)
        bins = jnp.zeros((128, 2), jnp.int8)
        out = jax.jit(lambda w, b: pl.pallas_call(
            kernel,
            grid=(1,),
            in_specs=[pl.BlockSpec((16, 128), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
                      pl.BlockSpec((128, 2), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((16, 16), lambda i: (0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((16, 16), jnp.float32),
            interpret=_INTERPRET,
        )(w, b))(w, bins)
        return bool(np.asarray(out)[0, 0] == 1.0)
    except Exception:
        return False


@functools.lru_cache(maxsize=None)
def pallas_fused_supported() -> bool:
    """Probe the fused-W kernel separately from the plain one.

    The fused kernel's in-VMEM bf16 concat at the n_pad=8 boundary (below the
    16-sublane tile) can fail to lower on real Mosaic even when
    :func:`hist_matmul_pallas` compiles — probing only the plain kernel would
    let a user-selected ``pallas_fused`` crash at first use.
    """
    if not pallas_supported():
        return False
    try:
        import jax
        import jax.numpy as jnp

        bins = jnp.zeros((128, 2), jnp.int32)
        node = jnp.zeros((128,), jnp.int32)
        one = jnp.ones((128,), jnp.float32)
        G, _ = jax.jit(lambda b, n, g, h: grad_hist_pallas_fused(
            b, n, g, h, num_nodes=4, num_bins=8, block_rows=128))(
                bins, node, one, one)
        return bool(np.asarray(G)[0, 0, 0] == 128.0)
    except Exception:
        return False
