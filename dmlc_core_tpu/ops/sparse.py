"""Sparse segment ops for flat-COO batches (the RowBlock SDot analog on TPU).

The reference's ``Row::SDot`` (data.h:133-148) is a scalar loop; on TPU the
batch-level equivalent is gather + ``segment_sum`` over the flat nonzero
stream of a :class:`dmlc_core_tpu.bridge.batching.SparseBatch` — one fused
XLA kernel per batch, static shapes via the nnz bucket ladder.
"""

from __future__ import annotations

__all__ = ["segment_matvec", "sparse_logit", "segment_transpose_matvec"]


def segment_matvec(w, value, index, row_id, batch_size: int):
    """Per-row sparse dot: out[b] = sum_{nnz in row b} w[index] * value.

    Padding entries carry ``row_id == batch_size`` and land in the dropped
    extra segment.
    """
    import jax
    import jax.numpy as jnp

    contrib = w[index] * value
    seg = jax.ops.segment_sum(contrib, row_id, num_segments=batch_size + 1)
    return seg[:batch_size]


def segment_transpose_matvec(r, value, index, row_id, num_feature: int):
    """Transpose product: out[f] = sum_{nnz with index==f} r[row] * value.

    ``r`` must have a trailing 0 sentinel slot (r[batch_size] == 0) so padding
    rows contribute nothing; pass ``jnp.append(r, 0.0)`` or a [B+1] array.
    """
    import jax

    contrib = r[row_id] * value
    return jax.ops.segment_sum(contrib, index, num_segments=num_feature)


def sparse_logit(w, b, batch, num_feature: int):
    """Margin for a SparseBatch under a linear model: Xw + b."""
    bsz = batch.label.shape[0]
    return segment_matvec(w, batch.value, batch.index, batch.row_id, bsz) + b
