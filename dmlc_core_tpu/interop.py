"""Framework interop boundary: zero-copy exchange with torch/numpy via dlpack.

The reference ships a header-only Lua/Torch bridge (include/dmlc/lua.h:62-739)
so DMLC libraries could exchange tensors with Torch7 plugins.  The modern
equivalent of that FFI boundary is dlpack: jax.Array <-> torch.Tensor <->
numpy without copies where layouts allow.
"""

from __future__ import annotations

from typing import Any

__all__ = ["to_torch", "from_torch", "to_numpy", "from_numpy"]


def to_torch(x: Any):
    """jax.Array/numpy -> torch.Tensor (dlpack zero-copy when possible)."""
    import torch

    try:
        return torch.from_dlpack(x)
    except Exception:
        import numpy as np

        return torch.from_numpy(np.asarray(x))


def from_torch(t: Any):
    """torch.Tensor -> jax.Array (dlpack zero-copy when device-compatible)."""
    import jax
    import jax.numpy as jnp

    try:
        return jnp.from_dlpack(t)
    except Exception:
        return jnp.asarray(t.detach().cpu().numpy())


def to_numpy(x: Any):
    import numpy as np

    return np.asarray(x)


def from_numpy(a: Any):
    import jax.numpy as jnp

    return jnp.asarray(a)
