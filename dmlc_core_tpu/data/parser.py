"""Parser iteration protocol + threaded decorator + text-chunk parallelism.

Capability parity with the reference's parser core (src/data/parser.h:23-126)
and ``TextParserBase`` (src/data/text_parser.h:24-118):

- :class:`Parser` — the ``DataIter<RowBlock>`` protocol (data.h:52-63):
  ``before_first`` / ``next`` / ``bytes_read``;
- :class:`ParserImpl` — block-vector iteration (parser.h:30-44): subclasses
  produce lists of :class:`RowBlockContainer` per source chunk;
- :class:`TextParserBase` — one InputSplit chunk is cut into per-worker
  sub-ranges realigned at newlines and parsed in parallel (FillData,
  text_parser.h:89-118); workers run in a thread pool (the reference's OpenMP
  team) and the heavy lifting is vectorized numpy, which releases the GIL.
  With ``DMLC_PARSE_PROC=N`` the fan-out moves to worker *processes* whose
  RowBlock columns come back through shared memory with zero copies
  (:mod:`dmlc_core_tpu.data.parse_proc`) — auto-off when the native core
  parses chunks itself, with a clean fallback to the thread path;
- :class:`ThreadedParser` — prefetch decorator running the whole parse on a
  producer thread with a bounded queue (parser.h:70-126, capacity 8); the
  queue is additionally bounded by decoded-block *bytes*
  (``DMLC_PARSE_QUEUE_BYTES``, default 256 MiB), since 8 blocks of wide CSV
  can dwarf 8 blocks of sparse libsvm.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

import numpy as np

from dmlc_core_tpu import telemetry
from dmlc_core_tpu.data import parse_proc
from dmlc_core_tpu.data.row_block import RowBlock, RowBlockContainer, concat_blocks
from dmlc_core_tpu.io.input_split import InputSplit
from dmlc_core_tpu.io.threadediter import ThreadedIter
from dmlc_core_tpu.utils.logging import CHECK, log_warning

__all__ = ["Parser", "ParserImpl", "TextParserBase", "ThreadedParser"]

DEFAULT_PARSE_QUEUE_BYTES = 256 << 20


def _parse_queue_bytes() -> Optional[int]:
    """DMLC_PARSE_QUEUE_BYTES: decoded-bytes bound for the parse prefetch
    queue (<=0 disables the byte bound; item-count capacity still applies)."""
    raw = os.environ.get("DMLC_PARSE_QUEUE_BYTES", "").strip()
    if not raw:
        return DEFAULT_PARSE_QUEUE_BYTES
    try:
        value = int(raw)
    except ValueError:
        log_warning(f"ignoring non-integer DMLC_PARSE_QUEUE_BYTES={raw!r}")
        return DEFAULT_PARSE_QUEUE_BYTES
    return value if value > 0 else None


class Parser:
    """DataIter over RowBlocks (reference Parser<IndexType>, data.h:252-285)."""

    def before_first(self) -> None:
        raise NotImplementedError

    def next(self) -> Optional[RowBlock]:
        """Next batch, or None at end of data."""
        raise NotImplementedError

    def bytes_read(self) -> int:
        raise NotImplementedError

    def __iter__(self):
        while True:
            block = self.next()
            if block is None:
                return
            yield block


class ParserImpl(Parser):
    """Block-vector iteration protocol (reference parser.h:30-66)."""

    def __init__(self):
        self._blocks: List[RowBlock] = []
        self._pos = 0

    def parse_next_blocks(self) -> Optional[List[RowBlockContainer]]:
        """Produce the containers parsed from the next source chunk (or None)."""
        raise NotImplementedError

    def next(self) -> Optional[RowBlock]:
        while self._pos >= len(self._blocks):
            containers = self.parse_next_blocks()
            if containers is None:
                # drop the last chunk's blocks at EOF: with the shm
                # transport each retained block pins a segment lease
                self._blocks, self._pos = [], 0
                return None
            self._blocks = [c.get_block() for c in containers if c.size > 0]
            self._pos = 0
        block = self._blocks[self._pos]
        self._pos += 1
        return block


class TextParserBase(ParserImpl):
    """Chunk -> per-worker newline-realigned sub-ranges -> parallel parse."""

    def __init__(self, source: InputSplit, nthread: int = 2):
        super().__init__()
        self._source = source
        self._bytes_read = 0
        self._nthread = max(1, nthread)
        self._nproc = parse_proc.resolve_nproc()
        self._proc_pool: Optional[parse_proc.ProcParsePool] = None
        self._proc_off = self._nproc < 2
        # acquired last: every statement after this is a plain assignment,
        # so a constructor failure can never orphan the executor
        self._pool = (ThreadPoolExecutor(max_workers=self._nthread,
                                         thread_name_prefix="dmlc-parse")
                      if self._nthread > 1 else None)

    def before_first(self) -> None:
        self._source.before_first()
        self._blocks, self._pos = [], 0

    def bytes_read(self) -> int:
        return self._bytes_read

    def parse_block(self, data: bytes) -> RowBlockContainer:
        """Parse one newline-delimited byte range (per-format)."""
        raise NotImplementedError

    def parse_chunk_native(self, data: bytes) -> Optional[RowBlockContainer]:
        """Whole-chunk parse via the C++ native core (dmlc_core_tpu/native);
        None to fall back to the numpy path.  The native parser threads
        internally (the reference's OpenMP team, text_parser.h:100-115)."""
        return None

    def _proc_spec(self):
        """``(module, class, kwargs)`` rebuilding a source-less, thread-less
        twin of this parser inside each worker process.  Subclasses whose
        constructor takes extra state (CSV args) extend the kwargs."""
        idx = np.dtype(getattr(self, "_index_dtype", np.uint32))
        return (type(self).__module__, type(self).__qualname__,
                {"nthread": 1, "index_dtype": idx.str})

    def _get_proc_pool(self) -> Optional[parse_proc.ProcParsePool]:
        """The lazy process pool, or None (off / native core / failed)."""
        if self._proc_off:
            return None
        if self._proc_pool is not None and not self._proc_pool.alive():
            # the shared pool this handle was built on died (worker kill):
            # drop the handle so a retried epoch self-heals on a fresh pool
            self._proc_pool = None
        if self._proc_pool is None:
            from dmlc_core_tpu import native_bridge

            if native_bridge.available():
                # the native parser threads internally without the GIL;
                # stacking processes on top only costs transport
                self._proc_off = True
                return None
            try:
                self._proc_pool = parse_proc.ProcParsePool(
                    self._proc_spec(), self._nproc)
            except Exception as exc:  # noqa: BLE001 - any bring-up failure
                log_warning("process parse backend unavailable "
                            f"({exc!r}); falling back to threads")
                self._proc_off = True
                return None
        return self._proc_pool

    def parse_next_blocks(self) -> Optional[List[RowBlockContainer]]:
        """One source chunk -> containers, with per-chunk telemetry (span +
        ``dmlc_parser_{rows,bytes}_total``, labeled by parser class)."""
        before = self._bytes_read
        with telemetry.span("parser.parse_chunk",
                            parser=type(self).__name__) as sp:
            out = self._parse_next_blocks_impl()
            if out is not None and telemetry.enabled():
                nrows = sum(c.size for c in out)
                nbytes = self._bytes_read - before
                sp.set(rows=nrows, nbytes=nbytes)
                telemetry.count("dmlc_parser_rows_total", nrows,
                                parser=type(self).__name__)
                telemetry.count("dmlc_parser_bytes_total", nbytes,
                                parser=type(self).__name__)
        return out

    def _parse_next_blocks_impl(self) -> Optional[List[RowBlockContainer]]:
        # zero-copy fast path: a native split hands an (addr, len) view
        # over its resident chunk buffer and the native parser reads it in
        # place — no Python bytes between the two C++ stages.  Only taken
        # when native parsing is certain (available() => every text
        # parser's parse_chunk_native succeeds), because the numpy
        # fallback needs a real bytes object.
        from dmlc_core_tpu import native_bridge

        view_fn = getattr(self._source, "next_chunk_view", None)
        if view_fn is not None and native_bridge.available():
            view = view_fn()
            if view is None:
                return None
            self._bytes_read += view[1]
            native = self.parse_chunk_native(view)
            if native is not None:
                return [native]
            # a parser without a native path: materialize and fall through
            import ctypes

            chunk = ctypes.string_at(*view)
        else:
            chunk = self._source.next_chunk()
            if chunk is None:
                return None
            self._bytes_read += len(chunk)
            native = self.parse_chunk_native(chunk)
            if native is not None:
                return [native]
        pool = self._get_proc_pool()
        ranges = self._split_ranges(chunk, pool.nproc if pool is not None
                                    else self._nthread)
        if pool is not None and len(ranges) > 1:
            return pool.parse_ranges(ranges, parser_name=type(self).__name__)
        if self._pool is None or len(ranges) <= 1:
            return [self.parse_block(r) for r in ranges]
        return list(self._pool.map(self.parse_block, ranges))

    @staticmethod
    def _split_ranges(chunk: bytes, n: int) -> List[bytes]:
        """Cut into ~n ranges ending on newlines (reference FillData +
        BackFindEndLine, text_parser.h:71-118)."""
        total = len(chunk)
        if total == 0:
            return []
        step = (total + n - 1) // n
        ranges: List[bytes] = []
        begin = 0
        while begin < total:
            end = min(begin + step, total)
            if end < total:
                nl = chunk.rfind(b"\n", begin, end)
                nr = chunk.rfind(b"\r", begin, end)
                cut = max(nl, nr)
                if cut < begin:
                    # no newline inside the range: extend to the next one
                    nxt = chunk.find(b"\n", end)
                    cut = nxt if nxt >= 0 else total - 1
                end = cut + 1
            ranges.append(chunk[begin:end])
            begin = end
        return ranges

    def close(self) -> None:
        if self._proc_pool is not None:
            self._proc_pool.close()
            self._proc_pool = None
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        self._source.close()


class _ParseProducer:
    def __init__(self, base: ParserImpl):
        self._base = base

    def before_first(self) -> None:
        self._base.before_first()

    def next(self, reuse):
        block = self._base.next()
        return block  # None ends the epoch


class ThreadedParser(Parser):
    """Prefetch decorator: parsing runs on a producer thread
    (reference ThreadedParser, parser.h:70-126, queue capacity 8).

    The queue is bounded both by item count and by decoded-block bytes
    (``max_bytes``, default from ``DMLC_PARSE_QUEUE_BYTES``): 8 queued
    blocks is ~8x chunk_size x fan-out of decoded arrays, which for wide
    rows can be gigabytes — the byte bound keeps prefetch memory flat
    regardless of row shape."""

    def __init__(self, base: ParserImpl, max_capacity: int = 8,
                 max_bytes: Optional[int] = None):
        self._base = base
        if max_bytes is None:
            max_bytes = _parse_queue_bytes()
        self._iter = ThreadedIter(_ParseProducer(base),
                                  max_capacity=max_capacity, name="parse",
                                  max_bytes=max_bytes,
                                  cost_fn=RowBlock.memory_cost_bytes)

    def before_first(self) -> None:
        self._iter.before_first()

    def next(self) -> Optional[RowBlock]:
        return self._iter.next()

    def bytes_read(self) -> int:
        return self._base.bytes_read()

    def close(self) -> None:
        self._iter.destroy()
        if hasattr(self._base, "close"):
            self._base.close()
