"""Columnar page cache v2: raw little-endian column buffers + mmap replay.

The v1 cache (``RowBlockContainer.save`` framing, reference
row_block.h:181-205) re-deserializes every page each epoch: every
``load`` is read -> frombuffer -> copy.  v2 lays pages out so that a later
epoch is *one mmap and zero copies*:

- **file header** (32 B): magic ``DMLCRBC2``, version, the index dtype the
  cache was built with, reserved;
- **pages**: an 80 B checksummed page header (page magic, a CRC32 covering
  the header's own size/count/max fields *and* the payload, payload size,
  six column element counts, max_field/max_index) followed by the six
  column buffers — offset ``int64``, label/weight/value ``float32``,
  field/index in the header dtype — each padded to 8-byte alignment so
  every ``np.frombuffer`` lands aligned;
- **footer**: a TOC (page count + page byte offsets) and a fixed 24 B tail
  (TOC offset, CRC32 of the TOC, magic ``DMLCRBE2``) written *last* — a
  build that died mid-write has no tail and is rejected as
  :class:`CacheFormatError`, never silently truncated data.

Builds are atomic: :class:`PageCacheWriter` writes to a temp file in the
cache's directory, fsyncs, and ``os.replace``s into place on
:meth:`commit` (plus a directory fsync so the rename itself is durable).

:class:`PageCacheReader` validates magic/version/dtype/TOC and every page
CRC once, then hands out RowBlocks whose arrays are read-only views into
the mapping — the same objects every epoch, which is what makes epoch>=2
zero-copy by construction.
"""

from __future__ import annotations

import hashlib
import mmap
import os
import struct
import tempfile
import threading
import uuid
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Tuple

import numpy as np

from dmlc_core_tpu import fault, telemetry
from dmlc_core_tpu.data.row_block import (COLUMN_ORDER, RowBlock,
                                          RowBlockContainer, align8)
from dmlc_core_tpu.param import get_env

__all__ = ["PageCacheWriter", "PageCacheReader", "CacheFormatError",
           "HEAD_MAGIC", "fetch_remote_cache", "publish_cache",
           "default_local_path"]

HEAD_MAGIC = b"DMLCRBC2"
TAIL_MAGIC = b"DMLCRBE2"
VERSION = 2
_PAGE_MAGIC = 0x32474150  # "PAG2", little-endian

_HEAD = struct.Struct("<8sI4s16x")          # magic, version, dtype str
_PAGE_HEAD = struct.Struct("<IIQ6Q2Q")  # magic, crc, payload, counts[6], maxes
# the page CRC covers the header fields after the CRC itself (payload size,
# column counts, maxes) AND the payload: a corrupted count is as fatal as a
# corrupted byte — it re-slices every column
_PAGE_META = struct.Struct("<Q6Q2Q")
_TAIL = struct.Struct("<QI4x8s")            # toc offset, toc crc, magic

# column layout order shared with the shm transport (row_block.COLUMN_ORDER);
# (real) dtypes resolved per cache index dtype
_COL_ORDER = COLUMN_ORDER
_align8 = align8


class CacheFormatError(RuntimeError):
    """A cache file that cannot be trusted (truncated, corrupt, or built
    with different parameters) — callers rebuild or abort loudly."""


def _dtype_tag(index_dtype: np.dtype) -> bytes:
    tag = np.dtype(index_dtype).newbyteorder("<").str.encode()
    return tag.ljust(4, b"\0")


def _page_dtypes(index_dtype) -> Tuple[np.dtype, ...]:
    """Per-column dtypes in :data:`_COL_ORDER` for one cache index dtype."""
    idx = np.dtype(index_dtype)
    return (np.dtype(np.int64), np.dtype(np.float32), np.dtype(np.float32),
            idx, idx, np.dtype(np.float32))


def _validate_page(view: memoryview, off: int, end: int, ctx: str,
                   index_dtype, exact: bool = False) -> Tuple[Tuple, int]:
    """The ONE page trust check, shared by the local mmap reader and the
    remote fetch: magic, header CRC over the size/count fields AND the
    payload, and counts-vs-payload agreement under the column dtype
    ladder.  ``exact`` additionally requires the page to fill
    ``[off, end)`` exactly (the fetch case: ``end - off`` is the TOC's
    span for this page).  Returns ``(counts, payload_start)``."""
    if off + _PAGE_HEAD.size > end:
        raise CacheFormatError(f"{ctx}: page header truncated at {off}")
    fields = _PAGE_HEAD.unpack_from(view, off)
    magic, crc, payload_bytes = fields[0], fields[1], fields[2]
    counts = fields[3:9]
    if magic != _PAGE_MAGIC:
        raise CacheFormatError(f"{ctx}: bad page magic at {off}")
    start = off + _PAGE_HEAD.size
    if start + payload_bytes > end:
        raise CacheFormatError(f"{ctx}: page payload truncated at {off}")
    if exact and start + payload_bytes != end:
        raise CacheFormatError(
            f"{ctx}: page payload disagrees with its TOC span")
    if zlib.crc32(view[start:start + payload_bytes],
                  zlib.crc32(view[off + 8:start])) != crc:
        raise CacheFormatError(
            f"{ctx}: page checksum mismatch at {off}")
    if sum(_align8(count * dtype.itemsize)
           for count, dtype in zip(counts, _page_dtypes(index_dtype))
           ) != payload_bytes:
        # CRC makes this unreachable short of a collision, but a
        # mis-sliced column must surface as a cache error, never as a
        # frombuffer ValueError outside the rebuild path
        raise CacheFormatError(
            f"{ctx}: column counts disagree with payload size")
    return counts, start


class PageCacheWriter:
    """Atomic v2 cache build: temp file -> fsync -> rename on commit."""

    def __init__(self, path: str, index_dtype=np.uint32):
        self._path = path
        self._index_dtype = np.dtype(index_dtype)
        self._tmp = f"{path}.build-{os.getpid()}.tmp"
        self._page_offsets: List[int] = []
        self._pos = 0
        self.pages_written = 0
        self._fo = open(self._tmp, "wb")
        try:
            self._write(_HEAD.pack(HEAD_MAGIC, VERSION,
                                   _dtype_tag(self._index_dtype)))
        except BaseException:
            # a failed header write (disk full) must not orphan the fd and
            # the temp file: the caller never receives the instance, so
            # abort() is unreachable
            self._fo.close()
            os.unlink(self._tmp)
            raise

    def _write(self, data: bytes) -> None:
        self._fo.write(data)
        self._pos += len(data)

    def _col_arrays(self, block: RowBlock) -> List[np.ndarray]:
        idx = self._index_dtype
        empty = np.empty(0, np.float32)
        return [
            np.ascontiguousarray(block.offset, dtype=np.int64),
            np.ascontiguousarray(block.label, dtype=np.float32),
            (np.ascontiguousarray(block.weight, dtype=np.float32)
             if block.weight is not None else empty),
            (np.ascontiguousarray(block.field, dtype=idx)
             if block.field is not None else np.empty(0, idx)),
            np.ascontiguousarray(block.index, dtype=idx),
            (np.ascontiguousarray(block.value, dtype=np.float32)
             if block.value is not None else empty),
        ]

    def write_page(self, container: RowBlockContainer) -> None:
        """Serialize one page (a RowBlockContainer worth of rows)."""
        self.write_block(container.get_block(),
                         max_field=container.max_field,
                         max_index=container.max_index)

    def write_block(self, block: RowBlock, max_field: int = 0,
                    max_index: int = 0) -> None:
        """Serialize one RowBlock as a page, container-free.

        The page serializer proper — :meth:`write_page` is a thin
        container adapter over it.  Block producers whose pages arrive
        already materialized (e.g. Arrow-mapped blocks from
        ``arrow_ingest.table_to_block``) can call this directly instead
        of re-staging through a RowBlockContainer; maxes default to the
        block's own."""
        cols = self._col_arrays(block)
        payload = bytearray()
        for arr in cols:
            raw = arr.astype(arr.dtype.newbyteorder("<"), copy=False).tobytes()
            payload += raw
            payload += b"\0" * (_align8(len(raw)) - len(raw))
        nnz = block.num_nonzero
        max_field = max_field or (
            int(block.field.max()) if block.field is not None and nnz else 0)
        max_index = max_index or (
            int(block.index.max()) if nnz else 0)
        meta = _PAGE_META.pack(len(payload), *(len(c) for c in cols),
                               max_field, max_index)
        payload = bytes(payload)
        crc = zlib.crc32(payload, zlib.crc32(meta))
        self._page_offsets.append(self._pos)
        self._write(struct.pack("<II", _PAGE_MAGIC, crc) + meta)
        self._write(payload)
        self.pages_written += 1
        telemetry.count("dmlc_cache_pages_written_total")

    def commit(self) -> None:
        """Write TOC + tail, fsync, and atomically move into place."""
        toc = struct.pack("<Q", len(self._page_offsets))
        toc += struct.pack(f"<{len(self._page_offsets)}Q",
                           *self._page_offsets)
        toc_offset = self._pos
        self._write(toc)
        self._write(_TAIL.pack(toc_offset, zlib.crc32(toc), TAIL_MAGIC))
        _commit_durable(self._fo, self._tmp, self._path)

    def abort(self) -> None:
        """Drop the partial build; the real cache path is untouched."""
        try:
            self._fo.close()
        finally:
            if os.path.exists(self._tmp):
                os.unlink(self._tmp)


class PageCacheReader:
    """Validate + mmap a v2 cache; serve zero-copy RowBlocks per page."""

    def __init__(self, path: str, index_dtype=np.uint32):
        self._path = path
        self._index_dtype = np.dtype(index_dtype)
        size = os.path.getsize(path)
        if size < _HEAD.size + _TAIL.size:
            raise CacheFormatError(f"{path}: too small for a v2 cache "
                                   f"({size} bytes)")
        self._fd = open(path, "rb")
        try:
            self._mm = mmap.mmap(self._fd.fileno(), 0,
                                 access=mmap.ACCESS_READ)
        except BaseException:
            # a failed mmap orphans the fd: close() can never reach it
            # because the constructor raise means no one holds the instance
            self._fd.close()
            raise
        try:
            self._pages = self._load_pages(size)
        except Exception:
            self.close()
            raise
        self.blocks: List[RowBlock] = [p for p in self._pages]

    def _load_pages(self, size: int) -> List[RowBlock]:
        mm = self._mm
        magic, version, dtype_tag = _HEAD.unpack(mm[:_HEAD.size])
        if magic != HEAD_MAGIC:
            raise CacheFormatError(f"{self._path}: not a v2 cache")
        if version != VERSION:
            raise CacheFormatError(
                f"{self._path}: cache version {version} != {VERSION}")
        want = _dtype_tag(self._index_dtype)
        if dtype_tag != want:
            have_s = dtype_tag.rstrip(b"\0").decode(errors="replace")
            want_s = want.rstrip(b"\0").decode(errors="replace")
            raise CacheFormatError(
                f"{self._path}: cache index dtype {have_s!r} != "
                f"requested {want_s!r}")
        toc_offset, toc_crc, tail_magic = _TAIL.unpack(mm[size - _TAIL.size:])
        if tail_magic != TAIL_MAGIC:
            raise CacheFormatError(
                f"{self._path}: missing footer (interrupted build or "
                "truncated file)")
        if not _HEAD.size <= toc_offset <= size - _TAIL.size - 8:
            raise CacheFormatError(f"{self._path}: TOC offset out of range")
        toc = bytes(mm[toc_offset:size - _TAIL.size])
        if zlib.crc32(toc) != toc_crc:
            raise CacheFormatError(f"{self._path}: TOC checksum mismatch")
        (npages,) = struct.unpack_from("<Q", toc, 0)
        if len(toc) != 8 + 8 * npages:
            raise CacheFormatError(f"{self._path}: TOC size mismatch")
        offsets = struct.unpack_from(f"<{npages}Q", toc, 8)
        # page CRCs run over a memoryview: slicing the mmap itself would
        # copy every payload byte just to checksum it
        view = memoryview(mm)
        try:
            return [self._load_page(off, toc_offset, view)
                    for off in offsets]
        finally:
            view.release()

    def _wrap(self, off: int, count: int, dtype) -> Optional[np.ndarray]:
        if count == 0:
            return None
        return np.frombuffer(self._mm, dtype=dtype, count=count, offset=off)

    def _load_page(self, off: int, limit: int, view: memoryview) -> RowBlock:
        counts, start = _validate_page(view, off, limit, self._path,
                                       self._index_dtype)
        views = []
        pos = start
        for count, dtype in zip(counts, _page_dtypes(self._index_dtype)):
            nbytes = count * dtype.itemsize
            views.append(self._wrap(pos, count, dtype))
            pos += _align8(nbytes)
        offset, label, weight, field, index, value = views
        return RowBlock(offset, label,
                        (index if index is not None
                         else np.empty(0, self._index_dtype)),
                        value, weight, field)

    def close(self) -> None:
        """Best-effort unmap; live views keep the mapping alive via GC."""
        try:
            self._mm.close()
        except BufferError:
            pass  # exported RowBlock views still hold pointers
        self._fd.close()


def _commit_durable(fo, tmp: str, path: str) -> None:
    """fsync + atomic rename + directory fsync: the shared tail of every
    cache build/fetch — a crash after commit() returns can lose neither the
    bytes nor the rename."""
    fo.flush()
    os.fsync(fo.fileno())
    fo.close()
    os.replace(tmp, path)
    dir_fd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)


# -- remote v2 caches over the ranged-read FS layer ---------------------------
#
# The v2 format was designed for exactly this: the CRC'd footer/TOC written
# last means ONE tail ranged read proves the remote object is a complete,
# trustworthy cache; the checksummed page headers mean every page fetch is
# independently validated before a byte of it is served.  A fetch
# materializes the remote cache into a local "cache of the cache"
# (atomic temp+fsync+rename, the builder's discipline), so this run — and
# every later run on this host — mmaps at PR 4 zero-copy speed while the
# fleet shares one parse.

# footer + TOC in one tail ranged read for caches up to ~32k pages (≈2 TB of
# 64 MB pages); bigger TOCs cost one extra ranged read
_TAIL_PROBE = 256 << 10

_FETCH_SITE = "io.cache.fetch"


class _RemoteLayout:
    """Validated layout of a remote v2 cache: everything the page fetch ring
    needs, learned from the header + one tail ranged read."""

    __slots__ = ("size", "header", "tail", "spans")

    def __init__(self, size: int, header: bytes, tail: bytes,
                 spans: List[Tuple[int, int]]):
        self.size = size          # total object bytes
        self.header = header      # the 32 B file header, validated
        self.tail = tail          # TOC + 24 B tail, CRC-validated
        self.spans = spans        # per-page (offset, nbytes)


def _read_span(stream, offset: int, nbytes: int, ctx: str) -> bytes:
    """Exactly ``nbytes`` at ``offset`` via the seekable stream, with
    ``io.cache.fetch`` fault injection (truncate models a cut object)."""
    if fault.enabled():
        fault.inject(_FETCH_SITE, uri=ctx, offset=offset)
        nbytes_injected = fault.truncate(_FETCH_SITE, nbytes, uri=ctx,
                                         offset=offset)
    else:
        nbytes_injected = nbytes
    stream.seek(offset)
    chunks = []
    remaining = nbytes_injected
    while remaining > 0:
        chunk = stream.read(remaining)
        if not chunk:
            break
        chunks.append(chunk)
        remaining -= len(chunk)
    data = b"".join(chunks)
    if len(data) != nbytes:
        raise CacheFormatError(
            f"{ctx}: short read at {offset} ({len(data)} of {nbytes} bytes)")
    return data


def _check_header(buf: bytes, ctx: str, index_dtype: np.dtype) -> None:
    """Validate the 32 B file header bytes (magic, version, index dtype)."""
    magic, version, dtype_tag = _HEAD.unpack(buf)
    if magic != HEAD_MAGIC:
        raise CacheFormatError(f"{ctx}: not a v2 cache")
    if version != VERSION:
        raise CacheFormatError(f"{ctx}: cache version {version} != {VERSION}")
    want = _dtype_tag(index_dtype)
    if dtype_tag != want:
        have_s = dtype_tag.rstrip(b"\0").decode(errors="replace")
        want_s = want.rstrip(b"\0").decode(errors="replace")
        raise CacheFormatError(
            f"{ctx}: cache index dtype {have_s!r} != requested {want_s!r}")


def _check_page(buf: bytes, ctx: str, index_dtype: np.dtype) -> None:
    """Validate one fetched page (exactly its TOC span) without building
    views — the fetch-side entry to the shared page trust check."""
    view = memoryview(buf)   # slicing bytes would copy the payload
    try:
        _validate_page(view, 0, len(buf), ctx, index_dtype, exact=True)
    finally:
        view.release()


def _open_remote_layout(uri: str, index_dtype: np.dtype) -> _RemoteLayout:
    """Open-by-footer: one tail ranged read (plus the 32 B header) proves the
    remote object is a complete v2 cache and yields the page spans.

    Raises FileNotFoundError when no object is at ``uri`` and
    :class:`CacheFormatError` for anything present but untrustable
    (footer-less/interrupted upload, v1 framing, dtype drift, corrupt TOC).
    """
    from dmlc_core_tpu.io import filesys as fsys

    uri_obj = fsys.URI(uri)
    fs = fsys.get_filesystem(uri_obj)
    info = fs.get_path_info(uri_obj)          # FileNotFoundError on absence
    size = info.size
    if size < _HEAD.size + _TAIL.size + 8:
        raise CacheFormatError(f"{uri}: too small for a v2 cache "
                               f"({size} bytes)")
    stream = fs.open_for_read(uri_obj)
    try:
        header = _read_span(stream, 0, _HEAD.size, uri)
        _check_header(header, uri, index_dtype)
        probe_len = min(size - _HEAD.size, _TAIL_PROBE)
        probe = _read_span(stream, size - probe_len, probe_len, uri)
        toc_offset, toc_crc, tail_magic = _TAIL.unpack(probe[-_TAIL.size:])
        if tail_magic != TAIL_MAGIC:
            raise CacheFormatError(
                f"{uri}: missing footer (interrupted upload or truncated "
                "object)")
        if not _HEAD.size <= toc_offset <= size - _TAIL.size - 8:
            raise CacheFormatError(f"{uri}: TOC offset out of range")
        if toc_offset >= size - probe_len:
            toc = probe[toc_offset - (size - probe_len):-_TAIL.size]
        else:  # TOC bigger than the probe: one extra ranged read
            toc = _read_span(stream, toc_offset,
                             size - _TAIL.size - toc_offset, uri)
        if zlib.crc32(toc) != toc_crc:
            raise CacheFormatError(f"{uri}: TOC checksum mismatch")
        (npages,) = struct.unpack_from("<Q", toc, 0)
        if len(toc) != 8 + 8 * npages:
            raise CacheFormatError(f"{uri}: TOC size mismatch")
        offsets = struct.unpack_from(f"<{npages}Q", toc, 8)
        bounds = list(offsets) + [toc_offset]
        # pages must tile [header, TOC) EXACTLY: the fetch materializes
        # header+pages+tail contiguously with the remote TOC copied
        # verbatim, so any gap (a foreign writer's padding) would shift
        # every local offset and commit a corrupt file
        if bounds[0] != _HEAD.size:
            raise CacheFormatError(
                f"{uri}: pages do not tile the file "
                f"(first page at {bounds[0]}, expected {_HEAD.size})")
        spans = []
        for i in range(npages):
            if not (bounds[i] < bounds[i + 1] <= toc_offset):
                raise CacheFormatError(f"{uri}: page offsets out of order")
            spans.append((bounds[i], bounds[i + 1] - bounds[i]))
        return _RemoteLayout(size, header, toc + _TAIL.pack(
            toc_offset, toc_crc, tail_magic), spans)
    finally:
        stream.close()


def default_local_path(remote_uri: str) -> str:
    """Where a remote cache materializes on this host: keyed by the URI's
    digest under ``DMLC_CACHE_LOCAL_DIR`` so every run (and every process)
    of the same dataset agrees on one local file.

    The default directory is per-user (uid-suffixed, created 0700 by the
    fetch/build path): a shared ``/tmp/dmlc-page-cache`` would break the
    second user's runs on a multi-user host (first-creator owns the dir)
    and let any local user plant a valid-CRC file at another user's
    digest path to be served as training data."""
    getuid = getattr(os, "getuid", None)      # absent on Windows
    suffix = f"-u{getuid()}" if getuid is not None else ""
    base = get_env("DMLC_CACHE_LOCAL_DIR", str,
                   os.path.join(tempfile.gettempdir(),
                                f"dmlc-page-cache{suffix}"))
    digest = hashlib.sha256(remote_uri.encode()).hexdigest()[:24]
    name = os.path.basename(remote_uri.rstrip("/")) or "cache"
    return os.path.join(base, f"{digest}-{name}")


def fetch_remote_cache(uri: str, local_path: str, index_dtype=np.uint32,
                       prefetch: Optional[int] = None) -> int:
    """Fetch + validate a remote v2 cache into ``local_path``; returns the
    bytes fetched.

    A pre-posted ring of ``prefetch`` (default ``DMLC_CACHE_PREFETCH``)
    ranged page fetches keeps the wire busy while earlier pages validate
    and land in the local temp file — the same dispatch-ahead/block-at-
    hand-off shape as the device feed's double buffering.  Every page's CRC
    is checked before its bytes are written; the local file appears only
    via atomic rename after everything validated, so a concurrent fetch of
    the same cache from another process races safely (both rename a fully
    validated file).  Raises FileNotFoundError / CacheFormatError / OSError
    — the caller falls back to stream-parsing.
    """
    index_dtype = np.dtype(index_dtype)
    if prefetch is None:
        prefetch = max(1, get_env("DMLC_CACHE_PREFETCH", int, 4))
    layout = _open_remote_layout(uri, index_dtype)
    from dmlc_core_tpu.io import filesys as fsys

    uri_obj = fsys.URI(uri)
    fs = fsys.get_filesystem(uri_obj)
    local = threading.local()
    streams: List = []   # every worker stream, closed once the pool drains

    def fetch_page(item: Tuple[int, Tuple[int, int]]) -> bytes:
        i, (off, nbytes) = item
        stream = getattr(local, "stream", None)
        if stream is None:
            stream = fs.open_for_read(uri_obj)
            local.stream = stream
            streams.append(stream)
        with telemetry.span("cache.fetch.page", page=i, bytes=nbytes):
            data = _read_span(stream, off, nbytes, uri)
        _check_page(data, f"{uri} page {i}", index_dtype)
        return data

    dirpath = os.path.dirname(os.path.abspath(local_path))
    # 0700 on creation: the default cache dir is per-user private (see
    # default_local_path); no-op for directories that already exist
    os.makedirs(dirpath, mode=0o700, exist_ok=True)
    # unique per CALL, not per process: two loaders in one process (train +
    # eval over the same dataset) fetching concurrently must not share a
    # temp file — a pid-only name would let one thread truncate the
    # other's in-progress bytes, and keep writing into the committed inode
    # after the rename
    tmp = (f"{local_path}.fetch-{os.getpid()}-{threading.get_ident()}-"
           f"{uuid.uuid4().hex[:8]}.tmp")
    fetched = 0
    with telemetry.span("cache.fetch", uri=uri, pages=len(layout.spans)):
        with ThreadPoolExecutor(max_workers=prefetch,
                                thread_name_prefix="cache-fetch") as pool:
            try:
                with open(tmp, "wb") as fo:
                    fo.write(layout.header)
                    pending = []
                    items = list(enumerate(layout.spans))
                    for item in items[:prefetch]:       # pre-post the ring
                        pending.append(pool.submit(fetch_page, item))
                    posted = len(pending)
                    while pending:
                        data = pending.pop(0).result()
                        if posted < len(items):         # keep the ring full
                            pending.append(pool.submit(fetch_page,
                                                       items[posted]))
                            posted += 1
                        fo.write(data)
                        fetched += len(data)
                        telemetry.count(
                            "dmlc_cache_remote_bytes_fetched_total",
                            len(data))
                    fo.write(layout.tail)
                    fetched += len(layout.header) + len(layout.tail)
                    telemetry.count("dmlc_cache_remote_bytes_fetched_total",
                                    len(layout.header) + len(layout.tail))
                    _commit_durable(fo, tmp, local_path)
            except BaseException:
                # don't wait out in-flight page fetches on the error path,
                # and leave no half-fetched file where a later run would
                # find-and-validate it
                pool.shutdown(wait=True, cancel_futures=True)
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise
            finally:
                for stream in streams:
                    try:
                        stream.close()
                    except Exception:
                        pass
    return fetched


def _delete_partial_publish(uri: str) -> None:
    """Best-effort removal of a half-written publish target on a
    write-through filesystem (the stream had no ``abort()``); a leftover
    footer-less object would send every fetcher down the loud
    invalid-classify-and-re-parse path until overwritten."""
    target = uri[7:] if uri.startswith("file://") else uri
    try:
        if "://" not in target:
            os.unlink(target)
            return
        from dmlc_core_tpu.io import filesys as fsys

        uri_obj = fsys.URI(uri)
        delete = getattr(fsys.get_filesystem(uri_obj), "delete", None)
        if delete is not None:
            delete(uri_obj)
    except Exception:
        pass


def publish_cache(local_path: str, uri: str) -> None:
    """Upload a locally built v2 cache so the fleet fetches instead of
    re-parsing: streamed through the URI's write path (multipart upload on
    the object stores), counted as ``dmlc_cache_remote_publishes_total``."""
    from dmlc_core_tpu.io.stream import create_stream

    size = os.path.getsize(local_path)
    with telemetry.span("cache.publish", uri=uri, bytes=size):
        fo = create_stream(uri, "w")
        try:
            with open(local_path, "rb") as fi:
                while True:
                    chunk = fi.read(8 << 20)
                    if not chunk:
                        break
                    fo.write(chunk)
        except BaseException:
            # a failed publish must ABANDON, never commit: close() is the
            # commit point on the buffered object stores
            # (CompleteMultipartUpload / Put Block List), and write-through
            # streams (plain files, hdfs://) have already materialized
            # partial bytes AT the target — either way a footer-less
            # truncated object at the fleet URI would make every worker's
            # fetch classify it invalid, warn, and re-parse until someone
            # overwrites it
            abort = getattr(fo, "abort", None)
            if abort is not None:
                abort()          # S3/Azure: nothing ever lands at the key
            else:
                try:
                    fo.close()
                except Exception:
                    pass
                _delete_partial_publish(uri)
            raise
        fo.close()
    telemetry.count("dmlc_cache_remote_publishes_total")
