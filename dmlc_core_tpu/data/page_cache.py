"""Columnar page cache v2: raw little-endian column buffers + mmap replay.

The v1 cache (``RowBlockContainer.save`` framing, reference
row_block.h:181-205) re-deserializes every page each epoch: every
``load`` is read -> frombuffer -> copy.  v2 lays pages out so that a later
epoch is *one mmap and zero copies*:

- **file header** (32 B): magic ``DMLCRBC2``, version, the index dtype the
  cache was built with, reserved;
- **pages**: an 80 B checksummed page header (page magic, a CRC32 covering
  the header's own size/count/max fields *and* the payload, payload size,
  six column element counts, max_field/max_index) followed by the six
  column buffers — offset ``int64``, label/weight/value ``float32``,
  field/index in the header dtype — each padded to 8-byte alignment so
  every ``np.frombuffer`` lands aligned;
- **footer**: a TOC (page count + page byte offsets) and a fixed 24 B tail
  (TOC offset, CRC32 of the TOC, magic ``DMLCRBE2``) written *last* — a
  build that died mid-write has no tail and is rejected as
  :class:`CacheFormatError`, never silently truncated data.

Builds are atomic: :class:`PageCacheWriter` writes to a temp file in the
cache's directory, fsyncs, and ``os.replace``s into place on
:meth:`commit` (plus a directory fsync so the rename itself is durable).

:class:`PageCacheReader` validates magic/version/dtype/TOC and every page
CRC once, then hands out RowBlocks whose arrays are read-only views into
the mapping — the same objects every epoch, which is what makes epoch>=2
zero-copy by construction.
"""

from __future__ import annotations

import mmap
import os
import struct
import zlib
from typing import List, Optional, Tuple

import numpy as np

from dmlc_core_tpu import telemetry
from dmlc_core_tpu.data.row_block import (COLUMN_ORDER, RowBlock,
                                          RowBlockContainer, align8)

__all__ = ["PageCacheWriter", "PageCacheReader", "CacheFormatError",
           "HEAD_MAGIC"]

HEAD_MAGIC = b"DMLCRBC2"
TAIL_MAGIC = b"DMLCRBE2"
VERSION = 2
_PAGE_MAGIC = 0x32474150  # "PAG2", little-endian

_HEAD = struct.Struct("<8sI4s16x")          # magic, version, dtype str
_PAGE_HEAD = struct.Struct("<IIQ6Q2Q")  # magic, crc, payload, counts[6], maxes
# the page CRC covers the header fields after the CRC itself (payload size,
# column counts, maxes) AND the payload: a corrupted count is as fatal as a
# corrupted byte — it re-slices every column
_PAGE_META = struct.Struct("<Q6Q2Q")
_TAIL = struct.Struct("<QI4x8s")            # toc offset, toc crc, magic

# column layout order shared with the shm transport (row_block.COLUMN_ORDER);
# (real) dtypes resolved per cache index dtype
_COL_ORDER = COLUMN_ORDER
_align8 = align8


class CacheFormatError(RuntimeError):
    """A cache file that cannot be trusted (truncated, corrupt, or built
    with different parameters) — callers rebuild or abort loudly."""


def _dtype_tag(index_dtype: np.dtype) -> bytes:
    tag = np.dtype(index_dtype).newbyteorder("<").str.encode()
    return tag.ljust(4, b"\0")


class PageCacheWriter:
    """Atomic v2 cache build: temp file -> fsync -> rename on commit."""

    def __init__(self, path: str, index_dtype=np.uint32):
        self._path = path
        self._index_dtype = np.dtype(index_dtype)
        self._tmp = f"{path}.build-{os.getpid()}.tmp"
        self._page_offsets: List[int] = []
        self._pos = 0
        self.pages_written = 0
        self._fo = open(self._tmp, "wb")
        try:
            self._write(_HEAD.pack(HEAD_MAGIC, VERSION,
                                   _dtype_tag(self._index_dtype)))
        except BaseException:
            # a failed header write (disk full) must not orphan the fd and
            # the temp file: the caller never receives the instance, so
            # abort() is unreachable
            self._fo.close()
            os.unlink(self._tmp)
            raise

    def _write(self, data: bytes) -> None:
        self._fo.write(data)
        self._pos += len(data)

    def _col_arrays(self, block: RowBlock) -> List[np.ndarray]:
        idx = self._index_dtype
        empty = np.empty(0, np.float32)
        return [
            np.ascontiguousarray(block.offset, dtype=np.int64),
            np.ascontiguousarray(block.label, dtype=np.float32),
            (np.ascontiguousarray(block.weight, dtype=np.float32)
             if block.weight is not None else empty),
            (np.ascontiguousarray(block.field, dtype=idx)
             if block.field is not None else np.empty(0, idx)),
            np.ascontiguousarray(block.index, dtype=idx),
            (np.ascontiguousarray(block.value, dtype=np.float32)
             if block.value is not None else empty),
        ]

    def write_page(self, container: RowBlockContainer) -> None:
        """Serialize one page (a RowBlockContainer worth of rows)."""
        block = container.get_block()
        cols = self._col_arrays(block)
        payload = bytearray()
        for arr in cols:
            raw = arr.astype(arr.dtype.newbyteorder("<"), copy=False).tobytes()
            payload += raw
            payload += b"\0" * (_align8(len(raw)) - len(raw))
        nnz = block.num_nonzero
        max_field = container.max_field or (
            int(block.field.max()) if block.field is not None and nnz else 0)
        max_index = container.max_index or (
            int(block.index.max()) if nnz else 0)
        meta = _PAGE_META.pack(len(payload), *(len(c) for c in cols),
                               max_field, max_index)
        payload = bytes(payload)
        crc = zlib.crc32(payload, zlib.crc32(meta))
        self._page_offsets.append(self._pos)
        self._write(struct.pack("<II", _PAGE_MAGIC, crc) + meta)
        self._write(payload)
        self.pages_written += 1
        telemetry.count("dmlc_cache_pages_written_total")

    def commit(self) -> None:
        """Write TOC + tail, fsync, and atomically move into place."""
        toc = struct.pack("<Q", len(self._page_offsets))
        toc += struct.pack(f"<{len(self._page_offsets)}Q",
                           *self._page_offsets)
        toc_offset = self._pos
        self._write(toc)
        self._write(_TAIL.pack(toc_offset, zlib.crc32(toc), TAIL_MAGIC))
        self._fo.flush()
        os.fsync(self._fo.fileno())
        self._fo.close()
        os.replace(self._tmp, self._path)
        # the rename must survive a crash too, not just the data
        dir_fd = os.open(os.path.dirname(os.path.abspath(self._path)),
                         os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)

    def abort(self) -> None:
        """Drop the partial build; the real cache path is untouched."""
        try:
            self._fo.close()
        finally:
            if os.path.exists(self._tmp):
                os.unlink(self._tmp)


class PageCacheReader:
    """Validate + mmap a v2 cache; serve zero-copy RowBlocks per page."""

    def __init__(self, path: str, index_dtype=np.uint32):
        self._path = path
        self._index_dtype = np.dtype(index_dtype)
        size = os.path.getsize(path)
        if size < _HEAD.size + _TAIL.size:
            raise CacheFormatError(f"{path}: too small for a v2 cache "
                                   f"({size} bytes)")
        self._fd = open(path, "rb")
        try:
            self._mm = mmap.mmap(self._fd.fileno(), 0,
                                 access=mmap.ACCESS_READ)
        except BaseException:
            # a failed mmap orphans the fd: close() can never reach it
            # because the constructor raise means no one holds the instance
            self._fd.close()
            raise
        try:
            self._pages = self._load_pages(size)
        except Exception:
            self.close()
            raise
        self.blocks: List[RowBlock] = [p for p in self._pages]

    def _load_pages(self, size: int) -> List[RowBlock]:
        mm = self._mm
        magic, version, dtype_tag = _HEAD.unpack(mm[:_HEAD.size])
        if magic != HEAD_MAGIC:
            raise CacheFormatError(f"{self._path}: not a v2 cache")
        if version != VERSION:
            raise CacheFormatError(
                f"{self._path}: cache version {version} != {VERSION}")
        want = _dtype_tag(self._index_dtype)
        if dtype_tag != want:
            have_s = dtype_tag.rstrip(b"\0").decode(errors="replace")
            want_s = want.rstrip(b"\0").decode(errors="replace")
            raise CacheFormatError(
                f"{self._path}: cache index dtype {have_s!r} != "
                f"requested {want_s!r}")
        toc_offset, toc_crc, tail_magic = _TAIL.unpack(mm[size - _TAIL.size:])
        if tail_magic != TAIL_MAGIC:
            raise CacheFormatError(
                f"{self._path}: missing footer (interrupted build or "
                "truncated file)")
        if not _HEAD.size <= toc_offset <= size - _TAIL.size - 8:
            raise CacheFormatError(f"{self._path}: TOC offset out of range")
        toc = bytes(mm[toc_offset:size - _TAIL.size])
        if zlib.crc32(toc) != toc_crc:
            raise CacheFormatError(f"{self._path}: TOC checksum mismatch")
        (npages,) = struct.unpack_from("<Q", toc, 0)
        if len(toc) != 8 + 8 * npages:
            raise CacheFormatError(f"{self._path}: TOC size mismatch")
        offsets = struct.unpack_from(f"<{npages}Q", toc, 8)
        return [self._load_page(off, toc_offset) for off in offsets]

    def _wrap(self, off: int, count: int, dtype) -> Optional[np.ndarray]:
        if count == 0:
            return None
        return np.frombuffer(self._mm, dtype=dtype, count=count, offset=off)

    def _load_page(self, off: int, limit: int) -> RowBlock:
        mm = self._mm
        if off + _PAGE_HEAD.size > limit:
            raise CacheFormatError(f"{self._path}: page header out of range")
        fields = _PAGE_HEAD.unpack(mm[off:off + _PAGE_HEAD.size])
        magic, crc, payload_bytes = fields[0], fields[1], fields[2]
        counts = fields[3:9]
        if magic != _PAGE_MAGIC:
            raise CacheFormatError(f"{self._path}: bad page magic at {off}")
        start = off + _PAGE_HEAD.size
        if start + payload_bytes > limit:
            raise CacheFormatError(f"{self._path}: page payload truncated")
        meta = mm[off + 8:off + _PAGE_HEAD.size]
        if zlib.crc32(mm[start:start + payload_bytes],
                      zlib.crc32(meta)) != crc:
            raise CacheFormatError(
                f"{self._path}: page checksum mismatch at {off}")
        idx = self._index_dtype
        dtypes = (np.dtype(np.int64), np.dtype(np.float32),
                  np.dtype(np.float32), idx, idx, np.dtype(np.float32))
        if sum(_align8(count * dtype.itemsize)
               for count, dtype in zip(counts, dtypes)) != payload_bytes:
            # CRC makes this unreachable short of a collision, but a
            # mis-sliced column must surface as a cache error, never as a
            # frombuffer ValueError outside the rebuild path
            raise CacheFormatError(
                f"{self._path}: column counts disagree with payload size")
        views = []
        pos = start
        for count, dtype in zip(counts, dtypes):
            nbytes = count * dtype.itemsize
            views.append(self._wrap(pos, count, dtype))
            pos += _align8(nbytes)
        offset, label, weight, field, index, value = views
        return RowBlock(offset, label,
                        index if index is not None else np.empty(0, idx),
                        value, weight, field)

    def close(self) -> None:
        """Best-effort unmap; live views keep the mapping alive via GC."""
        try:
            self._mm.close()
        except BufferError:
            pass  # exported RowBlock views still hold pointers
        self._fd.close()
