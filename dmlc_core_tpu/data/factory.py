"""Parser/iterator factories with format auto-detection from URI args.

Capability parity with the reference's src/data.cc:21-159: registry-driven
parser construction (DMLC_REGISTER_DATA_PARSER, data.h:330-333), ``format=``
auto-detection from the URI query string (data.cc:70-76, default libsvm), and
the RowBlockIter factory choosing in-memory vs disk-cached iteration by the
presence of a ``#cachefile`` (data.cc:87-107).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from dmlc_core_tpu.data.csv_parser import CSVParser
from dmlc_core_tpu.data.iterators import BasicRowIter, DiskRowIter, RowBlockIter
from dmlc_core_tpu.data.libfm_parser import LibFMParser
from dmlc_core_tpu.data.libsvm_parser import LibSVMParser
from dmlc_core_tpu.data.parser import Parser, ThreadedParser
from dmlc_core_tpu.io.input_split import create_input_split
from dmlc_core_tpu.io.uri_spec import URISpec
from dmlc_core_tpu.registry import Registry

__all__ = ["create_parser", "create_row_block_iter", "parser_registry"]

parser_registry = Registry.get("data_parser")


@parser_registry.register("libsvm", description="label[:weight] idx[:val]... lines")
def _make_libsvm(source, args, nthread, index_dtype):
    return LibSVMParser(source, nthread=nthread, index_dtype=index_dtype)


@parser_registry.register("libfm", description="label field:idx:val... lines")
def _make_libfm(source, args, nthread, index_dtype):
    return LibFMParser(source, nthread=nthread, index_dtype=index_dtype)


@parser_registry.register("csv", description="dense csv rows")
def _make_csv(source, args, nthread, index_dtype):
    return CSVParser(source, args=args, nthread=nthread, index_dtype=index_dtype)


@parser_registry.register("parquet",
                          description="columnar Parquet row groups, "
                                      "zero-copy Arrow buffer -> RowBlock")
def _make_parquet(uri, args, part_index, num_parts, nthread, index_dtype):
    # lazy import: pyarrow is optional and its absence must only surface
    # when a columnar source is actually requested (the HDFS gating pattern)
    from dmlc_core_tpu.data.arrow_ingest import ParquetParser

    return ParquetParser(uri, args=args, part_index=part_index,
                         num_parts=num_parts, index_dtype=index_dtype)


@parser_registry.register("arrow", aliases=["feather", "ipc"],
                          description="Arrow IPC record batches, mmap'd "
                                      "zero-copy views -> RowBlock")
def _make_arrow_ipc(uri, args, part_index, num_parts, nthread, index_dtype):
    from dmlc_core_tpu.data.arrow_ingest import ArrowIPCParser

    return ArrowIPCParser(uri, args=args, part_index=part_index,
                          num_parts=num_parts, index_dtype=index_dtype)


# columnar formats consume the URI itself (footer + unit ranged reads)
# instead of a newline-oriented InputSplit; sharding is by row group /
# record batch
_make_parquet.takes_uri = True
_make_arrow_ipc.takes_uri = True

# extension -> format when neither type= nor ?format= names one
_COLUMNAR_EXTENSIONS = {".parquet": "parquet", ".arrow": "arrow",
                        ".feather": "arrow", ".ipc": "arrow"}


def create_parser(
    uri: str,
    part_index: int = 0,
    num_parts: int = 1,
    type: str = "auto",
    nthread: int = 2,
    index_dtype=np.uint32,
    threaded: bool = True,
) -> Parser:
    """Create a parser (reference Parser<IndexType>::Create, src/data.cc:132-138).

    ``type="auto"`` reads ``?format=`` from the URI; a bare
    ``.parquet``/``.arrow``/``.feather`` path selects the columnar front
    door, anything else defaults to libsvm (reference data.cc:70-76).  The
    returned parser is wrapped in a :class:`ThreadedParser` prefetcher
    unless ``threaded=False``.
    """
    spec = URISpec(uri, part_index, num_parts)
    ptype = type
    if ptype == "auto":
        ext = "." + spec.uri.rsplit(".", 1)[-1] if "." in spec.uri else ""
        default = _COLUMNAR_EXTENSIONS.get(ext, "libsvm")
        ptype = spec.args.get("format", default)
    entry = parser_registry[ptype]
    if getattr(entry.body, "takes_uri", False):
        parser = entry(spec.uri, spec.args, part_index, num_parts, nthread,
                       np.dtype(index_dtype))
    else:
        split_uri = spec.uri + (f"#{spec.cache_file}" if spec.cache_file
                                else "")
        source = create_input_split(split_uri, part_index, num_parts, "text")
        parser = entry(source, spec.args, nthread, np.dtype(index_dtype))
    if threaded:
        return ThreadedParser(parser)
    return parser


def create_row_block_iter(
    uri: str,
    part_index: int = 0,
    num_parts: int = 1,
    type: str = "auto",
    nthread: int = 2,
    index_dtype=np.uint32,
) -> RowBlockIter:
    """Create a RowBlockIter (reference RowBlockIter::Create, src/data.cc:87-129):
    ``uri#cachefile`` gives a :class:`DiskRowIter`, otherwise everything is
    loaded in memory (:class:`BasicRowIter`)."""
    spec = URISpec(uri, part_index, num_parts)
    parser_uri = spec.uri + ("?" + "&".join(f"{k}={v}" for k, v in spec.args.items())
                             if spec.args else "")
    if spec.cache_file:
        # lazily: a warm cache (local materialization or a fleet-shared
        # remote fetch) serves without ever constructing the parser or its
        # input split — no stream opens, no remote stat/list traffic
        return DiskRowIter(
            lambda: create_parser(parser_uri, part_index, num_parts, type,
                                  nthread, index_dtype),
            spec.cache_file, index_dtype=index_dtype)
    parser = create_parser(parser_uri, part_index, num_parts, type, nthread,
                           index_dtype)
    return BasicRowIter(parser, index_dtype=index_dtype)
