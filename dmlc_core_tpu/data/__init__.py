"""ML data layer: CSR RowBlock batches, text/binary parsers, row iterators.

Reference: include/dmlc/data.h, src/data/ (the sparse-batch data model feeding
XGBoost/MXNet).  TPU-first recast: RowBlocks are numpy structure-of-arrays on
the host; :mod:`dmlc_core_tpu.bridge` turns them into mesh-placed jax.Arrays.
"""

from dmlc_core_tpu.data.row_block import Row, RowBlock, RowBlockContainer  # noqa: F401
from dmlc_core_tpu.data.parser import Parser, ParserImpl, ThreadedParser  # noqa: F401
from dmlc_core_tpu.data.libsvm_parser import LibSVMParser  # noqa: F401
from dmlc_core_tpu.data.libfm_parser import LibFMParser  # noqa: F401
from dmlc_core_tpu.data.csv_parser import CSVParser, CSVParserParam  # noqa: F401
from dmlc_core_tpu.data.iterators import BasicRowIter, DiskRowIter  # noqa: F401
from dmlc_core_tpu.data.factory import create_parser, create_row_block_iter  # noqa: F401
