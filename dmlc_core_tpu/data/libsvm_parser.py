"""LibSVM text parser: ``label[:weight] idx[:val] idx[:val] ...`` per line.

Capability parity with the reference (src/data/libsvm_parser.h:22-90):
- label token may carry a weight after ``:``;
- feature tokens are ``index[:value]``; a bare index means value 1.0 (the
  value vector stays empty when *no* token has a value);
- empty lines are skipped.

Vectorized: whole-chunk byte-array tokenization + one colon-split gather +
bulk ``astype`` per chunk sub-range (:mod:`dmlc_core_tpu.data.text_np`).
``parse_block`` is self-contained (no source, no pools), which is what lets
the ``DMLC_PARSE_PROC`` process backend run it inside worker processes and
ship the columns back through shared memory (:mod:`..data.parse_proc`).
"""

from __future__ import annotations

import numpy as np

from dmlc_core_tpu.data.parser import TextParserBase
from dmlc_core_tpu.data.row_block import RowBlock, RowBlockContainer
from dmlc_core_tpu.data import text_np
from dmlc_core_tpu.utils.logging import CHECK

__all__ = ["LibSVMParser"]


class LibSVMParser(TextParserBase):
    def __init__(self, source, nthread: int = 2, index_dtype=np.uint32):
        super().__init__(source, nthread)
        self._index_dtype = np.dtype(index_dtype)

    def parse_chunk_native(self, data: bytes):
        from dmlc_core_tpu import native_bridge

        if not native_bridge.available():
            return None
        offset, label, weight, index, value = native_bridge.parse_libsvm(
            data, nthread=max(self._nthread, 2))
        out = RowBlockContainer(self._index_dtype)
        if len(label):
            out.push_block(RowBlock(offset, label,
                                    index.astype(self._index_dtype, copy=False),
                                    value, weight))
            if index.size:
                out.max_index = int(index.max())
        return out

    def parse_block(self, data: bytes) -> RowBlockContainer:
        out = RowBlockContainer(self._index_dtype)
        tokens, counts = text_np.tokenize_ws(data)
        if counts.size == 0:
            return out
        starts = np.cumsum(counts) - counts           # first-token offset per line
        head, has_colon, tail = text_np.split_tokens_at_colon(tokens)

        labels = text_np.parse_floats(head[starts], "label")
        head_colon = has_colon[starts]
        weight = None
        if head_colon.any():
            weight = np.ones(len(labels), dtype=np.float32)
            weight[head_colon] = text_np.parse_floats(
                tail[starts[head_colon]], "weight")

        feat_mask = np.ones(len(tokens), dtype=bool)
        feat_mask[starts] = False
        index = text_np.parse_ints(head[feat_mask], self._index_dtype,
                                   "feature index")
        feat_colon = has_colon[feat_mask]
        if feat_colon.all():
            value = text_np.parse_floats(tail[feat_mask], "feature value")
        elif not feat_colon.any():
            value = None                               # implicit 1.0 values
        else:
            value = np.ones(len(index), dtype=np.float32)
            sel = np.nonzero(feat_mask)[0][feat_colon]
            value[feat_colon] = text_np.parse_floats(tail[sel], "feature value")

        nnz = counts - 1
        offset = np.zeros(len(counts) + 1, dtype=np.int64)
        np.cumsum(nnz, out=offset[1:])
        out.push_block(RowBlock(offset, labels, index, value, weight))
        if index.size:
            out.max_index = int(index.max())
        return out
