"""LibFM text parser: ``label field:idx:val ...`` per line.

Capability parity with the reference (src/data/libfm_parser.h): feature tokens
are ``field:index:value`` triples (ParseTriple, strtonum.h:265+); the label
token may carry a ``:weight``.

Vectorized on the shared byte-level tokenizer (two chained colon-split
gathers resolve the triples); ``parse_block`` is self-contained, so the
``DMLC_PARSE_PROC`` process backend can run it in worker processes with
shared-memory column transport (:mod:`..data.parse_proc`).
"""

from __future__ import annotations

import numpy as np

from dmlc_core_tpu.data.parser import TextParserBase
from dmlc_core_tpu.data.row_block import RowBlock, RowBlockContainer
from dmlc_core_tpu.data import text_np
from dmlc_core_tpu.utils.logging import CHECK

__all__ = ["LibFMParser"]


class LibFMParser(TextParserBase):
    def __init__(self, source, nthread: int = 2, index_dtype=np.uint32):
        super().__init__(source, nthread)
        self._index_dtype = np.dtype(index_dtype)

    def parse_chunk_native(self, data: bytes):
        from dmlc_core_tpu import native_bridge

        if not native_bridge.available():
            return None
        offset, label, weight, index, field, value = native_bridge.parse_libfm(
            data, nthread=max(self._nthread, 2))
        out = RowBlockContainer(self._index_dtype)
        if len(label):
            out.push_block(RowBlock(offset, label,
                                    index.astype(self._index_dtype, copy=False),
                                    value, weight,
                                    field.astype(self._index_dtype, copy=False)))
            if index.size:
                out.max_index = int(index.max())
            if field.size:
                out.max_field = int(field.max())
        return out

    def parse_block(self, data: bytes) -> RowBlockContainer:
        out = RowBlockContainer(self._index_dtype)
        tokens, counts = text_np.tokenize_ws(data)
        if counts.size == 0:
            return out
        starts = np.cumsum(counts) - counts
        head, has_colon, tail = text_np.split_tokens_at_colon(tokens)

        labels = text_np.parse_floats(head[starts], "label")
        head_colon = has_colon[starts]
        weight = None
        if head_colon.any():
            weight = np.ones(len(labels), dtype=np.float32)
            weight[head_colon] = text_np.parse_floats(
                tail[starts[head_colon]], "weight")

        feat_mask = np.ones(len(tokens), dtype=bool)
        feat_mask[starts] = False
        CHECK(bool(has_colon[feat_mask].all()),
              "libfm features must be field:index:value triples")
        field = text_np.parse_ints(head[feat_mask], self._index_dtype, "field id")
        rest = tail[feat_mask]
        mid, mid_colon, val_tok = text_np.split_tokens_at_colon(rest)
        CHECK(bool(mid_colon.all()) or mid.size == 0,
              "libfm features must be field:index:value triples")
        index = text_np.parse_ints(mid, self._index_dtype, "feature index")
        value = text_np.parse_floats(val_tok, "feature value")

        nnz = counts - 1
        offset = np.zeros(len(counts) + 1, dtype=np.int64)
        np.cumsum(nnz, out=offset[1:])
        out.push_block(RowBlock(offset, labels, index, value, weight, field))
        if index.size:
            out.max_index = int(index.max())
        if field.size:
            out.max_field = int(field.max())
        return out
