"""Row-block iterators: in-memory and disk-cached.

Capability parity with the reference's ``BasicRowIter``
(src/data/basic_row_iter.h:23-82, full in-memory load with MB/s progress logs)
and ``DiskRowIter`` (src/data/disk_row_iter.h:28-139, 64MB-page disk cache
built on first pass, replayed on later epochs).

Local caches are built in the **columnar v2 format**
(:mod:`dmlc_core_tpu.data.page_cache`): atomic temp+fsync+rename build,
checksummed pages, and mmap'd zero-copy replay — epoch >= 2 serves the same
read-only RowBlock views every time instead of re-deserializing.  A legacy
v1 cache (``RowBlockContainer`` framing) still loads through the stream
path, and remote (URI) cache files stay on the v1 stream format and are
rebuilt every run, since rename-atomicity, mmap, and footer validation
are local-filesystem concepts.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from dmlc_core_tpu import telemetry
from dmlc_core_tpu.data import page_cache
from dmlc_core_tpu.data.page_cache import CacheFormatError
from dmlc_core_tpu.data.parser import Parser
from dmlc_core_tpu.data.row_block import RowBlock, RowBlockContainer
from dmlc_core_tpu.io.stream import create_stream, create_stream_for_read
from dmlc_core_tpu.io.threadediter import ThreadedIter
from dmlc_core_tpu.utils.logging import CHECK, log_info, log_warning
from dmlc_core_tpu.utils.timer import get_time

__all__ = ["RowBlockIter", "BasicRowIter", "DiskRowIter"]


class RowBlockIter:
    """Iterator over RowBlocks (reference RowBlockIter, data.h:221-247)."""

    def before_first(self) -> None:
        raise NotImplementedError

    def next(self) -> Optional[RowBlock]:
        raise NotImplementedError

    def __iter__(self):
        while True:
            block = self.next()
            if block is None:
                return
            yield block


class BasicRowIter(RowBlockIter):
    """Load everything into memory up front (reference basic_row_iter.h:23-82)."""

    def __init__(self, parser: Parser, index_dtype=np.uint32):
        start = get_time()
        container = RowBlockContainer(index_dtype)
        bytes_logged = 0
        for block in parser:
            container.push_block(block)
            nread = parser.bytes_read()
            if nread >= bytes_logged + (10 << 20):  # every 10MB, ref :70-75
                elapsed = max(get_time() - start, 1e-9)
                log_info(f"{nread >> 20} MB read, "
                         f"{nread / (1 << 20) / elapsed:.2f} MB/sec")
                bytes_logged = nread
        self._block = container.get_block()
        elapsed = max(get_time() - start, 1e-9)
        log_info(f"finished reading {parser.bytes_read() / (1 << 20):.2f} MB, "
                 f"{parser.bytes_read() / (1 << 20) / elapsed:.2f} MB/sec")
        if hasattr(parser, "close"):
            parser.close()
        self._done = False

    def before_first(self) -> None:
        self._done = False

    def next(self) -> Optional[RowBlock]:
        if self._done:
            return None
        self._done = True
        return self._block

    def get_block(self) -> RowBlock:
        return self._block


class DiskRowIter(RowBlockIter):
    """Build a paged disk cache on the first pass, then iterate the cache
    (reference disk_row_iter.h:28-139).

    Local cache paths use the v2 columnar format: the build goes to a temp
    file and is renamed into place only after the checksummed footer is
    durable (a crash mid-build can never leave a trusted-but-truncated
    cache), and replay mmaps the file once — every epoch serves the *same*
    zero-copy RowBlock views.  An existing cache that fails validation
    (truncated tail, bad page CRC, different index dtype) is rebuilt with a
    loud warning.  v1 caches and remote cache URIs use the legacy
    serialize-per-epoch stream path."""

    PAGE_BYTES = 64 << 20  # reference kPageSize (disk_row_iter.h:32)

    def __init__(self, parser: Parser, cache_file: str, reuse_cache: bool = True,
                 index_dtype=np.uint32):
        self._cache_file = cache_file
        self._index_dtype = np.dtype(index_dtype)
        self._local = "://" not in cache_file
        self._reader: Optional[page_cache.PageCacheReader] = None
        self._iter: Optional[ThreadedIter] = None
        if reuse_cache and self._exists():
            try:
                self._open_cache()
            except CacheFormatError as exc:
                log_warning(f"cache {cache_file} failed validation ({exc}); "
                            "rebuilding")
                telemetry.count("dmlc_cache_rebuilds_total")
                self._build_cache(parser)
                self._open_cache()
        else:
            self._build_cache(parser)
            self._open_cache()
        self.before_first()

    def _exists(self) -> bool:
        # local paths only: a remote v1 stream has no footer or checksum
        # to validate, so a crash mid-build is indistinguishable from a
        # complete cache — remote URIs rebuild every run (the behavior
        # this class always had; os.path.exists is false for them)
        return self._local and os.path.exists(self._cache_file)

    # -- build ----------------------------------------------------------------
    def _build_cache(self, parser: Parser) -> None:
        start = get_time()
        if self._local:
            writer = page_cache.PageCacheWriter(self._cache_file,
                                                self._index_dtype)
        else:
            writer = None
            fo = create_stream(self._cache_file, "w")
        page = RowBlockContainer(self._index_dtype)
        page_bytes = 0
        total = 0
        try:
            for block in parser:
                page.push_block(block)
                page_bytes += block.memory_cost_bytes()
                if page_bytes >= self.PAGE_BYTES:
                    if writer is not None:
                        writer.write_page(page)
                    else:
                        page.save(fo)
                    total += page_bytes
                    elapsed = max(get_time() - start, 1e-9)
                    log_info(f"wrote {total >> 20} MB cache, "
                             f"{total / (1 << 20) / elapsed:.2f} MB/sec")
                    page = RowBlockContainer(self._index_dtype)
                    page_bytes = 0
            if page.size:
                if writer is not None:
                    writer.write_page(page)
                else:
                    page.save(fo)
            if writer is not None:
                writer.commit()
            else:
                fo.close()
        except BaseException:
            # never leave a half-written file where a trusted cache goes
            if writer is not None:
                writer.abort()
            else:
                fo.close()
            raise
        finally:
            if hasattr(parser, "close"):
                parser.close()

    # -- open -----------------------------------------------------------------
    def _open_cache(self) -> None:
        """Attach to the cache: v2 mmap when the header says so, else the
        legacy v1 stream path.  Raises CacheFormatError on an untrustable
        v2 file (missing footer, checksum mismatch, dtype drift)."""
        self._reader = None
        if self._local:
            with open(self._cache_file, "rb") as probe:
                head = probe.read(len(page_cache.HEAD_MAGIC))
            if head == page_cache.HEAD_MAGIC:
                self._reader = page_cache.PageCacheReader(self._cache_file,
                                                          self._index_dtype)
                telemetry.count("dmlc_cache_open_total", format="v2-mmap")
                return
        telemetry.count("dmlc_cache_open_total", format="v1")

    def _make_producer(self):
        parent = self
        if self._reader is not None:
            class _PageProducer:
                """Replays the reader's mmap-backed blocks: the same array
                objects every epoch — zero per-epoch copies."""

                def __init__(self) -> None:
                    self._pos = 0

                def before_first(self) -> None:
                    self._pos = 0

                def next(self, reuse):
                    blocks = parent._reader.blocks
                    if self._pos >= len(blocks):
                        return None
                    block = blocks[self._pos]
                    self._pos += 1
                    telemetry.count("dmlc_cache_page_reads_total",
                                    source="mmap")
                    return block

            return _PageProducer()

        class _Producer:
            def __init__(self) -> None:
                self._fi = create_stream_for_read(parent._cache_file)

            def before_first(self) -> None:
                self._fi.seek(0)

            def next(self, reuse):
                container = RowBlockContainer(parent._index_dtype)
                if not container.load(self._fi):
                    return None
                telemetry.count("dmlc_cache_page_reads_total",
                                source="stream")
                return container.get_block()

        return _Producer()

    def cache_blocks(self) -> Optional[list]:
        """The mmap'd zero-copy RowBlock views backing a v2 cache (the
        same objects every epoch), or None on the v1/stream path.

        This is the streaming-binner feed (``bridge.binning.fit_binner``):
        quantile edges are computed directly over the mapped views without
        a second parse or any row copy."""
        return None if self._reader is None else self._reader.blocks

    def before_first(self) -> None:
        if self._iter is None:
            self._iter = ThreadedIter(self._make_producer(), max_capacity=2,
                                      name="row_iter")
        else:
            self._iter.before_first()

    def next(self) -> Optional[RowBlock]:
        return self._iter.next()

    def close(self) -> None:
        if self._iter is not None:
            self._iter.destroy()
        if self._reader is not None:
            self._reader.close()
