"""Row-block iterators: in-memory and disk-cached.

Capability parity with the reference's ``BasicRowIter``
(src/data/basic_row_iter.h:23-82, full in-memory load with MB/s progress logs)
and ``DiskRowIter`` (src/data/disk_row_iter.h:28-139, 64MB-page disk cache
built on first pass, replayed on later epochs).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from dmlc_core_tpu.data.parser import Parser
from dmlc_core_tpu.data.row_block import RowBlock, RowBlockContainer
from dmlc_core_tpu.io.stream import create_stream, create_stream_for_read
from dmlc_core_tpu.io.threadediter import ThreadedIter
from dmlc_core_tpu.utils.logging import CHECK, log_info
from dmlc_core_tpu.utils.timer import get_time

__all__ = ["RowBlockIter", "BasicRowIter", "DiskRowIter"]


class RowBlockIter:
    """Iterator over RowBlocks (reference RowBlockIter, data.h:221-247)."""

    def before_first(self) -> None:
        raise NotImplementedError

    def next(self) -> Optional[RowBlock]:
        raise NotImplementedError

    def __iter__(self):
        while True:
            block = self.next()
            if block is None:
                return
            yield block


class BasicRowIter(RowBlockIter):
    """Load everything into memory up front (reference basic_row_iter.h:23-82)."""

    def __init__(self, parser: Parser, index_dtype=np.uint32):
        start = get_time()
        container = RowBlockContainer(index_dtype)
        bytes_logged = 0
        for block in parser:
            container.push_block(block)
            nread = parser.bytes_read()
            if nread >= bytes_logged + (10 << 20):  # every 10MB, ref :70-75
                elapsed = max(get_time() - start, 1e-9)
                log_info(f"{nread >> 20} MB read, "
                         f"{nread / (1 << 20) / elapsed:.2f} MB/sec")
                bytes_logged = nread
        self._block = container.get_block()
        elapsed = max(get_time() - start, 1e-9)
        log_info(f"finished reading {parser.bytes_read() / (1 << 20):.2f} MB, "
                 f"{parser.bytes_read() / (1 << 20) / elapsed:.2f} MB/sec")
        if hasattr(parser, "close"):
            parser.close()
        self._done = False

    def before_first(self) -> None:
        self._done = False

    def next(self) -> Optional[RowBlock]:
        if self._done:
            return None
        self._done = True
        return self._block

    def get_block(self) -> RowBlock:
        return self._block


class DiskRowIter(RowBlockIter):
    """Build a paged disk cache of serialized RowBlockContainers on the first
    pass, then iterate the cache (reference disk_row_iter.h:28-139)."""

    PAGE_BYTES = 64 << 20  # reference kPageSize (disk_row_iter.h:32)

    def __init__(self, parser: Parser, cache_file: str, reuse_cache: bool = True,
                 index_dtype=np.uint32):
        self._cache_file = cache_file
        self._index_dtype = index_dtype
        if not (reuse_cache and os.path.exists(cache_file)):
            self._build_cache(parser)
        self._iter: Optional[ThreadedIter] = None
        self.before_first()

    def _build_cache(self, parser: Parser) -> None:
        start = get_time()
        fo = create_stream(self._cache_file, "w")
        page = RowBlockContainer(self._index_dtype)
        page_bytes = 0
        total = 0
        for block in parser:
            page.push_block(block)
            page_bytes += block.memory_cost_bytes()
            if page_bytes >= self.PAGE_BYTES:
                page.save(fo)
                total += page_bytes
                elapsed = max(get_time() - start, 1e-9)
                log_info(f"wrote {total >> 20} MB cache, "
                         f"{total / (1 << 20) / elapsed:.2f} MB/sec")
                page = RowBlockContainer(self._index_dtype)
                page_bytes = 0
        if page.size:
            page.save(fo)
        fo.close()
        if hasattr(parser, "close"):
            parser.close()

    def _make_producer(self):
        parent = self

        class _Producer:
            def __init__(self) -> None:
                self._fi = create_stream_for_read(parent._cache_file)

            def before_first(self) -> None:
                self._fi.seek(0)

            def next(self, reuse):
                container = RowBlockContainer(parent._index_dtype)
                if not container.load(self._fi):
                    return None
                return container.get_block()

        return _Producer()

    def before_first(self) -> None:
        if self._iter is None:
            self._iter = ThreadedIter(self._make_producer(), max_capacity=2,
                                      name="row_iter")
        else:
            self._iter.before_first()

    def next(self) -> Optional[RowBlock]:
        return self._iter.next()

    def close(self) -> None:
        if self._iter is not None:
            self._iter.destroy()
