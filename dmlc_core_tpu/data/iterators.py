"""Row-block iterators: in-memory and disk-cached.

Capability parity with the reference's ``BasicRowIter``
(src/data/basic_row_iter.h:23-82, full in-memory load with MB/s progress logs)
and ``DiskRowIter`` (src/data/disk_row_iter.h:28-139, 64MB-page disk cache
built on first pass, replayed on later epochs).

Local caches are built in the **columnar v2 format**
(:mod:`dmlc_core_tpu.data.page_cache`): atomic temp+fsync+rename build,
checksummed pages, and mmap'd zero-copy replay — epoch >= 2 serves the same
read-only RowBlock views every time instead of re-deserializing.  A legacy
v1 cache (``RowBlockContainer`` framing) still loads through the stream
path.

Remote (URI) cache files ride the **fleet-shared remote page cache**: the
v2 file is fetched over the ranged-read FS layer (open-by-footer, a
prefetching page-fetch ring, per-page CRC validation) and materialized
into a local cache-of-cache under ``DMLC_CACHE_LOCAL_DIR``, so one worker
parses and publishes (``DMLC_CACHE_REMOTE``) while the rest of the fleet
fetches — and every epoch on every host still mmaps locally at zero-copy
speed.  Anything untrustable (footer-less object, v1 framing, dtype
drift, a corrupt or truncated page) falls back to stream-parsing with a
loud warning; a bad page is never served.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from dmlc_core_tpu import telemetry
from dmlc_core_tpu.data import page_cache
from dmlc_core_tpu.data.page_cache import CacheFormatError
from dmlc_core_tpu.data.parser import Parser
from dmlc_core_tpu.data.row_block import RowBlock, RowBlockContainer
from dmlc_core_tpu.io.stream import create_stream_for_read
from dmlc_core_tpu.io.threadediter import ThreadedIter
from dmlc_core_tpu.param import get_env
from dmlc_core_tpu.utils.logging import CHECK, log_info, log_warning
from dmlc_core_tpu.utils.timer import get_time

__all__ = ["RowBlockIter", "BasicRowIter", "DiskRowIter"]


class RowBlockIter:
    """Iterator over RowBlocks (reference RowBlockIter, data.h:221-247)."""

    def before_first(self) -> None:
        raise NotImplementedError

    def next(self) -> Optional[RowBlock]:
        raise NotImplementedError

    def __iter__(self):
        while True:
            block = self.next()
            if block is None:
                return
            yield block


class BasicRowIter(RowBlockIter):
    """Load everything into memory up front (reference basic_row_iter.h:23-82)."""

    def __init__(self, parser: Parser, index_dtype=np.uint32):
        start = get_time()
        container = RowBlockContainer(index_dtype)
        bytes_logged = 0
        for block in parser:
            container.push_block(block)
            nread = parser.bytes_read()
            if nread >= bytes_logged + (10 << 20):  # every 10MB, ref :70-75
                elapsed = max(get_time() - start, 1e-9)
                log_info(f"{nread >> 20} MB read, "
                         f"{nread / (1 << 20) / elapsed:.2f} MB/sec")
                bytes_logged = nread
        self._block = container.get_block()
        elapsed = max(get_time() - start, 1e-9)
        log_info(f"finished reading {parser.bytes_read() / (1 << 20):.2f} MB, "
                 f"{parser.bytes_read() / (1 << 20) / elapsed:.2f} MB/sec")
        if hasattr(parser, "close"):
            parser.close()
        self._done = False

    def before_first(self) -> None:
        self._done = False

    def next(self) -> Optional[RowBlock]:
        if self._done:
            return None
        self._done = True
        return self._block

    def get_block(self) -> RowBlock:
        return self._block


def _remote_cache_config(cache_file: str) -> tuple:
    """(remote_uri, publish): where a remote copy of the cache lives and
    whether a local build should be uploaded there.

    ``DMLC_CACHE_REMOTE`` is the fleet-sharing knob: ``1`` publishes a
    local build to the remote cache URI itself; an explicit ``<uri>``
    names the remote location (fetch + publish) even when the
    ``#cachefile`` is a local path.  A remote ``#cachefile`` is always
    *fetch*-eligible — publish stays opt-in so N racing cold workers
    don't all upload."""
    env = os.environ.get("DMLC_CACHE_REMOTE", "").strip()
    remote_uri = cache_file if "://" in cache_file else None
    publish = False
    if "://" in env:
        remote_uri = env
        publish = True
    elif env:
        # the repo-wide bool grammar (param._parse_bool, same as every
        # other DMLC_* boolean knob): "False"/"NO" disable regardless of
        # case, and garbage raises instead of silently enabling publish
        publish = get_env("DMLC_CACHE_REMOTE", bool, False)
    return remote_uri, publish and remote_uri is not None


class DiskRowIter(RowBlockIter):
    """Build a paged disk cache on the first pass, then iterate the cache
    (reference disk_row_iter.h:28-139).

    Caches use the v2 columnar format: the build goes to a temp file and
    is renamed into place only after the checksummed footer is durable (a
    crash mid-build can never leave a trusted-but-truncated cache), and
    replay mmaps the file once — every epoch serves the *same* zero-copy
    RowBlock views.  An existing cache that fails validation (truncated
    tail, bad page CRC, different index dtype) is rebuilt with a loud
    warning.  v1 caches still load via the legacy stream path.

    A remote cache URI (or an explicit ``DMLC_CACHE_REMOTE=<uri>``) makes
    the cache fleet-shared: a valid remote v2 object is fetched through
    the ranged-read FS layer and materialized locally (see
    :func:`page_cache.fetch_remote_cache`); otherwise this worker stream-
    parses, builds the v2 file locally, and — with publish enabled —
    uploads it so the rest of the fleet fetches instead of re-parsing."""

    PAGE_BYTES = 64 << 20  # reference kPageSize (disk_row_iter.h:32)

    def __init__(self, parser, cache_file: str, reuse_cache: bool = True,
                 index_dtype=np.uint32):
        # ``parser`` may be a zero-arg factory instead of a Parser: it is
        # only invoked when the cache actually has to be (re)built, so a
        # warm run — local materialization or fleet fetch — never pays
        # parser/input-split construction (or its remote stat traffic)
        self._cache_file = cache_file
        self._index_dtype = np.dtype(index_dtype)
        # page granularity is also the remote fetch/pipeline unit: smaller
        # pages let the prefetch ring overlap validation with the wire
        self._page_bytes = max(1 << 20, get_env("DMLC_CACHE_PAGE_BYTES", int,
                                                self.PAGE_BYTES))
        self._remote_uri, self._publish = _remote_cache_config(cache_file)
        self._local_path = (page_cache.default_local_path(self._remote_uri)
                            if "://" in cache_file else cache_file)
        self._reader: Optional[page_cache.PageCacheReader] = None
        self._iter: Optional[ThreadedIter] = None
        if reuse_cache and os.path.exists(self._local_path):
            try:
                self._open_cache()
            except CacheFormatError as exc:
                log_warning(f"cache {self._local_path} failed validation "
                            f"({exc}); rebuilding")
                telemetry.count("dmlc_cache_rebuilds_total")
                self._acquire_cache(parser)
        else:
            self._acquire_cache(parser)
        self.before_first()

    # -- acquire: remote fetch, else stream-parse build (+ publish) -----------
    def _acquire_cache(self, parser) -> None:
        if self._remote_uri is not None and self._try_fetch():
            try:
                self._open_cache()
                return
            except CacheFormatError as exc:
                # defense in depth: the fetch validated every page, but a
                # materialized file the reader still rejects must fall back
                # to the source, not crash the worker
                log_warning(f"fetched cache {self._local_path} failed local "
                            f"validation ({exc}); rebuilding from source")
                telemetry.count("dmlc_cache_rebuilds_total")
                self._reader = None
        if not isinstance(parser, Parser) and callable(parser):
            parser = parser()
        self._build_cache(parser)
        if self._publish:
            try:
                page_cache.publish_cache(self._local_path, self._remote_uri)
                log_info(f"published cache to {self._remote_uri}")
            except Exception as exc:  # noqa: BLE001 — data is served locally
                log_warning(f"cache publish to {self._remote_uri} failed "
                            f"({exc!r}); continuing with the local cache")
        self._open_cache()

    def _try_fetch(self) -> bool:
        """One attempt at the fleet-shared path; False falls back to the
        stream-parse build.  A bad page is never served: validation
        failures surface here, before the local file exists."""
        start = get_time()
        try:
            nbytes = page_cache.fetch_remote_cache(
                self._remote_uri, self._local_path, self._index_dtype)
        except Exception as exc:  # noqa: BLE001 — a bad remote store must
            # degrade to stream-parsing, never crash the worker: beyond
            # OSError, the FS layer raises logging.Error (a RuntimeError)
            # when an object store fails persistently (403, retry-exhausted
            # 5xx), and injected faults may raise ValueError/RuntimeError
            reason = ("absent" if isinstance(exc, FileNotFoundError)
                      else "invalid" if isinstance(exc, CacheFormatError)
                      else "io" if isinstance(exc, OSError)
                      else "error")
            telemetry.count("dmlc_cache_remote_misses_total", reason=reason)
            if reason != "absent":
                # an unusable remote cache is worth a loud warning and a
                # rebuild count — it means the fleet-shared copy is bad
                log_warning(f"remote cache {self._remote_uri} unusable "
                            f"({exc}); falling back to stream parse")
                telemetry.count("dmlc_cache_rebuilds_total")
            else:
                log_info(f"no remote cache at {self._remote_uri}; "
                         "stream-parsing")
            return False
        telemetry.count("dmlc_cache_remote_hits_total")
        elapsed = max(get_time() - start, 1e-9)
        log_info(f"fetched {nbytes >> 20} MB cache from {self._remote_uri}, "
                 f"{nbytes / (1 << 20) / elapsed:.2f} MB/sec")
        return True

    # -- build ----------------------------------------------------------------
    def _build_cache(self, parser: Parser) -> None:
        start = get_time()
        dirpath = os.path.dirname(os.path.abspath(self._local_path))
        # 0700 on creation: the default materialization dir is per-user
        # private (page_cache.default_local_path); existing dirs untouched
        os.makedirs(dirpath, mode=0o700, exist_ok=True)
        writer = page_cache.PageCacheWriter(self._local_path,
                                            self._index_dtype)
        page = RowBlockContainer(self._index_dtype)
        page_bytes = 0
        total = 0
        with telemetry.span("cache.build", path=self._local_path) as sp:
            try:
                for block in parser:
                    page.push_block(block)
                    page_bytes += block.memory_cost_bytes()
                    if page_bytes >= self._page_bytes:
                        writer.write_page(page)
                        total += page_bytes
                        elapsed = max(get_time() - start, 1e-9)
                        log_info(f"wrote {total >> 20} MB cache, "
                                 f"{total / (1 << 20) / elapsed:.2f} MB/sec")
                        page = RowBlockContainer(self._index_dtype)
                        page_bytes = 0
                if page.size:
                    writer.write_page(page)
                writer.commit()
                sp.set(pages=writer.pages_written,
                       nbytes=total + page_bytes)
            except BaseException:
                # never leave a half-written file where a trusted cache goes
                writer.abort()
                raise
            finally:
                if hasattr(parser, "close"):
                    parser.close()

    # -- open -----------------------------------------------------------------
    def _open_cache(self) -> None:
        """Attach to the cache: v2 mmap when the header says so, else the
        legacy v1 stream path.  Raises CacheFormatError on an untrustable
        v2 file (missing footer, checksum mismatch, dtype drift)."""
        self._reader = None
        with open(self._local_path, "rb") as probe:
            head = probe.read(len(page_cache.HEAD_MAGIC))
        if head == page_cache.HEAD_MAGIC:
            self._reader = page_cache.PageCacheReader(self._local_path,
                                                      self._index_dtype)
            telemetry.count("dmlc_cache_open_total", format="v2-mmap")
            return
        telemetry.count("dmlc_cache_open_total", format="v1")

    def _make_producer(self):
        parent = self
        if self._reader is not None:
            class _PageProducer:
                """Replays the reader's mmap-backed blocks: the same array
                objects every epoch — zero per-epoch copies."""

                def __init__(self) -> None:
                    self._pos = 0

                def before_first(self) -> None:
                    self._pos = 0

                def next(self, reuse):
                    blocks = parent._reader.blocks
                    if self._pos >= len(blocks):
                        return None
                    block = blocks[self._pos]
                    self._pos += 1
                    telemetry.count("dmlc_cache_page_reads_total",
                                    source="mmap")
                    return block

            return _PageProducer()

        class _Producer:
            def __init__(self) -> None:
                self._fi = create_stream_for_read(parent._local_path)

            def before_first(self) -> None:
                self._fi.seek(0)

            def next(self, reuse):
                container = RowBlockContainer(parent._index_dtype)
                if not container.load(self._fi):
                    return None
                telemetry.count("dmlc_cache_page_reads_total",
                                source="stream")
                return container.get_block()

        return _Producer()

    def cache_blocks(self) -> Optional[list]:
        """The mmap'd zero-copy RowBlock views backing a v2 cache (the
        same objects every epoch), or None on the v1/stream path.

        This is the streaming-binner feed (``bridge.binning.fit_binner``):
        quantile edges are computed directly over the mapped views without
        a second parse or any row copy."""
        return None if self._reader is None else self._reader.blocks

    def before_first(self) -> None:
        if self._iter is None:
            self._iter = ThreadedIter(self._make_producer(), max_capacity=2,
                                      name="row_iter")
        else:
            self._iter.before_first()

    def next(self) -> Optional[RowBlock]:
        return self._iter.next()

    def close(self) -> None:
        if self._iter is not None:
            self._iter.destroy()
        if self._reader is not None:
            self._reader.close()
