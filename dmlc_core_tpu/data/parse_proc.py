"""Process-parallel parse backend: GIL-free fan-out over worker processes
with zero-copy shared-memory RowBlock transport.

The thread-pool fan-out in :class:`~dmlc_core_tpu.data.parser.TextParserBase`
is the reference's OpenMP team (text_parser.h:89-118) minus real parallelism:
numpy releases the GIL inside each kernel, but the Python glue between
kernels serializes, so parse throughput plateaus long before the cores do.
This module moves the workers into processes:

- the consumer cuts a source chunk into newline-aligned sub-ranges exactly
  as the thread path does, and ships each range to a worker process;
- each worker runs the parser's ``parse_block`` (pure numpy, no source, no
  threads) and writes the resulting :class:`RowBlockContainer` columns into
  ONE ``multiprocessing.shared_memory`` segment — offsets/labels/indices/
  values never cross the pipe;
- the worker returns only plain metadata (segment name, per-column dtype/
  offset/length, max_index/max_field, in-worker parse seconds);
- the consumer attaches the segment, **unlinks it immediately** (the mapping
  outlives the name), and wraps every column with a ``np.frombuffer`` view —
  zero copies end to end.  A ``weakref.finalize`` on the shared base array
  closes the segment when the last RowBlock view dies, so lifetime is
  exactly "as long as anyone holds the block".

Array payloads are **never pickled** on this path (the analysis gate's
``shm-no-pickle`` rule enforces it stays that way); the executor pickles
only the input byte ranges and the metadata dicts.

One **shared, self-healing pool per process** serves every parser: workers
build per-format parser twins lazily by spec, bring-up cost is paid once
(not per parser or epoch), total worker count stays bounded however many
pipeline stages exist, and a pool broken by a worker death is dropped so
the next parser starts a fresh one.

Knobs:

- ``DMLC_PARSE_PROC=N``   — enable with N workers (``auto`` = cpu count;
  0/1/unset = off, the thread path is used);
- ``DMLC_PARSE_PROC_START`` — multiprocessing start method.  The default
  is ``spawn`` whenever the parent is multi-threaded or has jax loaded
  (forking then risks inherited-lock deadlocks in the child) and ``fork``
  otherwise; workers never import jax, so spawn stays cheap.

Block order is deterministic: ranges are submitted and collected in source
order (``Executor.map``).  A worker killed mid-chunk surfaces as a
``RuntimeError`` on the consumer (ferried through ``ThreadedParser`` like
any parse error) — never a hang.  The chaos suite drives this through the
``data.parse_worker`` fault site.
"""

from __future__ import annotations

import importlib
import os
import threading
import time
import weakref
from concurrent.futures import ProcessPoolExecutor
from concurrent.futures.process import BrokenProcessPool
from typing import Any, Dict, List, Optional, Sequence, Tuple

import multiprocessing as mp
from multiprocessing import shared_memory

import numpy as np

from dmlc_core_tpu import fault, telemetry
from dmlc_core_tpu.data.row_block import (COLUMN_ORDER, RowBlock,
                                          RowBlockContainer, align8)
from dmlc_core_tpu.telemetry import tracecontext
from dmlc_core_tpu.utils.logging import log_warning

__all__ = ["ProcParsePool", "resolve_nproc", "attach_block", "engaged",
           "shutdown"]

ENV_NPROC = "DMLC_PARSE_PROC"
ENV_START = "DMLC_PARSE_PROC_START"

# RowBlock columns in transport order (shared with the page cache via
# row_block.COLUMN_ORDER); offset is always int64, label/weight/value
# float32, field/index carry the parser's index dtype
_COLUMNS = COLUMN_ORDER


def resolve_nproc(environ: Optional[Dict[str, str]] = None) -> int:
    """Worker count from ``DMLC_PARSE_PROC`` (0 = backend off)."""
    raw = (environ if environ is not None else os.environ) \
        .get(ENV_NPROC, "").strip().lower()
    if not raw or raw in ("0", "off", "false", "no"):
        return 0
    if raw == "auto":
        return os.cpu_count() or 1
    try:
        return max(0, int(raw))
    except ValueError:
        log_warning(f"ignoring non-integer {ENV_NPROC}={raw!r}")
        return 0


_align8 = align8


def _untrack(shm: shared_memory.SharedMemory) -> None:
    """Drop the worker-side resource_tracker registration.

    The segment's lifetime belongs to the consumer (attach + unlink);
    without this the tracker inherited by the worker would re-unlink the
    already-unlinked name at exit and log spurious leak warnings."""
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass


# -- worker side --------------------------------------------------------------

# per-worker parser instances keyed by spec (one worker pool serves every
# parser/format in the process; the parser twin is built on first use)
_WORKER_PARSERS: Dict[str, Any] = {}


def _worker_init() -> None:
    if not fault.enabled():
        # spawn-started workers don't inherit the parent's configured plan;
        # re-read the env so chaos plans reach them either way
        try:
            fault._init_from_env()
        except Exception:
            pass


def _worker_ready() -> bool:
    """Warmup probe: forces worker spawn + import before the first chunk."""
    return True


def _spec_key(spec: Tuple[str, str, Dict[str, Any]]) -> str:
    module, qualname, kwargs = spec
    return f"{module}:{qualname}:{sorted(kwargs.items())!r}"


def _worker_parser(spec: Tuple[str, str, Dict[str, Any]]) -> Any:
    key = _spec_key(spec)
    parser = _WORKER_PARSERS.get(key)
    if parser is None:
        module, qualname, kwargs = spec
        cls = getattr(importlib.import_module(module), qualname)
        kw = dict(kwargs)
        if "index_dtype" in kw:
            kw["index_dtype"] = np.dtype(kw["index_dtype"])
        parser = _WORKER_PARSERS[key] = cls(None, **kw)
    return parser


def _worker_parse(spec: Tuple[str, str, Dict[str, Any]], data: bytes,
                  traceparent: Optional[str] = None) -> Dict[str, Any]:
    """Parse one newline-aligned range; columns go out via shared memory.

    ``traceparent`` is the consumer's trace context shipped alongside the
    range (the same W3C string the serving path puts in HTTP headers): the
    worker's parse span — recorded in ITS process, flushed in ITS span
    file — joins the parent's trace, so the assembled timeline shows the
    fan-out instead of orphaned worker activity.
    """
    t0 = time.monotonic()
    parser = _worker_parser(spec)
    with tracecontext.activate(tracecontext.from_traceparent(traceparent)):
        if fault.enabled():
            fault.inject("data.parse_worker", parser=type(parser).__name__)
        with telemetry.span("parse_worker.parse_block",
                            parser=type(parser).__name__, nbytes=len(data)):
            container = parser.parse_block(data)
    block = container.get_block()
    meta: Dict[str, Any] = {
        "rows": int(block.size),
        "max_index": int(container.max_index),
        "max_field": int(container.max_field),
        "shm": None, "nbytes": 0, "cols": [],
    }
    if block.size:
        cols: List[Tuple[str, str, int, int]] = []
        arrays: List[Optional[np.ndarray]] = []
        total = 0
        for name in _COLUMNS:
            arr = getattr(block, name)
            if arr is not None:
                arr = np.ascontiguousarray(arr)
                cols.append((name, arr.dtype.str, total, arr.nbytes))
                total += _align8(arr.nbytes)
            else:
                cols.append((name, "", 0, 0))
            arrays.append(arr)
        shm = shared_memory.SharedMemory(create=True, size=max(total, 1))
        try:
            for (name, _, off, nbytes), arr in zip(cols, arrays):
                if nbytes:
                    np.frombuffer(shm.buf, np.uint8, nbytes, off)[:] = \
                        arr.view(np.uint8).reshape(-1)
            meta.update(shm=shm.name, nbytes=total, cols=cols)
        except BaseException:
            # the consumer never learns this segment's name: unlink it
            # HERE or the bytes sit in /dev/shm until reboot
            shm.close()
            shm.unlink()
            raise
        shm.close()
        _untrack(shm)
    meta["busy_s"] = time.monotonic() - t0
    return meta


# -- consumer side ------------------------------------------------------------

def _discard_meta(meta: Optional[Dict[str, Any]]) -> None:
    """Unlink a worker result's segment without wrapping it (error paths).

    Already-attached metas are a no-op: attach_block unlinks on attach, so
    the name is gone and only the (lease-managed) mapping remains."""
    if not meta or not meta.get("shm"):
        return
    try:
        seg = shared_memory.SharedMemory(name=meta["shm"])
    except FileNotFoundError:
        return
    try:
        seg.unlink()
    except FileNotFoundError:
        pass
    seg.close()


def _release_lease(mm, buf, gauge_bytes: int) -> None:
    try:
        buf.release()
        mm.close()
    except BufferError:
        # interpreter-shutdown ordering: views may still be alive when the
        # atexit finalizer sweep runs; the OS reclaims the mapping anyway
        pass
    if gauge_bytes:
        try:
            telemetry.gauge_add("dmlc_parse_shm_bytes_in_flight",
                                -gauge_bytes)
        except Exception:
            pass  # observability must never block a mapping release


def attach_block(meta: Dict[str, Any], index_dtype) -> RowBlockContainer:
    """Wrap one worker result into a RowBlockContainer without copying."""
    out = RowBlockContainer(index_dtype)
    out.max_index = meta["max_index"]
    out.max_field = meta["max_field"]
    if not meta["shm"]:
        return out
    shm = shared_memory.SharedMemory(name=meta["shm"])
    try:
        shm.unlink()  # mapping survives; the name must not
    except FileNotFoundError:
        pass
    # steal the mapping from the SharedMemory object: its __del__ would
    # close() under GC/shutdown and raise BufferError while RowBlock views
    # still export pointers — lifetime belongs to the finalizer below
    mm, buf = shm._mmap, shm._buf
    shm._mmap = shm._buf = None
    if getattr(shm, "_fd", -1) >= 0:  # mmap no longer needs the fd
        os.close(shm._fd)
        shm._fd = -1
    try:
        seg = np.frombuffer(buf, dtype=np.uint8)
        track = meta["nbytes"] if telemetry.enabled() else 0
        if track:
            telemetry.gauge_add("dmlc_parse_shm_bytes_in_flight", track)
    except BaseException:
        # no finalizer is registered yet: release the stolen mapping here
        # or it outlives every view that could ever free it.  Gauge delta
        # 0: gauge_add raising means the increment never landed.
        _release_lease(mm, buf, 0)
        raise
    try:
        # every column view chains its .base to `seg`; when the last view
        # dies, seg dies, and the finalizer releases the mapping
        weakref.finalize(seg, _release_lease, mm, buf, track)
    except BaseException:
        # the increment above DID land: release with the full delta so
        # the in-flight gauge cannot drift upward on this path
        _release_lease(mm, buf, track)
        raise
    views: Dict[str, Optional[np.ndarray]] = {}
    for name, dtype_str, off, nbytes in meta["cols"]:
        views[name] = (seg[off:off + nbytes].view(dtype_str)
                       if nbytes else None)
    # a range of label-only rows has rows>0 but an empty index column —
    # RowBlock needs a real len-0 array there, not None
    index = views["index"] if views["index"] is not None \
        else np.empty(0, np.dtype(index_dtype))
    out.push_block(RowBlock(views["offset"], views["label"], index,
                            views["value"], views["weight"], views["field"]))
    return out


def _default_start_method() -> str:
    import sys

    methods = mp.get_all_start_methods()
    if "spawn" in methods and ("jax" in sys.modules
                               or threading.active_count() > 1):
        # forking a multi-threaded parent (a ThreadedParser producer, the
        # jax runtime, telemetry writers) can snapshot a held lock into the
        # child and deadlock the first worker that logs or counts; spawn is
        # safe and stays cheap because workers never import jax (lazy
        # package design).  The pool is usually created lazily on the
        # producer thread, so in practice spawn is the threaded default
        # and fork only serves single-threaded CLI/bench use.
        return "spawn"
    return "fork" if "fork" in methods else methods[0]


# -- the process-wide worker pool ---------------------------------------------
#
# ONE executor serves every parser in the process: spawn bring-up (~0.5s a
# worker under the thread-safe default start method) is paid once, not per
# parser/epoch, and total worker count stays bounded however many pipeline
# stages exist.  Workers build per-format parser twins lazily by spec.

_pool_lock = threading.Lock()
_shared_pool: Optional[ProcessPoolExecutor] = None
_shared_size = 0

# bound on the bring-up warmup probe below: it runs under _pool_lock, so a
# wedged spawn (e.g. an inherited-state deadlock in a worker) must surface
# as a loud bring-up failure — which the caller already handles by falling
# back to the thread path — instead of parking every parser thread on the
# lock forever (dmlclint deadlock-blocking-under-lock)
_WARMUP_TIMEOUT_S = 120.0


def _get_shared_pool(nproc: int) -> Tuple[ProcessPoolExecutor, int]:
    global _shared_pool, _shared_size
    with _pool_lock:
        if _shared_pool is None:
            method = (os.environ.get(ENV_START, "").strip()
                      or _default_start_method())
            pool = ProcessPoolExecutor(max_workers=nproc,
                                       mp_context=mp.get_context(method),
                                       initializer=_worker_init)
            # warmup probe: surfaces a broken start method HERE, where the
            # caller can still fall back to the thread path, instead of as
            # a BrokenProcessPool mid-parse — and forces worker spawn so
            # the first chunk doesn't pay it
            try:
                pool.submit(_worker_ready).result(_WARMUP_TIMEOUT_S)
            except BaseException:
                # a failed bring-up must not leak the executor's queue/
                # threads/half-spawned workers on every retrying parser
                pool.shutdown(wait=False, cancel_futures=True)
                raise
            _shared_pool, _shared_size = pool, nproc
            telemetry.gauge_set("dmlc_parse_proc_workers", nproc)
        elif _shared_size != nproc:
            log_warning(f"parse worker pool already sized {_shared_size}; "
                        f"ignoring request for {nproc}")
        return _shared_pool, _shared_size


def _unstick_call_queue(queue) -> None:
    """Free a feeder thread wedged on the call-queue pipe of a broken pool.

    CPython's executor (the gh-94777 deadlock lineage, unfixed on 3.10):
    after a worker is killed, ``terminate_broken`` joins the call queue's
    feeder thread — but with every worker dead nothing drains the call
    queue, ``Queue.close`` never closes the parent's read end (no EPIPE),
    and a feeder blocked mid-write on a full pipe never returns, so the
    executor's management thread parks on the join and interpreter exit
    then hangs in ``_python_exit`` forever.  Draining the parent-side read
    end lets the blocked write complete and the feeder reach its close
    sentinel.  Only safe on a BROKEN pool: with workers alive this would
    steal their work items off the shared pipe.  Takes the queue, not the
    executor — ``Executor.shutdown`` nulls ``_call_queue``."""
    feeder = getattr(queue, "_thread", None)
    reader = getattr(queue, "_reader", None)
    if feeder is None or reader is None:
        return
    deadline = time.monotonic() + 10.0
    while feeder.is_alive() and time.monotonic() < deadline:
        try:
            if reader.poll(0.05):
                reader.recv_bytes()
        except (OSError, EOFError):
            break


def _discard_shared_pool(pool: ProcessPoolExecutor) -> None:
    """Drop a broken pool so the next parser self-heals with a fresh one."""
    global _shared_pool, _shared_size
    with _pool_lock:
        if _shared_pool is pool:
            _shared_pool, _shared_size = None, 0
            telemetry.gauge_set("dmlc_parse_proc_workers", 0)
    queue = getattr(pool, "_call_queue", None)   # shutdown() nulls it
    pool.shutdown(wait=False, cancel_futures=True)
    _unstick_call_queue(queue)


def engaged() -> bool:
    """True while the shared worker pool is up (the process backend is
    actually serving parses, not the thread fallback) — the public probe
    benchmarks/monitoring should use."""
    return _shared_pool is not None


def shutdown() -> None:
    """Tear the shared pool down (tests / explicit lifecycle control)."""
    global _shared_pool, _shared_size
    with _pool_lock:
        pool, _shared_pool, _shared_size = _shared_pool, None, 0
    if pool is not None:
        pool.shutdown(wait=False, cancel_futures=True)


class ProcParsePool:
    """A handle for one TextParserBase onto the shared worker pool.

    ``spec`` is ``(module, qualname, kwargs)`` — enough to rebuild a
    source-less, single-threaded twin of the owning parser inside a worker
    (see ``TextParserBase._proc_spec``); workers cache twins by spec, so
    any mix of formats shares the same processes."""

    def __init__(self, spec: Tuple[str, str, Dict[str, Any]], nproc: int):
        self._spec = spec
        self._index_dtype = np.dtype(spec[2].get("index_dtype", np.uint32))
        self._pool, self.nproc = _get_shared_pool(max(2, int(nproc)))

    def alive(self) -> bool:
        """True while this handle's executor is still the shared pool (a
        worker death discards the shared pool; stale handles must not
        submit to a shut-down executor)."""
        return self._pool is not None and self._pool is _shared_pool

    def parse_ranges(self, ranges: Sequence[bytes],
                     parser_name: str = "") -> List[RowBlockContainer]:
        """Parse ranges on the workers; containers in submission order.

        Error discipline: if any range fails (parse error, killed worker),
        every segment the *other* ranges already created is unlinked before
        the error propagates — the workers unregister their segments from
        the resource tracker (the consumer owns cleanup), so a dropped meta
        would otherwise leak /dev/shm bytes until reboot."""
        # context propagation rides NEXT TO the payload, never inside it:
        # the worker re-activates it around the parse span only
        tp = (tracecontext.current_traceparent()
              if telemetry.enabled() else None)
        futures = [self._pool.submit(_worker_parse, self._spec, r, tp)
                   for r in ranges]
        metas: List[Dict[str, Any]] = []
        error: Optional[BaseException] = None
        for future in futures:
            try:
                metas.append(future.result())
            except BaseException as exc:  # noqa: BLE001 - cleanup then raise
                error = exc
                break
        if error is not None:
            # drain the uncollected tail's segments too (metas holds the
            # successes before the failure; futures[len(metas)] raised)
            for future in futures[len(metas) + 1:]:
                try:
                    metas.append(future.result())
                except BaseException:    # noqa: BLE001 - already failing
                    pass
            for meta in metas:
                _discard_meta(meta)
            if isinstance(error, BrokenProcessPool):
                # the executor is unusable after a worker death: drop it so
                # the next parser self-heals with a fresh pool
                _discard_shared_pool(self._pool)
                raise RuntimeError(
                    "parse worker died mid-chunk (killed or crashed); "
                    f"the parse cannot continue: {error}") from error
            raise error
        if telemetry.enabled():
            telemetry.count("dmlc_parse_proc_ranges_total", len(ranges),
                            parser=parser_name)
            telemetry.count("dmlc_parse_proc_busy_seconds_total",
                            sum(m["busy_s"] for m in metas),
                            parser=parser_name)
        try:
            return [attach_block(m, self._index_dtype) for m in metas]
        except BaseException:
            for meta in metas:           # unattached leftovers would leak
                _discard_meta(meta)
            raise

    def close(self) -> None:
        """Release the handle; the shared pool outlives any one parser
        (call :func:`shutdown` for explicit process-wide teardown)."""
        self._pool = None
