"""CSR sparse-batch data model.

Capability parity with the reference's ``dmlc::Row``/``RowBlock``
(include/dmlc/data.h:69-214) and ``RowBlockContainer``
(src/data/row_block.h:26-205), as numpy structure-of-arrays:

- ``offset``  int64[size+1] — CSR row pointers;
- ``label``   float32[size];
- ``weight``  float32[size] or None (None => all 1.0, data.h:120-125);
- ``field``   index_dtype[nnz] or None (libfm field ids);
- ``index``   index_dtype[nnz] — feature indices;
- ``value``   float32[nnz] or None (None => all values 1.0, data.h:106-112).

Binary save/load matches the reference's RowBlockContainer layout
(row_block.h:181-205): six u64-count-prefixed vectors then max_field/max_index
scalars, so caches interoperate with the C++ side.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np

from dmlc_core_tpu.io.stream import Stream
from dmlc_core_tpu.utils.logging import CHECK, CHECK_EQ, CHECK_LT

__all__ = ["Row", "RowBlock", "RowBlockContainer", "COLUMN_ORDER", "align8"]

real_t = np.float32

# canonical column transport/layout order shared by the shm parse transport
# (data/parse_proc.py), the columnar page cache (data/page_cache.py), and
# the Arrow/Parquet ingest (data/arrow_ingest.py), which maps Arrow
# buffers onto exactly these columns as zero-copy views
COLUMN_ORDER = ("offset", "label", "weight", "field", "index", "value")


def align8(n: int) -> int:
    """Round a byte count up to 8-byte alignment (column buffer layout)."""
    return (n + 7) & ~7


class Row:
    """One instance view into a RowBlock (reference Row, data.h:69-148)."""

    __slots__ = ("label", "weight", "field", "index", "value")

    def __init__(self, label, weight, field, index, value):
        self.label = label
        self.weight = weight
        self.field = field
        self.index = index
        self.value = value

    @property
    def length(self) -> int:
        return len(self.index)

    def get_value(self, i: int):
        return 1.0 if self.value is None else float(self.value[i])

    def get_weight(self):
        return 1.0 if self.weight is None else float(self.weight)

    def sdot(self, weights: np.ndarray) -> float:
        """Sparse dot with a dense vector (reference SDot, data.h:133-148)."""
        CHECK(self.index.size == 0 or int(self.index.max()) < len(weights),
              "feature index exceeds bound")
        if self.value is None:
            return float(weights[self.index].sum())
        return float(np.dot(weights[self.index], self.value))


class RowBlock:
    """A batch of rows in CSR layout (reference RowBlock, data.h:152-214)."""

    __slots__ = ("offset", "label", "weight", "field", "index", "value")

    def __init__(
        self,
        offset: np.ndarray,
        label: np.ndarray,
        index: np.ndarray,
        value: Optional[np.ndarray] = None,
        weight: Optional[np.ndarray] = None,
        field: Optional[np.ndarray] = None,
    ):
        self.offset = np.ascontiguousarray(offset, dtype=np.int64)
        self.label = np.ascontiguousarray(label, dtype=real_t)
        self.index = np.ascontiguousarray(index)
        self.value = None if value is None else np.ascontiguousarray(value, dtype=real_t)
        self.weight = None if weight is None else np.ascontiguousarray(weight, dtype=real_t)
        self.field = None if field is None else np.ascontiguousarray(field, dtype=self.index.dtype)
        CHECK_EQ(len(self.offset), len(self.label) + 1, "offset/label size mismatch")
        nnz = int(self.offset[-1] - self.offset[0])
        CHECK_EQ(len(self.index), nnz, "offset/index size mismatch")

    @property
    def size(self) -> int:
        return len(self.offset) - 1

    def __len__(self) -> int:
        return self.size

    @property
    def num_nonzero(self) -> int:
        return int(self.offset[-1] - self.offset[0])

    def memory_cost_bytes(self) -> int:
        """Approximate memory cost (reference MemCostBytes, data.h:181-191)."""
        cost = self.size * (8 + 4)  # offset + label
        if self.weight is not None:
            cost += self.size * 4
        ndata = self.num_nonzero
        cost += ndata * self.index.dtype.itemsize
        if self.field is not None:
            cost += ndata * self.field.dtype.itemsize
        if self.value is not None:
            cost += ndata * 4
        return cost

    def __getitem__(self, i) -> "Row | RowBlock":
        if isinstance(i, slice):
            start, stop, step = i.indices(self.size)
            CHECK_EQ(step, 1, "RowBlock slices must be contiguous")
            return self.slice(start, stop)
        CHECK_LT(i, self.size, "row index out of range")
        lo = int(self.offset[i] - self.offset[0])
        hi = int(self.offset[i + 1] - self.offset[0])
        return Row(
            float(self.label[i]),
            None if self.weight is None else float(self.weight[i]),
            None if self.field is None else self.field[lo:hi],
            self.index[lo:hi],
            None if self.value is None else self.value[lo:hi],
        )

    def slice(self, begin: int, end: int) -> "RowBlock":
        """Zero-copy sub-batch (reference Slice, data.h:198-213)."""
        CHECK(0 <= begin <= end <= self.size, "invalid slice range")
        lo = int(self.offset[begin] - self.offset[0])
        hi = int(self.offset[end] - self.offset[0])
        out = RowBlock.__new__(RowBlock)
        out.offset = self.offset[begin:end + 1]
        out.label = self.label[begin:end]
        out.weight = None if self.weight is None else self.weight[begin:end]
        out.field = None if self.field is None else self.field[lo:hi]
        out.index = self.index[lo:hi]
        out.value = None if self.value is None else self.value[lo:hi]
        return out

    def rows(self) -> Iterator[Row]:
        for i in range(self.size):
            yield self[i]


class RowBlockContainer:
    """Growable CSR builder with binary save/load
    (reference src/data/row_block.h:26-205)."""

    def __init__(self, index_dtype=np.uint32):
        self.index_dtype = np.dtype(index_dtype)
        self.offset: List[int] = [0]
        self.label: List[float] = []
        self.weight: List[float] = []
        self.field: List[int] = []
        self.index: List[int] = []
        self.value: List[float] = []
        self.max_field = 0
        self.max_index = 0
        # bulk numpy staging (fast path used by the vectorized parsers)
        self._np_chunks: List[RowBlock] = []

    # -- push API (reference Push(Row) row_block.h:87, Push(RowBlock) 119) ----
    def push_row(self, label: float, index: Sequence[int],
                 value: Optional[Sequence[float]] = None,
                 weight: Optional[float] = None,
                 field: Optional[Sequence[int]] = None) -> None:
        self.label.append(float(label))
        if weight is not None:
            self.weight.append(float(weight))
        self.index.extend(int(i) for i in index)
        if index:
            self.max_index = max(self.max_index, max(int(i) for i in index))
        if value is not None:
            self.value.extend(float(v) for v in value)
        if field is not None:
            self.field.extend(int(f) for f in field)
            if field:
                self.max_field = max(self.max_field, max(int(f) for f in field))
        self.offset.append(self.offset[-1] + len(index))

    def push_block(self, block: RowBlock) -> None:
        """Append a whole RowBlock (bulk, numpy-speed)."""
        self._np_chunks.append(block)

    @property
    def size(self) -> int:
        return len(self.offset) - 1 + sum(b.size for b in self._np_chunks)

    def clear(self) -> None:
        self.__init__(self.index_dtype)

    # -- materialize ----------------------------------------------------------
    def get_block(self) -> RowBlock:
        """Materialize as an immutable RowBlock (reference GetBlock, 162-180)."""
        blocks: List[RowBlock] = []
        if len(self.offset) > 1:
            blocks.append(RowBlock(
                np.asarray(self.offset, dtype=np.int64),
                np.asarray(self.label, dtype=real_t),
                np.asarray(self.index, dtype=self.index_dtype),
                np.asarray(self.value, dtype=real_t) if self.value else None,
                np.asarray(self.weight, dtype=real_t) if self.weight else None,
                np.asarray(self.field, dtype=self.index_dtype) if self.field else None,
            ))
        blocks.extend(self._np_chunks)
        if not blocks:
            return RowBlock(np.zeros(1, np.int64), np.zeros(0, real_t),
                            np.zeros(0, self.index_dtype))
        if len(blocks) == 1:
            return blocks[0]
        return concat_blocks(blocks)

    # -- binary IO (reference Save/Load, row_block.h:181-205) -----------------
    def save(self, stream: Stream) -> None:
        block = self.get_block()
        nnz = block.num_nonzero
        stream.write_array(np.asarray(block.offset - block.offset[0], dtype=np.uint64))
        stream.write_array(block.label)
        stream.write_array(block.weight if block.weight is not None
                           else np.zeros(0, real_t))
        stream.write_array(block.field if block.field is not None
                           else np.zeros(0, self.index_dtype))
        stream.write_array(np.asarray(block.index, dtype=self.index_dtype))
        stream.write_array(block.value if block.value is not None
                           else np.zeros(0, real_t))
        max_field = self.max_field or (int(block.field.max()) if
                                       (block.field is not None and nnz) else 0)
        max_index = self.max_index or (int(block.index.max()) if nnz else 0)
        stream.write(np.asarray([max_field, max_index], dtype=self.index_dtype).tobytes())

    def load(self, stream: Stream) -> bool:
        """Load one container; False at end of stream (reference Load)."""
        probe = stream.read(8)
        if len(probe) == 0:
            return False
        CHECK_EQ(len(probe), 8, "bad RowBlock format")
        n_offset = int(np.frombuffer(probe, dtype="<u8")[0])
        offset = np.frombuffer(stream.read_exact(8 * n_offset), dtype="<u8")
        label = stream.read_array(real_t)
        weight = stream.read_array(real_t)
        field = stream.read_array(self.index_dtype)
        index = stream.read_array(self.index_dtype)
        value = stream.read_array(real_t)
        tail = np.frombuffer(stream.read_exact(2 * self.index_dtype.itemsize),
                             dtype=self.index_dtype)
        self.clear()
        self._np_chunks = [RowBlock(
            offset.astype(np.int64), label, index,
            value if value.size else None,
            weight if weight.size else None,
            field if field.size else None,
        )]
        self.max_field, self.max_index = int(tail[0]), int(tail[1])
        return True


def concat_blocks(blocks: List[RowBlock]) -> RowBlock:
    """Concatenate RowBlocks into one (bulk path of Push(RowBlock))."""
    CHECK(len(blocks) > 0, "concat_blocks needs at least one block")
    offsets = [np.asarray(b.offset, dtype=np.int64) - int(b.offset[0]) for b in blocks]
    shifts = np.cumsum([0] + [int(o[-1]) for o in offsets[:-1]])
    offset = np.concatenate(
        [offsets[0]] + [o[1:] + s for o, s in zip(offsets[1:], shifts[1:])])
    label = np.concatenate([b.label for b in blocks])
    index = np.concatenate([b.index for b in blocks])
    any_value = any(b.value is not None for b in blocks)
    any_weight = any(b.weight is not None for b in blocks)
    any_field = any(b.field is not None for b in blocks)
    value = np.concatenate(
        [b.value if b.value is not None else np.ones(b.num_nonzero, real_t)
         for b in blocks]) if any_value else None
    weight = np.concatenate(
        [b.weight if b.weight is not None else np.ones(b.size, real_t)
         for b in blocks]) if any_weight else None
    field = np.concatenate(
        [b.field if b.field is not None else np.zeros(b.num_nonzero, b.index.dtype)
         for b in blocks]) if any_field else None
    return RowBlock(offset, label, index, value, weight, field)
