"""Zero-copy columnar ingest: Arrow/Parquet buffers -> RowBlock, no parse stage.

The text parsers are the repo's ingest front door (SURVEY §2.6), but
production feature stores speak columnar.  This module is the second front
door: Arrow columnar buffers map *directly* onto the ``row_block.py``
COLUMN_ORDER layout — ``np.frombuffer`` views over the Arrow data buffers,
no tokenize, no strtonum, no per-row loop anywhere — and the resulting
RowBlocks flow into everything downstream unchanged (BasicRowIter,
DiskRowIter's v2 page-cache build + ``publish_cache``, ``fit_binner``,
DeviceFeedLoader).

Two container formats are served by one mapping (:func:`table_to_block`):

- **Parquet** (:class:`ParquetParser`): row groups decode into Arrow
  buffers at C++ speed (pages are def/rep-level encoded — that decode is
  the format's price), then map as views.  The interchange format.
- **Arrow IPC / feather v2** (:class:`ArrowIPCParser`): the Arrow memory
  layout on disk — a local file memory-maps and record batches serve as
  views over the mapping with *no decode stage at all*, the columnar
  analog of the v2 page cache's epoch>=2 replay.  The speed format.

Two schemas are understood, mirroring the two text formats:

- **sparse** (libsvm/libfm-equivalent): a ``label`` float32 column plus an
  ``index`` list column (element dtype == the cache index dtype), with
  optional ``value`` (list<float32>), ``weight`` (float32) and ``field``
  (list, element dtype == index dtype) columns.  List *offsets* become the
  CSR row pointers and list *values* become the CSR columns — with
  ``large_list`` (64-bit offsets) every column is a pure buffer view.
- **dense** (csv-equivalent): every non-label column is a float32 feature;
  ``label_column`` selects the label positionally (CSV semantics; a column
  literally named ``label`` is used when ``label_column`` is not given).
  Feature indices are renumbered sequentially, exactly like the CSV
  parser, so the output is byte-identical to the text parse of the same
  logical data.

**Zero-copy accounting is explicit, never silent.**  Every materialized
column increments ``dmlc_ingest_columns_total`` labeled ``mode=zero_copy``
(a numpy view aliasing the Arrow buffer) or ``mode=bulk_copy`` (one
vectorized materialization: 32->64-bit list-offset widening, null fill,
multi-chunk concat, or the dense row-major interleave — CSR is row-major
by definition, so a dense columnar source always pays that one transform).
There is no per-row fallback path at all: schema or dtype drift (a float64
value column, an index list not matching the requested index dtype) raises
:class:`ArrowIngestError` naming the column, because a silent cast would
break the byte-identity contract with the text parsers.  Setting
``DMLC_ARROW_REQUIRE_ZERO_COPY=1`` escalates any ``bulk_copy`` to an error
— the engagement gate ``bench_pipeline.py columnar-ab`` (and CI) runs
under.

pyarrow is optional, gated like the HDFS backend: absent pyarrow, parser
construction raises one clear error and nothing else in the package is
affected.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from dmlc_core_tpu import telemetry
from dmlc_core_tpu.data.parser import Parser
from dmlc_core_tpu.data.row_block import RowBlock
from dmlc_core_tpu.param import get_env

try:  # the HDFS gating pattern: import errors surface at USE, not import
    import pyarrow as pa
    import pyarrow.parquet as pq
    _PYARROW_ERROR: Optional[BaseException] = None
except Exception as _exc:  # pragma: no cover - exercised via monkeypatch
    pa = None  # type: ignore[assignment]
    pq = None  # type: ignore[assignment]
    _PYARROW_ERROR = _exc

__all__ = ["ArrowIngestError", "ParquetParser", "ArrowIPCParser",
           "table_to_block", "pyarrow_available", "require_pyarrow"]


class ArrowIngestError(ValueError):
    """Schema/dtype drift between an Arrow source and the RowBlock layout.

    Raised instead of silently casting: the differential contract is that
    columnar ingest of a dataset is byte-identical to the text parse of
    the same logical data, and a quiet float64->float32 or int64->uint32
    narrowing would fork the two front doors' semantics."""


def pyarrow_available() -> bool:
    return pa is not None


def require_pyarrow() -> None:
    """Raise the one clear gating error when pyarrow is missing."""
    if pa is None:
        raise RuntimeError(
            "parquet/arrow ingest requires pyarrow (optional dependency, "
            "same gating as hdfs://): install pyarrow, or keep using the "
            f"text formats — import failed with: {_PYARROW_ERROR!r}")


def _require_zero_copy() -> bool:
    return get_env("DMLC_ARROW_REQUIRE_ZERO_COPY", bool, False)


class _CopyLedger:
    """Per-block zero-copy accounting: which columns were buffer views and
    which had to be materialized (and why).  The counters are the
    engagement gate's ground truth — a copy can regress loudly, never
    silently."""

    def __init__(self, ctx: str):
        self.ctx = ctx
        self.zero_copy = 0
        self.bulk_copy = 0
        self.bulk_reasons: List[str] = []

    def view(self, column: str) -> None:
        self.zero_copy += 1
        telemetry.count("dmlc_ingest_columns_total", mode="zero_copy")

    def bulk(self, column: str, why: str) -> None:
        if _require_zero_copy():
            raise ArrowIngestError(
                f"{self.ctx}: column {column!r} requires a bulk copy "
                f"({why}) and DMLC_ARROW_REQUIRE_ZERO_COPY is set")
        self.bulk_copy += 1
        self.bulk_reasons.append(f"{column}: {why}")
        telemetry.count("dmlc_ingest_columns_total", mode="bulk_copy")


def _np_dtype(pa_type) -> Optional[np.dtype]:
    try:
        return np.dtype(pa_type.to_pandas_dtype())
    except (NotImplementedError, TypeError):
        return None


def _one_chunk(chunked, column: str, ledger: _CopyLedger):
    """ChunkedArray -> Array; >1 chunk costs one combine (bulk, counted)."""
    if chunked.num_chunks == 1:
        return chunked.chunk(0)
    ledger.bulk(column, f"{chunked.num_chunks} chunks combined")
    return chunked.combine_chunks()


def _primitive_view(arr, want: np.dtype, column: str, ctx: str,
                    ledger: _CopyLedger, missing: Optional[float] = None
                    ) -> np.ndarray:
    """A primitive Arrow array as a numpy view of its data buffer.

    Exact-dtype only (drift raises).  Nulls are rejected unless ``missing``
    is given, in which case they are filled (one vectorized pass, counted
    as a bulk copy).  The returned view is read-only — same discipline as
    the page cache's mmap views."""
    have = _np_dtype(arr.type)
    if have is None or have != want:
        raise ArrowIngestError(
            f"{ctx}: dtype drift on column {column!r}: stored "
            f"{arr.type}, RowBlock layout needs {want.name} — cast at "
            "write time; columnar ingest never casts silently")
    filled = False
    if arr.null_count:
        if missing is None:
            raise ArrowIngestError(
                f"{ctx}: column {column!r} has {arr.null_count} null(s); "
                "only dense feature columns accept nulls (filled with the "
                "?missing= value)")
        ledger.bulk(column, f"{arr.null_count} nulls filled with {missing}")
        arr = arr.fill_null(missing)
        filled = True
    buf = arr.buffers()[1]
    view = np.frombuffer(buf, dtype=want, count=len(arr) + arr.offset
                         )[arr.offset:]
    view.flags.writeable = False
    if not filled:
        ledger.view(column)
    return view


def _list_parts(arr, column: str, ctx: str, ledger: _CopyLedger):
    """A (large_)list array -> (int64 CSR offsets, flat child array).

    ``large_list`` offsets are an int64 buffer view; plain ``list``
    (32-bit offsets) costs one widening pass, counted as a bulk copy —
    store ``large_list`` for the pure-view path."""
    if pa.types.is_large_list(arr.type):
        off_dtype = np.dtype(np.int64)
    elif pa.types.is_list(arr.type):
        off_dtype = np.dtype(np.int32)
    else:
        raise ArrowIngestError(
            f"{ctx}: column {column!r} must be a list/large_list, "
            f"stored {arr.type}")
    if arr.null_count:
        raise ArrowIngestError(
            f"{ctx}: sparse column {column!r} has {arr.null_count} "
            "null row(s); write empty lists for empty rows")
    raw = np.frombuffer(arr.buffers()[1], dtype=off_dtype,
                        count=len(arr) + 1 + arr.offset)[arr.offset:]
    if off_dtype == np.dtype(np.int64):
        offsets = raw
        offsets.flags.writeable = False
        ledger.view(f"{column}.offsets")
    else:
        ledger.bulk(f"{column}.offsets",
                    "32-bit list offsets widened to CSR int64 "
                    "(store large_list for the pure-view path)")
        offsets = raw.astype(np.int64)
    return offsets, arr.values


def _list_values_view(arr, offsets: np.ndarray, want: np.dtype, column: str,
                      ctx: str, ledger: _CopyLedger) -> np.ndarray:
    """The child-values span ``[offsets[0], offsets[-1])`` as a view."""
    values = _primitive_view(arr, want, f"{column}.values", ctx, ledger)
    return values[int(offsets[0]):int(offsets[-1])]


def _sparse_block(table, index_dtype: np.dtype, ctx: str,
                  ledger: _CopyLedger) -> RowBlock:
    names = table.column_names
    if "label" not in names:
        raise ArrowIngestError(
            f"{ctx}: sparse schema requires a 'label' column "
            f"(have {names})")
    label = _primitive_view(_one_chunk(table.column("label"), "label",
                                       ledger),
                            np.dtype(np.float32), "label", ctx, ledger)
    index_arr = _one_chunk(table.column("index"), "index", ledger)
    offsets, index_child = _list_parts(index_arr, "index", ctx, ledger)
    index = _list_values_view(index_child, offsets, index_dtype, "index",
                              ctx, ledger)

    def aligned_list(column: str, want: np.dtype) -> np.ndarray:
        arr = _one_chunk(table.column(column), column, ledger)
        col_offsets, child = _list_parts(arr, column, ctx, ledger)
        if not np.array_equal(offsets, col_offsets):
            raise ArrowIngestError(
                f"{ctx}: column {column!r} row lengths disagree with "
                "'index' — every sparse list column must have the same "
                "per-row element counts")
        return _list_values_view(child, col_offsets, want, column, ctx,
                                 ledger)

    value = (aligned_list("value", np.dtype(np.float32))
             if "value" in names else None)
    field = aligned_list("field", index_dtype) if "field" in names else None
    weight = (_primitive_view(_one_chunk(table.column("weight"), "weight",
                                         ledger),
                              np.dtype(np.float32), "weight", ctx, ledger)
              if "weight" in names else None)
    return RowBlock(offsets, label, index, value, weight, field)


def _dense_block(table, index_dtype: np.dtype, label_column: int,
                 missing: float, ctx: str, ledger: _CopyLedger) -> RowBlock:
    names = table.column_names
    ncol = len(names)
    if 0 <= label_column < ncol:
        label_name = names[label_column]
    elif label_column < 0 and "label" in names:
        label_name = "label"
    else:
        label_name = None
    float32 = np.dtype(np.float32)
    nrow = table.num_rows
    if label_name is not None:
        label = _primitive_view(_one_chunk(table.column(label_name),
                                           label_name, ledger),
                                float32, label_name, ctx, ledger)
    else:
        label = np.zeros(nrow, dtype=float32)
    cols = [_primitive_view(_one_chunk(table.column(name), name, ledger),
                            float32, name, ctx, ledger, missing=missing)
            for name in names if name != label_name]
    nfeat = len(cols)
    if nfeat == 0:
        raise ArrowIngestError(f"{ctx}: dense schema has no feature columns")
    # CSR is row-major by definition: a dense columnar source always pays
    # exactly this one vectorized interleave (documented caveat; use the
    # sparse list schema for the pure-view path)
    ledger.bulk("<features>", f"dense row-major interleave of {nfeat} "
                "float32 columns into the CSR value array")
    value = np.stack(cols, axis=1).reshape(-1)
    index = np.tile(np.arange(nfeat, dtype=index_dtype), nrow)
    offset = np.arange(nrow + 1, dtype=np.int64) * nfeat
    return RowBlock(offset, label, index, value)


def table_to_block(table, index_dtype=np.uint32, label_column: int = -1,
                   missing: float = 0.0, ctx: str = "arrow",
                   ) -> Tuple[Optional[RowBlock], Dict[str, object]]:
    """Map one Arrow table onto a RowBlock without a parse stage.

    Schema is detected from the columns: any list-typed column selects the
    sparse (libsvm-shaped) mapping, otherwise every non-label float32
    column is a dense feature (CSV-shaped).  Returns ``(block, stats)``;
    ``block`` is None for an empty table (empty row groups are legal and
    skipped).  ``stats`` carries the zero-copy ledger for this block.
    """
    require_pyarrow()
    ledger = _CopyLedger(ctx)
    if table.num_rows == 0:
        return None, {"rows": 0, "nbytes": 0, "zero_copy_columns": 0,
                      "bulk_copy_columns": 0, "bulk_copy_reasons": []}
    if any(pa.types.is_list(f.type) or pa.types.is_large_list(f.type)
           for f in table.schema):
        if "index" not in table.column_names:
            raise ArrowIngestError(
                f"{ctx}: list-typed columns present but no 'index' column "
                "— the sparse schema is label + index[, value, weight, "
                f"field] (have {table.column_names})")
        block = _sparse_block(table, np.dtype(index_dtype), ctx, ledger)
    else:
        block = _dense_block(table, np.dtype(index_dtype), label_column,
                             missing, ctx, ledger)
    nbytes = sum(int(col.nbytes) for col in
                 (block.offset, block.label, block.weight, block.field,
                  block.index, block.value) if col is not None)
    return block, {"rows": block.size, "nbytes": nbytes,
                   "zero_copy_columns": ledger.zero_copy,
                   "bulk_copy_columns": ledger.bulk_copy,
                   "bulk_copy_reasons": ledger.bulk_reasons}


class _ColumnarParserBase(Parser):
    """Shared machinery for the columnar front doors.

    A columnar file is a footer-indexed sequence of *units* (Parquet row
    groups / Arrow IPC record batches); both formats shard by unit: part
    ``k`` of ``n`` reads units ``k, k+n, k+2n, …`` — deterministic,
    exactly-once coverage, no byte-range realignment because units are
    the format's own split points.  Local files are memory-mapped; remote
    URIs ride :class:`~dmlc_core_tpu.io.ranged_read.RangedReadFile` — the
    footer and only the assigned units are ranged-read, the same
    open-by-footer discipline as the remote page cache.

    Construction is cheap and IO-free apart from the pyarrow gate; the
    file opens lazily on first use, so a warm page-cache run through
    ``DiskRowIter`` never pays footer traffic.
    """

    format_name = "?"

    def __init__(self, uri: str, args=None, part_index: int = 0,
                 num_parts: int = 1, index_dtype=np.uint32):
        require_pyarrow()
        args = dict(args or {})
        self._uri = uri
        self._index_dtype = np.dtype(index_dtype)
        self._label_column = int(args.get("label_column", -1))
        self._missing = float(args.get("missing", 0.0))
        self._part_index = part_index
        self._num_parts = max(1, num_parts)
        self._ranged = None
        self._opened = False
        self._units: List[int] = []
        self._pos = 0
        self._bytes_read = 0

    # -- per-format hooks -----------------------------------------------------
    def _open_local(self, path: str) -> int:
        """Open a local path (memory-mapped); return the unit count."""
        raise NotImplementedError

    def _open_file(self, fileobj) -> int:
        """Open a remote file-like (ranged reads); return the unit count."""
        raise NotImplementedError

    def _read_unit(self, unit: int):
        """One unit as an Arrow table."""
        raise NotImplementedError

    def _close_impl(self) -> None:
        raise NotImplementedError

    # -- Parser protocol ------------------------------------------------------
    def _open(self) -> None:
        if self._opened:
            return
        uri = self._uri
        with telemetry.span("ingest.arrow", uri=uri,
                            format=self.format_name) as sp:
            if "://" in uri and not uri.startswith("file://"):
                from dmlc_core_tpu.io.ranged_read import RangedReadFile

                self._ranged = RangedReadFile(uri)
                try:
                    nunits = self._open_file(self._ranged)
                except BaseException:
                    # a bad footer must not orphan the open FS stream: the
                    # caller never gets the instance state to close()
                    ranged, self._ranged = self._ranged, None
                    ranged.close()
                    raise
            else:
                path = uri[7:] if uri.startswith("file://") else uri
                nunits = self._open_local(path)
            self._units = [u for u in range(nunits)
                           if u % self._num_parts == self._part_index]
            sp.set(units=len(self._units))
        self._opened = True
        self._pos = 0

    def before_first(self) -> None:
        self._open()
        self._pos = 0

    def next(self) -> Optional[RowBlock]:
        self._open()
        while self._pos < len(self._units):
            unit = self._units[self._pos]
            self._pos += 1
            with telemetry.span("ingest.arrow.block", unit=unit,
                                format=self.format_name) as sp:
                table = self._read_unit(unit)
                block, stats = table_to_block(
                    table, self._index_dtype, self._label_column,
                    self._missing,
                    ctx=f"{self._uri} {self.format_name} unit {unit}")
                sp.set(rows=stats["rows"], nbytes=stats["nbytes"])
            self._bytes_read += int(stats["nbytes"])
            if telemetry.enabled() and stats["rows"]:
                telemetry.count("dmlc_ingest_rows_total", stats["rows"],
                                format=self.format_name)
                telemetry.count("dmlc_ingest_bytes_total", stats["nbytes"],
                                format=self.format_name)
            if block is not None:
                return block
        return None

    def bytes_read(self) -> int:
        return self._bytes_read

    def close(self) -> None:
        try:
            self._close_impl()
        finally:
            self._opened = False
            if self._ranged is not None:
                self._ranged.close()
                self._ranged = None


class ParquetParser(_ColumnarParserBase):
    """Parser over Parquet row groups: columnar in, RowBlock views out.

    Parquet pages are *encoded* (def/rep levels, optional codec), so the
    read decodes into fresh Arrow buffers at C++ speed — still no text
    parse anywhere — and the Arrow->RowBlock boundary maps those buffers
    as views.  For the pure end-to-end mmap path use the Arrow IPC format
    (:class:`ArrowIPCParser`)."""

    format_name = "parquet"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._pf = None

    def _open_local(self, path: str) -> int:
        self._pf = pq.ParquetFile(path, memory_map=True)
        return self._pf.num_row_groups

    def _open_file(self, fileobj) -> int:
        self._pf = pq.ParquetFile(fileobj)
        return self._pf.num_row_groups

    def _read_unit(self, unit: int):
        return self._pf.read_row_group(unit)

    def _close_impl(self) -> None:
        if self._pf is not None:
            try:
                self._pf.close()
            finally:
                self._pf = None


class ArrowIPCParser(_ColumnarParserBase):
    """Parser over Arrow IPC (feather v2) record batches.

    IPC *is* the Arrow memory layout on disk: a local file memory-maps and
    every batch is served as views over the mapping — no decode stage at
    all, the columnar analog of the v2 page cache's epoch>=2 replay.  A
    remote URI ranged-reads the footer and the assigned batches."""

    format_name = "arrow"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._reader = None
        self._mm = None

    def _open_local(self, path: str) -> int:
        self._mm = pa.memory_map(path)
        try:
            self._reader = pa.ipc.open_file(self._mm)
        except BaseException:
            mm, self._mm = self._mm, None
            mm.close()
            raise
        return self._reader.num_record_batches

    def _open_file(self, fileobj) -> int:
        self._reader = pa.ipc.open_file(fileobj)
        return self._reader.num_record_batches

    def _read_unit(self, unit: int):
        return pa.Table.from_batches([self._reader.get_batch(unit)])

    def _close_impl(self) -> None:
        self._reader = None
        if self._mm is not None:
            try:
                self._mm.close()
            except BaseException:
                # live RowBlock views pin the mapping; pyarrow refuses to
                # unmap under exported buffers — GC reclaims it later,
                # exactly like PageCacheReader.close under live views
                pass
            self._mm = None
