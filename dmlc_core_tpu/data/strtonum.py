"""Scalar string->number helpers (reference src/data/strtonum.h:37-300).

The bulk path is vectorized in :mod:`dmlc_core_tpu.data.text_np`; these scalar
helpers exist for API parity (ParsePair/ParseTriple are the token grammar of
the libsvm/libfm formats) and for host-side config parsing.
"""

from __future__ import annotations

from typing import Optional, Tuple

__all__ = ["str2float", "str2int", "parse_pair", "parse_triple"]


def str2float(s: bytes | str) -> float:
    """strtof equivalent (strtonum.h:37-101)."""
    if isinstance(s, bytes):
        s = s.decode("ascii")
    return float(s)


def str2int(s: bytes | str, base: int = 10) -> int:
    """strtoint/strtouint equivalent (strtonum.h:103-150)."""
    if isinstance(s, bytes):
        s = s.decode("ascii")
    return int(s, base)


def _tok(s: str) -> list:
    return s.replace(":", " ").split()


def parse_pair(token: bytes | str) -> Tuple[int, Optional[float], Optional[float]]:
    """Parse ``a[:b]``; returns (num_parsed, a, b) (reference ParsePair,
    strtonum.h:227-264)."""
    if isinstance(token, bytes):
        token = token.decode("ascii")
    parts = _tok(token)
    if not parts:
        return 0, None, None
    if len(parts) == 1:
        return 1, float(parts[0]), None
    return 2, float(parts[0]), float(parts[1])


def parse_triple(token: bytes | str) -> Tuple[int, Optional[float], Optional[float], Optional[float]]:
    """Parse ``a[:b[:c]]`` (reference ParseTriple, strtonum.h:265-300)."""
    if isinstance(token, bytes):
        token = token.decode("ascii")
    parts = _tok(token)
    out = [None, None, None]
    for i, p in enumerate(parts[:3]):
        out[i] = float(p)
    return min(len(parts), 3), out[0], out[1], out[2]
