"""Dense CSV parser.

Capability parity with the reference (src/data/csv_parser.h:22-102):
- every column is a dense float feature; feature indices are renumbered
  sequentially over non-label columns (csv_parser.h:78-92);
- ``label_column`` (from URI args, e.g. ``data.csv?format=csv&label_column=0``)
  selects the label; default -1 means label 0.0 for every row;
- empty lines are skipped.
"""

from __future__ import annotations

import numpy as np

from dmlc_core_tpu.data.parser import TextParserBase
from dmlc_core_tpu.data.row_block import RowBlock, RowBlockContainer
from dmlc_core_tpu.param import Parameter, field
from dmlc_core_tpu.utils.logging import CHECK, CHECK_EQ

__all__ = ["CSVParser", "CSVParserParam"]


class CSVParserParam(Parameter):
    """Reference CSVParserParam (csv_parser.h:22-32)."""

    format = field(str, default="csv", help="File format.")
    label_column = field(int, default=-1,
                         help="Column index that will be put into the label.")
    missing = field(float, default=0.0,
                    help="Value for empty cells. 0.0 matches the reference "
                         "(its strtof parses an empty field as zero, "
                         "csv_parser.h:83); pass nan (?missing=nan) to mark "
                         "them missing for sparsity-aware GBDT training.")


class CSVParser(TextParserBase):
    def __init__(self, source, args=None, nthread: int = 2, index_dtype=np.uint32):
        super().__init__(source, nthread)
        self._index_dtype = np.dtype(index_dtype)
        self.param = CSVParserParam()
        self.param.init(dict(args or {}), allow_unknown=True)
        CHECK_EQ(self.param.format, "csv")

    def _proc_spec(self):
        # the process-backend workers rebuild this parser source-less; the
        # CSV params ride along as URI-style strings (parse_proc)
        module, qualname, kwargs = super()._proc_spec()
        kwargs["args"] = {"format": "csv",
                          "label_column": str(self.param.label_column),
                          "missing": repr(self.param.missing)}
        return module, qualname, kwargs

    def parse_chunk_native(self, data: bytes):
        from dmlc_core_tpu import native_bridge

        if not native_bridge.available():
            return None
        parsed = native_bridge.parse_csv(data, nthread=max(self._nthread, 2),
                                         missing=self.param.missing,
                                         label_column=self.param.label_column)
        if isinstance(parsed, tuple):
            # native one-pass label split: no np.delete copy on this side
            labels, feats = parsed
            return self._assemble(labels, feats)
        return self._from_dense(parsed)

    def _from_dense(self, dense: np.ndarray) -> RowBlockContainer:
        nrow, ncol = dense.shape
        if nrow == 0:
            return RowBlockContainer(self._index_dtype)
        lc = self.param.label_column
        if 0 <= lc < ncol:
            labels = dense[:, lc].copy()
            feats = np.delete(dense, lc, axis=1)
        else:
            labels = np.zeros(nrow, dtype=np.float32)
            feats = dense
        return self._assemble(labels, feats)

    def _assemble(self, labels: np.ndarray,
                  feats: np.ndarray) -> RowBlockContainer:
        out = RowBlockContainer(self._index_dtype)
        nrow, nfeat = feats.shape
        if nrow == 0:
            return out
        index = np.tile(np.arange(nfeat, dtype=self._index_dtype), nrow)
        offset = np.arange(nrow + 1, dtype=np.int64) * nfeat
        out.push_block(RowBlock(offset, labels, index, feats.reshape(-1)))
        out.max_index = max(nfeat - 1, 0)
        return out

    def parse_block(self, data: bytes) -> RowBlockContainer:
        out = RowBlockContainer(self._index_dtype)
        rows = [r for r in data.splitlines() if r.strip()]
        if not rows:
            return out
        ncol = rows[0].count(b",") + 1
        flat = b",".join(rows).split(b",")
        CHECK_EQ(len(flat), len(rows) * ncol,
                 "CSV rows have inconsistent column counts")
        # empty cells take the configured missing value (reference parity:
        # its strtof parses an empty field as 0.0, csv_parser.h:83)
        fill = repr(float(self.param.missing)).encode()
        flat = [c if c.strip() else fill for c in flat]
        try:
            dense = np.array(flat).astype(np.float32).reshape(len(rows), ncol)
        except ValueError as exc:
            raise ValueError(f"invalid CSV number: {exc}") from None

        lc = self.param.label_column
        if 0 <= lc < ncol:
            labels = dense[:, lc]
            feats = np.delete(dense, lc, axis=1)
        else:
            labels = np.zeros(len(rows), dtype=np.float32)
            feats = dense
        return self._assemble(labels, feats)
