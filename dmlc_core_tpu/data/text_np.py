"""Shared numpy-vectorized text tokenization for the parsers.

The reference's hot loop is hand-rolled char scanning + ``strtof``
(src/data/strtonum.h:37-300).  The Python-side equivalent vectorizes at the
chunk level: C-speed ``bytes.split`` tokenization, one numpy ``S``-dtype array
per chunk, and bulk ``astype`` float/int conversion (numpy's C parser).  The
optional native core (dmlc_core_tpu/native) replaces this wholesale.
"""

from __future__ import annotations

from itertools import chain
from typing import List, Tuple

import numpy as np

from dmlc_core_tpu.utils.logging import CHECK

__all__ = ["tokenize_ws", "split_tokens_at_colon"]


def tokenize_ws(data: bytes) -> Tuple[np.ndarray, np.ndarray]:
    """Whitespace-tokenize all non-empty lines of `data`.

    Returns ``(tokens, counts)``: a 1-d S-dtype array of every token in order,
    and the per-line token counts (empty lines dropped — the reference skips
    them, libsvm_parser.h:53-57).
    """
    tok_lists: List[list] = [l.split() for l in data.splitlines()]
    tok_lists = [t for t in tok_lists if t]
    if not tok_lists:
        return np.empty(0, dtype="S1"), np.empty(0, dtype=np.int64)
    counts = np.fromiter((len(t) for t in tok_lists), np.int64, len(tok_lists))
    flat = list(chain.from_iterable(tok_lists))
    return np.array(flat), counts


def split_tokens_at_colon(tokens: np.ndarray):
    """Partition each token at its first ``:``.

    Returns ``(head, has_colon, tail)`` where ``head``/``tail`` are S-dtype
    arrays (tail is b"" when no colon).
    """
    if tokens.size == 0:
        empty = np.empty(0, dtype="S1")
        return empty, np.empty(0, dtype=bool), empty
    part = np.char.partition(tokens, b":")
    return part[:, 0], part[:, 1] == b":", part[:, 2]


def parse_floats(tokens: np.ndarray, what: str) -> np.ndarray:
    """Bulk str->float32 (the strtof analog); raises with context on garbage."""
    try:
        return tokens.astype(np.float32)
    except ValueError as exc:
        raise ValueError(f"invalid {what} in input: {exc}") from None


def parse_ints(tokens: np.ndarray, dtype, what: str) -> np.ndarray:
    """Bulk str->integer index (the strtoint analog)."""
    try:
        # S->int via float is lossy for huge ids; go through int64 directly
        return tokens.astype(np.int64).astype(dtype)
    except ValueError as exc:
        raise ValueError(f"invalid {what} in input: {exc}") from None
