"""Shared numpy-vectorized text tokenization for the parsers.

The reference's hot loop is hand-rolled char scanning + ``strtof``
(src/data/strtonum.h:37-300).  The Python-side equivalent vectorizes at the
chunk level with **whole-chunk byte arrays** — no per-line Python loop:

- one 256-entry class-table lookup marks whitespace/newline bytes;
- token boundaries come from shifted-mask comparisons (a token starts at a
  non-ws byte whose predecessor is ws), so start/end/length vectors for the
  whole chunk cost three O(n) passes in C;
- the token matrix is built with a single fancy-indexed gather into an
  ``S``-dtype array (numpy's C parser then bulk-converts via ``astype``);
- per-line token counts come from counting newline bytes before each token
  start — empty lines drop out for free (no token starts inside them).

The optional native core (dmlc_core_tpu/native) replaces this wholesale.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from dmlc_core_tpu.utils.logging import CHECK, CHECK_EQ

__all__ = ["tokenize_ws", "split_tokens_at_colon"]

# byte-class tables: bytes.split() whitespace (space \t \n \r \v \f) and the
# line separators bytes.splitlines() honors (\r, \n; \r\n collapses for free
# because grouping only compares newline *counts* for inequality)
_WS_TABLE = np.zeros(256, dtype=bool)
_WS_TABLE[[9, 10, 11, 12, 13, 32]] = True
_NL_TABLE = np.zeros(256, dtype=bool)
_NL_TABLE[[10, 13]] = True

# widest token the gather path will build a dense [n, w] matrix for; a chunk
# with a longer "token" (binary garbage, an unbroken line) falls back to the
# list path, which handles any width at bytes.split() speed
_MAX_GATHER_WIDTH = 256

_S1_EMPTY = np.empty(0, dtype="S1")
_I64_EMPTY = np.empty(0, dtype=np.int64)


def _line_counts(start_pos: np.ndarray, nl_pos: np.ndarray) -> np.ndarray:
    """Per-line token counts: two tokens share a line iff no newline byte
    sits between their start offsets (searchsorted over the newline
    positions — O(n log L), far cheaper than a full-chunk cumsum)."""
    line_of = np.searchsorted(nl_pos, start_pos)
    new_line = np.empty(len(start_pos), dtype=bool)
    new_line[0] = True
    np.not_equal(line_of[1:], line_of[:-1], out=new_line[1:])
    group_starts = np.flatnonzero(new_line)
    counts = np.empty(len(group_starts), dtype=np.int64)
    counts[:-1] = np.diff(group_starts)
    counts[-1] = len(start_pos) - group_starts[-1]
    return counts


def tokenize_ws(data: bytes) -> Tuple[np.ndarray, np.ndarray]:
    """Whitespace-tokenize all non-empty lines of `data`.

    Returns ``(tokens, counts)``: a 1-d S-dtype array of every token in order,
    and the per-line token counts (empty lines dropped — the reference skips
    them, libsvm_parser.h:53-57).
    """
    if not data:
        return _S1_EMPTY, _I64_EMPTY
    arr = np.frombuffer(data, dtype=np.uint8)
    ws = _WS_TABLE[arr]
    nonws = ~ws
    starts = nonws.copy()
    starts[1:] &= ws[:-1]
    start_pos = np.flatnonzero(starts)
    if start_pos.size == 0:
        return _S1_EMPTY, _I64_EMPTY
    ends = nonws
    ends[:-1] &= ws[1:]          # nonws is dead after this: reuse in place
    end_pos = np.flatnonzero(ends)
    lengths = end_pos - start_pos + 1
    counts = _line_counts(start_pos, np.flatnonzero(_NL_TABLE[arr]))

    width = int(lengths.max())
    n = len(start_pos)
    if width > _MAX_GATHER_WIDTH or len(arr) >= 2**31:
        # pathological token (unbroken binary line): the dense gather matrix
        # would be n*width bytes — and a >=2GiB buffer would wrap the int32
        # gather offsets below.  Let C bytes.split() handle both instead
        tokens = np.array(data.split())
        CHECK_EQ(len(tokens), n, "tokenizer boundary count mismatch")
        return tokens, counts

    # gather every token into one [n, width] byte matrix in a single fancy
    # index, then reinterpret the rows as a null-padded S array — no Python
    # bytes objects are ever created.  int32 offsets: chunks are bounded by
    # the 8MB input-split buffer, and halving index memory is ~2x gather
    # throughput
    col32 = np.arange(width, dtype=np.int32)
    idx = start_pos.astype(np.int32)[:, None] + col32
    np.minimum(idx, np.int32(len(arr) - 1), out=idx)  # clamp: masked below
    mat = arr[idx]
    mat[col32 >= lengths[:, None]] = 0
    tokens = mat.reshape(-1).view(f"S{width}")
    return tokens, counts


def split_tokens_at_colon(tokens: np.ndarray):
    """Partition each token at its first ``:``.

    Returns ``(head, has_colon, tail)`` where ``head``/``tail`` are S-dtype
    arrays (tail is b"" when no colon).  Vectorized: one byte-matrix compare
    finds the first colon per token, ``head`` masks bytes at/after it, and
    ``tail`` is a clamped fancy-indexed left-shift of each row.
    """
    if tokens.size == 0:
        return _S1_EMPTY, np.empty(0, dtype=bool), _S1_EMPTY
    tokens = np.ascontiguousarray(tokens)
    width = tokens.dtype.itemsize
    if width == 0:
        return tokens, np.zeros(len(tokens), dtype=bool), tokens
    n = len(tokens)
    mat = tokens.view(np.uint8).reshape(n, width)
    is_colon = mat == 0x3A
    has_colon = is_colon.any(axis=1)
    first = np.where(has_colon, is_colon.argmax(axis=1),
                     width).astype(np.int32)

    col = np.arange(width, dtype=np.int32)
    head = np.where(col < first[:, None], mat, np.uint8(0))
    head = np.ascontiguousarray(head).reshape(-1).view(f"S{width}")

    # tail row i = mat[i, first[i]+1:] — a per-row left shift done as one
    # gather; indexes clamped to a zeros column (S padding is 0 anyway)
    padded = np.zeros((n, width + 1), dtype=np.uint8)
    padded[:, :width] = mat
    idx = first[:, None] + 1 + col
    np.minimum(idx, np.int32(width), out=idx)
    tail = padded[np.arange(n, dtype=np.int32)[:, None], idx]
    tail = np.ascontiguousarray(tail).reshape(-1).view(f"S{width}")
    return head, has_colon, tail


def parse_floats(tokens: np.ndarray, what: str) -> np.ndarray:
    """Bulk str->float32 (the strtof analog); raises with context on garbage."""
    try:
        return tokens.astype(np.float32)
    except ValueError as exc:
        raise ValueError(f"invalid {what} in input: {exc}") from None


def parse_ints(tokens: np.ndarray, dtype, what: str) -> np.ndarray:
    """Bulk str->integer index (the strtoint analog)."""
    try:
        # S->int via float is lossy for huge ids; go through int64 directly
        return tokens.astype(np.int64).astype(dtype)
    except ValueError as exc:
        raise ValueError(f"invalid {what} in input: {exc}") from None
