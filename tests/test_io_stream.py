"""Stream/filesystem tests (reference: test/iostream_test.cc, test/filesys_test.cc)."""

import pytest

from dmlc_core_tpu.io import filesys as fsys
from dmlc_core_tpu.io.stream import create_stream, create_stream_for_read
from dmlc_core_tpu.utils.logging import Error


def test_uri_parse():
    u = fsys.URI("hdfs://namenode:9000/path/to/file")
    assert u.protocol == "hdfs://"
    assert u.host == "namenode:9000"
    assert u.name == "/path/to/file"
    v = fsys.URI("/plain/path.txt")
    assert v.protocol == "file://"
    assert v.name == "/plain/path.txt"
    assert v.str() == "/plain/path.txt"
    w = fsys.URI("s3://bucket/key/a.txt")
    assert w.protocol == "s3://" and w.host == "bucket" and w.name == "/key/a.txt"


def test_local_roundtrip(tmp_path):
    path = str(tmp_path / "x.bin")
    with create_stream(path, "w") as s:
        s.write(b"hello ")
        s.write(b"world")
    with create_stream(path, "r") as s:
        assert s.read(100) == b"hello world"
    with create_stream(path, "a") as s:
        s.write(b"!")
    fo = create_stream_for_read(path)
    assert fo.read(100) == b"hello world!"
    fo.seek(6)
    assert fo.read(5) == b"world"
    assert fo.tell() == 11
    fo.close()


def test_typed_io(tmp_path):
    path = str(tmp_path / "typed.bin")
    with create_stream(path, "w") as s:
        s.write_u32(7)
        s.write_u64(1 << 40)
        s.write_f64(2.5)
        s.write_string("hello")
    with create_stream(path, "r") as s:
        assert s.read_u32() == 7
        assert s.read_u64() == 1 << 40
        assert s.read_f64() == 2.5
        assert s.read_string() == b"hello"


def test_iostream_adapter(tmp_path):
    """The reference's ostream/istream adapters (test/iostream_test.cc)."""
    path = str(tmp_path / "lines.txt")
    with create_stream(path, "w") as s:
        f = s.as_file()
        f.write(b"line one\n")
        f.write(b"line two\n")
    with create_stream(path, "r") as s:
        lines = list(s.as_file())
    assert lines == [b"line one\n", b"line two\n"]


def test_path_info_and_listing(tmp_path):
    (tmp_path / "a.txt").write_bytes(b"123")
    (tmp_path / "sub").mkdir()
    fs = fsys.LocalFileSystem()
    info = fs.get_path_info(fsys.URI(str(tmp_path / "a.txt")))
    assert info.size == 3 and info.type == fsys.FileType.FILE
    entries = fs.list_directory(fsys.URI(str(tmp_path)))
    names = {e.path.name.rsplit("/", 1)[-1]: e.type for e in entries}
    assert names["a.txt"] == fsys.FileType.FILE
    assert names["sub"] == fsys.FileType.DIRECTORY


def test_unknown_protocol_raises():
    with pytest.raises(Error, match="unknown filesystem protocol"):
        fsys.get_filesystem(fsys.URI("bogus://x/y"))


def test_allow_null(tmp_path):
    assert create_stream(str(tmp_path / "missing"), "r", allow_null=True) is None
    with pytest.raises(OSError):
        create_stream(str(tmp_path / "missing"), "r")
