"""Fleet-shared remote page cache (ISSUE 12 tentpole):

- open-by-footer over the ranged-read FS layer: one tail ranged read
  proves the remote object is a complete v2 cache before any page moves;
- publish (``DMLC_CACHE_REMOTE``): one worker stream-parses + uploads,
  the fleet fetches and mmaps locally at zero-copy speed;
- every untrustable remote shape (absent, footer-less, v1 framing, dtype
  drift, truncated/corrupt page, mid-fetch faults) falls back to
  stream-parsing with the right metric — a bad page is never served;
- concurrent materialization from two processes is safe (atomic rename).
"""

import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

from dmlc_core_tpu import fault, telemetry
from dmlc_core_tpu.data import page_cache
from dmlc_core_tpu.data.factory import create_parser, create_row_block_iter
from dmlc_core_tpu.data.iterators import DiskRowIter, _remote_cache_config
from dmlc_core_tpu.data.page_cache import CacheFormatError
from dmlc_core_tpu.data.row_block import RowBlockContainer
from dmlc_core_tpu.io.stream import create_stream
from tests.mock_s3 import MockS3

ROWS = 3000


@pytest.fixture()
def mock_s3(monkeypatch, tmp_path):
    server = MockS3().start()
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "test-key")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "test-secret")
    monkeypatch.setenv("AWS_REGION", "us-east-1")
    monkeypatch.setenv("S3_ENDPOINT", f"http://127.0.0.1:{server.port}")
    monkeypatch.setenv("DMLC_CACHE_LOCAL_DIR", str(tmp_path / "materialized"))
    monkeypatch.delenv("DMLC_CACHE_REMOTE", raising=False)
    yield server
    server.stop()


def _corpus(tmp_path, rows=ROWS):
    rng = np.random.RandomState(3)
    lines = []
    for i in range(rows):
        feats = sorted(rng.choice(40, size=rng.randint(1, 6), replace=False))
        lines.append(f"{i % 2} " + " ".join(f"{j}:{rng.rand():.4f}"
                                            for j in feats))
    path = tmp_path / "data.libsvm"
    path.write_text("\n".join(lines) + "\n")
    return str(path)


def _counter(name, **labels):
    return telemetry.get_registry().counter(name, **labels)


def _publish_seed_cache(mock_s3, tmp_path, uri, remote):
    """One 'first worker': parse + publish the v2 cache to ``remote``."""
    it = create_row_block_iter(f"{uri}#{remote}", type="libsvm")
    rows = sum(b.size for b in it)
    it.close()
    assert rows == ROWS
    return rows


def _wipe_local(tmp_path):
    shutil.rmtree(str(tmp_path / "materialized"), ignore_errors=True)


# ---------------------------------------------------------------- happy path --

def test_publish_then_fleet_fetch_zero_copy(mock_s3, tmp_path, monkeypatch):
    uri = _corpus(tmp_path)
    remote = "s3://bucket/caches/c.cache"
    monkeypatch.setenv("DMLC_CACHE_REMOTE", "1")
    was_enabled = telemetry.enabled()
    telemetry.enable()
    try:
        publishes = _counter("dmlc_cache_remote_publishes_total")
        hits = _counter("dmlc_cache_remote_hits_total")
        fetched = _counter("dmlc_cache_remote_bytes_fetched_total")
        p0, h0, f0 = publishes.value, hits.value, fetched.value
        _publish_seed_cache(mock_s3, tmp_path, uri, remote)
        assert ("bucket", "caches/c.cache") in mock_s3.objects
        assert publishes.value == p0 + 1

        # "another host": no local materialization yet -> remote hit
        _wipe_local(tmp_path)
        it = create_row_block_iter(f"{uri}#{remote}", type="libsvm")
        epoch1 = list(it)
        it.before_first()
        epoch2 = list(it)
        assert sum(b.size for b in epoch1) == ROWS
        for a, b in zip(epoch1, epoch2):
            assert a.offset is b.offset      # mmap-backed, zero-copy epochs
            assert a.index is b.index
            assert not a.index.flags.writeable
        it.close()
        assert hits.value == h0 + 1
        remote_size = len(mock_s3.objects[("bucket", "caches/c.cache")])
        assert fetched.value - f0 == remote_size
    finally:
        if not was_enabled:
            telemetry.disable()


def test_second_run_on_same_host_skips_remote(mock_s3, tmp_path, monkeypatch):
    uri = _corpus(tmp_path)
    remote = "s3://bucket/caches/c.cache"
    monkeypatch.setenv("DMLC_CACHE_REMOTE", "1")
    _publish_seed_cache(mock_s3, tmp_path, uri, remote)
    _wipe_local(tmp_path)
    it = create_row_block_iter(f"{uri}#{remote}", type="libsvm")
    assert sum(b.size for b in it) == ROWS
    it.close()
    mock_s3.requests.clear()
    it2 = create_row_block_iter(f"{uri}#{remote}", type="libsvm")
    assert sum(b.size for b in it2) == ROWS
    it2.close()
    # warm local materialization: the object store sees zero traffic
    assert mock_s3.requests == []


def test_publish_opt_in_only(mock_s3, tmp_path):
    uri = _corpus(tmp_path)
    remote = "s3://bucket/caches/unpublished.cache"
    it = create_row_block_iter(f"{uri}#{remote}", type="libsvm")
    assert sum(b.size for b in it) == ROWS
    it.close()
    # fetch was attempted (miss), but nothing was uploaded
    assert ("bucket", "caches/unpublished.cache") not in mock_s3.objects


def test_explicit_remote_uri_with_local_cachefile(mock_s3, tmp_path,
                                                  monkeypatch):
    """DMLC_CACHE_REMOTE=<uri> names the fleet location even when the
    #cachefile is a plain local path."""
    uri = _corpus(tmp_path)
    remote = "s3://bucket/caches/explicit.cache"
    monkeypatch.setenv("DMLC_CACHE_REMOTE", remote)
    local = str(tmp_path / "local.cache")
    it = create_row_block_iter(f"{uri}#{local}", type="libsvm")
    assert sum(b.size for b in it) == ROWS
    it.close()
    assert ("bucket", "caches/explicit.cache") in mock_s3.objects
    assert os.path.exists(local)
    # a second worker with its own local path fetches the published cache
    local2 = str(tmp_path / "local2.cache")
    mock_s3.requests.clear()
    it2 = create_row_block_iter(f"{uri}#{local2}", type="libsvm")
    assert sum(b.size for b in it2) == ROWS
    it2.close()
    assert any(m == "GET" for m, _ in mock_s3.requests)
    with open(local2, "rb") as f:
        assert f.read(8) == page_cache.HEAD_MAGIC


def test_remote_cache_config_parsing(monkeypatch):
    monkeypatch.delenv("DMLC_CACHE_REMOTE", raising=False)
    assert _remote_cache_config("/tmp/c.cache") == (None, False)
    assert _remote_cache_config("s3://b/c.cache") == ("s3://b/c.cache", False)
    monkeypatch.setenv("DMLC_CACHE_REMOTE", "1")
    assert _remote_cache_config("s3://b/c.cache") == ("s3://b/c.cache", True)
    assert _remote_cache_config("/tmp/c.cache") == (None, False)
    monkeypatch.setenv("DMLC_CACHE_REMOTE", "s3://b/x.cache")
    assert _remote_cache_config("/tmp/c.cache") == ("s3://b/x.cache", True)
    monkeypatch.setenv("DMLC_CACHE_REMOTE", "0")
    assert _remote_cache_config("s3://b/c.cache") == ("s3://b/c.cache", False)
    # the repo-wide bool grammar: case-insensitive, garbage raises (a
    # hand-rolled lowercase falsy list silently ENABLED publish on "False")
    monkeypatch.setenv("DMLC_CACHE_REMOTE", "False")
    assert _remote_cache_config("s3://b/c.cache") == ("s3://b/c.cache", False)
    monkeypatch.setenv("DMLC_CACHE_REMOTE", "YES")
    assert _remote_cache_config("s3://b/c.cache") == ("s3://b/c.cache", True)
    monkeypatch.setenv("DMLC_CACHE_REMOTE", "maybe")
    with pytest.raises(ValueError):
        _remote_cache_config("s3://b/c.cache")


# ------------------------------------------------------- untrustable remotes --

def _expect_fallback(mock_s3, tmp_path, uri, remote, reason):
    was_enabled = telemetry.enabled()
    telemetry.enable()
    try:
        misses = _counter("dmlc_cache_remote_misses_total", reason=reason)
        m0 = misses.value
        it = create_row_block_iter(f"{uri}#{remote}", type="libsvm")
        rows = sum(b.size for b in it)
        it.close()
        assert rows == ROWS
        assert misses.value == m0 + 1
    finally:
        if not was_enabled:
            telemetry.disable()


def test_absent_remote_falls_back(mock_s3, tmp_path):
    uri = _corpus(tmp_path)
    _expect_fallback(mock_s3, tmp_path, uri, "s3://bucket/none.cache",
                     "absent")


def test_footerless_remote_falls_back(mock_s3, tmp_path):
    """A remote object that is a prefix of a real cache (interrupted
    upload) has no validated footer and must never be trusted."""
    uri = _corpus(tmp_path)
    remote = "s3://bucket/caches/footerless.cache"
    it = create_row_block_iter(f"{uri}#{str(tmp_path / 'seed.cache')}",
                               type="libsvm")
    it.close()
    blob = open(str(tmp_path / "seed.cache"), "rb").read()
    mock_s3.objects[("bucket", "caches/footerless.cache")] = blob[:-40]
    _expect_fallback(mock_s3, tmp_path, uri, remote, "invalid")


def test_v1_cache_at_remote_uri_falls_back(mock_s3, tmp_path):
    """Pre-PR 4 remote caches used v1 RowBlockContainer stream framing;
    they are not fetchable and must fall back, not crash."""
    uri = _corpus(tmp_path)
    container = RowBlockContainer(np.uint32)
    for block in create_parser(uri, type="libsvm", threaded=False):
        container.push_block(block)
    fo = create_stream("s3://bucket/caches/v1.cache", "w")
    container.save(fo)
    fo.close()
    _expect_fallback(mock_s3, tmp_path, uri, "s3://bucket/caches/v1.cache",
                     "invalid")


def test_dtype_mismatch_remote_falls_back(mock_s3, tmp_path, monkeypatch):
    uri = _corpus(tmp_path)
    remote = "s3://bucket/caches/u64.cache"
    monkeypatch.setenv("DMLC_CACHE_REMOTE", "1")
    it = DiskRowIter(create_parser(uri, type="libsvm"), remote,
                     index_dtype=np.uint64)
    it.close()
    monkeypatch.delenv("DMLC_CACHE_REMOTE")
    _wipe_local(tmp_path)
    was_enabled = telemetry.enabled()
    telemetry.enable()
    try:
        misses = _counter("dmlc_cache_remote_misses_total", reason="invalid")
        m0 = misses.value
        it2 = DiskRowIter(create_parser(uri, type="libsvm"), remote,
                          index_dtype=np.uint32)
        assert sum(b.size for b in it2) == ROWS
        it2.close()
        assert misses.value == m0 + 1
    finally:
        if not was_enabled:
            telemetry.disable()


def test_tiny_remote_object_falls_back(mock_s3, tmp_path):
    uri = _corpus(tmp_path)
    mock_s3.objects[("bucket", "tiny.cache")] = b"xx"
    _expect_fallback(mock_s3, tmp_path, uri, "s3://bucket/tiny.cache",
                     "invalid")


# ------------------------------------------------- concurrent materialization --

def test_concurrent_materialization_atomic_rename(mock_s3, tmp_path,
                                                  monkeypatch):
    """Two processes fetch the same remote cache into the same local path
    concurrently: both must serve every row; the rename race is safe
    because each renames a fully validated temp file."""
    uri = _corpus(tmp_path)
    remote = "s3://bucket/caches/race.cache"
    monkeypatch.setenv("DMLC_CACHE_REMOTE", "1")
    _publish_seed_cache(mock_s3, tmp_path, uri, remote)
    _wipe_local(tmp_path)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = (
        "import sys\n"
        "from dmlc_core_tpu.data.factory import create_row_block_iter\n"
        f"it = create_row_block_iter({uri + '#' + remote!r}, type='libsvm')\n"
        f"assert sum(b.size for b in it) == {ROWS}\n"
        "it.close()\n"
        "print('OK')\n")
    env = os.environ.copy()
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    procs = [subprocess.Popen([sys.executable, "-c", script], env=env,
                              cwd=repo, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for _ in range(2)]
    outs = [p.communicate(timeout=120)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    assert all("OK" in o for o in outs), outs
    local = page_cache.default_local_path(remote)
    reader = page_cache.PageCacheReader(local, np.uint32)
    assert sum(b.size for b in reader.blocks) == ROWS
    reader.close()
    # no orphaned fetch temps
    d = os.path.dirname(local)
    assert [n for n in os.listdir(d) if ".tmp" in n] == []


def test_concurrent_fetch_threads_same_process(mock_s3, tmp_path,
                                               monkeypatch):
    """Two loaders in ONE process (train + eval over the same dataset)
    fetching concurrently: per-call temp names keep one thread from
    truncating the other's in-progress bytes — and from writing into the
    committed inode after the rename (a pid-only temp name did both)."""
    import threading

    uri = _corpus(tmp_path)
    remote = "s3://bucket/caches/threads.cache"
    monkeypatch.setenv("DMLC_CACHE_REMOTE", "1")
    _publish_seed_cache(mock_s3, tmp_path, uri, remote)
    _wipe_local(tmp_path)
    local = page_cache.default_local_path(remote)
    results, errors = [], []

    def one_fetch():
        try:
            results.append(page_cache.fetch_remote_cache(
                remote, local, np.uint32))
        except BaseException as exc:  # noqa: BLE001 — ferried to the assert
            errors.append(exc)

    threads = [threading.Thread(target=one_fetch) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert errors == []
    assert len(results) == 2
    reader = page_cache.PageCacheReader(local, np.uint32)
    assert sum(b.size for b in reader.blocks) == ROWS
    reader.close()
    assert [n for n in os.listdir(os.path.dirname(local))
            if ".tmp" in n] == []


# ------------------------------------------------------------------ publish ---

def _build_seed_cache(tmp_path, uri):
    """Stream-parse the corpus into a local v2 cache file; returns its path."""
    seed = str(tmp_path / "seed.cache")
    it = create_row_block_iter(f"{uri}#{seed}", type="libsvm")
    it.close()
    return seed


def test_failed_publish_never_lands_truncated_object(mock_s3, tmp_path,
                                                     monkeypatch):
    """A publish that dies mid-upload must ABANDON, not commit: close()
    completes the multipart upload, so the old finally-close landed a
    footer-less truncated object at the fleet URI that every worker's
    fetch would classify invalid and re-parse around."""
    from dmlc_core_tpu.io import s3_filesys

    uri = _corpus(tmp_path)
    seed = _build_seed_cache(tmp_path, uri)

    calls = []

    def boom(self, data):
        calls.append(len(data))
        raise OSError("disk pulled mid-read")

    monkeypatch.setattr(s3_filesys.S3WriteStream, "write", boom)
    with pytest.raises(OSError, match="mid-read"):
        page_cache.publish_cache(seed, "s3://bucket/caches/partial.cache")
    assert calls, "publish never reached the stream"
    assert ("bucket", "caches/partial.cache") not in mock_s3.objects
    assert mock_s3.uploads == {}


def test_failed_publish_to_write_through_target_removes_partial(
        tmp_path, monkeypatch):
    """Streams with no abort() (plain files, hdfs://) have already
    materialized partial bytes AT the target when the publish dies:
    abandoning must delete them — a leftover footer-less object would be
    classified invalid by every fetcher until someone overwrites it."""
    from dmlc_core_tpu.io import filesys

    monkeypatch.delenv("DMLC_CACHE_REMOTE", raising=False)
    uri = _corpus(tmp_path)
    seed = _build_seed_cache(tmp_path, uri)
    target = str(tmp_path / "published.rbc")

    real_write = filesys._LocalFileStream.write

    def boom(self, data):
        real_write(self, data[: len(data) // 2])
        raise OSError("link dropped mid-publish")

    monkeypatch.setattr(filesys._LocalFileStream, "write", boom)
    with pytest.raises(OSError, match="mid-publish"):
        page_cache.publish_cache(seed, target)
    assert not os.path.exists(target)


def test_s3_write_stream_abort_leaves_nothing(mock_s3, monkeypatch):
    """abort() after multipart parts are already uploaded: the upload is
    aborted server-side, nothing lands at the key, and a later close()
    is a no-op rather than a second commit attempt."""
    monkeypatch.setenv("DMLC_S3_WRITE_BUFFER_MB", "5")  # 5 MB parts (floor)
    fo = create_stream("s3://bucket/aborted.bin", "w")
    fo.write(b"\0" * (6 << 20))          # > one part: multipart initiated
    assert mock_s3.uploads, "multipart upload never started"
    fo.abort()
    fo.close()                            # no-op after abort
    assert mock_s3.uploads == {}
    assert ("bucket", "aborted.bin") not in mock_s3.objects


# ------------------------------------------------------------------- chaos ----

@pytest.mark.chaos
def test_midfetch_truncation_falls_back(mock_s3, tmp_path):
    """An injected truncation mid page fetch (cut object / dropped
    connection) must warn, count a rebuild, and stream-parse — rows stay
    correct and complete."""
    uri = _corpus(tmp_path)
    remote = "s3://bucket/caches/trunc.cache"
    it = create_row_block_iter(f"{uri}#{str(tmp_path / 'seed.cache')}",
                               type="libsvm")
    it.close()
    mock_s3.objects[("bucket", "caches/trunc.cache")] = open(
        str(tmp_path / "seed.cache"), "rb").read()
    was_enabled = telemetry.enabled()
    telemetry.enable()
    fault.configure({"rules": [
        # after the header+tail probes: cut the first page fetch short
        {"site": "io.cache.fetch", "kind": "truncate", "keep": 64,
         "after": 2, "times": 1}]})
    try:
        rebuilds = _counter("dmlc_cache_rebuilds_total")
        misses = _counter("dmlc_cache_remote_misses_total", reason="invalid")
        r0, m0 = rebuilds.value, misses.value
        it2 = create_row_block_iter(f"{uri}#{remote}", type="libsvm")
        assert sum(b.size for b in it2) == ROWS
        it2.close()
        assert [s for s, _, _ in fault.fires()] == ["io.cache.fetch"]
        assert rebuilds.value == r0 + 1
        assert misses.value == m0 + 1
    finally:
        fault.clear()
        if not was_enabled:
            telemetry.disable()


@pytest.mark.chaos
def test_midfetch_reset_falls_back(mock_s3, tmp_path):
    uri = _corpus(tmp_path)
    remote = "s3://bucket/caches/reset.cache"
    it = create_row_block_iter(f"{uri}#{str(tmp_path / 'seed.cache')}",
                               type="libsvm")
    it.close()
    mock_s3.objects[("bucket", "caches/reset.cache")] = open(
        str(tmp_path / "seed.cache"), "rb").read()
    fault.configure({"rules": [
        {"site": "io.cache.fetch", "kind": "reset", "times": 1}]})
    was_enabled = telemetry.enabled()
    telemetry.enable()
    try:
        misses = _counter("dmlc_cache_remote_misses_total", reason="io")
        m0 = misses.value
        it2 = create_row_block_iter(f"{uri}#{remote}", type="libsvm")
        assert sum(b.size for b in it2) == ROWS
        it2.close()
        assert misses.value == m0 + 1
    finally:
        fault.clear()
        if not was_enabled:
            telemetry.disable()


@pytest.mark.chaos
def test_corrupt_remote_page_never_served(mock_s3, tmp_path):
    """Bit-rot inside one remote page: the per-page CRC rejects it, the
    local materialization never appears, and the rows come from a clean
    stream parse."""
    uri = _corpus(tmp_path)
    remote = "s3://bucket/caches/corrupt.cache"
    it = create_row_block_iter(f"{uri}#{str(tmp_path / 'seed.cache')}",
                               type="libsvm")
    it.close()
    blob = bytearray(open(str(tmp_path / "seed.cache"), "rb").read())
    blob[200:204] = b"\xff\xff\xff\xff"       # inside page 0's payload
    mock_s3.objects[("bucket", "caches/corrupt.cache")] = bytes(blob)
    was_enabled = telemetry.enabled()
    telemetry.enable()
    try:
        rebuilds = _counter("dmlc_cache_rebuilds_total")
        r0 = rebuilds.value
        it2 = create_row_block_iter(f"{uri}#{remote}", type="libsvm")
        assert sum(b.size for b in it2) == ROWS
        it2.close()
        assert rebuilds.value == r0 + 1
        # the corrupt fetch must not have materialized anything local
        local = page_cache.default_local_path(remote)
        # (the fallback BUILD materializes; what matters is it validates)
        reader = page_cache.PageCacheReader(local, np.uint32)
        assert sum(b.size for b in reader.blocks) == ROWS
        reader.close()
    finally:
        fault.clear()
        if not was_enabled:
            telemetry.disable()


# ------------------------------------------------------------ unit-level bits --

def test_open_remote_layout_spans(mock_s3, tmp_path):
    uri = _corpus(tmp_path)
    seed = str(tmp_path / "seed.cache")
    it = create_row_block_iter(f"{uri}#{seed}", type="libsvm")
    it.close()
    blob = open(seed, "rb").read()
    mock_s3.objects[("bucket", "layout.cache")] = blob
    layout = page_cache._open_remote_layout("s3://bucket/layout.cache",
                                            np.dtype(np.uint32))
    assert layout.size == len(blob)
    assert len(layout.header) == 32
    # spans tile [header, toc) exactly
    pos = 32
    for off, nbytes in layout.spans:
        assert off == pos
        pos += nbytes
    assert blob[:32] == layout.header
    assert blob[pos:] == layout.tail


def test_fetch_remote_cache_prefetch_depths(mock_s3, tmp_path, monkeypatch):
    """Every ring depth produces a byte-identical local file."""
    uri = _corpus(tmp_path)
    seed = str(tmp_path / "seed.cache")
    it = create_row_block_iter(f"{uri}#{seed}", type="libsvm")
    it.close()
    blob = open(seed, "rb").read()
    mock_s3.objects[("bucket", "depth.cache")] = blob
    for depth in (1, 2, 8):
        dst = str(tmp_path / f"fetched-{depth}.cache")
        nbytes = page_cache.fetch_remote_cache(
            "s3://bucket/depth.cache", dst, np.uint32, prefetch=depth)
        assert nbytes == len(blob)
        assert open(dst, "rb").read() == blob


def test_multi_page_fetch_ring_and_page_bytes_knob(mock_s3, tmp_path,
                                                   monkeypatch):
    """A multi-page fetch through the pre-posted ring reassembles the
    exact bytes in page order at every depth, and DMLC_CACHE_PAGE_BYTES
    plumbs into the build's page granularity (floored at 1 MB)."""
    # build a 3-page cache directly (the unit of the fetch pipeline)
    seed = str(tmp_path / "paged.cache")
    writer = page_cache.PageCacheWriter(seed, np.uint32)
    rng = np.random.RandomState(7)
    rows = 0
    for _ in range(3):
        container = RowBlockContainer(np.uint32)
        for i in range(500):
            feats = sorted(rng.choice(40, size=3, replace=False))
            container.push_row(float(i % 2), feats, rng.rand(3))
            rows += 1
        writer.write_page(container)
    writer.commit()
    blob = open(seed, "rb").read()
    mock_s3.objects[("bucket", "paged.cache")] = blob
    layout = page_cache._open_remote_layout("s3://bucket/paged.cache",
                                            np.dtype(np.uint32))
    assert len(layout.spans) == 3
    for depth in (1, 3):
        dst = str(tmp_path / f"paged-{depth}.cache")
        nbytes = page_cache.fetch_remote_cache(
            "s3://bucket/paged.cache", dst, np.uint32, prefetch=depth)
        assert nbytes == len(blob)
        assert open(dst, "rb").read() == blob
    # the materialized multi-page cache serves without re-parsing
    it = DiskRowIter(lambda: (_ for _ in ()).throw(AssertionError(
        "warm multi-page open must not re-parse")),
        str(tmp_path / "paged-3.cache"))
    assert sum(b.size for b in it) == rows
    it.close()
    # knob plumbing: env page size reaches the builder (1 MB floor)
    monkeypatch.setenv("DMLC_CACHE_PAGE_BYTES", str(3 << 20))
    uri = _corpus(tmp_path, rows=50)
    it2 = create_row_block_iter(f"{uri}#{tmp_path / 'k.cache'}",
                                type="libsvm")
    assert it2._page_bytes == 3 << 20
    it2.close()
    monkeypatch.setenv("DMLC_CACHE_PAGE_BYTES", "1024")   # below the floor
    it3 = DiskRowIter(lambda: (_ for _ in ()).throw(AssertionError("x")),
                      str(tmp_path / "paged-1.cache"))
    assert it3._page_bytes == 1 << 20
    it3.close()
