"""Test configuration: force an 8-device virtual CPU mesh before jax import.

Multi-chip hardware is not available in CI; sharding tests run on
``--xla_force_host_platform_device_count=8`` as the SURVEY.md §4 test strategy
prescribes (the "fake cluster" the reference never had).
"""

import os

# Force CPU even when the environment points at a real accelerator: the test
# suite validates sharding semantics on a virtual mesh, not device perf.
# Note: the image's sitecustomize registers the axon TPU plugin and pins
# jax_platforms via config, so the env var alone is not enough — override the
# config after import too.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def run_tracker_workers(tmp_path, script_text, nworkers, env_extra=None,
                        timeout=600, script_path=None, script_args=()):
    """Shared multi-process launch recipe: write a worker script (or use an
    existing one via ``script_path`` + ``script_args``), run it under
    `dmlc-submit --cluster local`, return the CompletedProcess.

    Used by the tracker/collective/distributed-model e2e tests so the env
    hygiene (CPU forcing, PYTHONPATH, XLA_FLAGS scrubbing, RESULT_DIR)
    lives in exactly one place.
    """
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if script_path is None:
        script_path = tmp_path / "worker.py"
        script_path.write_text(script_text)
    env = os.environ.copy()
    env["RESULT_DIR"] = str(tmp_path)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    env.update(env_extra or {})
    cmd = [sys.executable, "-m", "dmlc_core_tpu.tracker.submit",
           "--cluster", "local", "--num-workers", str(nworkers), "--",
           sys.executable, str(script_path), *map(str, script_args)]
    return subprocess.run(cmd, env=env, cwd=repo, capture_output=True,
                          text=True, timeout=timeout)
