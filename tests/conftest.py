"""Test configuration: force an 8-device virtual CPU mesh before jax import.

Multi-chip hardware is not available in CI; sharding tests run on
``--xla_force_host_platform_device_count=8`` as the SURVEY.md §4 test strategy
prescribes (the "fake cluster" the reference never had).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
