"""Test configuration: force an 8-device virtual CPU mesh before jax import.

Multi-chip hardware is not available in CI; sharding tests run on
``--xla_force_host_platform_device_count=8`` as the SURVEY.md §4 test strategy
prescribes (the "fake cluster" the reference never had).
"""

import os

# Force CPU even when the environment points at a real accelerator: the test
# suite validates sharding semantics on a virtual mesh, not device perf.
# Note: the image's sitecustomize registers the axon TPU plugin and pins
# jax_platforms via config, so the env var alone is not enough — override the
# config after import too.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
