"""Event-loop transport chaos: the hostile-client drills only a
non-blocking front end can survive.

The threaded transport holds one thread hostage per slow client; the
selectors transport (``DMLC_SERVE_TRANSPORT=evloop``) must instead
*time-box* every connection: byte-at-a-time headers (slowloris) and
stalled bodies get a structured 408 and a close, mid-response
disconnects are counted as aborts without crashing anything, pipelined
requests are answered in order, and idle keep-alive connections are
reaped silently.  Cross-transport behavior parity lives in
test_serve.py / test_serve_chaos.py (parametrized over both transports);
this file owns the drills that only make sense against the event loop.
"""

import json
import socket
import struct
import time
import urllib.error
import urllib.request

import pytest

from dmlc_core_tpu import fault, telemetry
from dmlc_core_tpu.serve import ScoringServer, build_runtime
from dmlc_core_tpu.serve.loadgen import run_churn, run_load

pytestmark = pytest.mark.chaos

NF = 4


@pytest.fixture(autouse=True)
def _clean_fault_plan():
    fault.clear()
    yield
    fault.clear()


def _server(**kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_delay_ms", 1.0)
    kw.setdefault("transport", "evloop")
    return ScoringServer(build_runtime("linear", NF, seed=0), **kw)


def _post(url, obj, timeout=10.0):
    body = obj if isinstance(obj, bytes) else json.dumps(obj).encode()
    req = urllib.request.Request(
        url + "/v1/score", data=body,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.load(resp)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


def _healthy(url):
    with urllib.request.urlopen(url + "/healthz", timeout=5) as resp:
        return json.load(resp)["status"] == "ok"


def _connect(srv):
    host, port = srv.url.replace("http://", "").rsplit(":", 1)
    return socket.create_connection((host, int(port)), timeout=10.0)


def _read_response(sock, timeout=10.0):
    """Read exactly one HTTP response off a raw socket; returns
    (status, headers dict, body bytes)."""
    sock.settimeout(timeout)
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("EOF before response head")
        buf += chunk
    head, _, rest = buf.partition(b"\r\n\r\n")
    lines = head.decode("latin-1").split("\r\n")
    status = int(lines[0].split(" ", 2)[1])
    headers = {}
    for line in lines[1:]:
        k, _, v = line.partition(":")
        headers[k.strip().lower()] = v.strip()
    need = int(headers.get("content-length", "0"))
    while len(rest) < need:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("EOF mid-body")
        rest += chunk
    return status, headers, rest[:need], rest[need:]


def test_slowloris_headers_get_structured_408_then_close(monkeypatch):
    # a client that drips header bytes forever must not pin a connection
    # (much less a thread): the header deadline fires, the envelope is a
    # parseable 408, and the socket is closed
    monkeypatch.setenv("DMLC_SERVE_HEADER_S", "0.5")
    with _server() as srv:
        s = _connect(srv)
        try:
            s.sendall(b"POST /v1/score HT")
            time.sleep(0.15)
            s.sendall(b"TP/1.1\r\nContent-")
            status, headers, body, _ = _read_response(s, timeout=10.0)
            assert status == 408
            err = json.loads(body)["error"]
            assert err["code"] == "client_timeout"
            assert err["details"]["timeout_s"] == 0.5
            # and the connection is gone: EOF on the next read
            assert s.recv(1) == b""
        finally:
            s.close()
        # the loop thread survived: normal traffic flows right after
        status, body = _post(srv.url, {"instances": [[0.0] * NF]})
        assert status == 200 and len(body["predictions"]) == 1
        assert _healthy(srv.url)


def test_stalled_body_gets_structured_408_then_close(monkeypatch):
    # full headers, partial body, then silence: the assembly deadline
    # covers the body too (the request began — abort accounting applies)
    monkeypatch.setenv("DMLC_SERVE_HEADER_S", "0.5")
    with _server() as srv:
        s = _connect(srv)
        try:
            s.sendall(b"POST /v1/score HTTP/1.1\r\n"
                      b"Host: x\r\nContent-Type: application/json\r\n"
                      b"Content-Length: 400\r\n\r\n"
                      b'{"instances": [[')
            status, headers, body, _ = _read_response(s, timeout=10.0)
            assert status == 408
            assert json.loads(body)["error"]["code"] == "client_timeout"
            assert s.recv(1) == b""
        finally:
            s.close()
        status, _ = _post(srv.url, {"instances": [[0.5] * NF]})
        assert status == 200
        assert _healthy(srv.url)


def test_mid_response_disconnect_counted_as_abort_not_crash():
    # the client RSTs while its request is in the batcher: the loop
    # records an abort (status-0 metrics + the aborts counter) and the
    # late completion is dropped by the seq guard — nothing crashes
    was_enabled = telemetry.enabled()
    telemetry.enable()
    fault.configure({"rules": [{"site": "serve.predict", "kind": "delay",
                                "seconds": 0.4, "times": None}]})
    try:
        with _server() as srv:
            before = telemetry.get_registry().counter(
                "dmlc_serve_connection_aborts_total").value
            s = _connect(srv)
            payload = json.dumps(
                {"instances": [[0.0] * NF]}).encode()
            s.sendall(b"POST /v1/score HTTP/1.1\r\nHost: x\r\n"
                      b"Content-Type: application/json\r\n"
                      b"Content-Length: %d\r\n\r\n" % len(payload)
                      + payload)
            time.sleep(0.1)  # let the loop submit to the batcher
            # SO_LINGER(0): close sends RST instead of FIN
            s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                         struct.pack("ii", 1, 0))
            s.close()
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                after = telemetry.get_registry().counter(
                    "dmlc_serve_connection_aborts_total").value
                if after > before:
                    break
                time.sleep(0.05)
            assert after > before, "abort was never counted"
            # the server shrugged it off
            fault.clear()
            status, _ = _post(srv.url, {"instances": [[1.0] * NF]})
            assert status == 200
            assert _healthy(srv.url)
    finally:
        if not was_enabled:
            telemetry.disable()


def test_pipelined_requests_answered_in_order():
    # two complete requests in one TCP segment: the loop must answer
    # both, in order, on the same connection (framing discipline)
    with _server() as srv:
        p1 = json.dumps({"instances": [[1.0] * NF]}).encode()
        p2 = json.dumps({"instances": [[2.0] * NF, [3.0] * NF]}).encode()
        wire = b"".join(
            b"POST /v1/score HTTP/1.1\r\nHost: x\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: %d\r\n\r\n" % len(p) + p
            for p in (p1, p2))
        s = _connect(srv)
        try:
            s.sendall(wire)
            status1, _, body1, extra = _read_response(s)
            assert status1 == 200
            assert len(json.loads(body1)["predictions"]) == 1
            # the second response may already be in `extra`
            buf = extra
            if b"\r\n\r\n" not in buf:
                status2, _, body2, _ = _read_response(s)
            else:
                head, _, rest = buf.partition(b"\r\n\r\n")
                lines = head.decode("latin-1").split("\r\n")
                status2 = int(lines[0].split(" ", 2)[1])
                need = int([v for k, _, v in
                            (l.partition(":") for l in lines[1:])
                            if k.strip().lower() == "content-length"][0])
                while len(rest) < need:
                    rest += s.recv(65536)
                body2 = rest[:need]
            assert status2 == 200
            assert len(json.loads(body2)["predictions"]) == 2
        finally:
            s.close()


def test_idle_keepalive_connections_are_reaped_silently(monkeypatch):
    was_enabled = telemetry.enabled()
    telemetry.enable()
    monkeypatch.setenv("DMLC_SERVE_IDLE_S", "0.5")
    try:
        with _server() as srv:
            s = _connect(srv)
            try:
                time.sleep(1.2)  # > idle timeout + sweep period
                # silent close: EOF, no error envelope
                s.settimeout(5.0)
                assert s.recv(1) == b""
            finally:
                s.close()
            reaped = telemetry.get_registry().counter(
                "dmlc_serve_connections_closed_total",
                reason="idle_timeout").value
            assert reaped >= 1
            # fresh connections are still welcome
            status, _ = _post(srv.url, {"instances": [[0.0] * NF]})
            assert status == 200
    finally:
        if not was_enabled:
            telemetry.disable()


def test_churn_report_shows_zero_refused_zero_resets(monkeypatch):
    # the c10k drill in miniature (the full 10k run lives in
    # benchmarks/bench_serving.py c10k): hundreds of mostly-idle
    # keep-alive connections churning while traffic flows — nothing
    # refused, nothing reset, no idle soldier dropped early
    monkeypatch.setenv("DMLC_SERVE_IDLE_S", "60")
    with _server() as srv:
        report = run_churn(srv.url, connections=256, duration_s=1.5,
                           num_feature=NF, active=8, churn_per_s=20,
                           seed=3)
        conns = report["connections"]
        assert conns["refused"] == 0
        assert conns["resets"] == 0
        assert conns["closed_by_server"] == 0
        assert conns["peak_open"] >= 256
        assert conns["churned"] > 0
        assert report["requests"]["ok"] > 0
        assert report["requests"]["errors"] == 0
        assert _healthy(srv.url)


def test_every_slo_report_carries_connection_accounting():
    # satellite contract: run_load's report states peak concurrent
    # connections and door-slam counts unconditionally
    with _server() as srv:
        report = run_load(srv.url, qps=30, duration_s=1.0,
                          num_feature=NF, seed=7)
        conns = report["connections"]
        assert set(conns) == {"peak_inflight", "refused", "resets"}
        assert conns["peak_inflight"] >= 1
        assert conns["refused"] == 0 and conns["resets"] == 0
        assert report["counts"]["crashed"] == 0
