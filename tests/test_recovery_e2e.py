"""Composed crash->resume e2e (r4 VERDICT item 3).

Rounds 1-4 proved the recovery pieces separately: rendezvous rank restore,
local-backend retry (DMLC_NUM_ATTEMPT), CheckpointManager save/restore.
This module composes them: a multi-process distributed GBDT fit
checkpoints every k rounds through CheckpointManager; workers are
SIGKILLed mid-fit (one worker, and separately the whole job); the job is
relaunched through the tracker; training resumes from the last checkpoint;
and the final ensemble must match the uninterrupted run BIT FOR BIT —
the slice-granular recovery story SURVEY §5.3/§5.4 commits to in place of
the reference's per-rank healing.

Recipe documented for users in docs/guide.md ("Crash recovery").
"""

import os

import numpy as np
import pytest

from tests.conftest import run_tracker_workers

# Worker: deterministic data -> distributed sketch -> round-by-round boost
# with a checkpoint every CKPT_EVERY rounds; optional self-SIGKILL mid-fit.
RECOVERY_WORKER = r"""
import os, signal
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2").strip()
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from dmlc_core_tpu import collective

collective.init()
rank = collective.get_rank()
world = collective.get_world_size()

from dmlc_core_tpu.bridge.checkpoint import CheckpointManager
from dmlc_core_tpu.models.gbdt import GBDT, GBDTParam
from dmlc_core_tpu.parallel.mesh import (data_sharding, make_mesh,
                                         replicated_sharding)

R = 6
CKPT_EVERY = 2
CRASH_MODE = os.environ.get("CRASH_MODE", "none")   # none|victim|all
CRASH_ROUND = int(os.environ.get("CRASH_ROUND", "3"))
VICTIM = int(os.environ.get("VICTIM_RANK", "1"))
out = os.environ["RESULT_DIR"]

rng = np.random.RandomState(0)
B, F = 1024, 6
x = rng.randn(B, F).astype(np.float32)
wvec = rng.randn(F).astype(np.float32)
y = ((x @ wvec) > 0).astype(np.float32)

param = GBDTParam(num_boost_round=R, max_depth=3, num_bins=32,
                  hist_method="scatter", learning_rate=0.5)
model = GBDT(param, num_feature=F)
half = B // world
lo = rank * half
model.make_bins(x[lo:lo + half], comm=collective)
bins_local = np.asarray(model.bin_features(x[lo:lo + half]), np.int32)
y_local = y[lo:lo + half]

mesh = make_mesh()
sh2 = data_sharding(mesh, ndim=2)
sh1 = data_sharding(mesh, ndim=1)
gbins = jax.make_array_from_process_local_data(sh2, bins_local, (B, F))
glabel = jax.make_array_from_process_local_data(sh1, y_local, (B,))
gw = jax.make_array_from_process_local_data(
    sh1, np.ones(half, np.float32), (B,))

mgr = CheckpointManager(os.path.join(out, "ckpt"), keep=3)
replicate = jax.jit(lambda a: a, out_shardings=replicated_sharding(mesh))

# resume point: every rank reads the same latest step AFTER the collective
# init barrier, so no rank can race a writer from a previous incarnation
latest = mgr.latest_step()
if latest is None:
    start = 0
    margin_full = np.full((B,), param.base_score, np.float32)
    trees = []
else:
    # flat checkpoint dict; keystr keys look like "['margin']"
    state = {k[2:-2]: v for k, v in mgr.restore(latest).items()}
    start = int(state["round"])
    margin_full = np.asarray(state["margin"], np.float32)
    trees = []
    for i in range(start):
        arity = len([k for k in state if k.startswith(f"t{i}_")])
        trees.append(tuple(np.asarray(state[f"t{i}_{j}"])
                           for j in range(arity)))

gmargin = jax.make_array_from_process_local_data(
    sh1, margin_full[lo:lo + half], (B,))

crash_flag = os.path.join(out, f"crashed-rank{rank}")
with mesh:
    for r in range(start, R):
        if r == CRASH_ROUND and not os.path.exists(crash_flag):
            if CRASH_MODE == "all" or (CRASH_MODE == "victim"
                                       and rank == VICTIM):
                open(crash_flag, "w").close()
                os.kill(os.getpid(), signal.SIGKILL)   # hard death, no cleanup
        gmargin, tree = model.boost_round(gmargin, gbins, glabel, gw,
                                          round_index=r)
        trees.append(tuple(np.asarray(replicate(a)) for a in tree))
        if (r + 1) % CKPT_EVERY == 0 and (r + 1) < R:
            # the replicate is a cross-process collective: EVERY rank must
            # participate; only rank 0 then writes the durable step
            margin_rep = np.asarray(replicate(gmargin))
            if rank == 0:
                payload = {"round": np.int64(r + 1), "margin": margin_rep}
                for i, t in enumerate(trees):
                    for j, arr in enumerate(t):
                        payload[f"t{i}_{j}"] = arr
                mgr.save(r + 1, payload, async_=False)
    margin_out = np.asarray(replicate(gmargin))

stacked = {f"t{i}_{j}": arr for i, t in enumerate(trees)
           for j, arr in enumerate(t)}
np.savez(os.path.join(out, f"final-rank{rank}.npz"), margin=margin_out,
         nrounds=len(trees), **stacked)
collective.finalize()
"""


def _load_final(tmp_path, rank):
    return np.load(tmp_path / f"final-rank{rank}.npz")


def _assert_identical(a, b):
    assert set(a.files) == set(b.files)
    for k in a.files:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


@pytest.mark.slow
def test_whole_job_crash_resume_bit_identical(tmp_path):
    """Every worker SIGKILLs itself mid-fit; a second submit resumes from
    the checkpoint and the final ensemble is bit-identical to an
    uninterrupted run."""
    base = tmp_path / "baseline"
    base.mkdir()
    proc = run_tracker_workers(base, RECOVERY_WORKER, 2,
                               env_extra={"CRASH_MODE": "none"})
    assert proc.returncode == 0, proc.stderr[-4000:]

    crash = tmp_path / "crash"
    crash.mkdir()
    # attempt budget 1: the whole job dies at CRASH_ROUND
    proc = run_tracker_workers(crash, RECOVERY_WORKER, 2,
                               env_extra={"CRASH_MODE": "all",
                                          "DMLC_NUM_ATTEMPT": "1"})
    assert proc.returncode != 0        # the job really died
    ckpts = list((crash / "ckpt").glob("ckpt-*"))
    assert ckpts, "no checkpoint survived the crash"
    assert not (crash / "final-rank0.npz").exists()

    # relaunch THROUGH THE TRACKER into the same job dir: resumes at the
    # last checkpoint (round 2), not from scratch
    proc = run_tracker_workers(crash, RECOVERY_WORKER, 2,
                               env_extra={"CRASH_MODE": "all"})
    assert proc.returncode == 0, proc.stderr[-4000:]

    for rank in range(2):
        _assert_identical(_load_final(base, rank), _load_final(crash, rank))
    assert int(_load_final(crash, 0)["nrounds"]) == 6


@pytest.mark.slow
def test_single_worker_sigkill_self_heals(tmp_path):
    """One worker is SIGKILLed mid-fit; the local backend's retry budget
    relaunches the failed processes, rendezvous re-forms, training resumes
    from the checkpoint, and the result is bit-identical."""
    base = tmp_path / "baseline"
    base.mkdir()
    proc = run_tracker_workers(base, RECOVERY_WORKER, 2,
                               env_extra={"CRASH_MODE": "none"})
    assert proc.returncode == 0, proc.stderr[-4000:]

    heal = tmp_path / "heal"
    heal.mkdir()
    proc = run_tracker_workers(
        heal, RECOVERY_WORKER, 2,
        env_extra={"CRASH_MODE": "victim", "VICTIM_RANK": "1",
                   "DMLC_NUM_ATTEMPT": "3"},
        timeout=900)
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert (heal / "crashed-rank1").exists()   # the kill really happened

    for rank in range(2):
        _assert_identical(_load_final(base, rank), _load_final(heal, rank))
