"""Pallas histogram kernel vs the exact scatter formulation (interpret mode).

The kernel's numerics are bf16-one-hot x bf16-W with f32 accumulation — the
same contract as the plain one-hot matmul — so tolerances below reflect bf16
rounding of g/h, not algorithmic drift.
"""

import numpy as np
import pytest

from dmlc_core_tpu.ops import hist_pallas
from dmlc_core_tpu.ops.histogram import grad_histogram


@pytest.fixture(autouse=True)
def interpret_mode():
    hist_pallas._INTERPRET = True
    hist_pallas.pallas_supported.cache_clear()
    hist_pallas.pallas_fused_supported.cache_clear()
    hist_pallas.pallas_i8_supported.cache_clear()
    yield
    hist_pallas._INTERPRET = False
    hist_pallas.pallas_supported.cache_clear()
    hist_pallas.pallas_fused_supported.cache_clear()
    hist_pallas.pallas_i8_supported.cache_clear()


def _rand_case(b, f, nbins, nnodes, seed=0):
    rng = np.random.RandomState(seed)
    bins = rng.randint(0, nbins, (b, f)).astype(np.int32)
    node = rng.randint(0, nnodes, b).astype(np.int32)
    g = rng.randn(b).astype(np.float32)
    h = rng.rand(b).astype(np.float32)
    return bins, node, g, h


@pytest.mark.parametrize("b,f,nbins,nnodes", [
    (256, 3, 8, 4),      # one tile exactly (block_rows padding no-op path)
    (300, 5, 16, 2),     # row padding inside the wrapper
    (700, 2, 4, 8),      # multi-tile accumulation across grid steps
])
def test_matches_scatter(b, f, nbins, nnodes):
    bins, node, g, h = _rand_case(b, f, nbins, nnodes)
    G, H = hist_pallas.grad_hist_pallas(bins, node, g, h, nnodes, nbins)
    Gr, Hr = grad_histogram(bins, node, g, h, nnodes, nbins, method="scatter")
    assert G.shape == (nnodes, f, nbins)
    np.testing.assert_allclose(np.asarray(G), np.asarray(Gr),
                               rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(H), np.asarray(Hr),
                               rtol=2e-2, atol=2e-2)


def test_negative_node_ids_drop_out():
    bins, node, g, h = _rand_case(128, 2, 4, 2, seed=1)
    node[:50] = -1
    G, H = hist_pallas.grad_hist_pallas(bins, node, g, h, 2, 4)
    mask = node >= 0
    Gr, Hr = grad_histogram(bins[mask], node[mask], g[mask], h[mask], 2, 4,
                            method="scatter")
    np.testing.assert_allclose(np.asarray(G), np.asarray(Gr),
                               rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(H), np.asarray(Hr),
                               rtol=2e-2, atol=2e-2)


def test_grad_histogram_dispatches_pallas():
    bins, node, g, h = _rand_case(256, 3, 8, 4, seed=2)
    G, H = grad_histogram(bins, node, g, h, 4, 8, method="pallas")
    Gr, Hr = grad_histogram(bins, node, g, h, 4, 8, method="scatter")
    np.testing.assert_allclose(np.asarray(G), np.asarray(Gr),
                               rtol=2e-2, atol=2e-2)


def test_vmem_overflow_blocks_or_falls_back():
    """Deep trees keep the kernel via node-blocked sweeps; onehot only when
    even an 8-node block overflows VMEM."""
    from dmlc_core_tpu.models.gbdt import GBDT, GBDTParam
    from dmlc_core_tpu.ops.hist_pallas import hist_fits_vmem, hist_node_block

    assert hist_fits_vmem(32, 28, 256)
    assert not hist_fits_vmem(512, 28, 256)       # depth-10 deepest level
    assert hist_node_block(512, 28, 256) == 128   # ... -> 4 blocked sweeps
    assert hist_node_block(32, 28, 256) == 32     # fits: single sweep
    assert hist_node_block(512, 512, 1024) is None  # 8-node block > VMEM
    deep = GBDT(GBDTParam(max_depth=10, num_bins=256, hist_method="pallas"),
                num_feature=28)
    assert deep._method() == "pallas"             # blocked, not onehot
    wide = GBDT(GBDTParam(max_depth=10, num_bins=1024,
                          hist_method="pallas"), num_feature=512)
    assert wide._method() == "onehot"
    # a user-selected fused method degrades to the (blockable) plain kernel
    deep_fused = GBDT(GBDTParam(max_depth=10, num_bins=256,
                                hist_method="pallas_fused"), num_feature=28)
    assert deep_fused._method() == "pallas"
    shallow = GBDT(GBDTParam(max_depth=6, num_bins=256,
                             hist_method="pallas"), num_feature=28)
    assert shallow._method() == "pallas"
    sharded = GBDT(GBDTParam(max_depth=6, num_bins=256,
                             hist_method="pallas"), num_feature=28,
                   model_axis="model")
    assert sharded._method() == "onehot"


def test_blocked_hist_matches_scatter():
    """Node counts beyond one VMEM accumulator: the blocked sweep must give
    the same histogram as the exact scatter."""
    # shrink the budget so blocking triggers at test-size shapes (module
    # attribute, NOT a from-import: the mutation must hit the live gate)
    orig = hist_pallas._ACC_BYTES_LIMIT
    hist_pallas._ACC_BYTES_LIMIT = 2 * 8 * 3 * 16 * 4   # 8-node blocks
    try:
        assert hist_pallas.hist_node_block(32, 3, 16) == 8
        bins, node, g, h = _rand_case(700, 3, 16, 32, seed=31)
        G, H = hist_pallas.grad_hist_pallas(bins, node, g, h, 32, 16)
        Gr, Hr = grad_histogram(bins, node, g, h, 32, 16, method="scatter")
        assert G.shape == (32, 3, 16)
        np.testing.assert_allclose(np.asarray(G), np.asarray(Gr),
                                   rtol=2e-2, atol=2e-2)
        np.testing.assert_allclose(np.asarray(H), np.asarray(Hr),
                                   rtol=2e-2, atol=2e-2)
        # non-power-of-two node count: last block is short
        bins, node, g, h = _rand_case(500, 3, 16, 20, seed=32)
        G, _ = hist_pallas.grad_hist_pallas(bins, node, g, h, 20, 16)
        Gr, _ = grad_histogram(bins, node, g, h, 20, 16, method="scatter")
        assert G.shape == (20, 3, 16)
        np.testing.assert_allclose(np.asarray(G), np.asarray(Gr),
                                   rtol=2e-2, atol=2e-2)
    finally:
        hist_pallas._ACC_BYTES_LIMIT = orig


def test_non_power_of_two_nodes_padding():
    """M = 2*n_pad must stay a multiple of the bf16 tile for any node count."""
    bins, node, g, h = _rand_case(256, 2, 8, 12, seed=4)
    G, H = hist_pallas.grad_hist_pallas(bins, node, g, h, 12, 8)
    Gr, _ = grad_histogram(bins, node, g, h, 12, 8, method="scatter")
    assert G.shape == (12, 2, 8)
    np.testing.assert_allclose(np.asarray(G), np.asarray(Gr),
                               rtol=2e-2, atol=2e-2)


def test_gbdt_fit_pallas_matches_scatter_splits():
    """End-to-end tiny fit: pallas and scatter grow the same trees."""
    from dmlc_core_tpu.models.gbdt import GBDT, GBDTParam

    rng = np.random.RandomState(3)
    x = rng.randn(300, 4).astype(np.float32)   # row count forces fit padding
    y = (x[:, 0] + 0.1 * rng.randn(300) > 0).astype(np.float32)
    param = GBDTParam(num_boost_round=2, max_depth=3, num_bins=16,
                      hist_method="pallas")
    model = GBDT(param, num_feature=4)
    model.make_bins(x)
    bins = np.asarray(model.bin_features(x))
    ens_p, margin_p = model.fit_binned(bins, y)

    model_s = GBDT(GBDTParam(num_boost_round=2, max_depth=3, num_bins=16,
                             hist_method="scatter"), num_feature=4)
    model_s.boundaries = model.boundaries
    ens_s, margin_s = model_s.fit_binned(bins, y)

    assert margin_p.shape == (300,)
    np.testing.assert_array_equal(np.asarray(ens_p.split_feat),
                                  np.asarray(ens_s.split_feat))
    np.testing.assert_allclose(np.asarray(margin_p), np.asarray(margin_s),
                               rtol=5e-2, atol=5e-2)


@pytest.mark.parametrize("b,f,nbins,nnodes", [
    (256, 3, 8, 4),
    (300, 5, 16, 2),      # padding path (pad rows carry node=-1)
    (700, 2, 4, 12),      # multi-tile + non-power-of-two nodes
])
def test_fused_matches_scatter(b, f, nbins, nnodes):
    bins, node, g, h = _rand_case(b, f, nbins, nnodes, seed=7)
    G, H = hist_pallas.grad_hist_pallas_fused(bins, node, g, h, nnodes,
                                              nbins)
    Gr, Hr = grad_histogram(bins, node, g, h, nnodes, nbins,
                            method="scatter")
    assert G.shape == (nnodes, f, nbins)
    np.testing.assert_allclose(np.asarray(G), np.asarray(Gr),
                               rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(H), np.asarray(Hr),
                               rtol=2e-2, atol=2e-2)


def test_fused_matches_unfused():
    bins, node, g, h = _rand_case(512, 4, 16, 8, seed=8)
    Gf, Hf = hist_pallas.grad_hist_pallas_fused(bins, node, g, h, 8, 16)
    Gu, Hu = hist_pallas.grad_hist_pallas(bins, node, g, h, 8, 16)
    np.testing.assert_allclose(np.asarray(Gf), np.asarray(Gu),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(Hf), np.asarray(Hu),
                               rtol=1e-5, atol=1e-5)


def test_fused_probe_gates_method(monkeypatch):
    """A user-selected pallas_fused falls back when the fused kernel's probe
    fails (ADVICE r1: fused may not lower on real Mosaic where the plain
    kernel does) — and never crashes at first use."""
    bins, node, g, h = _rand_case(256, 3, 8, 4, seed=9)
    monkeypatch.setattr(hist_pallas, "pallas_fused_supported", lambda: False)
    G, H = grad_histogram(bins, node, g, h, 4, 8, method="pallas_fused")
    Gr, Hr = grad_histogram(bins, node, g, h, 4, 8, method="scatter")
    np.testing.assert_allclose(np.asarray(G), np.asarray(Gr),
                               rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(H), np.asarray(Hr),
                               rtol=2e-2, atol=2e-2)


def test_fused_probe_passes_in_interpret_mode():
    assert hist_pallas.pallas_fused_supported() is True


def _mesh_2d(data=4, model=2):
    import jax
    from dmlc_core_tpu.parallel.mesh import make_mesh

    return make_mesh({"data": data, "model": model},
                     devices=jax.devices()[:data * model])


def test_sharded_pallas_matches_scatter():
    """Model-sharded hist keeps the pallas kernel via shard_map (VERDICT r1
    item 3) and matches the exact scatter result."""
    import jax

    bins, node, g, h = _rand_case(256, 8, 16, 4, seed=11)
    mesh = _mesh_2d()
    calls = []
    orig = hist_pallas.grad_hist_pallas_sharded

    def spy(*args, **kwargs):
        calls.append(kwargs.get("fused"))
        return orig(*args, **kwargs)

    hist_pallas.grad_hist_pallas_sharded = spy
    try:
        with mesh:
            G, H = jax.jit(lambda *a: grad_histogram(
                *a, 4, 16, model_axis="model", method="pallas"))(
                    bins, node, g, h)
            G, H = np.asarray(G), np.asarray(H)
    finally:
        hist_pallas.grad_hist_pallas_sharded = orig
    assert calls == [False], "sharded pallas path was not taken"
    Gr, Hr = grad_histogram(bins, node, g, h, 4, 16, method="scatter")
    np.testing.assert_allclose(G, np.asarray(Gr), rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(H, np.asarray(Hr), rtol=2e-2, atol=2e-2)


def test_sharded_pallas_fused_variant():
    import jax

    bins, node, g, h = _rand_case(512, 4, 8, 6, seed=12)
    mesh = _mesh_2d()
    with mesh:
        G, H = jax.jit(lambda *a: grad_histogram(
            *a, 6, 8, model_axis="model", method="pallas_fused"))(
                bins, node, g, h)
        G = np.asarray(G)
    Gr, _ = grad_histogram(bins, node, g, h, 6, 8, method="scatter")
    np.testing.assert_allclose(G, np.asarray(Gr), rtol=2e-2, atol=2e-2)


def test_sharded_pallas_uneven_features_falls_back():
    """F not divisible by the model axis must fall back, not crash."""
    import jax

    bins, node, g, h = _rand_case(256, 7, 8, 4, seed=13)   # 7 % 2 != 0
    mesh = _mesh_2d()
    with mesh:
        G, _ = jax.jit(lambda *a: grad_histogram(
            *a, 4, 8, model_axis="model", method="pallas"))(bins, node, g, h)
        G = np.asarray(G)
    Gr, _ = grad_histogram(bins, node, g, h, 4, 8, method="scatter")
    np.testing.assert_allclose(G, np.asarray(Gr), rtol=2e-2, atol=2e-2)


def test_gbdt_model_sharded_keeps_pallas():
    """Under an ambient mesh, a model-sharded GBDT resolves to pallas and
    trains on the kernel path end-to-end."""
    import jax
    import jax.numpy as jnp
    from dmlc_core_tpu.models.gbdt import GBDT, GBDTParam
    from dmlc_core_tpu.parallel.mesh import data_sharding

    mesh = _mesh_2d()
    rng = np.random.RandomState(5)
    B, F = 64, 8
    x = rng.randn(B, F).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    model = GBDT(GBDTParam(num_boost_round=2, max_depth=3, num_bins=16,
                           hist_method="pallas"), num_feature=F,
                 model_axis="model")
    model.make_bins(x)
    with mesh:
        assert model._method() == "pallas"
        bins = jax.device_put(model.bin_features(x),
                              data_sharding(mesh, ndim=2))
        label = jax.device_put(jnp.asarray(y), data_sharding(mesh, ndim=1))
        weight = jax.device_put(jnp.ones(B, jnp.float32),
                                data_sharding(mesh, ndim=1))
        margin = jax.device_put(jnp.zeros(B, jnp.float32),
                                data_sharding(mesh, ndim=1))
        new_margin, _ = model.boost_round(margin, bins, label, weight)
        new_margin = np.asarray(new_margin)
    assert np.isfinite(new_margin).all()
    # same trees as the unsharded scatter fit
    ref = GBDT(GBDTParam(num_boost_round=2, max_depth=3, num_bins=16,
                         hist_method="scatter"), num_feature=F)
    ref.boundaries = model.boundaries
    rm, _ = ref.boost_round(jnp.zeros(B, jnp.float32),
                            jnp.asarray(model.bin_features(x)),
                            jnp.asarray(y), jnp.ones(B, jnp.float32))
    np.testing.assert_allclose(new_margin, np.asarray(rm), rtol=5e-2,
                               atol=5e-2)


def test_ambient_mesh_probe_on_current_jax():
    """The ambient-mesh accessor reaches into jax internals
    (hist_pallas.ambient_mesh); if a jax upgrade moves it, the model-sharded
    kernel would silently degrade to onehot.  Pin the probe directly."""
    mesh = _mesh_2d()
    assert hist_pallas.ambient_mesh() is None
    with mesh:
        m = hist_pallas.ambient_mesh()
        assert m is not None, (
            "ambient_mesh() lost the enclosing mesh on jax "
            + __import__("jax").__version__)
        assert m.shape["model"] == 2
        # and the single-source-of-truth gate selects the kernel with it
        assert hist_pallas.sharded_hist_plan("model", 8, 4, 16,
                                             batch=256) is m
    assert hist_pallas.ambient_mesh() is None


@pytest.mark.parametrize("nbins", [256, 257])
def test_i8_compare_dtype_gate(nbins):
    """int8 bins compares apply exactly when bin ids fit 256 (wraparound
    keeps equality a bijection); wider binnings stay int32."""
    import jax.numpy as jnp

    dt = hist_pallas._bins_compare_dtype(nbins)
    if nbins <= 256:
        assert dt == (jnp.int8 if hist_pallas.pallas_i8_supported()
                      else jnp.int32)
    else:
        assert dt == jnp.int32


def test_i8_path_matches_scatter_at_256_bins(monkeypatch):
    """Full 256-bin case through the int8 compare path (bin 255 wraps to -1
    in int8 on both sides of the compare)."""
    monkeypatch.delenv("DMLC_TPU_HIST_I8", raising=False)
    hist_pallas.pallas_i8_supported.cache_clear()
    assert hist_pallas.pallas_i8_supported()   # interpret mode lowers it
    bins, node, g, h = _rand_case(512, 3, 256, 4, seed=21)
    bins[:16, 0] = 255                          # exercise the wrap edge
    G, H = hist_pallas.grad_hist_pallas(bins, node, g, h, 4, 256)
    Gr, Hr = grad_histogram(bins, node, g, h, 4, 256, method="scatter")
    np.testing.assert_allclose(np.asarray(G), np.asarray(Gr),
                               rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(H), np.asarray(Hr),
                               rtol=2e-2, atol=2e-2)


def test_i8_disable_env(monkeypatch):
    monkeypatch.setenv("DMLC_TPU_HIST_I8", "0")
    hist_pallas.pallas_i8_supported.cache_clear()
    try:
        assert not hist_pallas.pallas_i8_supported()
    finally:
        hist_pallas.pallas_i8_supported.cache_clear()


def test_subsample_draw_independent_of_row_padding(interpret_mode):
    """The per-tree subsample draw must be made over the UNPADDED row count:
    fit_binned pads rows to the pallas tile, boost_round does not — with
    padding-dependent sampling the two entry points would train different
    trees on identical data (n_rows deliberately not a tile multiple)."""
    import jax.numpy as jnp

    from dmlc_core_tpu.models.gbdt import GBDT, GBDTParam

    rng = np.random.RandomState(21)
    n, F = 1500, 4                       # 1500 % 1024 != 0 -> fit pads
    x = rng.randn(n, F).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    m = GBDT(GBDTParam(num_boost_round=3, max_depth=3, num_bins=16,
                       subsample=0.7, seed=5, hist_method="pallas"),
             num_feature=F)
    m.make_bins(x)
    bins = jnp.asarray(np.asarray(m.bin_features(x), np.int32))
    ens_fit, _ = m.fit_binned(bins, y)

    margin = jnp.zeros(n, jnp.float32)
    w = jnp.ones(n, jnp.float32)
    sfs = []
    for r in range(3):
        margin, tree = m.boost_round(margin, bins, jnp.asarray(y), w,
                                     round_index=r)
        sfs.append(np.asarray(tree[0]))
    np.testing.assert_array_equal(np.stack(sfs),
                                  np.asarray(ens_fit.split_feat))
