"""Registry tests (reference: test/registry_test.cc)."""

import pytest

from dmlc_core_tpu.registry import Registry


def test_register_find_call():
    reg = Registry.get("test_tree")

    @reg.register("binary", aliases=["bt"], description="binary tree")
    def make_binary(depth):
        return ("binary", depth)

    assert reg.find("binary") is not None
    assert reg.find("bt") is reg.find("binary")
    assert reg["binary"](3) == ("binary", 3)
    assert reg.find("missing") is None
    assert "binary" in reg
    assert reg.list_names() == ["binary"]
    reg.remove("binary")
    assert reg.find("bt") is None


def test_singleton_per_kind():
    assert Registry.get("kind_a") is Registry.get("kind_a")
    assert Registry.get("kind_a") is not Registry.get("kind_b")


def test_double_registration_raises():
    reg = Registry.get("test_dup")
    reg.add("x", lambda: 1)
    with pytest.raises(KeyError):
        reg.add("x", lambda: 2)
    reg.add("x", lambda: 3, override=True)
    assert reg["x"]() == 3
    reg.remove("x")


def test_unknown_lookup_message():
    reg = Registry.get("test_msg")
    reg.add("known", lambda: 1)
    with pytest.raises(KeyError, match="known"):
        reg["unknown"]
    reg.remove("known")
