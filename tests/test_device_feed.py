"""Device-feed pipeline tests (ISSUE 9): host binning determinism vs the
float path, double-buffered loader byte-identity, transfer telemetry, and
the epoch-boundary robustness of the loader's producer.

The load-bearing guarantees:

- training on host-binned uint8 wire bins makes bitwise-identical split
  decisions to the on-device float->apply_bins path (same searchsorted
  semantics host and device, widened to int32 inside the jit);
- the double-buffered DeviceFeedLoader reorders *time*, never data — the
  batch sequence is byte-identical to the synchronous path, including
  across a full before_first() epoch restart;
- every transfer is accounted (loader.transfer spans +
  dmlc_transfer_{bytes,seconds}_total) on BOTH the new device-feed mode
  and the pre-existing MeshBatchLoader._shard path.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dmlc_core_tpu import telemetry
from dmlc_core_tpu.bridge.batching import dense_batches
from dmlc_core_tpu.bridge.binning import (BinnedBatch, HostBinner,
                                          binned_batches, fit_binner,
                                          wire_dtype)
from dmlc_core_tpu.bridge.loader import (DeviceFeedLoader, MeshBatchLoader,
                                         _EpochProducer, batch_nbytes)
from dmlc_core_tpu.data.factory import create_parser
from dmlc_core_tpu.io.threadediter import ThreadedIter
from dmlc_core_tpu.models.gbdt import GBDT, GBDTParam
from dmlc_core_tpu.ops.histogram import apply_bins
from dmlc_core_tpu.parallel.mesh import make_mesh


@pytest.fixture(autouse=True)
def _clean_telemetry():
    was_enabled = telemetry.enabled()
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()
    if was_enabled:
        telemetry.enable()


def make_xy(n=3000, f=7, seed=0, nan_rate=0.0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f).astype(np.float32)
    if nan_rate:
        x[rng.rand(n, f) < nan_rate] = np.nan
    w = rng.randn(f).astype(np.float32)
    y = ((np.nan_to_num(x) @ w) > 0).astype(np.float32)
    return x, y


def counter_value(name, **labels):
    fam = telemetry.snapshot()["metrics"].get(name, {"samples": []})
    return sum(s["value"] for s in fam["samples"]
               if all(s["labels"].get(k) == v for k, v in labels.items()))


def span_names():
    return [e["name"] for e in telemetry.get_tracer().events()]


# -- host binner vs the on-device float path ---------------------------------

def test_host_binner_matches_apply_bins_bitwise():
    x, _ = make_xy(5000, 6, seed=1)
    # adversarial values: exact boundary hits, +-inf, huge magnitudes
    x[0, :] = 0.0
    x[1, :] = np.inf
    x[2, :] = -np.inf
    x[3, :] = 1e30
    model = GBDT(GBDTParam(num_bins=64), num_feature=6)
    model.make_bins(x[:2000])
    binner = HostBinner(model.boundaries, 64)
    host = binner.transform(x)
    dev = np.asarray(apply_bins(x, model.boundaries))
    assert host.dtype == np.uint8
    np.testing.assert_array_equal(host.astype(np.int32), dev)


def test_host_binner_matches_apply_bins_missing_mode():
    x, _ = make_xy(4000, 5, seed=2, nan_rate=0.15)
    model = GBDT(GBDTParam(num_bins=64, handle_missing=True), num_feature=5)
    model.make_bins(x[:2000])
    binner = HostBinner(model.boundaries, 64, handle_missing=True)
    host = binner.transform(x)
    dev = np.asarray(apply_bins(x, model.boundaries, missing_bin=63))
    np.testing.assert_array_equal(host.astype(np.int32), dev)
    assert (host[np.isnan(x)] == 63).all()


def test_prebinned_uint8_training_identical_to_float_path():
    """The tentpole contract: uint8 wire bins -> bitwise-equal trees."""
    x, y = make_xy(3000, 7, seed=0)
    param = GBDTParam(num_boost_round=4, max_depth=4, num_bins=256,
                      learning_rate=0.3)
    model = GBDT(param, num_feature=7)
    model.make_bins(x[:1000])
    wire = HostBinner(model.boundaries, 256).transform(x)
    assert wire.dtype == np.uint8
    ens_w, margin_w = model.fit_binned(wire, y)
    ens_f, margin_f = model.fit_binned(np.asarray(model.bin_features(x)), y)
    for a, b in zip(ens_w[:4], ens_f[:4]):  # feat/bin/leaf/default_left
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(margin_w),
                                  np.asarray(margin_f))
    # predict accepts the wire dtype too, and agrees bitwise
    np.testing.assert_array_equal(
        np.asarray(model.predict(ens_w, wire[:64])),
        np.asarray(model.predict(ens_f,
                                 np.asarray(model.bin_features(x[:64])))))


def test_prebinned_training_identical_missing_mode():
    x, y = make_xy(2000, 5, seed=3, nan_rate=0.2)
    param = GBDTParam(num_boost_round=3, max_depth=3, num_bins=64,
                      handle_missing=True)
    model = GBDT(param, num_feature=5)
    model.make_bins(x[:800])
    wire = HostBinner(model.boundaries, 64,
                      handle_missing=True).transform(x)
    ens_w, _ = model.fit_binned(wire, y)
    ens_f, _ = model.fit_binned(np.asarray(model.bin_features(x)), y)
    np.testing.assert_array_equal(np.asarray(ens_w.split_feat),
                                  np.asarray(ens_f.split_feat))
    np.testing.assert_array_equal(np.asarray(ens_w.split_bin),
                                  np.asarray(ens_f.split_bin))
    np.testing.assert_array_equal(np.asarray(ens_w.default_left),
                                  np.asarray(ens_f.default_left))


def test_set_boundaries_installs_streamed_edges():
    x, y = make_xy(1500, 4, seed=4)
    binner = fit_binner([x[:700], x[700:]], 32)
    model = GBDT(GBDTParam(num_boost_round=2, num_bins=32, max_depth=3),
                 num_feature=4)
    model.set_boundaries(binner.boundaries)
    ens, _ = model.fit_binned(binner.transform(x), y)
    assert np.asarray(ens.split_feat).shape[0] == 2
    with pytest.raises(Exception):
        model.set_boundaries(np.zeros((4, 5), np.float32))  # wrong width


# -- binning edge cases -------------------------------------------------------

def test_binning_constant_column():
    x = np.ones((500, 3), np.float32)
    x[:, 1] = np.arange(500, dtype=np.float32)
    binner = fit_binner(x, 16)
    ids = binner.transform(x)
    # constant columns collapse to one id; varying column spreads
    assert len(np.unique(ids[:, 0])) == 1
    assert len(np.unique(ids[:, 1])) > 8
    dev = np.asarray(apply_bins(x, binner.boundaries))
    np.testing.assert_array_equal(ids.astype(np.int32), dev)


def test_binning_nan_without_missing_mode_matches_device():
    x, _ = make_xy(800, 3, seed=5, nan_rate=0.1)
    binner = fit_binner(np.nan_to_num(x), 32)
    np.testing.assert_array_equal(
        binner.transform(x).astype(np.int32),
        np.asarray(apply_bins(x, binner.boundaries)))


def test_binning_many_distinct_values_saturates_ladder():
    rng = np.random.RandomState(6)
    x = rng.rand(20000, 2).astype(np.float32)  # >> 256 distinct values
    binner = fit_binner(x, 256)
    ids = binner.transform(x)
    assert ids.dtype == np.uint8
    assert ids.max() == 255 and ids.min() == 0
    # quantile property: every bin carries mass (uniform data)
    counts = np.bincount(ids[:, 0], minlength=256)
    assert (counts > 0).all()


def test_wire_dtype_ladder():
    assert wire_dtype(256) == np.uint8
    assert wire_dtype(257) == np.uint16
    assert wire_dtype(65536) == np.uint16
    assert wire_dtype(65537) == np.int32
    with pytest.raises(Exception):
        wire_dtype(1)


def test_fit_binner_empty_source_rejected():
    with pytest.raises(Exception):
        fit_binner([], 16)


# -- streaming sources --------------------------------------------------------

def write_libsvm(tmp_path, n=100):
    lines = [f"{i % 2} 0:{i} 2:{(i * 7) % 13}" for i in range(n)]
    p = tmp_path / "data.libsvm"
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def test_fit_binner_over_parser_blocks(tmp_path):
    uri = write_libsvm(tmp_path, 200)
    parser = create_parser(uri, type="libsvm", threaded=False)
    binner = fit_binner(parser, 16, num_feature=3)
    assert binner.boundaries.shape == (3, 15)
    ids = binner.transform(np.asarray([[0.0, 0.0, 0.0],
                                       [199.0, 0.0, 12.0]], np.float32))
    assert ids.shape == (2, 3) and ids.dtype == np.uint8
    assert ids[1, 0] > ids[0, 0]


def test_fit_binner_over_page_cache_views(tmp_path):
    """The zero-copy path the ROADMAP names: edges streamed directly off
    the mmap'd v2 cache's RowBlock views."""
    from dmlc_core_tpu.data.iterators import DiskRowIter

    uri = write_libsvm(tmp_path, 300)
    cache = str(tmp_path / "cache.v2")
    it = DiskRowIter(create_parser(uri, type="libsvm", threaded=False),
                     cache)
    try:
        blocks = it.cache_blocks()
        assert blocks is not None  # v2 mmap engaged
        binner = fit_binner(blocks, 16, num_feature=3)
        assert binner.boundaries.shape == (3, 15)
    finally:
        it.close()


def test_binned_batches_pipeline(tmp_path):
    uri = write_libsvm(tmp_path, 100)
    parser = create_parser(uri, type="libsvm", threaded=False)
    binner = fit_binner(np.arange(300, dtype=np.float32).reshape(100, 3),
                        16)
    parser2 = create_parser(uri, type="libsvm", threaded=False)
    batches = list(binned_batches(parser2, binner, batch_size=32))
    assert len(batches) == 4
    for b in batches[:3]:
        assert isinstance(b, BinnedBatch)
        assert b.bins.shape == (32, 3) and b.bins.dtype == np.uint8
        assert b.num_rows == 32
    tail = batches[-1]
    assert tail.num_rows == 4
    assert (tail.weight[4:] == 0).all()  # padding mask contract


def test_binned_batch_passes_through_jit():
    b = BinnedBatch(np.zeros((8, 3), np.uint8), np.zeros(8, np.float32),
                    np.ones(8, np.float32), num_rows=8)

    @jax.jit
    def rows(batch):
        return jnp.sum(batch.bins.astype(jnp.int32)) + batch.label.sum()

    assert float(rows(b)) == 0.0
    # num_rows is static aux data, readable under jit
    leaves, treedef = jax.tree_util.tree_flatten(b)
    assert len(leaves) == 3


# -- double-buffered device feed ----------------------------------------------

def host_batch_stream(n_batches=6, rows=64, f=3, seed=0):
    rng = np.random.RandomState(seed)
    return [BinnedBatch(rng.randint(0, 255, (rows, f)).astype(np.uint8),
                        rng.randn(rows).astype(np.float32),
                        np.ones(rows, np.float32), num_rows=rows)
            for _ in range(n_batches)]


@pytest.mark.parametrize("prefetch", [1, 2, 4])
def test_device_feed_identical_to_sync_with_epoch_restart(prefetch):
    batches = host_batch_stream()
    sync = [jax.device_put(b) for b in batches]
    loader = DeviceFeedLoader(lambda: iter(batches), prefetch=prefetch)
    for epoch in range(2):  # second epoch == full before_first() restart
        got = list(loader)
        assert len(got) == len(sync)
        for g, s in zip(got, sync):
            np.testing.assert_array_equal(np.asarray(g.bins),
                                          np.asarray(s.bins))
            np.testing.assert_array_equal(np.asarray(g.label),
                                          np.asarray(s.label))
            assert g.bins.dtype == jnp.uint8  # wire dtype survives


def test_device_feed_object_source_before_first():
    class Source:
        def __init__(self, batches):
            self._b = batches
            self.resets = 0

        def before_first(self):
            self.resets += 1

        def __iter__(self):
            return iter(self._b)

    src = Source(host_batch_stream(3))
    loader = DeviceFeedLoader(src, prefetch=2)
    assert len(list(loader)) == 3
    assert src.resets == 1
    loader.before_first()
    assert src.resets == 2
    assert len(list(loader)) == 3


def test_device_feed_transfer_telemetry():
    telemetry.enable()
    batches = host_batch_stream(4)
    expect_bytes = sum(batch_nbytes(b) for b in batches)
    loader = DeviceFeedLoader(lambda: iter(batches), prefetch=2)
    list(loader)
    assert counter_value("dmlc_transfer_bytes_total",
                         path="device_feed") == expect_bytes
    assert counter_value("dmlc_transfer_seconds_total", path="device_feed",
                         phase="dispatch") > 0
    names = span_names()
    assert names.count("loader.transfer") == 4
    assert names.count("loader.transfer.wait") == 4


def test_device_feed_rejects_bad_args():
    with pytest.raises(Exception):
        DeviceFeedLoader(lambda: iter([]), prefetch=0)
    with pytest.raises(Exception):
        DeviceFeedLoader(lambda: iter([]), device=jax.devices()[0],
                         sharding=object())


# -- mesh loader: transfer accounting + device prefetch ----------------------

def test_mesh_loader_shard_transfer_span(tmp_path):
    """Satellite: the pre-existing _shard path shows up in trace critical
    paths too, not just the new device-feed mode."""
    telemetry.enable()
    uri = write_libsvm(tmp_path, 128)
    mesh = make_mesh({"data": 8})
    parser = create_parser(uri, type="libsvm", threaded=False)
    loader = MeshBatchLoader(parser, mesh, form="dense",
                             global_batch_size=32, num_feature=3)
    batches = list(loader)
    loader.close()
    assert len(batches) == 4
    assert span_names().count("loader.transfer") == 4
    # 32 rows x (3 f32 feats + label + weight) per batch, 4 batches
    assert counter_value("dmlc_transfer_bytes_total",
                         path="mesh_shard") == 4 * 32 * (3 + 1 + 1) * 4
    assert counter_value("dmlc_transfer_seconds_total", path="mesh_shard",
                         phase="dispatch") > 0


def test_mesh_loader_device_prefetch_identical(tmp_path):
    uri = write_libsvm(tmp_path, 128)
    mesh = make_mesh({"data": 8})

    def batches_with(dp):
        parser = create_parser(uri, type="libsvm", threaded=False)
        loader = MeshBatchLoader(parser, mesh, form="dense",
                                 global_batch_size=32, num_feature=3,
                                 device_prefetch=dp)
        out = [(np.asarray(b.x), np.asarray(b.label)) for b in loader]
        # epoch restart under device prefetch too
        loader.before_first()
        out += [(np.asarray(b.x), np.asarray(b.label)) for b in loader]
        loader.close()
        return out

    sync = batches_with(0)
    buffered = batches_with(2)
    assert len(sync) == len(buffered) == 8
    for (xs, ls), (xb, lb) in zip(sync, buffered):
        np.testing.assert_array_equal(xs, xb)
        np.testing.assert_array_equal(ls, lb)


def test_mesh_loader_device_prefetch_survives_abandoned_iteration(tmp_path):
    """Break/resume parity with the sync path: batches already dispatched
    into the prefetch buffer when an iteration is abandoned must be
    yielded by the next one, not silently dropped from the epoch."""
    uri = write_libsvm(tmp_path, 128)
    mesh = make_mesh({"data": 8})

    def make_loader(dp):
        parser = create_parser(uri, type="libsvm", threaded=False)
        return MeshBatchLoader(parser, mesh, form="dense",
                               global_batch_size=32, num_feature=3,
                               device_prefetch=dp)

    sync = make_loader(0)
    expected = [np.asarray(b.x) for b in sync]
    sync.close()
    assert len(expected) == 4

    loader = make_loader(2)
    it = iter(loader)
    first = np.asarray(next(it).x)       # up to 2 more are now in flight
    del it                               # abandon mid-epoch
    rest = [np.asarray(b.x) for b in loader]
    np.testing.assert_array_equal(first, expected[0])
    assert len(rest) == 3                # nothing vanished with the iterator
    for got, want in zip(rest, expected[1:]):
        np.testing.assert_array_equal(got, want)
    # before_first drops the stale in-flight batches and restarts cleanly
    loader.before_first()
    fresh = [np.asarray(b.x) for b in loader]
    assert len(fresh) == 4
    np.testing.assert_array_equal(fresh[0], expected[0])
    loader.close()


# -- epoch-boundary robustness (satellite regression) ------------------------

class _FlakyFactory:
    """First epoch dies mid-iteration; later epochs are clean."""

    def __init__(self):
        self.calls = 0

    def __call__(self):
        self.calls += 1
        return self._gen(self.calls)

    @staticmethod
    def _gen(call):
        yield "a"
        if call == 1:
            raise RuntimeError("mid-epoch parse failure")
        yield "b"


class _NullParser:
    def before_first(self):
        pass


def test_epoch_producer_resets_iterator_on_midepoch_error():
    factory = _FlakyFactory()
    prod = _EpochProducer(_NullParser(), factory)
    assert prod.next(None) == "a"
    with pytest.raises(RuntimeError):
        prod.next(None)
    # the dead iterator must NOT read as a clean epoch end: the next pull
    # restarts the factory instead of returning None off the corpse
    assert prod.next(None) == "a"
    assert prod.next(None) == "b"
    assert prod.next(None) is None


def test_epoch_producer_recovers_through_threadediter():
    factory = _FlakyFactory()
    it = ThreadedIter(_EpochProducer(_NullParser(), factory),
                      max_capacity=2, name="test_feed")
    try:
        assert it.next() == "a"
        with pytest.raises(RuntimeError):
            while True:
                if it.next() is None:
                    raise AssertionError("error was swallowed")
        it.before_first()
        assert [it.next(), it.next(), it.next()] == ["a", "b", None]
    finally:
        it.destroy()


# -- serving-side skew-free contract (ISSUE 15) -------------------------------
# The model-lifecycle subsystem serves GBDT requests through the same
# uint8 binned wire training uses.  These tests pin the three-way
# bitwise identity: serving binner == training-time apply_bins ==
# float-path predict, for a runtime restored from a checkpoint.

_SERVING_RUNTIMES = {}


def _serving_runtime(handle_missing, num_feature=7, seed=0):
    """One trained GBDT runtime per config, memoized: the fit (a full jit
    compile) costs seconds and every test here only READS the model."""
    from dmlc_core_tpu.serve.model_runtime import GBDTRuntime

    key = (handle_missing, num_feature, seed)
    if key not in _SERVING_RUNTIMES:
        x, y = make_xy(n=600, f=num_feature, seed=seed,
                       nan_rate=0.15 if handle_missing else 0.0)
        gbdt = GBDT(GBDTParam(objective="logistic", num_boost_round=5,
                              max_depth=3, num_bins=64,
                              handle_missing=handle_missing), num_feature)
        gbdt.make_bins(x)
        ensemble, _ = gbdt.fit_binned(gbdt.bin_features(x), y)
        _SERVING_RUNTIMES[key] = (GBDTRuntime(gbdt, ensemble), x)
    return _SERVING_RUNTIMES[key]


@pytest.mark.parametrize("handle_missing", [False, True])
def test_serving_binner_bitwise_equal_training_apply_bins(handle_missing):
    rt, x = _serving_runtime(handle_missing)
    # adversarial rows: exact boundary values (ties go right), +-inf,
    # NaN, and all-zero padding rows like the scheduler emits
    probe = np.array(x[:50])
    probe[0, :] = rt.gbdt.boundaries[np.arange(x.shape[1]), 0]
    probe[1, :] = rt.gbdt.boundaries[np.arange(x.shape[1]), -1]
    probe[2, :] = np.inf
    probe[3, :] = -np.inf
    probe[4, :] = 0.0
    if handle_missing:
        probe[5, :] = np.nan
    miss = (rt.gbdt.param.num_bins - 1 if handle_missing else None)
    want = np.asarray(apply_bins(probe, rt.gbdt.boundaries,
                                 missing_bin=miss))
    got = rt.binner.transform(probe)
    # identical ids — the serving wire applies the exact training binning
    np.testing.assert_array_equal(got.astype(np.int32), want)
    assert got.dtype == wire_dtype(rt.gbdt.param.num_bins)


@pytest.mark.parametrize("handle_missing", [False, True])
def test_serving_uint8_path_bitwise_equal_float_predict(handle_missing):
    rt, x = _serving_runtime(handle_missing)
    probe = np.array(x[:40])
    probe[0, :] = rt.gbdt.boundaries[np.arange(x.shape[1]), 0]
    if handle_missing:
        probe[1, :] = np.nan
    got = rt.predict(probe)            # uint8 wire, widened in-jit
    want = rt.predict_float(probe)     # device-side float binning
    np.testing.assert_array_equal(got, want)


def test_serving_checkpoint_restore_keeps_the_skew_contract(tmp_path):
    # the swapped-in model (restored from a serving_state checkpoint)
    # still satisfies both identities — what the watcher actually serves
    from dmlc_core_tpu.bridge.checkpoint import CheckpointManager
    from dmlc_core_tpu.serve.model_runtime import build_runtime

    rt, x = _serving_runtime(False)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(1, rt.gbdt.serving_state(rt.ensemble), async_=False)
    restored = build_runtime("gbdt", x.shape[1],
                             checkpoint=mgr.step_uri(1))
    probe = x[:25]
    np.testing.assert_array_equal(
        restored.binner.transform(probe),
        rt.binner.transform(probe))
    np.testing.assert_array_equal(restored.predict(probe),
                                  rt.predict(probe))
    np.testing.assert_array_equal(restored.predict(probe),
                                  restored.predict_float(probe))

def test_resumed_model_keeps_the_skew_contract(tmp_path):
    """A continuous-training refresh (GBDT.resume + append_rounds) must not
    move the serving wire: the restored edges are frozen, so the uint8
    binning stays bitwise identical to apply_bins on the ORIGINAL edges,
    and the refreshed checkpoint serves bitwise-consistently."""
    from dmlc_core_tpu.bridge.checkpoint import (CheckpointManager,
                                                 load_checkpoint)
    from dmlc_core_tpu.serve.model_runtime import build_runtime

    x, y = make_xy(n=1200, f=5, seed=4)
    gbdt = GBDT(GBDTParam(objective="logistic", num_boost_round=3,
                          max_depth=3, num_bins=64), x.shape[1])
    gbdt.make_bins(x)
    ensemble, _ = gbdt.fit_binned(gbdt.bin_features(x), y)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(1, gbdt.serving_state(ensemble), async_=False)

    # the trainer daemon's refresh: resume from the checkpoint, append
    # rounds on drifted data (the edges must NOT refit to it)
    x2, y2 = make_xy(n=1200, f=5, seed=5)
    x2 = x2 * 3.0 + 1.5        # would yield different edges if refit
    resumed, ens2 = GBDT.resume(load_checkpoint(mgr.step_uri(1)))
    ens3, _ = resumed.append_rounds(ens2, resumed.bin_features(x2), y2,
                                    num_rounds=2)
    assert ens3.num_trees == ensemble.num_trees + 2
    np.testing.assert_array_equal(np.asarray(resumed.boundaries),
                                  np.asarray(gbdt.boundaries))
    mgr.save(2, resumed.serving_state(ens3), async_=False)

    # serving the refreshed step: HostBinner wire == apply_bins on the
    # ORIGINAL training edges, bitwise, on adversarial rows
    rt = build_runtime("gbdt", x.shape[1], checkpoint=mgr.step_uri(2))
    probe = np.array(x[:40])
    probe[0, :] = gbdt.boundaries[np.arange(x.shape[1]), 0]
    probe[1, :] = gbdt.boundaries[np.arange(x.shape[1]), -1]
    probe[2, :] = np.inf
    probe[3, :] = -np.inf
    probe[4, :] = 0.0
    want = np.asarray(apply_bins(probe, gbdt.boundaries))
    got = rt.binner.transform(probe)
    np.testing.assert_array_equal(got.astype(np.int32), want)
    assert got.dtype == wire_dtype(gbdt.param.num_bins)
    # and the uint8 wire path scores bitwise-equal to float binning
    np.testing.assert_array_equal(rt.predict(probe),
                                  rt.predict_float(probe))
