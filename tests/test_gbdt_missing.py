"""Sparsity-aware GBDT splits: missing values with learned default
directions (XGBoost's algorithm 3; the capability its sparse libsvm
ingestion rests on).  Missing = NaN features -> reserved last bin; every
split is scored with the missing mass on each side and routes missing rows
down the better one."""

import numpy as np
import pytest

from dmlc_core_tpu.models.gbdt import GBDT, GBDTParam
from dmlc_core_tpu.ops.histogram import apply_bins


def _make_model(**kw):
    kw.setdefault("num_boost_round", 5)
    kw.setdefault("max_depth", 3)
    kw.setdefault("num_bins", 16)
    kw.setdefault("learning_rate", 0.5)
    kw.setdefault("handle_missing", True)
    num_feature = kw.pop("_F", 4)
    return GBDT(GBDTParam(**kw), num_feature=num_feature)


def test_apply_bins_missing_id():
    rng = np.random.RandomState(0)
    x = rng.randn(100, 3).astype(np.float32)
    x[::7, 1] = np.nan
    m = GBDT(GBDTParam(num_bins=16, handle_missing=True), num_feature=3)
    m.make_bins(x)
    assert m.boundaries.shape == (3, 14)     # num_bins-1 finite bins
    bins = np.asarray(m.bin_features(x))
    assert (bins[::7, 1] == 15).all()        # reserved last bin
    finite = np.delete(bins, np.arange(0, 100, 7), axis=0)
    assert finite.max() <= 14                # finite values never take it


def test_learns_informative_missingness():
    """Missingness itself predicts the label: rows with feature 0 missing
    are positive.  A sparsity-aware model must exploit that; routing all
    missing to a fixed side can't separate them from the overlapping
    negatives."""
    rng = np.random.RandomState(1)
    n = 4000
    x = rng.randn(n, 4).astype(np.float32)
    y = (rng.rand(n) < 0.5).astype(np.float32)
    x[y == 1, 0] = np.nan                    # positives: feature 0 missing
    model = _make_model()
    model.make_bins(x)
    bins = model.bin_features(x)
    ens, margin = model.fit_binned(bins, y)
    acc = float(((np.asarray(margin) > 0) == y).mean())
    assert acc > 0.99, acc


def test_default_direction_learned_left():
    """Construct data where the gain is higher sending missing LEFT:
    missing rows share the label of small feature values."""
    rng = np.random.RandomState(2)
    n = 4000
    x = rng.randn(n, 2).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    # knock out feature 0 on a slice of the negatives (x0 < 0 = label 0):
    # their only recoverable signal is "missing behaves like small x0"
    neg = np.where(y == 0)[0][:800]
    x[neg, 0] = np.nan
    model = _make_model(_F=2, num_boost_round=3)
    model.make_bins(x)
    bins = model.bin_features(x)
    ens, margin = model.fit_binned(bins, y)
    acc = float(((np.asarray(margin) > 0) == y).mean())
    assert acc > 0.97, acc
    assert bool(np.asarray(ens.default_left).any()), \
        "expected at least one learned default-left split"


def test_predict_matches_fit_margin_with_missing():
    rng = np.random.RandomState(3)
    n = 2000
    x = rng.randn(n, 4).astype(np.float32)
    x[rng.rand(n, 4) < 0.3] = np.nan         # 30% missing everywhere
    w = np.array([1.5, -2.0, 0.7, 0.0], np.float32)
    y = (np.where(np.isnan(x), 0.0, x) @ w > 0).astype(np.float32)
    model = _make_model()
    model.make_bins(x)
    bins = model.bin_features(x)
    ens, margin = model.fit_binned(bins, y)
    pred = model.predict_margin(ens, bins)
    np.testing.assert_allclose(np.asarray(pred), np.asarray(margin),
                               rtol=1e-4, atol=1e-5)


def test_save_load_roundtrip_with_default_left(tmp_path):
    rng = np.random.RandomState(4)
    x = rng.randn(1000, 4).astype(np.float32)
    x[rng.rand(1000, 4) < 0.2] = np.nan
    y = (rng.rand(1000) < (np.isnan(x[:, 0]) * 0.8 + 0.1)).astype(np.float32)
    model = _make_model()
    model.make_bins(x)
    bins = model.bin_features(x)
    ens, _ = model.fit_binned(bins, y)
    uri = str(tmp_path / "m.bin")
    model.save_model(uri, ens)
    model2 = _make_model()
    ens2 = model2.load_model(uri)
    np.testing.assert_array_equal(np.asarray(ens.default_left),
                                  np.asarray(ens2.default_left))
    np.testing.assert_allclose(
        np.asarray(model.predict_margin(ens, bins)),
        np.asarray(model2.predict_margin(ens2, model2.bin_features(x))),
        rtol=1e-5)


def test_legacy_model_loads_without_default_left(tmp_path):
    """Checkpoints written before the field exists must load with all-False
    directions (exact legacy routing)."""
    from dmlc_core_tpu.bridge.checkpoint import save_checkpoint

    model = GBDT(GBDTParam(num_boost_round=2, max_depth=2, num_bins=8),
                 num_feature=3)
    sf = np.array([[0, 1, -1], [2, -1, -1]], np.int32)
    sb = np.array([[3, 2, 0], [1, 0, 0]], np.int32)
    lv = np.ones((2, 4), np.float32)
    uri = str(tmp_path / "legacy.bin")
    save_checkpoint(uri, {"split_feat": sf, "split_bin": sb,
                          "leaf_value": lv,
                          "boundaries": np.ones((3, 7), np.float32)})
    ens = model.load_model(uri)
    assert ens.default_left.shape == sf.shape
    assert not ens.default_left.any()


def test_disabled_missing_is_legacy_exact():
    """handle_missing=False must produce bit-identical trees to the
    pre-sparsity code path (default_left all False, same splits)."""
    rng = np.random.RandomState(5)
    x = rng.randn(3000, 4).astype(np.float32)
    y = (x[:, 0] * x[:, 1] > 0).astype(np.float32)
    model = GBDT(GBDTParam(num_boost_round=4, max_depth=4, num_bins=32),
                 num_feature=4)
    model.make_bins(x)
    bins = model.bin_features(x)
    ens, _ = model.fit_binned(bins, y)
    assert not np.asarray(ens.default_left).any()


def test_missing_with_eval_and_early_stopping():
    rng = np.random.RandomState(6)
    n = 3000
    x = rng.randn(n, 4).astype(np.float32)
    x[rng.rand(n, 4) < 0.2] = np.nan
    y = (np.isnan(x[:, 0]) | (np.nan_to_num(x[:, 1]) > 0.5)).astype(np.float32)
    model = _make_model(num_boost_round=20)
    model.make_bins(x[:2000])
    bins = np.asarray(model.bin_features(x))
    ens, hist = model.fit_with_eval(bins[:2000], y[:2000], bins[2000:],
                                    y[2000:], early_stopping_rounds=5)
    assert hist[-1]["eval_loss"] <= hist[0]["eval_loss"]


def test_missing_multiclass_smoke():
    rng = np.random.RandomState(7)
    n = 1500
    x = rng.randn(n, 4).astype(np.float32)
    y = rng.randint(0, 3, n).astype(np.float32)
    x[y == 2, 0] = np.nan                    # class 2 signalled by missing
    model = _make_model(objective="softmax", num_class=3,
                        num_boost_round=6)
    model.make_bins(x)
    bins = model.bin_features(x)
    ens, margin = model.fit_binned(bins, y)
    acc = float((np.asarray(margin).argmax(1) == y).mean())
    assert acc > 0.5, acc
    assert ens.default_left.shape == ens.split_feat.shape


def test_dense_batches_nan_fill(tmp_path):
    """Sparse libsvm rows densified with fill_value=nan: absent features are
    missing, present ones keep their value, padding rows stay zero."""
    from dmlc_core_tpu.bridge.batching import dense_batches
    from dmlc_core_tpu.data.factory import create_parser

    f = tmp_path / "t.libsvm"
    f.write_text("1 0:1.5 2:2.5\n0 1:3.5\n")
    parser = create_parser(str(f), 0, 1, type="auto")
    batches = list(dense_batches(parser, 4, 3, fill_value=np.nan))
    x = batches[0].x
    np.testing.assert_allclose(x[0], [1.5, np.nan, 2.5])
    np.testing.assert_allclose(x[1], [np.nan, 3.5, np.nan])
    assert (x[2:] == 0).all()                # padding rows zero, not NaN
    assert batches[0].weight[2:].sum() == 0


def test_load_refuses_mismatched_missing_mode(tmp_path):
    rng = np.random.RandomState(8)
    x = rng.randn(500, 4).astype(np.float32)
    x[rng.rand(500, 4) < 0.2] = np.nan
    y = (rng.rand(500) < 0.5).astype(np.float32)
    model = _make_model(num_boost_round=2)
    model.make_bins(x)
    ens, _ = model.fit_binned(model.bin_features(x), y)
    uri = str(tmp_path / "m.bin")
    model.save_model(uri, ens)
    plain = GBDT(GBDTParam(num_boost_round=2, max_depth=3, num_bins=16),
                 num_feature=4)
    with pytest.raises(Exception, match="handle_missing"):
        plain.load_model(uri)
