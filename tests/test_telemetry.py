"""Telemetry subsystem tests: registry thread-safety (exact counts under N
writers), histogram bucket-edge semantics, Chrome-trace export validity
(``ph``/``ts``/``pid``/``tid`` on every event), the disabled-mode no-op
path, exporter round-trips, and multi-rank ``report`` aggregation."""

import json
import os
import subprocess
import sys
import threading

import pytest

from dmlc_core_tpu import telemetry
from dmlc_core_tpu.telemetry import clock, export, report
from dmlc_core_tpu.telemetry.registry import (DEFAULT_BUCKETS, Histogram,
                                              MetricRegistry)
from dmlc_core_tpu.telemetry.spans import SpanTracer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Every test starts disabled with empty state; afterwards the prior
    enabled/disabled state is restored (the module is process-global, and
    a suite-wide DMLC_TELEMETRY_DIR run — CI — relies on collection staying
    on so the atexit flush produces the artifact)."""
    was_enabled = telemetry.enabled()
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()
    if was_enabled:
        telemetry.enable()


# -- registry: thread safety --------------------------------------------------

def test_counter_exact_under_n_writer_threads():
    reg = MetricRegistry()
    n_threads, per_thread = 8, 5000

    def work():
        for _ in range(per_thread):
            reg.counter("hits", worker="shared").inc()
            reg.histogram("lat").observe(0.01)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.counter("hits", worker="shared").value == n_threads * per_thread
    hist = reg.histogram("lat")
    assert hist.count == n_threads * per_thread
    assert hist.sum == pytest.approx(0.01 * n_threads * per_thread)


def test_gauge_and_labels_are_independent_children():
    reg = MetricRegistry()
    reg.gauge("depth", name="a").set(3)
    reg.gauge("depth", name="b").set(7)
    reg.gauge("depth", name="a").inc(2)
    assert reg.gauge("depth", name="a").value == 5
    assert reg.gauge("depth", name="b").value == 7
    # same family, kind clash is an error, not silent corruption
    with pytest.raises(ValueError):
        reg.counter("depth")


def test_counter_rejects_negative_increment():
    reg = MetricRegistry()
    with pytest.raises(ValueError):
        reg.counter("c").inc(-1)


# -- histogram bucket edges ---------------------------------------------------

def test_histogram_bucket_edges_are_le_inclusive():
    hist = Histogram(buckets=(1.0, 2.0, 5.0))
    for v in (0.5, 1.0, 1.5, 2.0, 5.0, 99.0):
        hist.observe(v)
    # Prometheus `le` semantics: an observation exactly on a bound belongs
    # to that bound's bucket, not the next one up
    assert hist.bucket_counts == [2, 2, 1, 1]  # <=1, <=2, <=5, +Inf
    assert hist.cumulative() == [2, 4, 5, 6]
    assert hist.count == 6
    assert hist.sum == pytest.approx(0.5 + 1.0 + 1.5 + 2.0 + 5.0 + 99.0)


def test_histogram_rejects_bad_buckets():
    with pytest.raises(ValueError):
        Histogram(buckets=())
    with pytest.raises(ValueError):
        Histogram(buckets=(1.0, 1.0))
    with pytest.raises(ValueError):
        Histogram(buckets=(2.0, 1.0))


def test_default_buckets_ascending():
    assert list(DEFAULT_BUCKETS) == sorted(DEFAULT_BUCKETS)


# -- disabled-mode no-op path -------------------------------------------------

def test_disabled_mode_records_nothing():
    assert not telemetry.enabled()
    telemetry.count("dmlc_x_total", 5)
    telemetry.gauge_set("dmlc_x_depth", 3)
    telemetry.observe("dmlc_x_seconds", 0.1)
    with telemetry.span("x", k=1) as sp:
        sp.set(extra=2)
    telemetry.record_span("y", clock.monotonic(), clock.monotonic())
    assert telemetry.get_registry().families() == []
    assert telemetry.get_tracer().events() == []


def test_disabled_span_is_shared_noop_object():
    a = telemetry.span("a")
    b = telemetry.span("b", attr=1)
    assert a is b  # no allocation on the disabled path


def test_enable_disable_round_trip():
    telemetry.enable()
    telemetry.count("dmlc_x_total")
    telemetry.disable()
    telemetry.count("dmlc_x_total")
    telemetry.enable()
    telemetry.count("dmlc_x_total")
    assert telemetry.get_registry().counter("dmlc_x_total").value == 2


# -- spans / Chrome trace -----------------------------------------------------

def test_chrome_trace_event_shape():
    telemetry.enable()
    with telemetry.span("outer", stage="io"):
        with telemetry.span("inner"):
            pass
    trace = telemetry.get_tracer().chrome_trace()
    # must survive a JSON round trip (what Perfetto actually loads)
    trace = json.loads(json.dumps(trace))
    events = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert len(events) == 2
    for event in events:
        for key in ("name", "ph", "ts", "pid", "tid", "dur"):
            assert key in event, f"missing {key}: {event}"
    outer = next(e for e in events if e["name"] == "outer")
    inner = next(e for e in events if e["name"] == "inner")
    assert outer["args"] == {"stage": "io"}
    # inner completed within outer on the same thread
    assert outer["ts"] <= inner["ts"]
    assert outer["ts"] + outer["dur"] >= inner["ts"] + inner["dur"]
    # thread-name metadata events accompany the spans
    assert any(e.get("ph") == "M" and e.get("name") == "thread_name"
               for e in trace["traceEvents"])


def test_span_records_exception_and_propagates():
    telemetry.enable()
    with pytest.raises(KeyError):
        with telemetry.span("boom"):
            raise KeyError("x")
    [event] = telemetry.get_tracer().events()
    assert event["args"]["error"] == "KeyError"


def test_record_span_uses_monotonic_domain():
    telemetry.enable()
    start = clock.monotonic()
    end = start + 0.25
    telemetry.record_span("phase", start, end, rank=3)
    [event] = telemetry.get_tracer().events()
    assert event["dur"] == pytest.approx(0.25e6, rel=1e-6)
    assert event["args"]["rank"] == 3


def test_span_buffer_is_bounded():
    tracer = SpanTracer(max_events=10)
    for i in range(15):
        tracer.record("s", float(i), 1.0)
    assert len(tracer.events()) == 10
    assert tracer.dropped == 5


def test_jsonl_one_object_per_line():
    telemetry.enable()
    with telemetry.span("a"):
        pass
    lines = list(telemetry.get_tracer().jsonl())
    assert len(lines) == 1
    assert json.loads(lines[0])["name"] == "a"


# -- exporters ----------------------------------------------------------------

def test_prometheus_text_format():
    telemetry.enable()
    telemetry.count("dmlc_parser_rows_total", 42, parser="LibSVMParser")
    telemetry.gauge_set("dmlc_threadediter_queue_depth", 5, name="p")
    telemetry.observe("dmlc_filesystem_request_seconds", 0.004, fs="s3",
                      op="GET")
    text = telemetry.prometheus_text()
    assert "# TYPE dmlc_parser_rows_total counter" in text
    assert 'dmlc_parser_rows_total{parser="LibSVMParser"} 42' in text
    assert "# TYPE dmlc_threadediter_queue_depth gauge" in text
    assert "# TYPE dmlc_filesystem_request_seconds histogram" in text
    assert 'le="+Inf"' in text
    # cumulative bucket counts: 0.004 lands at le="0.005" and everything up
    assert 'dmlc_filesystem_request_seconds_bucket{fs="s3",op="GET",le="0.005"} 1' in text
    assert 'dmlc_filesystem_request_seconds_bucket{fs="s3",op="GET",le="0.001"} 0' in text
    assert 'dmlc_filesystem_request_seconds_count{fs="s3",op="GET"} 1' in text


def test_json_snapshot_shape():
    telemetry.enable()
    telemetry.count("dmlc_x_total", 3, k="v")
    telemetry.observe("dmlc_y_seconds", 0.2)
    snap = telemetry.snapshot()
    snap = json.loads(json.dumps(snap))  # must be JSON-serializable
    assert snap["metrics"]["dmlc_x_total"]["kind"] == "counter"
    [sample] = snap["metrics"]["dmlc_x_total"]["samples"]
    assert sample == {"labels": {"k": "v"}, "value": 3}
    hist = snap["metrics"]["dmlc_y_seconds"]["samples"][0]
    assert hist["count"] == 1 and len(hist["counts"]) == len(hist["buckets"]) + 1
    assert snap["spans"] == {"recorded": 0, "dropped": 0}


def test_flush_writes_all_forms_atomically(tmp_path):
    telemetry.enable()
    telemetry.count("dmlc_x_total")
    with telemetry.span("s"):
        pass
    written = telemetry.flush(str(tmp_path))
    assert sorted(written) == ["json", "jsonl", "prom", "trace.json"]
    for path in written.values():
        assert os.path.exists(path)
    assert not [p for p in os.listdir(tmp_path) if p.endswith(".tmp")]
    trace = json.load(open(written["trace.json"]))
    assert any(e.get("ph") == "X" for e in trace["traceEvents"])


def test_flush_without_dir_raises(monkeypatch):
    telemetry.enable()
    # neutralize both directory sources: the ambient env var AND the
    # module-level dir latched from it at import (the CI suite itself runs
    # under DMLC_TELEMETRY_DIR)
    monkeypatch.delenv("DMLC_TELEMETRY_DIR", raising=False)
    monkeypatch.setattr(telemetry, "_flush_dir", None)
    with pytest.raises(ValueError):
        telemetry.flush()


def test_env_bring_up_and_atexit_flush(tmp_path):
    """DMLC_TELEMETRY_DIR enables collection in a fresh interpreter and
    flushes every export form at exit without any explicit call."""
    out_dir = tmp_path / "tel"
    code = ("from dmlc_core_tpu import telemetry\n"
            "assert telemetry.enabled()\n"
            "telemetry.count('dmlc_child_total', 2)\n"
            "with telemetry.span('child.work'):\n"
            "    pass\n")
    env = dict(os.environ, DMLC_TELEMETRY_DIR=str(out_dir),
               DMLC_TASK_ID="4", JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    files = sorted(os.listdir(out_dir))
    assert [f for f in files if f.startswith("metrics-r4-") and
            f.endswith(".json")]
    assert [f for f in files if f.endswith(".prom")]
    assert [f for f in files if f.endswith(".trace.json")]
    snap_path = next(str(out_dir / f) for f in files
                     if f.startswith("metrics-r4-") and f.endswith(".json"))
    snap = json.load(open(snap_path))
    assert snap["rank"] == 4
    assert snap["metrics"]["dmlc_child_total"]["samples"][0]["value"] == 2


# -- multi-rank report aggregation --------------------------------------------

def _write_rank_snapshot(dirpath, rank, counter_v, gauge_v, hist_counts):
    reg = MetricRegistry()
    reg.counter("dmlc_parser_rows_total", parser="p").inc(counter_v)
    reg.gauge("dmlc_threadediter_queue_depth").set(gauge_v)
    for v in hist_counts:
        reg.histogram("dmlc_collective_op_seconds",
                      buckets=(0.1, 1.0)).observe(v)
    snap = export.json_snapshot(reg)
    snap["rank"] = rank
    path = os.path.join(dirpath, f"metrics-r{rank}-p{1000 + rank}.json")
    with open(path, "w") as f:
        json.dump(snap, f)


def test_report_aggregates_ranks(tmp_path):
    _write_rank_snapshot(str(tmp_path), 0, 100, 3.0, [0.05, 0.5])
    _write_rank_snapshot(str(tmp_path), 1, 250, 7.0, [2.0])
    merged = report.aggregate(report.load_snapshots(str(tmp_path)))
    counter = merged['dmlc_parser_rows_total{parser="p"}']
    assert counter["total"] == 350 and sorted(counter["ranks"]) == [0, 1]
    gauge = merged["dmlc_threadediter_queue_depth"]
    assert gauge["min"] == 3.0 and gauge["max"] == 7.0
    hist = merged["dmlc_collective_op_seconds"]
    assert hist["count"] == 3
    assert hist["counts"] == [1, 1, 1]  # <=0.1, <=1.0, +Inf summed across ranks
    assert hist["mean"] == pytest.approx((0.05 + 0.5 + 2.0) / 3)
    table = report.render_table(merged)
    assert "dmlc_parser_rows_total" in table and "350" in table


def test_report_skips_corrupt_snapshots(tmp_path):
    (tmp_path / "metrics-r0-p1.json").write_text("{not json")
    (tmp_path / "metrics-r1-p2.json").write_text('{"no_metrics": 1}')
    _write_rank_snapshot(str(tmp_path), 2, 5, 0.0, [])
    snaps = report.load_snapshots(str(tmp_path))
    assert len(snaps) == 1 and snaps[0]["rank"] == 2


def test_report_cli_end_to_end(tmp_path):
    _write_rank_snapshot(str(tmp_path), 0, 10, 1.0, [])
    _write_rank_snapshot(str(tmp_path), 1, 20, 2.0, [])
    proc = subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.telemetry", "report",
         str(tmp_path)],
        cwd=REPO, capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stderr
    assert "2 snapshot(s) from rank(s) 0,1" in proc.stdout
    assert "30" in proc.stdout
    # --json form parses and carries the same totals
    proc = subprocess.run(
        [sys.executable, "-m", "dmlc_core_tpu.telemetry", "report",
         str(tmp_path), "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    merged = json.loads(proc.stdout)
    assert merged['dmlc_parser_rows_total{parser="p"}']["total"] == 30


def test_report_cli_empty_dir_exit_code(tmp_path):
    assert report.main(str(tmp_path)) == 1


# -- facades over the registry ------------------------------------------------

def test_throughput_meter_feeds_registry_when_enabled():
    from dmlc_core_tpu.utils.profiler import ThroughputMeter

    telemetry.enable()
    meter = ThroughputMeter("bench", log_every_bytes=1 << 40)
    meter.add(1024, nrows=10)
    meter.add(1024, nrows=5)
    reg = telemetry.get_registry()
    assert reg.counter("dmlc_pipeline_bytes_total", meter="bench").value == 2048
    assert reg.counter("dmlc_pipeline_rows_total", meter="bench").value == 15
    assert meter.mb == pytest.approx(2048 / (1 << 20))


def test_fs_metrics_helper_families():
    from dmlc_core_tpu.io import fs_metrics

    assert fs_metrics.request_start() == 0.0  # disabled: no clock read
    telemetry.enable()
    t0 = fs_metrics.request_start()
    assert t0 > 0.0
    fs_metrics.note_request("s3", "GET", t0, nread=512)
    fs_metrics.note_request("azure", "PUT", t0, nwritten=64)
    reg = telemetry.get_registry()
    assert reg.counter("dmlc_filesystem_read_bytes_total", fs="s3").value == 512
    assert reg.counter("dmlc_filesystem_write_bytes_total",
                       fs="azure").value == 64
    assert reg.histogram("dmlc_filesystem_request_seconds",
                         fs="s3", op="GET").count == 1


def test_net_retry_metrics(monkeypatch):
    import time as time_mod

    from dmlc_core_tpu.io import net_retry

    monkeypatch.setattr(time_mod, "sleep", lambda s: None)
    telemetry.enable()
    calls = {"n": 0}

    def perform():
        calls["n"] += 1
        if calls["n"] < 3:
            return 503, {}, b"busy"
        return 200, {}, b"ok"

    status, _, data = net_retry.request_with_retries(perform, (200,), "GET /x")
    assert status == 200 and data == b"ok"
    reg = telemetry.get_registry()
    assert reg.counter("dmlc_net_retry_retries_total",
                       status_class="5xx").value == 2
    # full-jitter backoff: each sleep is uniform in [0, 0.1) + [0, 0.2),
    # summed by status class — bounded by the pre-jitter doubling windows
    backoff = reg.counter("dmlc_net_retry_backoff_seconds_total",
                          status_class="5xx").value
    assert 0.0 <= backoff < 0.3


# -- review-hardening regressions ---------------------------------------------

def test_prometheus_label_values_escaped():
    telemetry.enable()
    telemetry.count("dmlc_x_total", 1, name='shard "a"\\b\nc')
    text = telemetry.prometheus_text()
    assert 'name="shard \\"a\\"\\\\b\\nc"' in text
    assert "\n\n" not in text  # the raw newline never leaks into the format


def test_report_bucket_clash_marked_not_dropped(tmp_path):
    _write_rank_snapshot(str(tmp_path), 0, 1, 0.0, [0.05])
    # rank 1 registered the same family with a different bucket list
    reg = MetricRegistry()
    reg.histogram("dmlc_collective_op_seconds",
                  buckets=(0.5, 1.0, 2.0, 4.0)).observe(3.0)
    snap = export.json_snapshot(reg)
    snap["rank"] = 1
    with open(os.path.join(str(tmp_path), "metrics-r1-p9.json"), "w") as f:
        json.dump(snap, f)
    merged = report.aggregate(report.load_snapshots(str(tmp_path)))
    hist = merged["dmlc_collective_op_seconds"]
    assert hist["bucket_clash"] is True
    assert hist["counts"] == [1, 0, 0]  # rank 0's fold kept, not overwritten
    assert hist["count"] == 2           # ...while count/sum cover both ranks


def test_fs_metrics_skips_unmeasured_latency_sample():
    from dmlc_core_tpu.io import fs_metrics

    start = fs_metrics.request_start()  # disabled: 0.0 sentinel
    telemetry.enable()                  # enabled mid-request
    fs_metrics.note_request("s3", "GET", start, nread=128)
    reg = telemetry.get_registry()
    # bytes still counted, but no fabricated 0.0-latency observation
    assert reg.counter("dmlc_filesystem_read_bytes_total", fs="s3").value == 128
    assert reg.histogram("dmlc_filesystem_request_seconds",
                         fs="s3", op="GET").count == 0


def test_prometheus_nonfinite_values_export_without_crashing():
    telemetry.enable()
    telemetry.gauge_set("dmlc_x_ratio", float("inf"))
    telemetry.gauge_set("dmlc_y_ratio", float("nan"))
    text = telemetry.prometheus_text()  # must not raise
    assert "dmlc_x_ratio +Inf" in text
    assert "dmlc_y_ratio NaN" in text


def test_histogram_bucket_clash_raises():
    reg = MetricRegistry()
    reg.histogram("h", buckets=(1.0, 2.0)).observe(0.5)
    reg.histogram("h", buckets=(1.0, 2.0)).observe(1.5)  # same buckets: fine
    reg.histogram("h").observe(0.5)                      # unspecified: fine
    with pytest.raises(ValueError):
        reg.histogram("h", buckets=(0.5, 1.0))


def test_net_retry_exhausted_counts_status_exhaustion(monkeypatch):
    import time as time_mod

    from dmlc_core_tpu.io import net_retry

    monkeypatch.setattr(time_mod, "sleep", lambda s: None)
    telemetry.enable()
    status, _, _ = net_retry.request_with_retries(
        lambda: (503, {}, b"busy"), (200,), "GET /always-busy")
    assert status == 503  # returned to the caller after exhaustion
    reg = telemetry.get_registry()
    assert reg.counter("dmlc_net_retry_exhausted_total",
                       status_class="5xx").value == 1


def test_report_warns_on_duplicate_rank_snapshots(tmp_path, capsys):
    _write_rank_snapshot(str(tmp_path), 0, 10, 1.0, [])
    reg = MetricRegistry()
    reg.counter("dmlc_parser_rows_total", parser="p").inc(5)
    snap = export.json_snapshot(reg)
    snap["rank"] = 0
    with open(os.path.join(str(tmp_path), "metrics-r0-p2.json"), "w") as f:
        json.dump(snap, f)
    assert report.main(str(tmp_path)) == 0
    out = capsys.readouterr().out
    assert "multiple snapshots" in out
    assert "15" in out  # still sums — the note explains, it doesn't hide


# -- histogram quantile estimation (serving SLOs) ------------------------------

def test_estimate_quantiles_uniform_counts_interpolate_exactly():
    # 10 observations per decade bucket: quantile ranks land on bucket
    # boundaries and interior points with closed-form expectations
    buckets = (10.0, 20.0, 30.0, 40.0)
    counts = [10, 10, 10, 10, 0]
    p25, p50, p99 = report.estimate_quantiles(buckets, counts,
                                              (0.25, 0.50, 0.99))
    assert p25 == pytest.approx(10.0)
    assert p50 == pytest.approx(20.0)
    assert p99 == pytest.approx(39.6)


def test_estimate_quantiles_first_bucket_lower_edge_is_zero():
    # everything in the first bucket: interpolation starts at 0, not at the
    # first bound (latency observations are non-negative)
    (p50,) = report.estimate_quantiles((0.1, 1.0), [100, 0, 0], (0.5,))
    assert p50 == pytest.approx(0.05)


def test_estimate_quantiles_inf_bucket_floors_at_last_finite_bound():
    # mass past the last finite bound cannot be resolved: the estimate
    # reports the highest finite bound (histogram_quantile convention),
    # never an invented extrapolation
    buckets = (0.1, 1.0)
    qs = report.estimate_quantiles(buckets, [0, 0, 7], (0.5, 0.99))
    assert qs == [1.0, 1.0]
    # mixed: p50 resolves inside the finite buckets, p99 floors
    p50, p99 = report.estimate_quantiles(buckets, [6, 0, 4], (0.5, 0.99))
    assert p50 == pytest.approx(0.1 * (5.0 / 6.0))
    assert p99 == pytest.approx(1.0)


def test_estimate_quantiles_degenerate_inputs_are_none():
    assert report.estimate_quantiles((1.0,), [0, 0], (0.5,)) == [None]
    # counts length not bounds+1 (a cross-rank bucket clash)
    assert report.estimate_quantiles((1.0, 2.0), [1, 1], (0.5,)) == [None]
    assert report.estimate_quantiles((), [], (0.5,)) == [None]
    # out-of-range q
    assert report.estimate_quantiles((1.0,), [3, 0], (1.5,)) == [None]


def test_estimate_quantiles_tracks_numpy_percentile_within_bucket_width():
    import numpy as np

    rng = np.random.RandomState(7)
    sample = rng.gamma(2.0, 0.05, size=5000)  # latency-shaped
    bounds = tuple(np.linspace(0.01, 1.0, 100))
    h = telemetry.Histogram(buckets=bounds)
    for v in sample:
        h.observe(v)
    width = bounds[1] - bounds[0]
    for q in (0.5, 0.95, 0.99):
        (est,) = report.estimate_quantiles(bounds, h.bucket_counts, (q,))
        assert abs(est - float(np.percentile(sample, q * 100))) <= width


def test_report_aggregate_emits_quantiles(tmp_path):
    _write_rank_snapshot(str(tmp_path), 0, 1, 0.0, [0.05] * 9)
    _write_rank_snapshot(str(tmp_path), 1, 1, 0.0, [2.0])
    merged = report.aggregate(report.load_snapshots(str(tmp_path)))
    hist = merged["dmlc_collective_op_seconds"]
    # 9 of 10 samples land <= 0.1, the last in +Inf: p50 interpolates in
    # the first bucket, p99 floors at the last finite bound (1.0)
    assert hist["p50"] == pytest.approx(0.1 * (5.0 / 9.0))
    assert hist["p99"] == pytest.approx(1.0)
    table = report.render_table(merged)
    assert "p50=" in table and "p99=" in table
