"""Continuous trainer daemon: sources, crash-resume, publish discipline.

The ring's robustness claims (docs/training.md), each proven here
in-process:

- :class:`DirectorySource` consumes spool files once each in name order,
  returns poison batches (``error`` set) instead of raising, and honors
  the ``_DONE`` drain sentinel;
- a cold daemon fits bin edges from its first batch, publishes on the
  every-N-rounds cadence, and the published checkpoint hot-swaps through
  the PR 13 watcher;
- a restarted daemon resumes from the last *valid* manifest — falling
  past corrupt steps, skipping (and idempotently re-publishing) a
  manifest-less step a dead incarnation left behind — restoring trees,
  frozen edges, and the ingest cursor;
- a torn publish (injected ``train.publish`` truncate) is rejected by the
  trainer's own verify, counted, never manifested, and re-published;
- poisoned batches (NaN features, arity drift, bad labels) are
  quarantined and counted, never fatal;
- :class:`FleetSource` feeds the daemon from a real in-process
  ``ShardLeaseCoordinator`` (the PR 12 path).
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from dmlc_core_tpu import fault
from dmlc_core_tpu.bridge.checkpoint import (CheckpointManager,
                                             load_checkpoint)
from dmlc_core_tpu.models.gbdt import GBDT, GBDTParam
from dmlc_core_tpu.train import (Batch, CURSOR_KEY, DirectorySource,
                                 DONE_SENTINEL, FleetSource, ROUND_KEY,
                                 TrainerDaemon)

F = 6
ROWS = 80


def _param(**over):
    p = GBDTParam()
    kw = {"num_bins": 16, "max_depth": 3, "learning_rate": 0.3}
    kw.update(over)
    p.update(kw)
    return p


def _write_libsvm(path, n=ROWS, bias=0.0, seed=0, nan_features=False):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n):
            x = rng.normal(size=F)
            y = int(rng.random() < 1 / (1 + np.exp(-(x[0] + bias))))
            if nan_features:
                feats = " ".join(f"{j}:nan" for j in range(F))
            else:
                feats = " ".join(f"{j}:{x[j]:.5f}" for j in range(F))
            f.write(f"{y} {feats}\n")


def _spool(tmp_path, n_files=3, start_seed=0):
    d = tmp_path / "spool"
    d.mkdir(exist_ok=True)
    for i in range(n_files):
        _write_libsvm(d / f"part-{i:04d}.libsvm", seed=start_seed + i,
                      bias=0.3 * i)
    return str(d)


def _daemon(tmp_path, spool, **kw):
    kw.setdefault("param", _param())
    kw.setdefault("rounds_per_batch", 2)
    kw.setdefault("publish_every_rounds", 4)
    kw.setdefault("poll_s", 0.05)
    return TrainerDaemon(str(tmp_path / "ckpt"),
                         DirectorySource(spool, F), F, **kw)


# -- DirectorySource ----------------------------------------------------------

def test_directory_source_name_order_and_cursor(tmp_path):
    spool = _spool(tmp_path, n_files=3)
    src = DirectorySource(spool, F)
    seen = []
    cursor = 0
    while True:
        b = src.next_batch(cursor)
        if b is None:
            break
        assert b.error is None
        assert b.x.shape == (ROWS, F) and b.x.dtype == np.float32
        assert b.cursor == cursor + 1
        seen.append(os.path.basename(b.origin))
        cursor = b.cursor
    assert seen == sorted(seen) and len(seen) == 3
    # not exhausted until the sentinel lands
    assert not src.exhausted(cursor)
    open(os.path.join(spool, DONE_SENTINEL), "w").close()
    assert src.exhausted(cursor)
    assert not src.exhausted(cursor - 1)


def test_directory_source_skips_hidden_and_tmp_names(tmp_path):
    spool = _spool(tmp_path, n_files=1)
    open(os.path.join(spool, ".tmp-part-9999.libsvm"), "w").close()
    open(os.path.join(spool, "_scratch"), "w").close()
    src = DirectorySource(spool, F)
    assert src.next_batch(0).error is None
    assert src.next_batch(1) is None


def test_directory_source_poison_is_a_batch_not_a_raise(tmp_path):
    spool = _spool(tmp_path, n_files=1)
    with open(os.path.join(spool, "part-0000.libsvm"), "w") as f:
        f.write("utterly : not : libsvm\n")
    b = DirectorySource(spool, F).next_batch(0)
    assert b.error is not None and b.x is None
    assert b.cursor == 1  # the cursor advances past poison


# -- daemon: cold start, cadence, and the serving ring ------------------------

def test_cold_train_publish_and_hot_swap(tmp_path):
    from dmlc_core_tpu.serve import (CheckpointWatcher, ModelRegistry,
                                     build_runtime, runtime_builder)

    spool = _spool(tmp_path, n_files=4)
    open(os.path.join(spool, DONE_SENTINEL), "w").close()
    d = _daemon(tmp_path, spool)
    d.run(exit_when_idle=True)
    assert d.rounds_completed == 8
    assert d.publishes_completed == 2  # every 4 rounds
    assert d.resumed_from is None

    mgr = d.manager
    step, manifest = mgr.latest_valid(verify=True)
    assert step == 2
    state = load_checkpoint(mgr.step_uri(step))
    # the resume leaves ride the same blob as the trees
    assert int(np.asarray(state[f"['{CURSOR_KEY}']"])[0]) == 4
    assert int(np.asarray(state[f"['{ROUND_KEY}']"])[0]) == 8

    # the published checkpoint swaps through the PR 13 watcher
    registry = ModelRegistry()
    registry.add("m", build_runtime("gbdt", F,
                                    checkpoint=mgr.step_uri(1)),
                 version=1, max_batch=8, max_delay_ms=1.0)
    w = CheckpointWatcher(registry, "m", str(tmp_path / "ckpt"),
                          runtime_builder("gbdt", F), poll_s=60,
                          manager=mgr)
    assert w.poll_once() == 2
    assert registry.get("m").version == 2


def test_publish_clock_thread_publishes_on_cadence(tmp_path):
    spool = _spool(tmp_path, n_files=2)
    d = _daemon(tmp_path, spool, publish_every_rounds=0,
                publish_every_s=0.15)
    with d:
        assert d.step_once() and d.step_once()
        deadline = time.monotonic() + 10
        while d.publishes_completed == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
    assert d.publishes_completed >= 1
    # idempotence: once trained state is flushed, publish_now is a no-op
    d.publish_now()
    before = d.publishes_completed
    assert d.publish_now() is None
    assert d.publishes_completed == before


# -- daemon: crash resume -----------------------------------------------------

def test_resume_restores_trees_cursor_and_appends(tmp_path):
    spool = _spool(tmp_path, n_files=4)
    d1 = _daemon(tmp_path, spool)
    d1.run(max_batches=4)
    assert d1.publishes_completed == 2

    # "crash": a fresh daemon against the same directories
    d2 = _daemon(tmp_path, spool, incarnation=1)
    assert d2.resumed_from == 2
    st = d2.describe()
    assert st["cursor"] == 4 and st["rounds_completed"] == 8
    assert st["trees"] == 8  # restored, not retrained

    # appended rounds continue on the restored (frozen) edges
    _write_libsvm(os.path.join(spool, "part-0004.libsvm"), seed=9)
    open(os.path.join(spool, DONE_SENTINEL), "w").close()
    d2.run(exit_when_idle=True)
    assert d2.rounds_completed == 10 and d2.publishes_completed == 1
    step, _ = d2.manager.latest_valid(verify=True)
    assert step == 3
    flat = load_checkpoint(d2.manager.step_uri(step))
    gbdt, ens = GBDT.resume(flat)
    assert ens.num_trees == 10


def test_resume_falls_past_corrupt_newest_step(tmp_path):
    spool = _spool(tmp_path, n_files=4)
    d1 = _daemon(tmp_path, spool)
    d1.run(max_batches=4)
    mgr = d1.manager
    # bit-rot the newest blob AFTER its manifest landed
    blob = mgr.step_uri(2)[len("file://"):] \
        if mgr.step_uri(2).startswith("file://") else mgr.step_uri(2)
    with open(blob, "r+b") as f:
        f.seek(16)
        f.write(b"\xff" * 8)
    d2 = _daemon(tmp_path, spool, incarnation=1)
    assert d2.resumed_from == 1  # fell back past the corrupt step 2
    assert d2.describe()["cursor"] == 2
    # and the corrupt step's number is NOT reused: fresh work goes above
    assert d2.describe()["next_step"] == 3


def test_resume_skips_manifestless_step_and_republishes_it(tmp_path):
    spool = _spool(tmp_path, n_files=4)
    d1 = _daemon(tmp_path, spool)
    d1.run(max_batches=4)
    mgr = d1.manager
    # simulate dying between blob and manifest on step 3: blob, no manifest
    import shutil
    shutil.copy(mgr.step_uri(2).replace("file://", ""),
                mgr.step_uri(3).replace("file://", ""))
    assert mgr.all_steps() == [1, 2, 3]
    d2 = _daemon(tmp_path, spool, incarnation=1)
    assert d2.resumed_from == 2  # the orphan step never resumes anyone
    assert d2.describe()["next_step"] == 3  # ...but its number is reused
    _write_libsvm(os.path.join(spool, "part-0004.libsvm"), seed=9)
    open(os.path.join(spool, DONE_SENTINEL), "w").close()
    d2.run(exit_when_idle=True)
    step, manifest = mgr.latest_valid(verify=True)
    assert step == 3 and manifest is not None  # completed idempotently


# -- daemon: publish discipline under chaos -----------------------------------

@pytest.mark.chaos
def test_torn_publish_rejected_then_republished(tmp_path):
    fault.configure({"rules": [
        {"site": "train.publish", "kind": "truncate", "keep": 48,
         "match": {"phase": "durable"}, "times": 1}]})
    try:
        spool = _spool(tmp_path, n_files=4)
        open(os.path.join(spool, DONE_SENTINEL), "w").close()
        d = _daemon(tmp_path, spool)
        d.run(exit_when_idle=True)
        # first cadence publish was torn -> rejected by the trainer's own
        # verify; the SAME step was re-published on the next cadence
        assert d.publish_rejections == 1
        assert d.publishes_completed >= 1
        assert ("train.publish", "truncate") in \
            [(s, k) for s, k, _ in fault.fires()]
        step, _ = d.manager.latest_valid(verify=True)
        assert step is not None
        # nothing manifest-less or corrupt is left behind
        for s in d.manager.all_steps():
            assert d.manager.read_manifest(s) is not None
    finally:
        fault.clear()


@pytest.mark.chaos
def test_ingest_fault_retries_without_advancing_cursor(tmp_path):
    fault.configure({"rules": [
        {"site": "train.ingest", "kind": "error",
         "exception": "RuntimeError", "times": 2}]})
    try:
        spool = _spool(tmp_path, n_files=1)
        open(os.path.join(spool, DONE_SENTINEL), "w").close()
        d = _daemon(tmp_path, spool)
        d.run(exit_when_idle=True)
        assert d.ingest_failures == 2
        assert d.describe()["cursor"] == 1  # batch still consumed after
        assert d.rounds_completed == 2
    finally:
        fault.clear()


def test_poison_quarantined_not_fatal(tmp_path):
    spool = _spool(tmp_path, n_files=1)
    _write_libsvm(os.path.join(spool, "part-0001.libsvm"),
                  nan_features=True, seed=3)
    _write_libsvm(os.path.join(spool, "part-0002.libsvm"), seed=4)
    open(os.path.join(spool, DONE_SENTINEL), "w").close()
    d = _daemon(tmp_path, spool)
    d.run(exit_when_idle=True)
    assert d.quarantined == 1  # NaN without handle_missing
    assert d.rounds_completed == 4  # both healthy files trained
    assert d.describe()["cursor"] == 3


def test_state_file_is_atomic_and_current(tmp_path):
    spool = _spool(tmp_path, n_files=2)
    open(os.path.join(spool, DONE_SENTINEL), "w").close()
    state_path = tmp_path / "state.json"
    d = _daemon(tmp_path, spool, state_file=str(state_path))
    d.run(exit_when_idle=True)
    with open(state_path) as f:
        st = json.load(f)
    assert st == d.describe()
    assert not list(tmp_path.glob("state.json.tmp.*"))


# -- FleetSource --------------------------------------------------------------

@pytest.mark.slow
def test_fleet_source_feeds_daemon_from_coordinator(tmp_path):
    from dmlc_core_tpu.parallel import fleet_ingest
    from dmlc_core_tpu.tracker.rendezvous import ShardLeaseCoordinator

    corpus = tmp_path / "fleet.libsvm"
    _write_libsvm(corpus, n=200, seed=11)
    units = fleet_ingest.plan_units(str(corpus), 2, num_units=4,
                                    fmt="libsvm")
    coord = ShardLeaseCoordinator("127.0.0.1", units, lease_timeout=10.0)
    coord.start()
    try:
        src = FleetSource("w0", F, host="127.0.0.1",
                          port=coord.port).start()
        d = TrainerDaemon(str(tmp_path / "ckpt"), src, F,
                          param=_param(), rounds_per_batch=1,
                          publish_every_rounds=1, poll_s=0.05)
        d.run(exit_when_idle=True)
    finally:
        coord.stop()
    assert d.rounds_completed >= 1
    assert d.publishes_completed >= 1
    step, _ = d.manager.latest_valid(verify=True)
    assert step is not None


# -- concurrency: publish clock vs ingest loop --------------------------------

def test_concurrent_publish_clock_and_training_is_consistent(tmp_path):
    """The clock thread snapshots while the loop trains; every published
    checkpoint must be internally consistent (rounds leaf == trees)."""
    spool = _spool(tmp_path, n_files=6)
    open(os.path.join(spool, DONE_SENTINEL), "w").close()
    d = _daemon(tmp_path, spool, publish_every_rounds=2,
                publish_every_s=0.05, rounds_per_batch=1)
    d.run(exit_when_idle=True)
    mgr = d.manager
    for step in mgr.all_steps():
        if mgr.read_manifest(step) is None:
            continue
        flat = load_checkpoint(mgr.step_uri(step))
        _, ens = GBDT.resume(flat)
        assert int(np.asarray(flat[f"['{ROUND_KEY}']"])[0]) \
            == ens.num_trees
