"""JAX bridge tests: batching, mesh loader, URI checkpointing."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dmlc_core_tpu.bridge.batching import (
    block_to_dense,
    block_to_sparse,
    bucket_size,
    dense_batches,
    sparse_batches,
)
from dmlc_core_tpu.bridge.checkpoint import load_checkpoint, save_checkpoint
from dmlc_core_tpu.bridge.loader import MeshBatchLoader
from dmlc_core_tpu.data.factory import create_parser
from dmlc_core_tpu.data.row_block import RowBlock
from dmlc_core_tpu.parallel.mesh import make_mesh


def make_block(n=5):
    offset = np.arange(n + 1) * 2
    return RowBlock(
        offset=offset,
        label=np.arange(n, dtype=np.float32),
        index=np.tile(np.array([0, 3], dtype=np.uint32), n),
        value=np.ones(2 * n, dtype=np.float32),
    )


def test_bucket_ladder():
    assert bucket_size(1) == 256
    assert bucket_size(256) == 256
    assert bucket_size(257) >= 257
    sizes = {bucket_size(n) for n in range(1, 100000, 97)}
    assert len(sizes) < 20  # logarithmic ladder


def test_block_to_dense():
    batch = block_to_dense(make_block(5), num_feature=6, batch_size=8)
    assert batch.x.shape == (8, 6)
    np.testing.assert_allclose(batch.x[0], [1, 0, 0, 1, 0, 0])
    np.testing.assert_allclose(batch.weight[:5], 1.0)
    np.testing.assert_allclose(batch.weight[5:], 0.0)  # padding marked
    assert batch.label[3] == 3.0


def test_block_to_sparse():
    batch = block_to_sparse(make_block(5), nnz_bucket=16, batch_size=8)
    assert batch.value.shape == (16,)
    assert batch.row_id[:10].tolist() == [0, 0, 1, 1, 2, 2, 3, 3, 4, 4]
    assert (batch.row_id[10:] == 8).all()  # padding segment
    # segment_sum drops padding into segment B
    seg = jax.ops.segment_sum(jnp.asarray(batch.value),
                              jnp.asarray(batch.row_id), num_segments=9)
    np.testing.assert_allclose(np.asarray(seg)[:8],
                               [2, 2, 2, 2, 2, 0, 0, 0])


def write_libsvm(tmp_path, n=100):
    lines = []
    for i in range(n):
        lines.append(f"{i % 2} 0:{i} 3:1.0")
    p = tmp_path / "data.libsvm"
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def test_dense_batches_rebatching(tmp_path):
    uri = write_libsvm(tmp_path, 100)
    parser = create_parser(uri, type="libsvm", threaded=False)
    batches = list(dense_batches(parser, batch_size=32, num_feature=4))
    assert len(batches) == 4  # 3 full + remainder
    assert batches[0].x.shape == (32, 4)
    total_rows = int(sum(b.weight.sum() for b in batches))
    assert total_rows == 100
    # values survive rebatching in order
    np.testing.assert_allclose(batches[0].x[:5, 0], np.arange(5.0))


def test_sparse_batches_fixed_bucket(tmp_path):
    uri = write_libsvm(tmp_path, 64)
    parser = create_parser(uri, type="libsvm", threaded=False)
    batches = list(sparse_batches(parser, batch_size=16, nnz_bucket=64))
    assert len(batches) == 4
    for b in batches:
        assert b.value.shape == (64,)


def test_mesh_loader_dense(tmp_path):
    uri = write_libsvm(tmp_path, 128)
    mesh = make_mesh({"data": 8})
    parser = create_parser(uri, type="libsvm", threaded=False)
    loader = MeshBatchLoader(parser, mesh, form="dense",
                             global_batch_size=32, num_feature=4)
    batches = list(loader)
    assert len(batches) == 4
    x = batches[0].x
    assert x.shape == (32, 4)
    assert "data" in str(x.sharding.spec)
    # device-side compute over the sharded batch
    total = float(jnp.sum(batches[0].weight))
    assert total == 32.0
    # epoch restart
    loader.before_first()
    assert len(list(loader)) == 4
    loader.close()


def test_mesh_loader_sparse(tmp_path):
    uri = write_libsvm(tmp_path, 64)
    mesh = make_mesh({"data": 8})
    parser = create_parser(uri, type="libsvm", threaded=False)
    loader = MeshBatchLoader(parser, mesh, form="sparse",
                             global_batch_size=64, nnz_bucket=256)
    batches = list(loader)
    assert len(batches) == 1
    assert batches[0].value.shape == (256 * 1,)
    loader.close()


def test_checkpoint_roundtrip(tmp_path):
    tree = {
        "w": jnp.arange(6.0).reshape(2, 3),
        "b": jnp.float32(1.5),
        "inner": {"count": np.int64(7), "arr": np.ones(4, np.float32)},
    }
    uri = str(tmp_path / "ckpt.bin")
    save_checkpoint(uri, tree)
    flat = load_checkpoint(uri)
    assert len(flat) == 4
    restored = load_checkpoint(uri, template=jax.tree.map(np.zeros_like, tree))
    np.testing.assert_allclose(restored["w"], np.arange(6.0).reshape(2, 3))
    assert restored["inner"]["count"] == 7
    assert float(restored["b"]) == 1.5


def test_checkpoint_shape_mismatch(tmp_path):
    uri = str(tmp_path / "c.bin")
    save_checkpoint(uri, {"w": np.zeros(3)})
    with pytest.raises(Exception, match="shape mismatch"):
        load_checkpoint(uri, template={"w": np.zeros(4)})


def test_async_checkpointer_roundtrip(tmp_path):
    from dmlc_core_tpu.bridge.checkpoint import AsyncCheckpointer

    ck = AsyncCheckpointer()
    tree = {"w": np.arange(10, dtype=np.float32), "step": np.int32(3)}
    uri = str(tmp_path / "async.ckpt")
    ck.save(uri, tree)
    ck.wait_until_finished()
    got = load_checkpoint(uri, template=jax.tree.map(np.zeros_like, tree))
    np.testing.assert_array_equal(got["w"], tree["w"])
    assert int(got["step"]) == 3


def test_async_checkpointer_snapshot_isolated(tmp_path):
    """Mutating state right after save must not corrupt the checkpoint."""
    from dmlc_core_tpu.bridge.checkpoint import AsyncCheckpointer

    ck = AsyncCheckpointer()
    w = np.arange(1000, dtype=np.float32)
    uri = str(tmp_path / "snap.ckpt")
    ck.save(uri, {"w": w})
    w += 999.0  # simulate the next training step
    ck.wait_until_finished()
    got = load_checkpoint(uri)
    np.testing.assert_array_equal(next(iter(got.values())),
                                  np.arange(1000, dtype=np.float32))


def test_async_checkpointer_error_surfaces(tmp_path):
    from dmlc_core_tpu.bridge.checkpoint import AsyncCheckpointer

    ck = AsyncCheckpointer()
    ck.save(str(tmp_path / "no-such-dir" / "x.ckpt"), {"w": np.zeros(2)})
    with pytest.raises(RuntimeError, match="async checkpoint"):
        ck.wait_until_finished()
    # the error is consumed; the checkpointer is reusable afterwards
    ck.save(str(tmp_path / "ok.ckpt"), {"w": np.zeros(2)})
    ck.wait_until_finished()


def test_checkpoint_manager_latest_and_retention(tmp_path):
    from dmlc_core_tpu.bridge.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "ckpts"), keep=2)
    assert mgr.latest_step() is None
    for step in (1, 5, 9):
        mgr.save(step, {"w": np.full(4, float(step))}, async_=(step != 5))
    mgr.wait_until_finished()
    assert mgr.latest_step() == 9
    assert mgr.all_steps() == [5, 9]          # step 1 aged out (keep=2)
    got = mgr.restore(template={"w": np.zeros(4)})
    np.testing.assert_array_equal(got["w"], np.full(4, 9.0))
    got5 = mgr.restore(step=5, template={"w": np.zeros(4)})
    np.testing.assert_array_equal(got5["w"], np.full(4, 5.0))


def test_checkpoint_atomic_no_tmp_left(tmp_path):
    uri = str(tmp_path / "a.ckpt")
    save_checkpoint(uri, {"w": np.zeros(8)})
    assert os.path.exists(uri)
    # pid-unique temp (concurrent savers must not share one temp file) and
    # nothing left behind after the rename
    assert list(tmp_path.glob("a.ckpt.tmp*")) == []


def test_checkpoint_retention_waits_for_async_durability(tmp_path, monkeypatch):
    """keep=1 + a failing async write must never delete the last good step."""
    import dmlc_core_tpu.bridge.checkpoint as ckpt_mod
    from dmlc_core_tpu.bridge.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "ckpts"), keep=1)
    mgr.save(1, {"w": np.full(2, 1.0)}, async_=False)
    assert mgr.all_steps() == [1]

    def boom(uri, tree):
        raise OSError("injected write failure")

    monkeypatch.setattr(ckpt_mod, "save_checkpoint", boom)
    mgr.save(2, {"w": np.full(2, 2.0)}, async_=True)
    with pytest.raises(RuntimeError, match="async checkpoint"):
        mgr.wait_until_finished()
    monkeypatch.undo()
    # step 1 must still be restorable: retention may only run after the new
    # step is durable
    assert mgr.all_steps() == [1]
    got = mgr.restore(template={"w": np.zeros(2)})
    np.testing.assert_array_equal(got["w"], np.full(2, 1.0))
    # and a successful async save ages step 1 out once durable
    mgr.save(3, {"w": np.full(2, 3.0)}, async_=True)
    mgr.wait_until_finished()
    assert mgr.all_steps() == [3]


def test_checkpoint_retention_failure_does_not_mask_durable_write(
        tmp_path, monkeypatch):
    """A post-write retention error must not make restore() refuse a durable
    checkpoint."""
    from dmlc_core_tpu.bridge.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "ckpts"), keep=1)
    monkeypatch.setattr(mgr, "_retain",
                        lambda step: (_ for _ in ()).throw(OSError("boom")))
    mgr.save(1, {"w": np.full(2, 1.0)}, async_=True)
    mgr.wait_until_finished()          # must NOT raise: the write succeeded
    got = mgr.restore(template={"w": np.zeros(2)})
    np.testing.assert_array_equal(got["w"], np.full(2, 1.0))


def test_checkpoint_orphan_temps_swept(tmp_path):
    """pid-unique temps from crashed (dead-pid) writers are cleaned; temps
    owned by live processes are left alone."""
    import subprocess
    import sys

    from dmlc_core_tpu.bridge.checkpoint import CheckpointManager

    d = tmp_path / "ckpts"
    d.mkdir()
    # a genuinely dead pid: spawn-and-reap a child
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait()
    import socket

    host = socket.gethostname()
    dead = f"{host}.{proc.pid}"
    live = f"{host}.1"   # init: always alive (kill(1,0)->EPERM counts alive)
    foreign = f"other-host.{proc.pid}"   # dead pid but NOT this host
    (d / f"ckpt-00000001.tmp.{dead}").write_bytes(b"torn")   # crash orphan
    (d / f"ckpt-00000001.tmp.{live}").write_bytes(b"live")   # in-flight writer
    (d / f"ckpt-00000001.tmp.{foreign}").write_bytes(b"?")   # foreign host
    (d / f"ckpt-00000000.tmp.{dead}").write_bytes(b"torn")
    (d / "ckpt-00000000").write_bytes(b"DMLCTPU1\x00")       # old partial step
    mgr = CheckpointManager(str(d), keep=1)
    mgr.save(1, {"w": np.zeros(2)}, async_=False)
    assert not (d / f"ckpt-00000001.tmp.{dead}").exists()    # swept at save
    assert (d / f"ckpt-00000001.tmp.{live}").exists()        # live: preserved
    # foreign host's temp: local pid probe is meaningless -> preserved
    assert (d / f"ckpt-00000001.tmp.{foreign}").exists()
    assert not (d / f"ckpt-00000000.tmp.{dead}").exists()    # swept at retain
    assert mgr.all_steps() == [1]


def test_checkpoint_manager_falls_back_past_corrupt_newest(tmp_path):
    from dmlc_core_tpu.bridge.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "ckpts"), keep=5)
    mgr.save(1, {"w": np.full(3, 1.0)}, async_=False)
    mgr.save(2, {"w": np.full(3, 2.0)}, async_=False)
    # simulate a partial write surviving at the newest step
    newest = tmp_path / "ckpts" / "ckpt-00000003"
    newest.write_bytes(b"DMLCTPU1\x00")
    assert mgr.latest_step() == 3
    got = mgr.restore(template={"w": np.zeros(3)})
    np.testing.assert_array_equal(got["w"], np.full(3, 2.0))


def test_checkpoint_manager_wide_step_numbers(tmp_path):
    from dmlc_core_tpu.bridge.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "ckpts"), keep=10)
    mgr.save(99_999_999, {"w": np.zeros(2)}, async_=False)
    mgr.save(100_000_000, {"w": np.ones(2)}, async_=False)
    assert mgr.latest_step() == 100_000_000
    got = mgr.restore(template={"w": np.zeros(2)})
    np.testing.assert_array_equal(got["w"], np.ones(2))


def test_checkpoint_manager_latest_valid_scan(tmp_path):
    """latest_valid is the ONE shared fallback scan (watcher candidate pick
    AND trainer crash-resume): manifest-first, newest-first, honouring the
    above floor and the rejected-candidate ledger."""
    from dmlc_core_tpu.bridge.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "ckpts"), keep=10)
    for step in (1, 2, 3):
        mgr.save(step, {"w": np.full(2, float(step))}, async_=False)

    step, manifest = mgr.latest_valid()
    assert step == 3 and manifest is not None
    assert manifest["step"] == 3
    # the floor is exclusive: nothing newer than the current version -> miss
    assert mgr.latest_valid(above=3) == (None, None)
    assert mgr.latest_valid(above=2)[0] == 3

    # a rejected (step, crc32) pair falls back to the next-older step
    bad = {(3, manifest["crc32"])}
    step2, manifest2 = mgr.latest_valid(known_bad=bad)
    assert step2 == 2 and manifest2["step"] == 2
    # ...but a stale ledger entry (same step, different bytes) does not hide
    # a re-published step
    assert mgr.latest_valid(known_bad={(3, manifest["crc32"] ^ 1)})[0] == 3


def test_checkpoint_manager_latest_valid_unpublished_newest(tmp_path):
    """A manifest-less newest step stops the scan by default (its writer may
    still be in flight — watcher semantics) but is skipped for a resuming
    trainer, which knows the previous writer is dead."""
    from dmlc_core_tpu.bridge.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "ckpts"), keep=10)
    mgr.save(1, {"w": np.full(2, 1.0)}, async_=False)
    # a blob with no manifest: an in-flight (or abandoned) publish
    orphan = tmp_path / "ckpts" / "ckpt-00000002"
    orphan.write_bytes((tmp_path / "ckpts" / "ckpt-00000001").read_bytes())

    assert mgr.latest_valid() == (None, None)            # watcher: wait
    step, manifest = mgr.latest_valid(skip_unpublished=True)
    assert step == 1 and manifest["step"] == 1           # trainer: fall back


def test_checkpoint_manager_latest_valid_verify_falls_past_rot(tmp_path):
    """verify=True re-hashes each candidate blob and falls back past
    corrupt/truncated steps whose manifests still parse."""
    from dmlc_core_tpu.bridge.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "ckpts"), keep=10)
    mgr.save(1, {"w": np.full(2, 1.0)}, async_=False)
    mgr.save(2, {"w": np.full(2, 2.0)}, async_=False)
    # bit-rot the newest blob AFTER publish: manifest says one thing, the
    # bytes say another
    blob = tmp_path / "ckpts" / "ckpt-00000002"
    blob.write_bytes(blob.read_bytes()[:-4] + b"\x00\x00\x00\x00")

    # manifest-only scan still trusts step 2...
    assert mgr.latest_valid()[0] == 2
    # ...but a verifying scan (trainer resume) falls back to step 1
    step, manifest = mgr.latest_valid(verify=True)
    assert step == 1 and manifest["step"] == 1


def test_checkpoint_manager_remote_retention_warns_once(tmp_path):
    """On a remote store retention is a no-op and the 'remote steps are left
    in place' warning fires exactly once per manager, not per save."""
    from dmlc_core_tpu.bridge.checkpoint import CheckpointManager
    from dmlc_core_tpu.utils import logging as L

    mgr = CheckpointManager(str(tmp_path / "ckpts"), keep=2)
    mgr.save(1, {"w": np.zeros(2)}, async_=False)
    mgr.save(2, {"w": np.zeros(2)}, async_=False)
    # flip the manager to remote semantics AFTER the local writes so _retain
    # exercises the remote branch without needing a remote filesystem; keep=2
    # would delete step 1 on a local manager if another step landed
    mgr._is_local = False
    mgr.keep = 1
    captured = []
    L.set_log_sink(lambda sev, line: captured.append((sev, line)))
    try:
        mgr._retain(2)
        mgr._retain(3)
        mgr._retain(4)
    finally:
        L.set_log_sink(None)
    warnings = [line for sev, line in captured
                if sev == L.WARNING and "remote steps are left in place" in line]
    assert len(warnings) == 1
    # and nothing was deleted: remote retention must not touch steps
    assert mgr.all_steps() == [1, 2]


def test_num_rows_with_explicit_row_weights(tmp_path):
    """Explicitly-weighted libsvm rows (label:weight) must not corrupt the
    real-row count: num_rows is structural, not weight.sum()."""
    from dmlc_core_tpu.bridge.batching import dense_batches
    from dmlc_core_tpu.data.factory import create_parser

    f = tmp_path / "w.libsvm"
    f.write_text("1:0.5 0:1.0\n0:2.0 1:2.0\n1:0.25 0:3.0\n")
    parser = create_parser(str(f), 0, 1, type="auto")
    batches = list(dense_batches(parser, 8, 2))
    b = batches[0]
    assert b.num_rows == 3
    assert abs(float(b.weight[:3].sum()) - 2.75) < 1e-6   # != row count
    assert (b.weight[3:] == 0).all()


def test_num_rows_is_static_under_jit(tmp_path):
    """num_rows is pytree aux data: usable for slicing inside a jit'd step
    (a leaf would be a tracer and ConcretizationTypeError here)."""
    import jax
    import jax.numpy as jnp

    from dmlc_core_tpu.bridge.batching import dense_batches
    from dmlc_core_tpu.data.factory import create_parser

    f = tmp_path / "t.libsvm"
    f.write_text("1 0:1.0\n0 1:2.0\n1 0:3.0\n")
    parser = create_parser(str(f), 0, 1, type="auto")
    (b,) = list(dense_batches(parser, 8, 2))

    @jax.jit
    def real_label_sum(batch):
        return jnp.sum(batch.label[:batch.num_rows])

    assert float(real_label_sum(b)) == 2.0
    # structure round-trips through tree_map with aux preserved
    b2 = jax.tree_util.tree_map(lambda a: a, b)
    assert b2.num_rows == 3


# -- final-partial-batch semantics (the serving scheduler depends on these) ---

def test_bucket_ladder_from_one():
    # minimum=1 is the serving batch ladder; it must terminate and ascend
    assert bucket_size(1, minimum=1) == 1
    assert bucket_size(2, minimum=1) == 2
    assert bucket_size(5, minimum=1) == 6
    ladder = [bucket_size(n, minimum=1) for n in range(1, 65)]
    assert ladder == sorted(ladder)
    assert set(ladder) == {1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64}


def test_dense_batches_remainder_mask_and_num_rows(tmp_path):
    uri = write_libsvm(tmp_path, 10)
    parser = create_parser(uri, type="libsvm", threaded=False)
    batches = list(dense_batches(parser, batch_size=4, num_feature=4))
    assert [b.num_rows for b in batches] == [4, 4, 2]
    tail = batches[-1]
    assert tail.x.shape == (4, 4)                      # static shape held
    np.testing.assert_allclose(tail.weight, [1, 1, 0, 0])
    np.testing.assert_allclose(tail.label[2:], 0.0)
    np.testing.assert_allclose(tail.x[2:], 0.0)        # padding zeroed
    # real rows kept their features (rows 8 and 9 of the corpus)
    np.testing.assert_allclose(tail.x[:2, 0], [8.0, 9.0])


def test_dense_batches_drop_remainder_drops_short_tail(tmp_path):
    uri = write_libsvm(tmp_path, 10)
    parser = create_parser(uri, type="libsvm", threaded=False)
    batches = list(dense_batches(parser, batch_size=4, num_feature=4,
                                 drop_remainder=True))
    assert [b.num_rows for b in batches] == [4, 4]


def test_sparse_batches_remainder_mask(tmp_path):
    uri = write_libsvm(tmp_path, 6)
    parser = create_parser(uri, type="libsvm", threaded=False)
    batches = list(sparse_batches(parser, batch_size=4, nnz_bucket=16))
    assert [b.num_rows for b in batches] == [4, 2]
    tail = batches[-1]
    assert tail.label.shape == (4,) and tail.value.shape == (16,)
    np.testing.assert_allclose(tail.weight, [1, 1, 0, 0])
    # padding nnz slots route to the drop segment (row_id == batch_size)
    real_nnz = 4  # 2 rows x 2 features in the corpus
    assert (tail.row_id[real_nnz:] == 4).all()
    seg = jax.ops.segment_sum(jnp.asarray(tail.value),
                              jnp.asarray(tail.row_id), num_segments=5)
    assert float(seg[2]) == 0.0 and float(seg[3]) == 0.0


def test_batches_empty_parser_yields_nothing(tmp_path):
    # a parser with no rows (blank-line-only file: the input split rejects
    # zero-byte files outright) must yield no batches — never an
    # all-padding one
    p = tmp_path / "empty.libsvm"
    p.write_text("\n\n")
    parser = create_parser(str(p), type="libsvm", threaded=False)
    assert list(dense_batches(parser, batch_size=4, num_feature=4)) == []
    parser = create_parser(str(p), type="libsvm", threaded=False)
    assert list(sparse_batches(parser, batch_size=4, nnz_bucket=8)) == []
    # same contract for a block stream that is empty altogether
    assert list(dense_batches(iter(()), batch_size=4, num_feature=4)) == []


def test_batches_batch_size_one(tmp_path):
    uri = write_libsvm(tmp_path, 3)
    parser = create_parser(uri, type="libsvm", threaded=False)
    batches = list(dense_batches(parser, batch_size=1, num_feature=4))
    assert [b.num_rows for b in batches] == [1, 1, 1]
    for i, b in enumerate(batches):
        assert b.x.shape == (1, 4)
        np.testing.assert_allclose(b.x[0, 0], float(i))
        np.testing.assert_allclose(b.weight, [1.0])


def test_dense_batches_remainder_keeps_explicit_weights(tmp_path):
    # explicit libsvm row weights (label:weight) must survive into the
    # masked tail: weight-0 padding is the mask, not a rescale of real
    # rows — which is exactly why num_rows, not weight.sum(), is the
    # true row count
    p = tmp_path / "weighted.libsvm"
    p.write_text("\n".join(f"{i % 2}:2.5 0:{i} 3:1.0" for i in range(3))
                 + "\n")
    parser = create_parser(str(p), type="libsvm", threaded=False)
    batches = list(dense_batches(parser, batch_size=2, num_feature=4))
    assert [b.num_rows for b in batches] == [2, 1]
    np.testing.assert_allclose(batches[-1].weight, [2.5, 0.0])
