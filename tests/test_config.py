"""Config parser tests (reference: test/unittest/unittest_config.cc:13-101)."""

import io

import pytest

from dmlc_core_tpu.config import Config
from dmlc_core_tpu.utils.logging import Error


def test_basics():
    cfg = Config('k1 = 1243\nk2=  okay\n k3 = "a ok" # comment\n# full comment\nk4 = 1e-4')
    assert cfg.get_param("k1") == "1243"
    assert cfg.get_param("k2") == "okay"
    assert cfg.get_param("k3") == "a ok"
    assert cfg.get_param("k4") == "1e-4"
    assert [k for k, _ in cfg.items()] == ["k1", "k2", "k3", "k4"]


def test_escapes():
    cfg = Config('msg = "line1\\nline2\\ttabbed \\"quoted\\""')
    assert cfg.get_param("msg") == 'line1\nline2\ttabbed "quoted"'
    # writer restores escaping
    assert '\\n' in cfg.to_proto_string()


def test_overwrite_vs_multi_value():
    text = "k = 1\nk = 2\n"
    single = Config(text)
    assert single.get_param("k") == "2"
    assert len(list(single.items())) == 1

    multi = Config(text, multi_value=True)
    assert multi.get_param("k") == "2"
    assert [v for _, v in multi.items()] == ["1", "2"]


def test_set_param_and_order():
    cfg = Config()
    cfg.set_param("b", 2)
    cfg.set_param("a", 1)
    cfg.set_param("b", 3)
    assert [(k, v) for k, v in cfg.items()] == [("b", "3"), ("a", "1")]


def test_proto_string():
    cfg = Config('x = 10\nname = "hi there"')
    proto = cfg.to_proto_string()
    assert "x : 10\n" in proto
    assert 'name : "hi there"\n' in proto


def test_stream_input():
    cfg = Config(io.StringIO("k = v\n"))
    assert cfg.get_param("k") == "v"


def test_errors():
    with pytest.raises(Error):
        Config('k = "unterminated')
    with pytest.raises(Error):
        Config("k =")   # missing value
    with pytest.raises(Error):
        Config("= v")   # stray =
