"""Zero-copy columnar ingest (ISSUE 13 tentpole, data/arrow_ingest.py):

- **differential**: Arrow/Parquet ingest of a dataset produces
  byte-identical RowBlock columns to the text parse of the same logical
  data — dense (csv-equivalent, incl. NaN and null->missing cells) and
  sparse (libsvm-equivalent, incl. weights) alike;
- **zero-copy**: CSR columns are numpy views aliasing the Arrow buffers
  (buffer identity, read-only), the accounting counters see every bulk
  materialization, and ``DMLC_ARROW_REQUIRE_ZERO_COPY`` escalates any
  bulk copy to an error;
- **rejection, never drift**: wrong dtypes, nulls in sparse columns, and
  misaligned list offsets raise :class:`ArrowIngestError` naming the
  column — there is no silent cast or per-row fallback path;
- **composition**: row-group sharding is exactly-once, DiskRowIter builds
  (and publishes) the v2 page cache straight from Parquet row groups,
  remote Parquet rides the ranged-read FS layer, and pyarrow stays an
  optional dependency with one clear gating error.
"""

import os

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")
import pyarrow.parquet as pq  # noqa: E402

from dmlc_core_tpu import telemetry  # noqa: E402
from dmlc_core_tpu.data import arrow_ingest  # noqa: E402
from dmlc_core_tpu.data.arrow_ingest import (ArrowIngestError,  # noqa: E402
                                             table_to_block)
from dmlc_core_tpu.data.factory import (create_parser,  # noqa: E402
                                        create_row_block_iter)
from dmlc_core_tpu.data.iterators import DiskRowIter  # noqa: E402
from dmlc_core_tpu.data.row_block import concat_blocks  # noqa: E402
from dmlc_core_tpu.io.ranged_read import RangedReadFile  # noqa: E402
from tests.mock_s3 import MockS3  # noqa: E402

ROWS = 3000


# ------------------------------------------------------------------ corpora --

def _sparse_data(rows=ROWS, seed=3, with_weight=False):
    rng = np.random.RandomState(seed)
    labels = (np.arange(rows) % 2).astype(np.float32)
    weights = (rng.rand(rows).astype(np.float32) + np.float32(0.5)
               if with_weight else None)
    idx_lists, val_lists = [], []
    for _ in range(rows):
        feats = np.sort(rng.choice(40, size=rng.randint(1, 6),
                                   replace=False)).astype(np.uint32)
        idx_lists.append(feats)
        val_lists.append(rng.rand(len(feats)).astype(np.float32))
    return labels, weights, idx_lists, val_lists


def _write_sparse_text(path, labels, weights, idx_lists, val_lists):
    lines = []
    for i, (idx, val) in enumerate(zip(idx_lists, val_lists)):
        head = (f"{float(labels[i])!r}:{float(weights[i])!r}"
                if weights is not None else f"{float(labels[i])!r}")
        lines.append(head + " " + " ".join(
            f"{j}:{float(v)!r}" for j, v in zip(idx, val)))
    path.write_text("\n".join(lines) + "\n")
    return str(path)


def _sparse_table(labels, weights, idx_lists, val_lists, list_type=None,
                  index_type=None, value_type=None):
    list_type = list_type or pa.large_list
    cols = {
        "label": pa.array(labels, type=pa.float32()),
        "index": pa.array([[int(j) for j in idx] for idx in idx_lists],
                          type=list_type(index_type or pa.uint32())),
        "value": pa.array([[float(v) for v in val] for val in val_lists],
                          type=list_type(value_type or pa.float32())),
    }
    if weights is not None:
        cols["weight"] = pa.array(weights, type=pa.float32())
    return pa.table(cols)


def _write_parquet(path, table, row_group_size=700):
    pq.write_table(table, str(path), row_group_size=row_group_size,
                   compression="none", use_dictionary=False)
    return str(path)


def _write_ipc(path, table, batch_rows=700):
    with pa.ipc.new_file(str(path), table.schema) as writer:
        for batch in table.to_batches(max_chunksize=batch_rows):
            writer.write_batch(batch)
    return str(path)


def _drain(uri, **kwargs):
    parser = create_parser(uri, **kwargs)
    blocks = list(parser)
    if hasattr(parser, "close"):
        parser.close()
    return concat_blocks(blocks)


def _assert_blocks_byte_identical(a, b, with_weight=False):
    assert a.size == b.size
    assert np.array_equal(a.offset - a.offset[0], b.offset - b.offset[0])
    assert a.label.tobytes() == b.label.tobytes()
    assert a.index.tobytes() == b.index.tobytes()
    assert a.index.dtype == b.index.dtype
    assert a.value.tobytes() == b.value.tobytes()
    if with_weight:
        assert a.weight.tobytes() == b.weight.tobytes()


# -------------------------------------------------------------- differential --

def test_sparse_parquet_byte_identical_to_libsvm(tmp_path):
    labels, weights, idx, val = _sparse_data()
    text = _write_sparse_text(tmp_path / "d.libsvm", labels, weights, idx,
                              val)
    parquet = _write_parquet(tmp_path / "d.parquet",
                             _sparse_table(labels, weights, idx, val))
    _assert_blocks_byte_identical(_drain(text, type="libsvm"),
                                  _drain(parquet))


def test_sparse_weights_byte_identical(tmp_path):
    labels, weights, idx, val = _sparse_data(with_weight=True)
    text = _write_sparse_text(tmp_path / "d.libsvm", labels, weights, idx,
                              val)
    parquet = _write_parquet(tmp_path / "d.parquet",
                             _sparse_table(labels, weights, idx, val))
    _assert_blocks_byte_identical(_drain(text, type="libsvm"),
                                  _drain(parquet), with_weight=True)


def test_sparse_arrow_ipc_byte_identical(tmp_path):
    labels, weights, idx, val = _sparse_data()
    text = _write_sparse_text(tmp_path / "d.libsvm", labels, weights, idx,
                              val)
    ipc = _write_ipc(tmp_path / "d.arrow",
                     _sparse_table(labels, weights, idx, val))
    _assert_blocks_byte_identical(_drain(text, type="libsvm"), _drain(ipc))


def _dense_data(rows=ROWS, feats=9, seed=5):
    rng = np.random.RandomState(seed)
    x = rng.randn(rows, feats).astype(np.float32)
    x[rows // 3, 2] = np.float32("nan")          # a real NaN VALUE
    y = rng.randint(0, 2, rows).astype(np.float32)
    missing_at = (rows // 2, 4)                  # a MISSING cell (null)
    return x, y, missing_at


def test_dense_parquet_byte_identical_to_csv(tmp_path):
    x, y, (mi, mj) = _dense_data()
    csv = tmp_path / "d.csv"
    with open(csv, "w") as f:
        for i, (yi, row) in enumerate(zip(y, x)):
            cells = [repr(float(v)) for v in row]
            if i == mi:
                cells[mj] = ""                   # empty cell -> ?missing=
            f.write(repr(float(yi)) + "," + ",".join(cells) + "\n")
    cols = {"label": pa.array(y, type=pa.float32())}
    for j in range(x.shape[1]):
        col = x[:, j].tolist()
        if j == mj:
            col[mi] = None                       # null cell -> ?missing=
        cols[f"f{j}"] = pa.array(col, type=pa.float32())
    parquet = _write_parquet(tmp_path / "d.parquet", pa.table(cols))
    for missing in ("0.0", "nan"):
        a = _drain(f"{csv}?format=csv&label_column=0&missing={missing}")
        b = _drain(f"{parquet}?label_column=0&missing={missing}")
        # tobytes compares bit patterns, so NaNs must match exactly too
        _assert_blocks_byte_identical(a, b)


def test_dense_named_label_column_default(tmp_path):
    x, y, _ = _dense_data(rows=100)
    cols = {f"f{j}": pa.array(x[:, j], type=pa.float32())
            for j in range(x.shape[1])}
    cols["label"] = pa.array(y, type=pa.float32())
    parquet = _write_parquet(tmp_path / "d.parquet", pa.table(cols))
    block = _drain(parquet)                      # no label_column given
    assert block.label.tobytes() == y.tobytes()
    assert block.size == 100


def test_empty_row_groups_skipped(tmp_path):
    schema = pa.schema([("label", pa.float32()),
                        ("index", pa.large_list(pa.uint32()))])
    path = str(tmp_path / "e.parquet")
    with pq.ParquetWriter(path, schema) as writer:
        writer.write_table(pa.table({"label": pa.array([], pa.float32()),
                                     "index": pa.array(
                                         [], pa.large_list(pa.uint32()))}))
        writer.write_table(pa.table({"label": pa.array([1.0], pa.float32()),
                                     "index": pa.array(
                                         [[3]], pa.large_list(pa.uint32()))}))
    parser = create_parser(path, threaded=False)
    blocks = list(parser)
    parser.close()
    assert [b.size for b in blocks] == [1]
    assert blocks[0].index.tolist() == [3]


def test_row_group_sharding_exactly_once(tmp_path):
    labels, weights, idx, val = _sparse_data(rows=1000)
    parquet = _write_parquet(tmp_path / "d.parquet",
                             _sparse_table(labels, weights, idx, val),
                             row_group_size=128)
    whole = _drain(parquet)
    parts = [_drain(parquet, part_index=k, num_parts=3) for k in range(3)]
    assert sum(p.size for p in parts) == whole.size == 1000
    # shard k of n reads row groups k, k+n, ... — concatenating the parts
    # in round-robin group order reproduces the whole dataset exactly
    merged = concat_blocks([blk for blk in _interleave(parts, parquet)])
    assert merged.label.tobytes() == whole.label.tobytes()
    assert merged.value.tobytes() == whole.value.tobytes()


def _interleave(parts, parquet):
    """Re-drain per part as block lists to reassemble round-robin."""
    out = []
    lists = []
    for k in range(len(parts)):
        parser = create_parser(parquet, part_index=k, num_parts=len(parts),
                               threaded=False)
        lists.append(list(parser))
        parser.close()
    longest = max(len(lst) for lst in lists)
    for i in range(longest):
        for lst in lists:
            if i < len(lst):
                out.append(lst[i])
    return out


# ----------------------------------------------------- rejection, not drift --

def test_dense_float64_feature_rejected(tmp_path):
    table = pa.table({"label": pa.array([1.0, 0.0], pa.float32()),
                      "f0": pa.array([1.0, 2.0], pa.float64())})
    path = _write_parquet(tmp_path / "drift.parquet", table)
    with pytest.raises(ArrowIngestError, match="f0.*double|double.*f0"):
        _drain(path, threaded=False)


def test_sparse_value_float64_rejected(tmp_path):
    labels, weights, idx, val = _sparse_data(rows=50)
    table = _sparse_table(labels, weights, idx, val,
                          value_type=pa.float64())
    path = _write_parquet(tmp_path / "drift.parquet", table)
    with pytest.raises(ArrowIngestError, match="value"):
        _drain(path, threaded=False)


def test_sparse_index_dtype_drift_rejected(tmp_path):
    labels, weights, idx, val = _sparse_data(rows=50)
    table = _sparse_table(labels, weights, idx, val,
                          index_type=pa.int64())
    path = _write_parquet(tmp_path / "drift.parquet", table)
    with pytest.raises(ArrowIngestError, match="index"):
        _drain(path, threaded=False)
    # ... but an int64 index column IS the right dtype for an int64 cache
    block = _drain(path, threaded=False, index_dtype=np.int64)
    assert block.index.dtype == np.dtype(np.int64)


def test_misaligned_value_lists_rejected(tmp_path):
    table = pa.table({
        "label": pa.array([0.0, 1.0], pa.float32()),
        "index": pa.array([[0, 1], [2]], pa.large_list(pa.uint32())),
        "value": pa.array([[1.0], [2.0]], pa.large_list(pa.float32())),
    })
    path = _write_parquet(tmp_path / "mis.parquet", table)
    with pytest.raises(ArrowIngestError, match="row lengths"):
        _drain(path, threaded=False)


def test_null_sparse_row_rejected(tmp_path):
    table = pa.table({
        "label": pa.array([0.0, 1.0], pa.float32()),
        "index": pa.array([[0, 1], None], pa.large_list(pa.uint32())),
    })
    path = _write_parquet(tmp_path / "null.parquet", table)
    with pytest.raises(ArrowIngestError, match="null"):
        _drain(path, threaded=False)


def test_list_without_index_column_rejected(tmp_path):
    table = pa.table({
        "label": pa.array([0.0], pa.float32()),
        "vals": pa.array([[1.0]], pa.large_list(pa.float32())),
    })
    path = _write_parquet(tmp_path / "noindex.parquet", table)
    with pytest.raises(ArrowIngestError, match="index"):
        _drain(path, threaded=False)


# ------------------------------------------------------- zero-copy evidence --

def test_ipc_views_alias_arrow_buffers_and_are_readonly(tmp_path):
    labels, weights, idx, val = _sparse_data(rows=400, with_weight=True)
    ipc = _write_ipc(tmp_path / "d.arrow",
                     _sparse_table(labels, weights, idx, val),
                     batch_rows=400)
    mm = pa.memory_map(ipc)
    table = pa.Table.from_batches([pa.ipc.open_file(mm).get_batch(0)])
    block, stats = table_to_block(table)
    assert stats["bulk_copy_columns"] == 0
    assert stats["zero_copy_columns"] >= 6   # label/weight + 2x(offsets+values)
    for name in ("offset", "label", "weight", "index", "value"):
        arr = getattr(block, name)
        assert not arr.flags.writeable, name
        assert not arr.flags.owndata, name   # a view, not a materialization
    # buffer identity against the Arrow child buffers themselves
    child = table.column("value").chunk(0).values
    arrow_view = np.frombuffer(child.buffers()[1], dtype=np.float32,
                               count=len(child) + child.offset)
    assert np.shares_memory(block.value, arrow_view)
    idx_child = table.column("index").chunk(0).values
    idx_view = np.frombuffer(idx_child.buffers()[1], dtype=np.uint32,
                             count=len(idx_child) + idx_child.offset)
    assert np.shares_memory(block.index, idx_view)


def test_plain_list_offsets_counted_as_bulk_copy(tmp_path, monkeypatch):
    labels, weights, idx, val = _sparse_data(rows=50)
    table = _sparse_table(labels, weights, idx, val, list_type=pa.list_)
    block, stats = table_to_block(table)
    assert block.size == 50
    # 32-bit list offsets widen to CSR int64: visible, never silent
    assert stats["bulk_copy_columns"] >= 1
    assert any("offsets" in r for r in stats["bulk_copy_reasons"])
    monkeypatch.setenv("DMLC_ARROW_REQUIRE_ZERO_COPY", "1")
    with pytest.raises(ArrowIngestError, match="REQUIRE_ZERO_COPY"):
        table_to_block(table)


def test_strict_knob_rejects_dense_interleave(tmp_path, monkeypatch):
    x, y, _ = _dense_data(rows=20)
    cols = {"label": pa.array(y, pa.float32())}
    for j in range(x.shape[1]):
        cols[f"f{j}"] = pa.array(x[:, j], pa.float32())
    table = pa.table(cols)
    block, stats = table_to_block(table, label_column=0)
    assert stats["bulk_copy_columns"] == 1   # exactly the interleave
    monkeypatch.setenv("DMLC_ARROW_REQUIRE_ZERO_COPY", "1")
    with pytest.raises(ArrowIngestError, match="interleave"):
        table_to_block(table, label_column=0)


def test_ingest_telemetry_counters(tmp_path):
    labels, weights, idx, val = _sparse_data(rows=500)
    parquet = _write_parquet(tmp_path / "d.parquet",
                             _sparse_table(labels, weights, idx, val))
    was_enabled = telemetry.enabled()
    telemetry.enable()
    try:
        reg = telemetry.get_registry()
        rows_c = reg.counter("dmlc_ingest_rows_total", format="parquet")
        zc = reg.counter("dmlc_ingest_columns_total", mode="zero_copy")
        bc = reg.counter("dmlc_ingest_columns_total", mode="bulk_copy")
        r0, z0, b0 = rows_c.value, zc.value, bc.value
        block = _drain(parquet, threaded=False)
        assert block.size == 500
        assert rows_c.value - r0 == 500
        assert zc.value > z0
        assert bc.value == b0        # large_list sparse: pure views
    finally:
        if not was_enabled:
            telemetry.disable()


# ------------------------------------------------- page cache + remote paths --

def test_page_cache_from_parquet_epoch2_buffer_identity(tmp_path):
    labels, weights, idx, val = _sparse_data()
    text = _write_sparse_text(tmp_path / "d.libsvm", labels, weights, idx,
                              val)
    parquet = _write_parquet(tmp_path / "d.parquet",
                             _sparse_table(labels, weights, idx, val))
    it = create_row_block_iter(f"{parquet}#{tmp_path / 'c.cache'}")
    assert isinstance(it, DiskRowIter)
    epoch1 = list(it)
    it.before_first()
    epoch2 = list(it)
    assert sum(b.size for b in epoch1) == ROWS == sum(b.size for b in epoch2)
    for a, b in zip(epoch1, epoch2):
        assert a.offset is b.offset          # the same mmap views per epoch
        assert a.index is b.index
        assert a.value is b.value
        assert not a.index.flags.writeable
    it.close()
    # and the cached columns equal the text parse of the same logical data
    cached = concat_blocks(epoch1)
    _assert_blocks_byte_identical(_drain(text, type="libsvm"), cached)


def test_write_block_direct_arrow_to_page_cache(tmp_path):
    """Arrow-mapped blocks write straight into a v2 cache via
    ``PageCacheWriter.write_block`` — no RowBlockContainer re-staging —
    and the reader serves them back column-identical."""
    from dmlc_core_tpu.data import page_cache

    labels, weights, idx, val = _sparse_data(rows=300, with_weight=True)
    table = _sparse_table(labels, weights, idx, val)
    block, stats = table_to_block(table)
    assert stats["bulk_copy_columns"] == 0
    cache = str(tmp_path / "direct.cache")
    writer = page_cache.PageCacheWriter(cache)
    writer.write_block(block)
    writer.commit()
    reader = page_cache.PageCacheReader(cache)
    [served] = reader.blocks
    assert served.label.tobytes() == block.label.tobytes()
    assert served.index.tobytes() == block.index.tobytes()
    assert served.value.tobytes() == block.value.tobytes()
    assert served.weight.tobytes() == block.weight.tobytes()
    assert np.array_equal(served.offset, block.offset)
    reader.close()


def test_fit_binner_over_parquet_cache_views(tmp_path):
    """The streamed-quantile feed consumes the parquet-built cache's mmap
    views directly — the full zero-copy chain parquet -> page cache ->
    binner edges with no text stage anywhere."""
    from dmlc_core_tpu.bridge.binning import fit_binner

    x = np.random.RandomState(7).randn(800, 4).astype(np.float32)
    cols = {"label": pa.array(np.zeros(800, np.float32), pa.float32())}
    for j in range(4):
        cols[f"f{j}"] = pa.array(x[:, j], pa.float32())
    parquet = _write_parquet(tmp_path / "d.parquet", pa.table(cols))
    it = create_row_block_iter(f"{parquet}#{tmp_path / 'c.cache'}")
    list(it)
    blocks = it.cache_blocks()
    assert blocks is not None
    binner = fit_binner(blocks, num_bins=16, num_feature=4)
    direct = fit_binner(x, num_bins=16, num_feature=4)
    for a, b in zip(binner.boundaries, direct.boundaries):
        assert np.allclose(a, b)
    it.close()


@pytest.fixture()
def mock_s3(monkeypatch, tmp_path):
    server = MockS3().start()
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "test-key")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "test-secret")
    monkeypatch.setenv("AWS_REGION", "us-east-1")
    monkeypatch.setenv("S3_ENDPOINT", f"http://127.0.0.1:{server.port}")
    monkeypatch.setenv("DMLC_CACHE_LOCAL_DIR", str(tmp_path / "materialized"))
    monkeypatch.delenv("DMLC_CACHE_REMOTE", raising=False)
    yield server
    server.stop()


def test_remote_parquet_ranged_reads(mock_s3, tmp_path):
    labels, weights, idx, val = _sparse_data(rows=800)
    parquet = _write_parquet(tmp_path / "d.parquet",
                             _sparse_table(labels, weights, idx, val),
                             row_group_size=100)
    with open(parquet, "rb") as f:
        mock_s3.objects[("bucket", "d.parquet")] = f.read()
    local = _drain(parquet)
    remote = _drain("s3://bucket/d.parquet")
    _assert_blocks_byte_identical(local, remote)
    # sharded remote read: only the assigned row groups move
    part0 = _drain("s3://bucket/d.parquet", part_index=0, num_parts=2)
    part1 = _drain("s3://bucket/d.parquet", part_index=1, num_parts=2)
    assert part0.size + part1.size == 800


def test_remote_parquet_to_published_cache_fleet_fetch(mock_s3, tmp_path,
                                                      monkeypatch):
    """The full ISSUE 13 composition: a cold worker ingests remote Parquet
    (no text anywhere), builds the v2 page cache from its row groups, and
    publishes it; a second host fetches the published cache instead of
    touching the Parquet object at all."""
    import shutil

    labels, weights, idx, val = _sparse_data(rows=600)
    parquet = _write_parquet(tmp_path / "d.parquet",
                             _sparse_table(labels, weights, idx, val))
    with open(parquet, "rb") as f:
        mock_s3.objects[("bucket", "d.parquet")] = f.read()
    monkeypatch.setenv("DMLC_CACHE_REMOTE", "1")
    was_enabled = telemetry.enabled()
    telemetry.enable()
    try:
        reg = telemetry.get_registry()
        hits = reg.counter("dmlc_cache_remote_hits_total")
        publishes = reg.counter("dmlc_cache_remote_publishes_total")
        h0, p0 = hits.value, publishes.value
        uri = "s3://bucket/d.parquet#s3://bucket/caches/d.rbc"
        it = create_row_block_iter(uri)
        assert sum(b.size for b in it) == 600
        it.close()
        assert publishes.value == p0 + 1
        assert ("bucket", "caches/d.rbc") in mock_s3.objects

        # second "host": fresh local dir, fetches the cache, parquet unread
        shutil.rmtree(str(tmp_path / "materialized"), ignore_errors=True)
        del mock_s3.objects[("bucket", "d.parquet")]   # prove it: source gone
        it2 = create_row_block_iter(uri)
        assert sum(b.size for b in it2) == 600
        it2.close()
        assert hits.value == h0 + 1
    finally:
        if not was_enabled:
            telemetry.disable()


# ------------------------------------------------------------ io + gating ----

def test_ranged_read_file_semantics(tmp_path):
    path = tmp_path / "blob.bin"
    payload = bytes(range(256)) * 16
    path.write_bytes(payload)
    with RangedReadFile(str(path)) as f:
        assert f.size() == len(payload)
        assert f.read(4) == payload[:4]
        assert f.tell() == 4
        assert f.seek(-8, 2) == len(payload) - 8
        assert f.read() == payload[-8:]
        assert f.seek(2, 0) == 2
        assert f.seek(3, 1) == 5
        assert f.read(1) == payload[5:6]
        f.seek(len(payload) + 100)
        assert f.read(10) == b""             # past EOF: empty, not an error
        with pytest.raises(ValueError):
            f.seek(0, 9)
    with pytest.raises(ValueError, match="closed"):
        f.read(1)


def test_pyarrow_absent_raises_one_clear_error(tmp_path, monkeypatch):
    monkeypatch.setattr(arrow_ingest, "pa", None)
    monkeypatch.setattr(arrow_ingest, "pq", None)
    monkeypatch.setattr(arrow_ingest, "_PYARROW_ERROR",
                        ImportError("No module named 'pyarrow'"))
    assert not arrow_ingest.pyarrow_available()
    with pytest.raises(RuntimeError, match="pyarrow"):
        create_parser(str(tmp_path / "d.parquet"))
    with pytest.raises(RuntimeError, match="pyarrow"):
        arrow_ingest.ParquetParser(str(tmp_path / "d.parquet"))
    # ... and the text front door is untouched by the absence
    (tmp_path / "t.libsvm").write_text("1 0:1.5\n")
    block = _drain(str(tmp_path / "t.libsvm"), type="libsvm")
    assert block.size == 1
