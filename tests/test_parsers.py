"""Parser tests (reference: test/libsvm_parser_test.cc, libfm_parser_test.cc,
csv_parser_test.cc, dataiter_test.cc, strtonum_test.cc)."""

import numpy as np
import pytest

from dmlc_core_tpu.data import strtonum
from dmlc_core_tpu.data.factory import create_parser, create_row_block_iter
from dmlc_core_tpu.data.iterators import BasicRowIter, DiskRowIter


LIBSVM = b"""1 0:1.5 3:2.0
0 1:1.0
1
0 2:0.5 4:0.25 5:1
"""

LIBSVM_WEIGHTED = b"""1:2.0 0:1.5
0:0.5 1:1.0
"""

LIBSVM_NOVALS = b"""1 3 5 7
0 2
"""

LIBFM = b"""1 0:0:1.5 1:3:2.0
0:0.25 2:1:1.0
"""

CSV = b"""1.0,2.0,3.0
4.0,5.0,6.0
"""


def write(tmp_path, name, data):
    p = tmp_path / name
    p.write_bytes(data)
    return str(p)


def all_rows(parser):
    rows = []
    for block in parser:
        rows.extend(block.rows())
    return rows


def test_libsvm_basic(tmp_path):
    uri = write(tmp_path, "a.libsvm", LIBSVM)
    parser = create_parser(uri, type="libsvm", threaded=False)
    rows = all_rows(parser)
    assert len(rows) == 4
    assert rows[0].label == 1.0
    assert rows[0].index.tolist() == [0, 3]
    assert rows[0].value.tolist() == [1.5, 2.0]
    assert rows[2].length == 0
    assert rows[3].index.tolist() == [2, 4, 5]
    assert parser.bytes_read() > 0


def test_libsvm_weights(tmp_path):
    uri = write(tmp_path, "w.libsvm", LIBSVM_WEIGHTED)
    rows = all_rows(create_parser(uri, type="libsvm", threaded=False))
    assert rows[0].label == 1.0
    assert rows[0].get_weight() == 2.0
    assert rows[1].get_weight() == 0.5


def test_libsvm_no_values(tmp_path):
    uri = write(tmp_path, "nv.libsvm", LIBSVM_NOVALS)
    rows = all_rows(create_parser(uri, type="libsvm", threaded=False))
    assert rows[0].index.tolist() == [3, 5, 7]
    assert rows[0].value is None
    assert rows[0].get_value(0) == 1.0


def test_libsvm_threaded_matches(tmp_path):
    rng = np.random.RandomState(0)
    lines = []
    for i in range(5000):
        nnz = rng.randint(1, 10)
        idx = sorted(rng.choice(100, size=nnz, replace=False))
        feats = " ".join(f"{j}:{rng.rand():.4f}" for j in idx)
        lines.append(f"{i % 2} {feats}")
    data = ("\n".join(lines) + "\n").encode()
    uri = write(tmp_path, "big.libsvm", data)
    plain = all_rows(create_parser(uri, type="libsvm", threaded=False))
    threaded = all_rows(create_parser(uri, type="libsvm", threaded=True))
    assert len(plain) == len(threaded) == 5000
    for a, b in zip(plain, threaded):
        assert a.label == b.label
        assert a.index.tolist() == b.index.tolist()


def test_libfm(tmp_path):
    uri = write(tmp_path, "a.libfm", LIBFM)
    rows = all_rows(create_parser(uri, type="libfm", threaded=False))
    assert rows[0].field.tolist() == [0, 1]
    assert rows[0].index.tolist() == [0, 3]
    assert rows[0].value.tolist() == [1.5, 2.0]
    assert rows[1].get_weight() == 0.25
    assert rows[1].field.tolist() == [2]


def test_csv(tmp_path):
    uri = write(tmp_path, "a.csv", CSV)
    rows = all_rows(create_parser(uri + "?format=csv", threaded=False))
    assert rows[0].label == 0.0
    assert rows[0].value.tolist() == [1.0, 2.0, 3.0]
    assert rows[0].index.tolist() == [0, 1, 2]


def test_csv_label_column(tmp_path):
    uri = write(tmp_path, "b.csv", CSV)
    rows = all_rows(create_parser(uri + "?format=csv&label_column=1", threaded=False))
    assert rows[0].label == 2.0
    assert rows[0].value.tolist() == [1.0, 3.0]
    assert rows[1].label == 5.0


def test_csv_native_label_split_matches_python(tmp_path):
    """The native one-pass label split (dmlc_tpu_result_fill_csv) must
    produce byte-identical blocks to the pure-python parse_block for every
    label position, including empty cells."""
    import numpy as np

    from dmlc_core_tpu import native_bridge
    from dmlc_core_tpu.data.csv_parser import CSVParser

    if not native_bridge.available():
        import pytest

        pytest.skip("native lib unavailable")
    rng = np.random.RandomState(7)
    lines = []
    for i in range(500):
        cells = [f"{v:.4f}" for v in rng.randn(6)]
        if i % 17 == 0:
            cells[rng.randint(6)] = ""          # empty cell -> missing value
        lines.append(",".join(cells))
    data = ("\n".join(lines) + "\n").encode()
    for lc in (-1, 0, 3, 5):
        p = CSVParser(None, {"label_column": str(lc)}, nthread=1)
        native = p.parse_chunk_native(data)
        python = p.parse_block(data)
        nb, pb = native.get_block(), python.get_block()
        assert nb.size == pb.size == 500
        np.testing.assert_array_equal(nb.label, pb.label)
        np.testing.assert_array_equal(nb.offset, pb.offset)
        np.testing.assert_array_equal(nb.index, pb.index)
        np.testing.assert_array_equal(nb.value, pb.value)


def test_format_autodetect_default_libsvm(tmp_path):
    uri = write(tmp_path, "c.txt", LIBSVM)
    rows = all_rows(create_parser(uri, threaded=False))
    assert len(rows) == 4


def test_parser_sharding_covers_all(tmp_path):
    lines = b"".join(b"%d 0:%d\n" % (i % 2, i) for i in range(1000))
    uri = write(tmp_path, "shard.libsvm", lines)
    values = []
    for part in range(4):
        parser = create_parser(uri, part, 4, type="libsvm", threaded=False)
        for block in parser:
            values.extend(int(v) for v in block.value)
    assert sorted(values) == list(range(1000))


def test_basic_row_iter(tmp_path):
    uri = write(tmp_path, "d.libsvm", LIBSVM)
    it = create_row_block_iter(uri, type="libsvm")
    assert isinstance(it, BasicRowIter)
    blocks = list(it)
    assert len(blocks) == 1 and blocks[0].size == 4
    it.before_first()
    assert len(list(it)) == 1


def test_disk_row_iter(tmp_path):
    uri = write(tmp_path, "e.libsvm", LIBSVM)
    cache = tmp_path / "e.cache"
    it = create_row_block_iter(f"{uri}#{cache}", type="libsvm")
    assert isinstance(it, DiskRowIter)
    rows1 = [r for b in it for r in b.rows()]
    assert len(rows1) == 4
    it.before_first()
    rows2 = [r for b in it for r in b.rows()]
    assert len(rows2) == 4
    assert cache.exists()
    it.close()


def test_bad_input_raises(tmp_path):
    uri = write(tmp_path, "bad.libsvm", b"1 abc:def\n")
    parser = create_parser(uri, type="libsvm", threaded=False)
    with pytest.raises(ValueError, match="feature"):
        list(parser)


def test_strtonum():
    assert strtonum.str2float(b"1.5e3") == 1500.0
    assert strtonum.str2int("42") == 42
    assert strtonum.parse_pair(b"3:4.5") == (2, 3.0, 4.5)
    assert strtonum.parse_pair(b"7") == (1, 7.0, None)
    assert strtonum.parse_pair(b"") == (0, None, None)
    assert strtonum.parse_triple(b"1:2:3.5") == (3, 1.0, 2.0, 3.5)


def test_csv_empty_cells_default_zero(tmp_path):
    """Reference parity: strtof parses an empty field as 0.0
    (csv_parser.h:83) — empty cells must not error."""
    f = tmp_path / "e.csv"
    f.write_text("1,0.5,,2.0\n0,,1.5,\n")
    parser = create_parser(str(f), 0, 1, type="csv")
    rows = [r for b in parser for r in b.rows()]
    assert len(rows) == 2
    np.testing.assert_allclose(rows[0].value, [1.0, 0.5, 0.0, 2.0])
    np.testing.assert_allclose(rows[1].value, [0.0, 0.0, 1.5, 0.0])


def test_csv_missing_nan(tmp_path):
    f = tmp_path / "m.csv"
    f.write_text("1,0.5,\n0,,1.5\n")
    parser = create_parser(str(f) + "?missing=nan", 0, 1, type="csv")
    rows = [r for b in parser for r in b.rows()]
    np.testing.assert_allclose(rows[0].value, [1.0, 0.5, np.nan])
    np.testing.assert_allclose(rows[1].value, [0.0, np.nan, 1.5])
