"""The C++ consumer story: compile and run examples/cpp/consumer_demo.cc
against include/dmlc_tpu/ + libdmlc_tpu_native.so.

SURVEY §7 commits to a native-consumable substrate ("downstream C++ libs
like XGBoost consume the C++ API", reference include/dmlc/parameter.h);
this test is the proof: a standalone C++ program declares parameters,
registers factories, shard-reads a libsvm file through the native split
engine, and parses it — linked only against the shipped library + headers.
"""

import shutil
import subprocess

import pytest

from dmlc_core_tpu import native_bridge

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or not native_bridge.available(),
    reason="needs g++ and the native library")

import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def demo_bin(tmp_path_factory):
    out = tmp_path_factory.mktemp("cppdemo") / "consumer_demo"
    native_dir = os.path.join(REPO, "native")
    cmd = [
        "g++", "-std=c++17", "-Wall", "-Wextra", "-Werror",
        "-I", os.path.join(REPO, "include"),
        os.path.join(REPO, "examples", "cpp", "consumer_demo.cc"),
        "-L", native_dir, "-ldmlc_tpu_native",
        f"-Wl,-rpath,{native_dir}", "-o", str(out),
    ]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return str(out)


def _write_libsvm(path, n_rows):
    nnz = 0
    label_sum = 0
    with open(path, "w") as f:
        for i in range(n_rows):
            y = i % 2
            feats = [(j, (i + j) % 10 / 10.0) for j in range(i % 4 + 1)]
            f.write(f"{y} " + " ".join(f"{j}:{v}" for j, v in feats) + "\n")
            nnz += len(feats)
            label_sum += y
    return nnz, label_sum


@pytest.mark.parametrize("nparts", [1, 3])
def test_demo_end_to_end(demo_bin, tmp_path, nparts):
    data = tmp_path / "train.libsvm"
    nnz, label_sum = _write_libsvm(data, 500)
    proc = subprocess.run([demo_bin, str(data), str(nparts)],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    # partition coverage: all rows/nnz seen exactly once across parts
    assert f"rows=500 nnz={nnz} label_sum={float(label_sum):.1f}" \
        in proc.stdout
    # the parameter docgen and range-check paths ran
    assert "nthread : int, default=2" in proc.stdout
    assert "range check ok" in proc.stdout
