"""The C++ consumer story: compile and run examples/cpp/consumer_demo.cc
against include/dmlc_tpu/ + libdmlc_tpu_native.so.

SURVEY §7 commits to a native-consumable substrate ("downstream C++ libs
like XGBoost consume the C++ API", reference include/dmlc/parameter.h);
this test is the proof: a standalone C++ program declares parameters,
registers factories, shard-reads a libsvm file through the native split
engine, and parses it — linked only against the shipped library + headers.
"""

import shutil
import subprocess

import pytest

from dmlc_core_tpu import native_bridge

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or not native_bridge.available(),
    reason="needs g++ and the native library")

import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def demo_bin(tmp_path_factory):
    out = tmp_path_factory.mktemp("cppdemo") / "consumer_demo"
    native_dir = os.path.join(REPO, "native")
    cmd = [
        "g++", "-std=c++17", "-Wall", "-Wextra", "-Werror",
        "-I", os.path.join(REPO, "include"),
        os.path.join(REPO, "examples", "cpp", "consumer_demo.cc"),
        "-L", native_dir, "-ldmlc_tpu_native",
        f"-Wl,-rpath,{native_dir}", "-o", str(out),
    ]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return str(out)


def _write_libsvm(path, n_rows):
    nnz = 0
    label_sum = 0
    with open(path, "w") as f:
        for i in range(n_rows):
            y = i % 2
            feats = [(j, (i + j) % 10 / 10.0) for j in range(i % 4 + 1)]
            f.write(f"{y} " + " ".join(f"{j}:{v}" for j, v in feats) + "\n")
            nnz += len(feats)
            label_sum += y
    return nnz, label_sum


@pytest.mark.parametrize("nparts", [1, 3])
def test_demo_end_to_end(demo_bin, tmp_path, nparts):
    data = tmp_path / "train.libsvm"
    nnz, label_sum = _write_libsvm(data, 500)
    proc = subprocess.run([demo_bin, str(data), str(nparts)],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    # partition coverage: all rows/nnz seen exactly once across parts
    assert f"rows=500 nnz={nnz} label_sum={float(label_sum):.1f}" \
        in proc.stdout
    # the parameter docgen and range-check paths ran
    assert "nthread : int, default=2" in proc.stdout
    assert "range check ok" in proc.stdout


def test_serializer_interop_python_to_cpp(demo_bin, tmp_path):
    """A blob written by the Python serializer loads in C++ (the shared
    wire format, include/dmlc_tpu/io.h vs dmlc_core_tpu/serializer.py)."""
    import numpy as np

    from dmlc_core_tpu import serializer as ser
    from dmlc_core_tpu.io.stream import create_stream

    spec = ser.Pair(ser.Map(ser.Str, ser.Vector(ser.POD(np.float32))),
                    ser.Vector(ser.Pair(ser.Str, ser.POD(np.int64))))
    # std::map iterates sorted keys; write in the same order for the C++
    # side's byte-identical re-serialization check
    blob = ({"bias": np.array([0.125], np.float32),
             "weights": np.array([1.5, -2.25, 0.0], np.float32)},
            [("rounds", 10), ("depth", 6)])
    path = tmp_path / "py.bin"
    with create_stream(str(path), "w") as s:
        ser.save(s, blob, spec)
    proc = subprocess.run([demo_bin, "--deserialize", str(path)],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "maps=2 wsum=-0.6250 rounds=10 depth=6" in proc.stdout
    assert "roundtrip ok" in proc.stdout


def test_serializer_interop_cpp_to_python(demo_bin, tmp_path):
    """A blob written by C++ loads in Python with identical content."""
    import numpy as np

    from dmlc_core_tpu import serializer as ser
    from dmlc_core_tpu.io.stream import create_stream_for_read

    path = tmp_path / "cpp.bin"
    proc = subprocess.run([demo_bin, "--serialize", str(path)],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    spec = ser.Pair(ser.Map(ser.Str, ser.Vector(ser.POD(np.float32))),
                    ser.Vector(ser.Pair(ser.Str, ser.POD(np.int64))))
    with create_stream_for_read(str(path)) as s:
        maps, meta = ser.load(s, spec)
    assert set(maps) == {"weights", "bias"}
    assert list(maps["weights"]) == [1.5, -2.25, 0.0]
    assert list(maps["bias"]) == [0.125]
    assert meta == [("rounds", 10), ("depth", 6)]


def test_deserialize_garbage_fails_cleanly(demo_bin, tmp_path):
    """A garbage file must produce 'deserialize failed' + exit 1 — never an
    uncaught length_error/bad_alloc from an untrusted u64 count."""
    bad = tmp_path / "garbage.bin"
    bad.write_bytes(b"\xff" * 64)
    proc = subprocess.run([demo_bin, "--deserialize", str(bad)],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert "deserialize failed" in proc.stderr
    # unknown flags are rejected with usage semantics, not a crash
    proc = subprocess.run([demo_bin, "--serialise", str(bad)],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 2
