"""Sklearn-facade estimators over the hist GBDT."""

import numpy as np
import pytest

from dmlc_core_tpu.models.sklearn import GBDTClassifier, GBDTRegressor


def _binary(n=3000, F=6, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, F).astype(np.float32)
    w = rng.randn(F)
    y = (x @ w > 0).astype(int)
    return x, y


def test_classifier_binary():
    x, y = _binary()
    clf = GBDTClassifier(num_boost_round=10, max_depth=4, num_bins=32,
                         learning_rate=0.5)
    clf.fit(x, y)
    assert clf.score(x, y) > 0.95
    proba = clf.predict_proba(x)
    assert proba.shape == (len(x), 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-6)
    assert set(np.unique(clf.predict(x))) <= {0, 1}


def test_classifier_multiclass_string_labels():
    rng = np.random.RandomState(1)
    n = 2000
    x = rng.randn(n, 4).astype(np.float32)
    labels = np.array(["cat", "dog", "fish"])
    y = labels[(x[:, 0] > 0).astype(int) + (x[:, 1] > 0).astype(int)]
    clf = GBDTClassifier(num_boost_round=8, max_depth=4, num_bins=32,
                         learning_rate=0.5)
    clf.fit(x, y)
    assert list(clf.classes_) == ["cat", "dog", "fish"]
    pred = clf.predict(x)
    assert set(pred) <= set(labels)
    assert (pred == y).mean() > 0.9
    proba = clf.predict_proba(x)
    assert proba.shape == (n, 3)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-6)


def test_regressor_r2():
    rng = np.random.RandomState(2)
    n = 3000
    x = rng.randn(n, 5).astype(np.float32)
    y = x[:, 0] * 2 - x[:, 1] + 0.1 * rng.randn(n)
    reg = GBDTRegressor(num_boost_round=20, max_depth=4, num_bins=64,
                        learning_rate=0.3)
    reg.fit(x, y)
    assert reg.score(x, y) > 0.8


def test_nan_autoselects_missing_mode():
    x, y = _binary(seed=3)
    x[::5, 0] = np.nan
    clf = GBDTClassifier(num_boost_round=5, max_depth=3, num_bins=16)
    clf.fit(x, y)
    assert clf.model_.param.handle_missing is True
    assert np.isfinite(clf.predict_proba(x)).all()
    # explicit override wins
    clf2 = GBDTClassifier(num_boost_round=2, max_depth=2, num_bins=16,
                          handle_missing=False)
    clf2.fit(np.nan_to_num(x), y)
    assert clf2.model_.param.handle_missing is False


def test_eval_set_early_stopping():
    x, y = _binary(n=4000, seed=4)
    clf = GBDTClassifier(num_boost_round=40, max_depth=3, num_bins=32,
                         learning_rate=0.8)
    clf.fit(x[:3000], y[:3000], eval_set=(x[3000:], y[3000:]),
            early_stopping_rounds=5)
    assert clf.eval_history_
    assert "eval_loss" in clf.eval_history_[0]
    assert clf.ensemble_.num_trees <= 40


def test_feature_importances_normalized():
    x, y = _binary()
    clf = GBDTClassifier(num_boost_round=5, max_depth=3, num_bins=32)
    clf.fit(x, y)
    imp = clf.feature_importances_
    assert imp.shape == (x.shape[1],)
    assert abs(imp.sum() - 1.0) < 1e-6
    assert (imp >= 0).all()


def test_get_set_params_roundtrip():
    clf = GBDTClassifier(num_boost_round=7, max_depth=5)
    p = clf.get_params()
    assert p["num_boost_round"] == 7 and p["max_depth"] == 5
    clf.set_params(max_depth=3, handle_missing=True)
    assert clf.get_params()["max_depth"] == 3
    assert clf.get_params()["handle_missing"] is True
    with pytest.raises(Exception):
        clf.set_params(bogus=1)
    with pytest.raises(Exception):
        GBDTClassifier(bogus=1)


def test_unfitted_raises():
    with pytest.raises(Exception, match="not fitted"):
        GBDTClassifier().predict(np.zeros((2, 2), np.float32))


def test_save_model_interops_with_low_level(tmp_path):
    from dmlc_core_tpu.models.gbdt import GBDT, GBDTParam

    x, y = _binary(seed=5)
    clf = GBDTClassifier(num_boost_round=4, max_depth=3, num_bins=16)
    clf.fit(x, y)
    uri = str(tmp_path / "m.bin")
    clf.save_model(uri)
    low = GBDT(GBDTParam(num_boost_round=4, max_depth=3, num_bins=16),
               num_feature=x.shape[1])
    ens = low.load_model(uri)
    margin = np.asarray(low.predict_margin(ens, low.bin_features(x)))
    np.testing.assert_allclose(margin, np.asarray(clf._margin(x)),
                               rtol=1e-5, atol=1e-6)


def test_eval_set_list_form_and_multiclass():
    x, y = _binary(n=2000, seed=6)
    clf = GBDTClassifier(num_boost_round=4, max_depth=3, num_bins=16)
    clf.fit(x[:1500], y[:1500], eval_set=[(x[1500:], y[1500:])])
    assert "eval_loss" in clf.eval_history_[0]
    # multiclass eval_set tracks mlogloss and can early-stop
    rng = np.random.RandomState(7)
    x3 = rng.randn(2000, 3).astype(np.float32)
    y3 = (x3[:, 0] > 0).astype(int) + (x3[:, 1] > 0).astype(int)  # 3 classes
    clf3 = GBDTClassifier(num_boost_round=20, max_depth=3, num_bins=16,
                          learning_rate=0.5)
    clf3.fit(x3[:1500], y3[:1500], eval_set=(x3[1500:], y3[1500:]),
             early_stopping_rounds=5)
    hist = clf3.eval_history_
    assert hist[-1]["eval_loss"] < hist[0]["eval_loss"]
    assert clf3.score(x3[1500:], y3[1500:]) > 0.9


def test_unseen_eval_labels_rejected():
    x, y = _binary(n=1000, seed=8)
    clf = GBDTClassifier(num_boost_round=2, max_depth=2, num_bins=8)
    with pytest.raises(Exception, match="not in"):
        clf.fit(x[:800], y[:800],
                eval_set=(x[800:], np.full(200, 7)))


def test_nan_at_predict_without_missing_support_rejected():
    x, y = _binary(n=1000, seed=9)
    clf = GBDTClassifier(num_boost_round=2, max_depth=2, num_bins=8)
    clf.fit(x, y)                    # dense fit -> missing mode off
    x_bad = x.copy()
    x_bad[0, 0] = np.nan
    with pytest.raises(Exception, match="handle_missing"):
        clf.predict(x_bad)


def test_estimator_save_load_roundtrip(tmp_path):
    rng = np.random.RandomState(10)
    x = rng.randn(1500, 4).astype(np.float32)
    labels = np.array(["a", "b", "c"])
    y = labels[(x[:, 0] > 0).astype(int) + (x[:, 1] > 0).astype(int)]
    clf = GBDTClassifier(num_boost_round=5, max_depth=3, num_bins=16,
                         learning_rate=0.5)
    clf.fit(x, y)
    uri = str(tmp_path / "clf.bin")
    clf.save_model(uri)
    loaded = GBDTClassifier.load_model(uri)
    assert list(loaded.classes_) == ["a", "b", "c"]
    np.testing.assert_array_equal(loaded.predict(x), clf.predict(x))
    np.testing.assert_allclose(loaded.predict_proba(x),
                               clf.predict_proba(x), rtol=1e-6)
    assert loaded.get_params()["max_depth"] == 3

    # regressor roundtrip
    yr = (x[:, 0] * 2).astype(np.float32)
    reg = GBDTRegressor(num_boost_round=5, max_depth=3, num_bins=16)
    reg.fit(x, yr)
    uri2 = str(tmp_path / "reg.bin")
    reg.save_model(uri2)
    loaded_reg = GBDTRegressor.load_model(uri2)
    np.testing.assert_allclose(loaded_reg.predict(x), reg.predict(x),
                               rtol=1e-6)

    # cross-type loads refuse clearly
    with pytest.raises(Exception, match="GBDTClassifier"):
        GBDTRegressor.load_model(uri)
    with pytest.raises(Exception, match="regressor"):
        GBDTClassifier.load_model(uri2)
    # low-level checkpoints are not estimator checkpoints
    from dmlc_core_tpu.models.gbdt import GBDT, GBDTParam

    low = GBDT(GBDTParam(num_boost_round=2, max_depth=2, num_bins=8),
               num_feature=4)
    low.make_bins(x)
    ens, _ = low.fit_binned(low.bin_features(x), (yr > 0).astype(np.float32))
    uri3 = str(tmp_path / "low.bin")
    low.save_model(uri3, ens)
    with pytest.raises(Exception, match="sk_param"):
        GBDTClassifier.load_model(uri3)


def test_nan_missing_mode_survives_save_load(tmp_path):
    x, y = _binary(n=1200, seed=11)
    x[::4, 1] = np.nan
    clf = GBDTClassifier(num_boost_round=4, max_depth=3, num_bins=16)
    clf.fit(x, y)
    assert clf.model_.param.handle_missing
    uri = str(tmp_path / "m.bin")
    clf.save_model(uri)
    loaded = GBDTClassifier.load_model(uri)
    assert loaded.model_.param.handle_missing
    np.testing.assert_array_equal(loaded.predict(x), clf.predict(x))


def test_object_dtype_classes_rejected_at_save(tmp_path):
    x, y = _binary(n=400, seed=12)
    y_obj = np.array(["n", "p"], dtype=object)[y]     # pandas-style labels
    clf = GBDTClassifier(num_boost_round=2, max_depth=2, num_bins=8)
    clf.fit(x, y_obj)
    with pytest.raises(Exception, match="object dtype"):
        clf.save_model(str(tmp_path / "bad.bin"))


def test_multiple_eval_sets():
    x, y = _binary(n=3000, seed=14)
    clf = GBDTClassifier(num_boost_round=6, max_depth=3, num_bins=16,
                         learning_rate=0.5)
    clf.fit(x[:2000], y[:2000],
            eval_set=[(x[2000:2500], y[2000:2500]),
                      (x[2500:], y[2500:])],
            early_stopping_rounds=3)
    hist = clf.eval_history_
    assert "eval_loss" in hist[0]        # the LAST set (drives stopping)
    assert "eval0_loss" in hist[0]       # the first set's curve
    kept = clf.ensemble_.num_trees       # entries past truncation carry
    last = hist[kept - 1]                # only the primary eval_loss
    assert last["eval0_loss"] < hist[0]["eval0_loss"]
    # list-of-rows X in a bare pair must not be misread as a pair list
    clf2 = GBDTClassifier(num_boost_round=3, max_depth=2, num_bins=8)
    clf2.fit(x[:500], y[:500],
             eval_set=(x[500:700].tolist(), y[500:700].tolist()))
    assert "eval_loss" in clf2.eval_history_[0]


def test_multi_eval_sets_share_metric():
    x, y = _binary(n=2400, seed=15)
    clf = GBDTClassifier(num_boost_round=5, max_depth=3, num_bins=16,
                         learning_rate=0.5)
    clf.fit(x[:1600], y[:1600],
            eval_set=[(x[1600:2000], y[1600:2000]), (x[2000:], y[2000:])],
            eval_metric="error")
    h = clf.eval_history_[-1]
    # both curves are ERROR RATES (comparable), not logloss vs error
    assert 0.0 <= h["eval_loss"] <= 1.0
    assert 0.0 <= h["eval0_loss"] <= 1.0
