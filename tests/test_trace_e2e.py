"""End-to-end distributed-tracing tests (ISSUE 8 acceptance):

- one trace_id survives client -> HTTP server (separate OS process) ->
  micro-batcher -> predict AND parent -> parse_proc pool workers (two more
  OS processes), assembling into a single trace with no orphan spans;
- the loadgen SLO report names its worst offenders by trace id;
- (chaos) an injected fault fire lands as an instant event ON the
  enclosing span, and a chaos-killed parse worker leaves a flight-recorder
  dump that the trace assembler reports as a crashed process.
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from dmlc_core_tpu import fault, telemetry
from dmlc_core_tpu.data import parse_proc
from dmlc_core_tpu.telemetry import flight, tracecontext as tc, traceview

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_LIBSVM_SPEC = ("dmlc_core_tpu.data.libsvm_parser", "LibSVMParser",
                {"nthread": 1, "index_dtype": "<u4"})


@pytest.fixture(autouse=True)
def _clean_tracing():
    was_enabled = telemetry.enabled()
    prior_root = tc.get_process_root()
    telemetry.disable()
    telemetry.reset()
    flight.reset()
    tc.set_process_root(None)
    yield
    fault.clear()
    telemetry.disable()
    telemetry.reset()
    flight.reset()
    tc.set_process_root(prior_root)
    if was_enabled:
        telemetry.enable()


def _spawn_server(telemetry_dir, num_feature):
    env = dict(os.environ,
               DMLC_TELEMETRY_DIR=str(telemetry_dir),
               JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "dmlc_core_tpu.serve", "--model", "linear",
         "--num-feature", str(num_feature), "--port", "0", "--no-warmup"],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    url = None
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        if "serving linear on http://" in line:
            url = line.split("on ", 1)[1].split()[0]
            break
    return proc, url


def _stop_server(proc):
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
    if proc.stdout is not None:
        proc.stdout.close()


def _assemble_until(telemetry_dir, predicate, timeout_s=30.0):
    """Assemble repeatedly until ``predicate(asm)`` holds (pool workers and
    the server flush their span files asynchronously at process exit)."""
    deadline = time.monotonic() + timeout_s
    asm = traceview.assemble(str(telemetry_dir))
    while not predicate(asm) and time.monotonic() < deadline:
        time.sleep(0.5)
        asm = traceview.assemble(str(telemetry_dir))
    return asm


def test_trace_propagation_three_processes(tmp_path, monkeypatch):
    """The acceptance walk: one trace spanning the test process (client +
    parse consumer), the scoring server process, and parse pool worker
    processes — >=3 OS pids in one assembled trace, zero orphans."""
    tel = tmp_path / "tel"
    tel.mkdir()
    # pool workers inherit this env and flush their own span files into it
    monkeypatch.setenv("DMLC_TELEMETRY_DIR", str(tel))
    parse_proc.shutdown()          # fresh pool under the new env
    num_feature = 4
    server, url = _spawn_server(tel, num_feature)
    try:
        assert url, "server did not come up"
        telemetry.enable()
        with tc.activate(tc.new_root()):
            with telemetry.span("e2e.root") as root:
                trace_id = root.trace_id
                # leg 1: HTTP with the ambient context as traceparent
                body = json.dumps(
                    {"instances": [[0.1, 0.2, 0.3, 0.4]]}).encode()
                req = urllib.request.Request(
                    url + "/v1/score", data=body, method="POST",
                    headers={"Content-Type": "application/json",
                             "traceparent": tc.current_traceparent()})
                with urllib.request.urlopen(req, timeout=60) as resp:
                    payload = json.load(resp)
                assert len(payload["predictions"]) == 1
                # leg 2: parse fan-out to pool worker processes
                pool = parse_proc.ProcParsePool(_LIBSVM_SPEC, 2)
                blocks = pool.parse_ranges([b"1 0:1.5\n0 2:2.0\n" * 200,
                                            b"1 1:0.5\n" * 150])
                assert sum(b.size for b in blocks) == 550
                pool.close()
    finally:
        _stop_server(server)
    parse_proc.shutdown()          # workers exit -> atexit flush
    telemetry.flush(str(tel))

    def ready(asm):
        ours = [t for t in asm["traces"] if t["trace_id"] == trace_id]
        return ours and len(ours[0]["pids"]) >= 3 and ours[0]["orphans"] == 0

    asm = _assemble_until(tel, ready)
    ours = [t for t in asm["traces"] if t["trace_id"] == trace_id]
    assert len(ours) == 1, "the request must resolve to exactly one trace"
    trace = ours[0]
    assert len(trace["pids"]) >= 3, \
        f"expected >=3 processes in the trace, got pids={trace['pids']}"
    assert trace["orphans"] == 0, trace
    stages = {p["stage"] for p in trace["critical_path"]}
    # client -> HTTP -> batcher -> predict, and parent -> parse worker
    assert {"e2e.root", "serve.request", "serve.predict",
            "serve.queue.wait", "parse_worker.parse_block"} <= stages, stages
    assert trace["total_ms"] > 0
    # the critical path is computed and normalized
    assert sum(p["share"] for p in trace["critical_path"]) \
        == pytest.approx(1.0, abs=0.01)


def test_loadgen_report_names_slowest_traces(tmp_path):
    """Satellite: every loadgen sample records its trace_id and the report
    prints the top-5 slowest — joinable against the assembled trace."""
    from dmlc_core_tpu.serve.loadgen import run_load
    from dmlc_core_tpu.serve.model_runtime import build_runtime
    from dmlc_core_tpu.serve.server import ScoringServer

    telemetry.enable()
    runtime = build_runtime("linear", 6)
    server = ScoringServer(runtime, max_batch=8, max_delay_ms=1.0).start()
    try:
        report = run_load(server.url, qps=40, duration_s=1.0, num_feature=6,
                          rows_per_request=1, seed=5, timeout_s=10.0)
    finally:
        server.close()
    assert report["counts"]["ok"] > 0
    slowest = report["slowest_traces"]
    assert 0 < len(slowest) <= 5
    assert slowest == sorted(slowest, key=lambda s: -s["latency_ms"])
    for entry in slowest:
        assert len(entry["trace_id"]) == 32
        assert entry["outcome"] in ("ok", "shed", "timeout", "rejected",
                                    "error", "crashed")
    # the named ids are real: each resolves in the recorded spans
    telemetry.flush(str(tmp_path))
    asm = traceview.assemble(str(tmp_path))
    assembled = {t["trace_id"] for t in asm["traces"]}
    assert {s["trace_id"] for s in slowest} <= assembled


# -- chaos --------------------------------------------------------------------

@pytest.mark.chaos
def test_fault_fire_is_event_on_enclosing_span():
    telemetry.enable()
    fault.configure({"rules": [{"site": "tracker.accept", "kind": "delay",
                                "seconds": 0.0}]})
    try:
        with tc.activate(tc.new_root()):
            with telemetry.span("guarded.op"):
                fault.inject("tracker.accept", host="t")
    finally:
        fault.clear()
    events = telemetry.get_tracer().events()
    fire = [e for e in events if e["name"] == "fault.injected"][0]
    span = [e for e in events if e["name"] == "guarded.op"][0]
    assert fire["ph"] == "i"
    assert fire["trace_id"] == span["trace_id"]
    assert fire["parent_id"] == span["span_id"]
    assert fire["args"] == {"site": "tracker.accept", "kind": "delay"}
    # the ring saw it too: this is what a post-mortem dump would carry
    assert any(e.get("name") == "fault.injected" for e in flight.snapshot())


_KILL_PLAN = ('{"rules": [{"site": "data.parse_worker", "kind": "exit", '
              '"times": null}]}')


@pytest.mark.chaos
def test_killed_worker_leaves_flight_dump(tmp_path, monkeypatch):
    """A chaos-killed worker (fault 'exit' -> os._exit) writes its flight
    dump on the way down, and the assembler reports the process as
    crashed — the killed side of the story is evidence, not silence."""
    tel = tmp_path / "tel"
    tel.mkdir()
    monkeypatch.setenv("DMLC_TELEMETRY_DIR", str(tel))
    monkeypatch.setenv("DMLC_FAULT_PLAN", _KILL_PLAN)
    parse_proc.shutdown()          # workers read env at start
    pool = parse_proc.ProcParsePool(_LIBSVM_SPEC, 2)
    with pytest.raises(RuntimeError, match="parse worker died"):
        pool.parse_ranges([b"1 0:1.0\n" * 500, b"0 1:2.0\n" * 500])
    parse_proc.shutdown()
    deadline = time.monotonic() + 20
    dumps = []
    while not dumps and time.monotonic() < deadline:
        dumps = [p for p in os.listdir(tel) if p.startswith("flight-")]
        time.sleep(0.2)
    assert dumps, "killed worker left no flight dump"
    with open(tel / dumps[0]) as f:
        payload = json.load(f)
    assert payload["reason"] == "fault_exit:data.parse_worker"
    assert any(e.get("name") == "fault.injected"
               for e in payload["entries"])
    # and the merged view names the crash instead of omitting the process
    asm = traceview.assemble(str(tel))
    assert any(c["reason"] == "fault_exit:data.parse_worker"
               for c in asm["flights"])
