"""S3 concurrency soak: the reference's parallel-cat-and-md5 protocol.

The reference validated its S3 stack by running 10 parallel jobs of repeated
``filesys_test cat s3://...`` with per-rep md5 comparison against real
buckets (test/README.md:1-30).  This is that soak against the in-process
mock server, strictly harder: the server tears down every Nth GET mid-body,
so the client's connection-reestablishing retry path
(s3_filesys._S3Client.request) is exercised under concurrency — which the
reference could only ever hit by accident on a flaky network.
"""

import hashlib
import threading

import numpy as np
import pytest

from tests.mock_s3 import MockS3

from dmlc_core_tpu.io import s3_filesys  # noqa: F401 (registration)
from dmlc_core_tpu.io.stream import create_stream, create_stream_for_read

N_JOBS = 8
N_REPS = 4
# parts are clamped to >=5 MiB (DMLC_S3_WRITE_BUFFER_MB floor), so 6 MiB
# genuinely takes the multipart path: one 5 MiB part + a 1 MiB tail part
OBJ_MB = 6


@pytest.fixture()
def flaky_s3(monkeypatch):
    server = MockS3(fail_every=7).start()
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "test-key")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "test-secret")
    monkeypatch.setenv("AWS_REGION", "us-east-1")
    monkeypatch.setenv("S3_ENDPOINT", f"http://127.0.0.1:{server.port}")
    # small read buffer => many ranged GETs => many injected drops
    monkeypatch.setenv("DMLC_S3_WRITE_BUFFER_MB", "1")
    # the every-Nth drop counter is shared across jobs, so one request's
    # retries can keep landing on drop slots (p ~ (1/7)^k); a generous
    # budget makes spurious exhaustion ~impossible without weakening the
    # retry exercise (the dedicated exhaustion test pins its own budget)
    monkeypatch.setenv("S3_MAX_ERROR_RETRY", "6")
    yield server
    server.stop()


def _cat_md5(uri, buffer_bytes):
    md5 = hashlib.md5()
    fo = create_stream_for_read(uri)
    fo._buffer_bytes = buffer_bytes    # force many ranged GETs
    while True:
        block = fo.read(64 * 1024)
        if not block:
            break
        md5.update(block)
    return md5.hexdigest()


def test_parallel_repeated_cat_with_connection_drops(flaky_s3):
    rng = np.random.RandomState(0)
    payload = rng.bytes(OBJ_MB << 20)
    expected = hashlib.md5(payload).hexdigest()
    with create_stream("s3://dmlc/soak/val.rec", "w") as s:
        for off in range(0, len(payload), 256 * 1024):
            s.write(payload[off:off + 256 * 1024])
    # the write really went multipart (an upload id was created+consumed)
    assert flaky_s3.next_upload[0] == 1
    assert flaky_s3.objects[("dmlc", "soak/val.rec")] == payload

    results = [[] for _ in range(N_JOBS)]
    errors = []

    def job(i):
        try:
            for rep in range(N_REPS):
                # alternate buffer sizes: whole-file-ish vs many-range reads
                buf = (256 << 10) if (i + rep) % 2 else (4 << 20)
                results[i].append(_cat_md5("s3://dmlc/soak/val.rec", buf))
        except Exception as exc:   # noqa: BLE001 - collected for the assert
            errors.append((i, repr(exc)))

    threads = [threading.Thread(target=job, args=(i,)) for i in range(N_JOBS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    assert not errors, f"soak jobs failed: {errors}"
    for i, job_md5s in enumerate(results):
        assert job_md5s == [expected] * N_REPS, f"md5 mismatch in job {i}"
    # the point of the soak: drops actually happened and were survived
    assert flaky_s3.injected_failures >= N_JOBS, (
        f"only {flaky_s3.injected_failures} failures injected; "
        "soak did not exercise the retry path")


def test_ranged_read_survives_drop_exactly_at_boundary(flaky_s3):
    """Deterministic single-threaded variant: every GET for this object is
    dropped once (fail_every=1 would starve retries, so use 2: each retry
    succeeds)."""
    flaky_s3.fail_every = 2
    payload = bytes(range(256)) * 4096   # 1 MiB
    flaky_s3.objects[("dmlc", "b.bin")] = payload
    fo = create_stream_for_read("s3://dmlc/b.bin")
    fo._buffer_bytes = 64 * 1024
    got = b""
    while True:
        block = fo.read(50_000)
        if not block:
            break
        got += block
    assert got == payload
    assert flaky_s3.injected_failures > 0


def test_retry_exhaustion_raises(flaky_s3, monkeypatch):
    """When every attempt is dropped, the client fails loudly, not silently."""
    flaky_s3.fail_every = 1            # sabotage every GET
    monkeypatch.setenv("S3_MAX_ERROR_RETRY", "2")
    flaky_s3.objects[("dmlc", "dead.bin")] = b"x" * 100_000
    fo = create_stream_for_read("s3://dmlc/dead.bin")
    with pytest.raises(Exception):
        fo.read(100_000)


def test_complete_multipart_retry_after_commit_is_success(flaky_s3):
    """The retry-after-server-side-commit hazard: the complete POST commits
    but the response is lost; the retried complete gets 404 NoSuchUpload and
    must verify the object (size-exact) instead of failing the write."""
    flaky_s3.fail_every = 0                  # only the complete is sabotaged
    flaky_s3.fail_complete_once = True
    payload = np.random.RandomState(1).bytes(6 << 20)
    with create_stream("s3://dmlc/ck/model.bin", "w") as s:
        s.write(payload)
    assert flaky_s3.objects[("dmlc", "ck/model.bin")] == payload
    assert flaky_s3.next_upload[0] == 1      # multipart path taken


def test_complete_multipart_lost_upload_fails_loudly(flaky_s3, monkeypatch):
    """404 on complete with no (or wrong-size) object at the key is a real
    loss and must raise, even when a stale object sits under the key."""
    from dmlc_core_tpu.io import filesys as fsys

    flaky_s3.fail_every = 0
    # stale object of a DIFFERENT size pre-exists under the key
    flaky_s3.objects[("dmlc", "ck/stale.bin")] = b"old" * 100
    fs = fsys.get_filesystem(fsys.URI("s3://dmlc/ck/stale.bin"))
    stream = fs.open(fsys.URI("s3://dmlc/ck/stale.bin"), "w")
    # exactly one full part: write() uploads it inline, so close() goes
    # straight to the complete POST
    stream.write(np.random.RandomState(2).bytes(5 << 20))
    # sabotage: the upload vanishes server-side before complete (abort /
    # lifecycle expiry), so complete 404s and the key holds stale bytes
    flaky_s3.uploads.clear()
    with pytest.raises(Exception, match="lost"):
        stream.close()


def test_complete_multipart_same_size_stale_object_still_fails(flaky_s3):
    """Fixed-shape checkpoints overwrite the same key with the same byte
    count every round: a lost upload must not pass verification just because
    a same-size previous-round object sits at the key (ETag distinguishes)."""
    from dmlc_core_tpu.io import filesys as fsys

    flaky_s3.fail_every = 0
    size = 5 << 20
    stale = np.random.RandomState(3).bytes(size)
    flaky_s3.objects[("dmlc", "ck/fixed.bin")] = stale
    fs = fsys.get_filesystem(fsys.URI("s3://dmlc/ck/fixed.bin"))
    stream = fs.open(fsys.URI("s3://dmlc/ck/fixed.bin"), "w")
    stream.write(np.random.RandomState(4).bytes(size))   # same size
    flaky_s3.uploads.clear()                              # upload lost
    with pytest.raises(Exception, match="lost"):
        stream.close()
    # the stale object was not clobbered or blessed
    assert flaky_s3.objects[("dmlc", "ck/fixed.bin")] == stale
