"""Mesos backend test with the task runner stubbed out.

Reference behavior under test (tracker/dmlc_tracker/mesos.py): one task per
worker/server with cpus/mem resources, DMLC_ROLE + per-role id env, env
whitelist forwarding, mesos-execute command construction, MESOS_MASTER
requirement with default port 5050.
"""

import json

import pytest

from dmlc_core_tpu.tracker import mesos
from dmlc_core_tpu.tracker.opts import get_opts


def test_mesos_requires_master(monkeypatch):
    monkeypatch.delenv("MESOS_MASTER", raising=False)
    opts = get_opts(["--cluster", "mesos", "--num-workers", "1", "--",
                     "true"])
    with pytest.raises(RuntimeError, match="MESOS_MASTER"):
        mesos.submit(opts)


def test_mesos_master_default_port(monkeypatch):
    monkeypatch.setenv("MESOS_MASTER", "m1")
    opts = get_opts(["--cluster", "mesos", "--num-workers", "1", "--",
                     "true"])
    assert mesos._resolve_master(opts) == "m1:5050"


def test_mesos_explicit_env_wins_over_forwarded(monkeypatch):
    launched = []

    def fake_run(master, prog, env, resources):
        launched.append(env)

    monkeypatch.setattr(mesos, "_run_task", fake_run)
    monkeypatch.setenv("LD_LIBRARY_PATH", "/shell/lib")
    opts = get_opts(["--cluster", "mesos", "--num-workers", "1",
                     "--mesos-master", "m", "--env",
                     "LD_LIBRARY_PATH=/custom/lib", "--", "true"])
    mesos.submit(opts)
    assert launched[0]["LD_LIBRARY_PATH"] == "/custom/lib"


def test_mesos_submit_tasks(monkeypatch):
    launched = []

    def fake_run(master, prog, env, resources):
        launched.append((master, prog, env, resources))

    monkeypatch.setattr(mesos, "_run_task", fake_run)
    monkeypatch.setenv("OMP_NUM_THREADS", "3")

    opts = get_opts(["--cluster", "mesos", "--num-workers", "2",
                     "--num-servers", "1", "--mesos-master", "master-host",
                     "--worker-cores", "2", "--worker-memory", "2g",
                     "--server-cores", "1", "--server-memory", "512m",
                     "--", "python", "train.py"])
    mesos.submit(opts)  # fun_submit joins its task threads before returning
    assert len(launched) == 3

    roles = sorted(env["DMLC_ROLE"] for _, _, env, _ in launched)
    assert roles == ["server", "worker", "worker"]
    # task ids are role-relative: they are the collective's process ids
    by_role = sorted((env["DMLC_ROLE"], env["DMLC_TASK_ID"])
                     for _, _, env, _ in launched)
    assert by_role == [("server", "0"), ("worker", "0"), ("worker", "1")]
    for master, prog, env, resources in launched:
        assert master == "master-host:5050"
        assert prog == "python train.py"
        assert env["OMP_NUM_THREADS"] == "3"
        assert "DMLC_TRACKER_URI" in env
        if env["DMLC_ROLE"] == "server":
            assert env["DMLC_SERVER_ID"] == "0"
            assert resources == {"cpus": 1.0, "mem": 512.0}
        else:
            assert env["DMLC_WORKER_ID"] in ("0", "1")
            assert resources == {"cpus": 2.0, "mem": 2048.0}


def test_mesos_execute_argv():
    argv = mesos._mesos_execute_argv(
        "m1:5050", "python train.py", {"A": "1"}, {"cpus": 2.0, "mem": 64.0})
    assert argv[0] == "mesos-execute"
    assert argv[1] == "--master=m1:5050"
    assert argv[3].startswith("--command=cd ")
    assert argv[3].endswith("&& python train.py")
    assert json.loads(argv[4][len("--env="):]) == {"A": "1"}
    assert argv[5] == "--resources=cpus:2.0;mem:64.0"
