"""In-process mock S3 server for filesystem tests (zero-egress substitute for
the reference's real-bucket soak, test/README.md:1-30).

Implements the subset our client uses: PUT/GET(Range)/HEAD objects,
ListObjectsV2 with prefix+delimiter, and the multipart-upload flow
(initiate / upload part / complete).  Verifies that every request carries a
SigV4 Authorization header.
"""

from __future__ import annotations

import hashlib
import socket
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer



def drop_mid_body(handler, status, body):
    """Advertise the full Content-Length, send half the bytes, then force a
    FIN: the client observes IncompleteRead/reset mid-transfer.  shutdown(),
    not close() — the rfile/wfile makefile wrappers hold socket refs, so
    close() alone never sends the FIN.  Shared by the S3 and Azure mocks so
    the subtlety lives in one place."""
    handler.send_response(status)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body[:max(1, len(body) // 2)])
    handler.wfile.flush()
    handler.close_connection = True
    handler.connection.shutdown(socket.SHUT_RDWR)


class MockS3:
    def __init__(self, fail_every: int = 0):
        self.objects = {}      # (bucket, key) -> bytes
        self.etags = {}        # (bucket, key) -> etag (no quotes)
        self.meta = {}         # (bucket, key) -> {meta header: value}
        self.uploads = {}      # upload_id -> {"key":..., "parts": {n: bytes}}
        self.next_upload = [0]
        self.lock = threading.Lock()
        self.requests = []     # (method, path) log
        # failure injection for the concurrency soak (reference
        # test/README.md protocol): every Nth GET is sabotaged — half the
        # body, then the connection is torn down mid-transfer (0 = off)
        self.fail_every = fail_every
        self.injected_failures = 0
        self._get_count = 0
        # when set: the next CompleteMultipartUpload COMMITS server-side but
        # the response is dropped — the client's retried complete then sees
        # 404 NoSuchUpload (the real-S3 retry-after-commit hazard)
        self.fail_complete_once = False

    def start(self):
        store = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _parse(self):
                parsed = urllib.parse.urlparse(self.path)
                parts = parsed.path.lstrip("/").split("/", 1)
                bucket = parts[0]
                key = parts[1] if len(parts) > 1 else ""
                query = dict(urllib.parse.parse_qsl(parsed.query,
                                                    keep_blank_values=True))
                return bucket, key, query

            def _reply(self, status, body=b"", headers=None):
                headers = dict(headers or {})
                self.send_response(status)
                if "Content-Length" not in headers:
                    headers["Content-Length"] = str(len(body))
                for k, v in headers.items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _check_auth(self):
                auth = self.headers.get("Authorization", "")
                if not auth.startswith("AWS4-HMAC-SHA256"):
                    self._reply(403, b"<Error>missing sigv4</Error>")
                    return False
                return True

            def do_HEAD(self):
                if not self._check_auth():
                    return
                bucket, key, _ = self._parse()
                store.requests.append(("HEAD", self.path))
                data = store.objects.get((bucket, key))
                if data is None:
                    self._reply(404)
                else:
                    etag = store.etags.get(
                        (bucket, key), hashlib.md5(data).hexdigest())
                    headers = {"Content-Length": str(len(data)),
                               "ETag": f'"{etag}"'}
                    headers.update(store.meta.get((bucket, key), {}))
                    self._reply(200, b"", headers)
                    return

            def _should_fail(self):
                if not store.fail_every:
                    return False
                with store.lock:
                    store._get_count += 1
                    if store._get_count % store.fail_every == 0:
                        store.injected_failures += 1
                        return True
                return False

            def _drop_mid_body(self, status, body):
                drop_mid_body(self, status, body)

            def do_GET(self):
                if not self._check_auth():
                    return
                bucket, key, query = self._parse()
                store.requests.append(("GET", self.path))
                if "list-type" in query:
                    return self._list(bucket, query)
                data = store.objects.get((bucket, key))
                if data is None:
                    return self._reply(404, b"<Error>NoSuchKey</Error>")
                rng = self.headers.get("Range")
                if rng:
                    spec = rng.split("=")[1]
                    start_s, end_s = spec.split("-")
                    start = int(start_s)
                    end = min(int(end_s), len(data) - 1) if end_s else len(data) - 1
                    piece = data[start:end + 1]
                    if self._should_fail():
                        return self._drop_mid_body(206, piece)
                    return self._reply(206, piece)
                if self._should_fail():
                    return self._drop_mid_body(200, data)
                self._reply(200, data)

            def _list(self, bucket, query):
                prefix = query.get("prefix", "")
                delim = query.get("delimiter", "")
                contents, prefixes = [], set()
                for (b, k), v in sorted(store.objects.items()):
                    if b != bucket or not k.startswith(prefix):
                        continue
                    rest = k[len(prefix):]
                    if delim and delim in rest:
                        prefixes.add(prefix + rest.split(delim)[0] + delim)
                    else:
                        contents.append(
                            f"<Contents><Key>{k}</Key>"
                            f"<Size>{len(v)}</Size></Contents>")
                cps = "".join(f"<CommonPrefixes><Prefix>{p}</Prefix>"
                              f"</CommonPrefixes>" for p in sorted(prefixes))
                body = (f"<ListBucketResult>{''.join(contents)}{cps}"
                        f"</ListBucketResult>").encode()
                self._reply(200, body)

            def do_PUT(self):
                if not self._check_auth():
                    return
                bucket, key, query = self._parse()
                store.requests.append(("PUT", self.path))
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                if "uploadId" in query:
                    uid = query["uploadId"]
                    part = int(query["partNumber"])
                    with store.lock:
                        up = store.uploads.get(uid)
                        if up is None:
                            return self._reply(
                                404, b"<Error><Code>NoSuchUpload</Code>"
                                     b"</Error>")
                        up["parts"][part] = body
                    # real S3 part ETags are the part body's md5 — the
                    # client derives the multipart object ETag from them
                    return self._reply(
                        200, b"",
                        {"ETag": f'"{hashlib.md5(body).hexdigest()}"'})
                store.objects[(bucket, key)] = body
                store.etags[(bucket, key)] = hashlib.md5(body).hexdigest()
                self._reply(200, b"", {"ETag": '"etag"'})

            def do_POST(self):
                if not self._check_auth():
                    return
                bucket, key, query = self._parse()
                store.requests.append(("POST", self.path))
                length = int(self.headers.get("Content-Length", 0))
                self.rfile.read(length)
                if "uploads" in query:
                    meta = {k.lower(): v for k, v in self.headers.items()
                            if k.lower().startswith("x-amz-meta-")}
                    with store.lock:
                        store.next_upload[0] += 1
                        uid = f"upload-{store.next_upload[0]}"
                        store.uploads[uid] = {"key": (bucket, key),
                                              "parts": {}, "meta": meta}
                    body = (f"<InitiateMultipartUploadResult>"
                            f"<UploadId>{uid}</UploadId>"
                            f"</InitiateMultipartUploadResult>").encode()
                    return self._reply(200, body)
                if "uploadId" in query:
                    uid = query["uploadId"]
                    with store.lock:
                        up = store.uploads.pop(uid, None)
                        if up is None:
                            # completed/aborted upload ids no longer exist
                            return self._reply(
                                404, b"<Error><Code>NoSuchUpload</Code>"
                                     b"</Error>")
                        parts = [v for _, v in sorted(up["parts"].items())]
                        data = b"".join(parts)
                        store.objects[up["key"]] = data
                        store.etags[up["key"]] = (
                            hashlib.md5(b"".join(
                                hashlib.md5(p).digest() for p in parts)
                            ).hexdigest() + f"-{len(parts)}")
                        store.meta[up["key"]] = up.get("meta", {})
                        drop = store.fail_complete_once
                        store.fail_complete_once = False
                    if drop:
                        # committed, but the client never hears back
                        self.close_connection = True
                        self.connection.shutdown(socket.SHUT_RDWR)
                        return
                    return self._reply(
                        200, b"<CompleteMultipartUploadResult/>")
                self._reply(400, b"<Error>bad post</Error>")

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)
        self.thread.start()
        return self

    def stop(self):
        self.server.shutdown()
        self.server.server_close()
