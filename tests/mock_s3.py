"""In-process mock S3 server for filesystem tests (zero-egress substitute for
the reference's real-bucket soak, test/README.md:1-30).

Implements the subset our client uses: PUT/GET(Range)/HEAD objects,
ListObjectsV2 with prefix+delimiter+pagination, and the multipart-upload
flow (initiate / upload part / complete).

STRICT by default (round 4; no real endpoint is reachable in this image, so
the mock carries the conformance duties a minio smoke would have): every
request's SigV4 signature is recomputed server-side from the wire form —
canonical URI taken raw, canonical query rebuilt from decoded pairs, the
derived signing key, the whole dance — and the x-amz-content-sha256 payload
hash is checked against the received body.  A client that encodes URLs or
canonicalizes differently from what it signs fails here exactly as it would
against AWS (403 SignatureDoesNotMatch), which is the real-endpoint
breakage class (auth / URL-encoding / pagination) this server exists to
catch.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import re
import socket
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_AUTH_RE = re.compile(
    r"AWS4-HMAC-SHA256 Credential=([^,]+),\s*"
    r"SignedHeaders=([^,]+),\s*Signature=([0-9a-f]{64})")


def _aws_quote(s: str) -> str:
    return urllib.parse.quote(s, safe="-_.~")


def verify_sigv4(handler, body: bytes, secrets=None):
    """Recompute the request's SigV4 signature the way a real endpoint does
    and return None when it matches, else a short failure reason.
    ``secrets``: registered keys; defaults to the env credentials."""
    auth = handler.headers.get("Authorization", "")
    m = _AUTH_RE.match(auth)
    if not m:
        return "missing or malformed sigv4 Authorization"
    credential, signed_headers, got_sig = m.groups()
    cred_parts = credential.split("/")
    if len(cred_parts) != 5 or cred_parts[4] != "aws4_request":
        return "malformed credential scope"
    _access, datestamp, region, service, _term = cred_parts
    amzdate = handler.headers.get("x-amz-date", "")
    if not amzdate.startswith(datestamp):
        return "x-amz-date does not match credential date"
    payload_hash = handler.headers.get("x-amz-content-sha256", "")
    if not payload_hash:
        return "missing x-amz-content-sha256"
    if (payload_hash != "UNSIGNED-PAYLOAD"
            and hashlib.sha256(body).hexdigest() != payload_hash):
        return "payload hash mismatch"
    parsed = urllib.parse.urlparse(handler.path)
    # canonical URI: S3 servers use the raw received path (no normalization)
    canon_uri = parsed.path or "/"
    # canonical query: decode each raw pair WITHOUT plus-to-space (real S3
    # signs '+' as a literal plus; a client that sends '+' for a space it
    # signed as %20 must fail here, not be normalized clean), then
    # re-encode with AWS rules and sort
    pairs = []
    if parsed.query:
        for item in parsed.query.split("&"):
            k, _, v = item.partition("=")
            pairs.append((urllib.parse.unquote(k), urllib.parse.unquote(v)))
    canon_query = "&".join(
        f"{_aws_quote(k)}={_aws_quote(v)}" for k, v in sorted(pairs))
    names = signed_headers.split(";")
    if sorted(names) != names:
        return "SignedHeaders not sorted"
    canon_headers = "".join(
        f"{h}:{' '.join((handler.headers.get(h) or '').split())}\n"
        for h in names)
    canonical_request = "\n".join([
        handler.command, canon_uri, canon_query, canon_headers,
        signed_headers, payload_hash])
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amzdate, scope,
        hashlib.sha256(canonical_request.encode()).hexdigest()])

    def _hmac(key, msg):
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    # the server knows every registered credential (AWS- and GCS-interop
    # HMAC keys); the request must verify under one of them
    if secrets is None:
        secrets = [os.environ.get(name) for name in
                   ("AWS_SECRET_ACCESS_KEY", "GCS_SECRET_ACCESS_KEY")]
    for secret in filter(None, secrets):
        k = _hmac(("AWS4" + secret).encode(), datestamp)
        k = _hmac(k, region)
        k = _hmac(k, service)
        k = _hmac(k, "aws4_request")
        want = hmac.new(k, string_to_sign.encode(),
                        hashlib.sha256).hexdigest()
        if hmac.compare_digest(want, got_sig):
            return None
    return "SignatureDoesNotMatch"


def _xml_escape(s: str) -> str:
    return (s.replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;"))



def drop_mid_body(handler, status, body):
    """Advertise the full Content-Length, send half the bytes, then force a
    FIN: the client observes IncompleteRead/reset mid-transfer.  shutdown(),
    not close() — the rfile/wfile makefile wrappers hold socket refs, so
    close() alone never sends the FIN.  Shared by the S3 and Azure mocks so
    the subtlety lives in one place."""
    handler.send_response(status)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body[:max(1, len(body) // 2)])
    handler.wfile.flush()
    handler.close_connection = True
    handler.connection.shutdown(socket.SHUT_RDWR)


class MockS3:
    def __init__(self, fail_every: int = 0, strict: bool = True,
                 page_size: int = 0, secrets=None):
        # strict: full server-side SigV4 + payload-hash verification
        # page_size: >0 forces ListObjectsV2 pagination at that many keys
        # (clients must follow NextContinuationToken)
        # secrets: pin the registered keys (default: read env per request)
        self.strict = strict
        self.page_size = page_size
        self.secrets = secrets
        self.objects = {}      # (bucket, key) -> bytes
        self.etags = {}        # (bucket, key) -> etag (no quotes)
        self.meta = {}         # (bucket, key) -> {meta header: value}
        self.uploads = {}      # upload_id -> {"key":..., "parts": {n: bytes}}
        self.next_upload = [0]
        self.lock = threading.Lock()
        self.requests = []     # (method, path) log
        # failure injection for the concurrency soak (reference
        # test/README.md protocol): every Nth GET is sabotaged — half the
        # body, then the connection is torn down mid-transfer (0 = off)
        self.fail_every = fail_every
        self.injected_failures = 0
        self._get_count = 0
        # when set: the next CompleteMultipartUpload COMMITS server-side but
        # the response is dropped — the client's retried complete then sees
        # 404 NoSuchUpload (the real-S3 retry-after-commit hazard)
        self.fail_complete_once = False

    def start(self):
        store = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _parse(self):
                parsed = urllib.parse.urlparse(self.path)
                # split on the (encoded) separator FIRST, then decode each
                # part — %2F inside a key must not become a separator
                parts = parsed.path.lstrip("/").split("/", 1)
                bucket = urllib.parse.unquote(parts[0])
                key = (urllib.parse.unquote(parts[1])
                       if len(parts) > 1 else "")
                query = dict(urllib.parse.parse_qsl(parsed.query,
                                                    keep_blank_values=True))
                return bucket, key, query

            def _reply(self, status, body=b"", headers=None):
                headers = dict(headers or {})
                self.send_response(status)
                if "Content-Length" not in headers:
                    headers["Content-Length"] = str(len(body))
                for k, v in headers.items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _check_auth(self, body: bytes = b""):
                if store.strict:
                    why = verify_sigv4(self, body, secrets=store.secrets)
                    if why is not None:
                        self._reply(403, (f"<Error><Code>SignatureDoesNot"
                                          f"Match</Code><Message>{why}"
                                          f"</Message></Error>").encode())
                        return False
                    return True
                auth = self.headers.get("Authorization", "")
                if not auth.startswith("AWS4-HMAC-SHA256"):
                    self._reply(403, b"<Error>missing sigv4</Error>")
                    return False
                return True

            def do_HEAD(self):
                if not self._check_auth():
                    return
                bucket, key, _ = self._parse()
                store.requests.append(("HEAD", self.path))
                data = store.objects.get((bucket, key))
                if data is None:
                    self._reply(404)
                else:
                    etag = store.etags.get(
                        (bucket, key), hashlib.md5(data).hexdigest())
                    headers = {"Content-Length": str(len(data)),
                               "ETag": f'"{etag}"'}
                    headers.update(store.meta.get((bucket, key), {}))
                    self._reply(200, b"", headers)
                    return

            def _should_fail(self):
                if not store.fail_every:
                    return False
                with store.lock:
                    store._get_count += 1
                    if store._get_count % store.fail_every == 0:
                        store.injected_failures += 1
                        return True
                return False

            def _drop_mid_body(self, status, body):
                drop_mid_body(self, status, body)

            def do_GET(self):
                if not self._check_auth():
                    return
                bucket, key, query = self._parse()
                store.requests.append(("GET", self.path))
                if "list-type" in query:
                    return self._list(bucket, query)
                data = store.objects.get((bucket, key))
                if data is None:
                    return self._reply(404, b"<Error>NoSuchKey</Error>")
                rng = self.headers.get("Range")
                if rng:
                    spec = rng.split("=")[1]
                    start_s, end_s = spec.split("-")
                    start = int(start_s)
                    end = min(int(end_s), len(data) - 1) if end_s else len(data) - 1
                    piece = data[start:end + 1]
                    if self._should_fail():
                        return self._drop_mid_body(206, piece)
                    return self._reply(206, piece)
                if self._should_fail():
                    return self._drop_mid_body(200, data)
                self._reply(200, data)

            def _list(self, bucket, query):
                prefix = query.get("prefix", "")
                delim = query.get("delimiter", "")
                after = query.get("continuation-token", "")
                entries = []   # (key, size) leaves and (prefix, None) dirs
                prefixes = set()
                for (b, k), v in sorted(store.objects.items()):
                    if b != bucket or not k.startswith(prefix):
                        continue
                    rest = k[len(prefix):]
                    if delim and delim in rest:
                        prefixes.add(prefix + rest.split(delim)[0] + delim)
                    else:
                        entries.append((k, len(v)))
                # pagination over leaf keys (continuation token = last key
                # of the previous page, opaque to the client).  Common
                # prefixes go out exactly once — on the first page — like
                # real S3, which never repeats a prefix across pages
                if after:
                    entries = [e for e in entries if e[0] > after]
                    prefixes = set()
                truncated = False
                if store.page_size and len(entries) > store.page_size:
                    entries = entries[:store.page_size]
                    truncated = True
                contents = "".join(
                    f"<Contents><Key>{_xml_escape(k)}</Key>"
                    f"<Size>{n}</Size></Contents>" for k, n in entries)
                cps = "".join(f"<CommonPrefixes><Prefix>{_xml_escape(p)}"
                              f"</Prefix></CommonPrefixes>"
                              for p in sorted(prefixes))
                nct = (f"<NextContinuationToken>"
                       f"{_xml_escape(entries[-1][0])}"
                       f"</NextContinuationToken>" if truncated else "")
                body = (f"<ListBucketResult><IsTruncated>"
                        f"{'true' if truncated else 'false'}</IsTruncated>"
                        f"{contents}{cps}{nct}</ListBucketResult>").encode()
                self._reply(200, body)

            def do_DELETE(self):
                if not self._check_auth():
                    return
                bucket, key, query = self._parse()
                store.requests.append(("DELETE", self.path))
                if "uploadId" in query:
                    # AbortMultipartUpload: drop the pending parts
                    with store.lock:
                        up = store.uploads.pop(query["uploadId"], None)
                    if up is None:
                        return self._reply(
                            404, b"<Error><Code>NoSuchUpload</Code></Error>")
                    return self._reply(204)
                with store.lock:
                    store.objects.pop((bucket, key), None)
                    store.etags.pop((bucket, key), None)
                self._reply(204)

            def do_PUT(self):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                if not self._check_auth(body):
                    return
                bucket, key, query = self._parse()
                store.requests.append(("PUT", self.path))
                if "uploadId" in query:
                    uid = query["uploadId"]
                    part = int(query["partNumber"])
                    with store.lock:
                        up = store.uploads.get(uid)
                        if up is None:
                            return self._reply(
                                404, b"<Error><Code>NoSuchUpload</Code>"
                                     b"</Error>")
                        up["parts"][part] = body
                    # real S3 part ETags are the part body's md5 — the
                    # client derives the multipart object ETag from them
                    return self._reply(
                        200, b"",
                        {"ETag": f'"{hashlib.md5(body).hexdigest()}"'})
                store.objects[(bucket, key)] = body
                store.etags[(bucket, key)] = hashlib.md5(body).hexdigest()
                self._reply(200, b"", {"ETag": '"etag"'})

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                if not self._check_auth(body):
                    return
                bucket, key, query = self._parse()
                store.requests.append(("POST", self.path))
                if "uploads" in query:
                    meta = {k.lower(): v for k, v in self.headers.items()
                            if k.lower().startswith("x-amz-meta-")}
                    with store.lock:
                        store.next_upload[0] += 1
                        uid = f"upload-{store.next_upload[0]}"
                        store.uploads[uid] = {"key": (bucket, key),
                                              "parts": {}, "meta": meta}
                    body = (f"<InitiateMultipartUploadResult>"
                            f"<UploadId>{uid}</UploadId>"
                            f"</InitiateMultipartUploadResult>").encode()
                    return self._reply(200, body)
                if "uploadId" in query:
                    uid = query["uploadId"]
                    with store.lock:
                        up = store.uploads.pop(uid, None)
                        if up is None:
                            # completed/aborted upload ids no longer exist
                            return self._reply(
                                404, b"<Error><Code>NoSuchUpload</Code>"
                                     b"</Error>")
                        parts = [v for _, v in sorted(up["parts"].items())]
                        data = b"".join(parts)
                        store.objects[up["key"]] = data
                        store.etags[up["key"]] = (
                            hashlib.md5(b"".join(
                                hashlib.md5(p).digest() for p in parts)
                            ).hexdigest() + f"-{len(parts)}")
                        store.meta[up["key"]] = up.get("meta", {})
                        drop = store.fail_complete_once
                        store.fail_complete_once = False
                    if drop:
                        # committed, but the client never hears back
                        self.close_connection = True
                        self.connection.shutdown(socket.SHUT_RDWR)
                        return
                    return self._reply(
                        200, b"<CompleteMultipartUploadResult/>")
                self._reply(400, b"<Error>bad post</Error>")

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)
        self.thread.start()
        return self

    def stop(self):
        self.server.shutdown()
        self.server.server_close()
