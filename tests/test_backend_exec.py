"""Execution tests for the ssh / mpi / sge launcher backends.

The reference's tracker had zero tests; SURVEY §4 commits to exceeding that.
The local backend has real e2e coverage (test_tracker.py); here the other
launchers run end-to-end against fake cluster binaries on PATH:

- ``ssh``   — consumes the option flags and runs the remote command locally
  through ``sh -c`` (what sshd would do on the far side);
- ``mpirun``— parses -n/-x like OpenMPI, then spawns N local processes with
  OMPI_COMM_WORLD_RANK set (exactly the env a real OpenMPI gives ranks);
- ``qsub``  — parses the array-job spec and runs each task with SGE_TASK_ID.

Workers are real processes doing a real jax.distributed collective, so the
whole path — env contract assembly, command quoting, per-task identity,
coordinator rendezvous — is executed, not just string-asserted.
"""

import os
import stat
import sys

import pytest

from dmlc_core_tpu.tracker.opts import get_opts

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# real collective worker (same shape as test_tracker.py's WORKER_SCRIPT)
WORKER = r"""
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from dmlc_core_tpu import collective

collective.init()
rank = collective.get_rank()
world = collective.get_world_size()
out = collective.allreduce(np.array([float(rank + 1)], dtype=np.float32))
assert abs(float(out[0]) - world * (world + 1) / 2) < 1e-5
with open(os.environ["RESULT_DIR"] + f"/rank{rank}.ok", "w") as f:
    f.write(os.environ.get("WORKER_VIA", "?"))
collective.finalize()
"""

FAKE_SSH = """#!/bin/sh
# fake sshd: swallow ssh options, then run the remote command locally
while [ $# -gt 0 ]; do
  case "$1" in
    -o|-p) shift 2 ;;
    *) break ;;
  esac
done
host="$1"; shift
WORKER_VIA="ssh:$host" ; export WORKER_VIA
exec sh -c "$*"
"""

FAKE_MPIRUN = """#!/usr/bin/env python3
import os, subprocess, sys
args = sys.argv[1:]
if "--version" in args:
    print("mpirun (Open MPI) 9.fake")
    sys.exit(0)
n, env, cmd, i = 1, {}, [], 0
while i < len(args):
    a = args[i]
    if a == "-n":
        n = int(args[i + 1]); i += 2
    elif a == "--hostfile":
        i += 2
    elif a == "-x":
        k, _, v = args[i + 1].partition("="); env[k] = v; i += 2
    else:
        cmd = args[i:]; break
procs = []
for r in range(n):
    e = os.environ.copy(); e.update(env)
    e["OMPI_COMM_WORLD_RANK"] = str(r)
    e["OMPI_COMM_WORLD_SIZE"] = str(n)
    e["WORKER_VIA"] = "mpi"
    procs.append(subprocess.Popen(cmd, env=e))
sys.exit(max([p.wait() for p in procs], default=0))
"""

FAKE_QSUB = """#!/usr/bin/env python3
import os, subprocess, sys
args = sys.argv[1:]
lo = hi = 1
script = args[-1]
for i, a in enumerate(args):
    if a == "-t":
        lo, hi = (int(x) for x in args[i + 1].split("-"))
procs = []
for t in range(lo, hi + 1):
    e = os.environ.copy()
    e["SGE_TASK_ID"] = str(t)
    e["WORKER_VIA"] = "sge"
    procs.append(subprocess.Popen(["/bin/bash", script], env=e))
sys.exit(max([p.wait() for p in procs], default=0))
"""


@pytest.fixture()
def fake_cluster(tmp_path, monkeypatch):
    """Fake cluster binaries on PATH + a worker script + no_wait submit."""
    bindir = tmp_path / "bin"
    bindir.mkdir()
    for name, body in (("ssh", FAKE_SSH), ("mpirun", FAKE_MPIRUN),
                       ("qsub", FAKE_QSUB)):
        p = bindir / name
        p.write_text(body)
        p.chmod(p.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
    monkeypatch.setenv("RESULT_DIR", str(tmp_path))
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("PYTHONPATH",
                       REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    return tmp_path, worker


def _no_wait_submit(module, monkeypatch):
    from dmlc_core_tpu.tracker import submit as submit_mod

    orig = submit_mod.submit_job

    def no_wait(opts_, fun, wait=True):
        return orig(opts_, fun, wait=False)

    monkeypatch.setattr(module, "submit_job", no_wait)


def _assert_ranks(tmp_path, n, via):
    for r in range(n):
        f = tmp_path / f"rank{r}.ok"
        assert f.exists(), f"rank {r} never completed (via {via})"
        assert f.read_text().startswith(via)


def test_ssh_backend_executes_workers(fake_cluster, monkeypatch):
    tmp_path, worker = fake_cluster
    hostfile = tmp_path / "hosts"
    hostfile.write_text("nodeA\nnodeB:2222\n")
    from dmlc_core_tpu.tracker import ssh

    opts = get_opts(["--cluster", "ssh", "--num-workers", "2",
                     "--host-file", str(hostfile), "--",
                     sys.executable, str(worker)])
    ssh.submit(opts)
    _assert_ranks(tmp_path, 2, "ssh")
    # round-robin host assignment reached both hosts
    seen = {(tmp_path / f"rank{r}.ok").read_text() for r in range(2)}
    assert seen == {"ssh:nodeA", "ssh:nodeB"}


def test_mpi_backend_executes_workers(fake_cluster, monkeypatch):
    tmp_path, worker = fake_cluster
    from dmlc_core_tpu.tracker import mpi

    opts = get_opts(["--cluster", "mpi", "--num-workers", "2", "--",
                     sys.executable, str(worker)])
    mpi.submit(opts)
    # ranks derived from OMPI_COMM_WORLD_RANK (no DMLC_TASK_ID under mpirun)
    _assert_ranks(tmp_path, 2, "mpi")


def test_sge_backend_executes_workers(fake_cluster, monkeypatch, tmp_path):
    work, worker = fake_cluster
    from dmlc_core_tpu.tracker import sge

    _no_wait_submit(sge, monkeypatch)   # workers are not rabit clients
    monkeypatch.chdir(work)
    opts = get_opts(["--cluster", "sge", "--num-workers", "2",
                     "--jobname", "sgejob", "--",
                     sys.executable, str(worker)])
    sge.submit(opts)
    _assert_ranks(work, 2, "sge")
    assert (work / "sgejob.sge.sh").exists()


def test_task_id_env_fallback_ignores_garbage():
    from dmlc_core_tpu.collective.api import _task_id_from_env

    assert _task_id_from_env({"DMLC_TASK_ID": "3"}) == 3
    assert _task_id_from_env({"OMPI_COMM_WORLD_RANK": "2"}) == 2
    # DMLC_TASK_ID wins over launcher vars
    assert _task_id_from_env({"DMLC_TASK_ID": "1",
                              "OMPI_COMM_WORLD_RANK": "7"}) == 1
    # stale/garbage inherited vars must not break standalone init
    assert _task_id_from_env({"PMI_RANK": ""}) == 0
    assert _task_id_from_env({"SLURM_PROCID": "garbage"}) == 0
    assert _task_id_from_env({"PMI_RANK": "x", "SLURM_PROCID": "4"}) == 4


def test_sge_task_ids_are_role_relative(fake_cluster, monkeypatch):
    """With servers in the job, worker DMLC_TASK_IDs must still be
    0..nw-1 (they are the collective's process ids)."""
    work, _ = fake_cluster
    from dmlc_core_tpu.tracker import sge

    _no_wait_submit(sge, monkeypatch)
    monkeypatch.chdir(work)
    probe = work / "probe.py"
    probe.write_text(
        "import os\n"
        "role = os.environ['DMLC_ROLE']\n"
        "tid = os.environ['DMLC_TASK_ID']\n"
        "open(os.environ['RESULT_DIR'] + f'/{role}{tid}.seen', 'w').close()\n")
    opts = get_opts(["--cluster", "sge", "--num-workers", "2",
                     "--num-servers", "1", "--jobname", "rolejob", "--",
                     sys.executable, str(probe)])
    sge.submit(opts)
    assert (work / "server0.seen").exists()
    assert (work / "worker0.seen").exists()
    assert (work / "worker1.seen").exists()


FAKE_GCLOUD = """#!/usr/bin/env python3
# fake `gcloud compute tpus tpu-vm ssh NAME --worker=all --command=...`:
# run the command once per "host" with TPU_WORKER_ID set, like the real
# per-host agent environment.
import os, subprocess, sys
cmd = None
for a in sys.argv[1:]:
    if a.startswith("--command="):
        cmd = a[len("--command="):]
assert cmd, sys.argv
n = int(os.environ.get("FAKE_TPU_HOSTS", "2"))
procs = []
for w in range(n):
    e = os.environ.copy()
    e["TPU_WORKER_ID"] = str(w)
    e["WORKER_VIA"] = "tpu-vm"
    procs.append(subprocess.Popen(["/bin/sh", "-c", cmd], env=e))
sys.exit(max([p.wait() for p in procs], default=0))
"""


def test_tpu_vm_backend_hostfile_path(fake_cluster, monkeypatch):
    tmp_path, worker = fake_cluster
    hostfile = tmp_path / "tpu_hosts"
    hostfile.write_text("tpu-w0\ntpu-w1\n")
    from dmlc_core_tpu.tracker import tpu_vm

    opts = get_opts(["--cluster", "tpu-vm", "--num-workers", "2",
                     "--host-file", str(hostfile), "--",
                     sys.executable, str(worker)])
    tpu_vm.submit(opts)
    _assert_ranks(tmp_path, 2, "ssh")   # rides the ssh machinery


def test_tpu_vm_backend_gcloud_path(fake_cluster, monkeypatch):
    tmp_path, worker = fake_cluster
    gcloud = tmp_path / "bin" / "gcloud"
    gcloud.write_text(FAKE_GCLOUD)
    gcloud.chmod(gcloud.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("TPU_NAME", "fake-slice")
    monkeypatch.setenv("FAKE_TPU_HOSTS", "2")
    from dmlc_core_tpu.tracker import tpu_vm

    opts = get_opts(["--cluster", "tpu-vm", "--num-workers", "2", "--",
                     sys.executable, str(worker)])
    tpu_vm.submit(opts)
    # per-host identity came from TPU_WORKER_ID through the env contract
    _assert_ranks(tmp_path, 2, "tpu-vm")


def test_tpu_vm_gcloud_path_ships_files(fake_cluster, monkeypatch):
    """--files on the gcloud path: the launcher materializes the shipped
    file into each task's cwd (host-visible source, e.g. a mounted GCS
    path) and the auto-cached worker token is rewritten."""
    tmp_path, _ = fake_cluster
    gcloud = tmp_path / "bin" / "gcloud"
    gcloud.write_text(FAKE_GCLOUD)
    gcloud.chmod(gcloud.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("TPU_NAME", "fake-slice")
    monkeypatch.setenv("FAKE_TPU_HOSTS", "2")
    payload = tmp_path / "manifest.txt"
    payload.write_text("shipped-manifest\n")
    rundir = tmp_path / "rundir"
    rundir.mkdir()
    monkeypatch.chdir(rundir)   # tasks run here; source sits elsewhere
    reader = tmp_path / "read_manifest.py"
    reader.write_text(
        "import os\n"
        "tid = os.environ['DMLC_TASK_ID']\n"
        "body = open('manifest.txt').read().strip()\n"
        "open(os.environ['RESULT_DIR'] + f'/ship{tid}.out', 'w')"
        ".write(body)\n")
    from dmlc_core_tpu.tracker import tpu_vm

    opts = get_opts(["--cluster", "tpu-vm", "--num-workers", "2",
                     "--files", str(payload), "--",
                     sys.executable, str(reader)])
    tpu_vm.submit(opts)
    for tid in range(2):
        assert (tmp_path / f"ship{tid}.out").read_text() == \
            "shipped-manifest"
    # resubmit with an EDITED payload: per-job cwds mean no stale copy
    # from the previous run can be served (skip-if-exists materialization
    # in a persistent home dir was the hazard)
    payload.write_text("edited-manifest\n")
    opts = get_opts(["--cluster", "tpu-vm", "--num-workers", "2",
                     "--files", str(payload), "--",
                     sys.executable, str(reader)])
    tpu_vm.submit(opts)
    for tid in range(2):
        assert (tmp_path / f"ship{tid}.out").read_text() == \
            "edited-manifest"
