"""Serving-path tests: scheduler coalescing/bucketing, model runtimes,
admission accounting, request parsing, and the HTTP surface end-to-end.

The failure paths (injected stalls, killed predict, reset storms) live in
tests/test_serve_chaos.py under the ``chaos`` marker.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from dmlc_core_tpu.serve import (AdmissionController, BadRequest,
                                 MicroBatcher, ModelRuntime, Overloaded,
                                 ScoringServer, batch_buckets, build_runtime)
from dmlc_core_tpu.serve.server import parse_instances


# -- helpers ------------------------------------------------------------------

class StubRuntime(ModelRuntime):
    """Deterministic predict (row sums) that records every batch shape."""

    name = "stub"

    def __init__(self, num_feature=4):
        super().__init__(num_feature)
        self.shapes = []
        self.lock = threading.Lock()

    def predict(self, x):
        with self.lock:
            self.shapes.append(tuple(x.shape))
        return x.sum(axis=1)


def post(url, obj, timeout=10.0):
    """POST /v1/score; returns (status, parsed body) for 2xx and errors."""
    body = obj if isinstance(obj, bytes) else json.dumps(obj).encode()
    req = urllib.request.Request(
        url + "/v1/score", data=body,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.load(resp)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


def get(url, path, timeout=10.0):
    with urllib.request.urlopen(url + path, timeout=timeout) as resp:
        ctype = resp.headers.get("Content-Type", "")
        raw = resp.read()
        return resp.status, (json.loads(raw) if "json" in ctype
                             else raw.decode())


# -- bucket ladder ------------------------------------------------------------

def test_batch_buckets_ladder_shape():
    assert batch_buckets(1) == [1]
    assert batch_buckets(4) == [1, 2, 3, 4]
    assert batch_buckets(64) == [1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64]
    # a max_batch off the ladder caps the last rung exactly
    assert batch_buckets(5) == [1, 2, 3, 4, 5]
    with pytest.raises(ValueError):
        batch_buckets(0)


# -- request parsing ----------------------------------------------------------

def test_parse_instances_dense_sparse_mixed():
    x = parse_instances({"instances": [
        [1.0, 2.0, 3.0],
        {"index": [2], "value": [5.0]},
        {"index": [], "value": []},
    ]}, 3)
    np.testing.assert_allclose(x, [[1, 2, 3], [0, 0, 5], [0, 0, 0]])
    assert x.dtype == np.float32


def test_parse_instances_rejects_non_finite_values():
    # json.loads admits 1e400 (inf) and NaN; a 200 carrying them back
    # would be RFC-invalid JSON, so they stop at the door
    with pytest.raises(BadRequest, match="non-finite"):
        parse_instances({"instances": [[float("inf"), 0.0, 0.0]]}, 3)
    with pytest.raises(BadRequest, match="non-finite"):
        parse_instances({"instances": [[float("nan"), 0.0, 0.0]]}, 3)
    with pytest.raises(BadRequest, match="non-finite"):
        parse_instances({"instances": [
            {"index": [1], "value": [float("inf")]}]}, 3)


@pytest.mark.parametrize("body,frag", [
    ([1, 2], "body must be a JSON object"),
    ({}, "'instances'"),
    ({"instances": []}, "'instances'"),
    ({"instances": [[1.0]]}, "expected 3 features"),
    ({"instances": [["a", "b", "c"]]}, "non-numeric"),
    ({"instances": [{"index": [0]}]}, "equal-length"),
    ({"instances": [{"index": [3], "value": [1.0]}]}, "out of"),
    ({"instances": [{"index": [-1], "value": [1.0]}]}, "out of"),
    ({"instances": ["nope"]}, "each row"),
])
def test_parse_instances_rejects_malformed(body, frag):
    with pytest.raises(BadRequest, match=frag.replace("[", r"\[")):
        parse_instances(body, 3)


# -- admission ---------------------------------------------------------------

def test_admission_reserves_and_sheds():
    adm = AdmissionController(max_queue_bytes=100)
    adm.try_admit(60)
    adm.try_admit(40)
    assert adm.queued_bytes == 100
    with pytest.raises(Overloaded) as ei:
        adm.try_admit(1)
    err = ei.value
    assert err.status == 503 and err.code == "overloaded"
    assert err.payload()["error"]["retry_after"] >= 1
    assert "Retry-After" in err.headers()
    adm.release(60)
    adm.try_admit(10)  # admits again after drain
    assert adm.queued_bytes == 50


def test_admission_oversized_request_is_a_400_not_a_shed():
    adm = AdmissionController(max_queue_bytes=100)
    with pytest.raises(BadRequest):
        adm.try_admit(101)
    assert adm.queued_bytes == 0  # nothing reserved


def test_admission_retry_after_tracks_drain_rate_within_clamps():
    import time

    adm = AdmissionController(max_queue_bytes=100)
    adm.try_admit(100)
    # releases spread past the sampling window establish a drain EWMA;
    # back-to-back releases inside one window must NOT fabricate a rate
    adm.release(50)
    time.sleep(0.08)
    adm.release(30)
    assert adm._drain_rate is not None and adm._drain_rate > 0
    with pytest.raises(Overloaded) as ei:
        adm.try_admit(90)  # 20 still queued: 110 > 100 sheds
    ra = ei.value.retry_after
    assert 1.0 <= ra <= 30.0


def test_admission_microsecond_releases_do_not_swamp_drain_rate():
    adm = AdmissionController(max_queue_bytes=1000)
    adm.try_admit(1000)
    for _ in range(10):
        adm.release(100)  # all inside one sampling window
    # at most the first window could have closed; the rate, if any, must
    # not be the absurd bytes/microsecond of per-call spacing
    assert adm._drain_rate is None or adm._drain_rate < 1e9


def test_admission_release_never_goes_negative():
    adm = AdmissionController(max_queue_bytes=10)
    adm.release(5)
    assert adm.queued_bytes == 0


# -- scheduler ---------------------------------------------------------------

def test_scheduler_coalesces_concurrent_requests():
    rt = StubRuntime(num_feature=4)
    mb = MicroBatcher(rt, max_batch=16, max_delay_ms=30.0)
    mb.start()
    try:
        rows = [np.full((1, 4), i, np.float32) for i in range(8)]
        futures = [mb.submit(r) for r in rows]
        results = [f.result(timeout=10) for f in futures]
        for i, r in enumerate(results):
            np.testing.assert_allclose(r, [4.0 * i])
        # concurrent submits coalesced: fewer predict calls than requests
        assert len(rt.shapes) < 8
    finally:
        mb.close()


def test_scheduler_pads_to_bucket_ladder_shapes():
    rt = StubRuntime(num_feature=4)
    mb = MicroBatcher(rt, max_batch=8, max_delay_ms=20.0)
    mb.start()
    try:
        f = mb.submit(np.ones((5, 4), np.float32))
        np.testing.assert_allclose(f.result(timeout=10), [4.0] * 5)
        # 5 rows pad to the 6-rung, never an arbitrary shape
        assert rt.shapes == [(6, 4)]
        assert all(s[0] in mb.buckets for s in rt.shapes)
    finally:
        mb.close()


def test_scheduler_contract_violations_are_bad_requests():
    rt = StubRuntime(num_feature=4)
    mb = MicroBatcher(rt, max_batch=4, max_delay_ms=1.0)
    mb.start()
    try:
        with pytest.raises(BadRequest, match="empty"):
            mb.submit(np.zeros((0, 4), np.float32))
        with pytest.raises(BadRequest, match="max_batch"):
            mb.submit(np.zeros((5, 4), np.float32))
        with pytest.raises(BadRequest, match="instances must be"):
            mb.submit(np.zeros((2, 3), np.float32))
    finally:
        mb.close()


def test_scheduler_splits_overflow_across_batches():
    rt = StubRuntime(num_feature=2)
    mb = MicroBatcher(rt, max_batch=4, max_delay_ms=40.0)
    mb.start()
    try:
        a = mb.submit(np.ones((3, 2), np.float32))
        b = mb.submit(np.ones((3, 2), np.float32))
        np.testing.assert_allclose(a.result(timeout=10), [2.0] * 3)
        np.testing.assert_allclose(b.result(timeout=10), [2.0] * 3)
        # 3+3 > max_batch: the second request carried over to its own batch
        assert len(rt.shapes) == 2
    finally:
        mb.close()


def test_scheduler_submit_after_close_sheds_structurally():
    rt = StubRuntime()
    mb = MicroBatcher(rt, max_batch=4, max_delay_ms=1.0)
    mb.start()
    mb.close()
    with pytest.raises(Overloaded, match="shutting down"):
        mb.submit(np.ones((1, 4), np.float32))


def test_scheduler_releases_admission_bytes_on_completion():
    rt = StubRuntime(num_feature=4)
    adm = AdmissionController(max_queue_bytes=1 << 20)
    mb = MicroBatcher(rt, max_batch=8, max_delay_ms=1.0, admission=adm)
    mb.start()
    try:
        futures = [mb.submit(np.ones((2, 4), np.float32)) for _ in range(5)]
        for f in futures:
            f.result(timeout=10)
        assert adm.queued_bytes == 0
    finally:
        mb.close()


# -- model runtimes -----------------------------------------------------------

def test_linear_runtime_matches_model_math():
    rt = build_runtime("linear", 6, seed=3)
    x = np.random.RandomState(0).normal(size=(5, 6)).astype(np.float32)
    got = rt.predict(x)
    w, b = np.asarray(rt.params["w"]), float(rt.params["b"])
    want = 1.0 / (1.0 + np.exp(-(x @ w + b)))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_mlp_runtime_matches_model_predict():
    rt = build_runtime("mlp", 5, seed=1, hidden="8", num_class=3)
    x = np.random.RandomState(1).normal(size=(4, 5)).astype(np.float32)
    got = rt.predict(x)
    want = np.asarray(rt.model.predict(rt.params, x))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    assert got.shape == (4, 3)
    np.testing.assert_allclose(got.sum(axis=1), 1.0, rtol=1e-4)


def test_gbdt_runtime_predicts_probabilities():
    rt = build_runtime("gbdt", 4, seed=2)
    x = np.random.RandomState(2).normal(size=(6, 4)).astype(np.float32)
    got = rt.predict(x)
    assert got.shape == (6,)
    assert np.all((got > 0) & (got < 1))
    want = np.asarray(rt.gbdt.predict(rt.ensemble, rt.gbdt.bin_features(x)))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_gbdt_runtime_serves_through_the_binned_wire_skew_free():
    # the skew-free contract (ISSUE 15): serving scores ride the uint8
    # HostBinner wire and are BITWISE-equal to the float-path predict —
    # including on exact boundary values, where any binning skew would
    # flip a split decision
    rt = build_runtime("gbdt", 4, seed=2)
    assert rt.binner.dtype == np.uint8  # 16 bins fit the narrowest wire
    x = np.random.RandomState(3).normal(size=(12, 4)).astype(np.float32)
    x[0, :] = rt.gbdt.boundaries[np.arange(4), 0]   # ties go right
    x[1, :] = rt.gbdt.boundaries[np.arange(4), -1]
    np.testing.assert_array_equal(rt.predict(x), rt.predict_float(x))
    # and the wire really is the narrow dtype end to end
    assert rt.binner.transform(x).dtype == np.uint8


def test_runtime_warmup_compiles_each_bucket_once():
    rt = StubRuntime(num_feature=3)
    assert rt.warmup([1, 2, 4, 4, 2]) == 3
    assert sorted(rt.shapes) == [(1, 3), (2, 3), (4, 3)]


def test_build_runtime_unknown_kind():
    with pytest.raises(ValueError, match="unknown model kind"):
        build_runtime("resnet", 4)


# -- HTTP surface -------------------------------------------------------------

# parametrized over both transports: every HTTP contract test below runs
# against the threaded ThreadingHTTPServer AND the selectors event loop
# with zero test forks (docs/serving.md "Transport")
@pytest.fixture(scope="module", params=["threaded", "evloop"])
def linear_server(request):
    rt = build_runtime("linear", 4, seed=0)
    server = ScoringServer(rt, max_batch=4, max_delay_ms=1.0,
                           request_timeout_s=10.0,
                           transport=request.param)
    with server:
        yield server


def test_http_score_dense_and_sparse(linear_server):
    url = linear_server.url
    status, body = post(url, {"instances": [[0.5, 0.5, 0.5, 0.5]]})
    assert status == 200
    assert body["model"] == "linear" and body["num_rows"] == 1
    # every response names the model version that scored it (the
    # lifecycle drill's atomicity probe; 0 = unmanaged/day-0)
    assert body["version"] == 0
    assert len(body["predictions"]) == 1
    # the sparse form of the same row scores identically
    status, sparse = post(url, {"instances": [
        {"index": [0, 1, 2, 3], "value": [0.5, 0.5, 0.5, 0.5]}]})
    assert status == 200
    assert sparse["predictions"] == pytest.approx(body["predictions"])


def test_http_malformed_bodies_are_structured_400s(linear_server):
    url = linear_server.url
    status, body = post(url, b"{not json")
    assert status == 400 and body["error"]["code"] == "bad_request"
    status, body = post(url, {"instances": [[1.0]]})
    assert status == 400 and "expected 4 features" in body["error"]["message"]
    status, body = post(url, {"instances": "x"})
    assert status == 400 and body["error"]["code"] == "bad_request"


def test_http_unknown_paths_are_structured(linear_server):
    req = urllib.request.Request(
        linear_server.url + "/v1/wrong", data=b"{}",
        headers={"Content-Type": "application/json"})
    try:
        urllib.request.urlopen(req, timeout=10)
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400 and json.load(e)["error"]["code"] == "bad_request"


def test_http_healthz_and_stats(linear_server):
    from dmlc_core_tpu import telemetry

    status, health = get(linear_server.url, "/healthz")
    assert status == 200 and health["status"] == "ok"
    assert health["model"] == "linear" and health["num_feature"] == 4

    was_enabled = telemetry.enabled()
    telemetry.enable()
    try:
        status, _ = post(linear_server.url, {"instances": [[0, 0, 0, 0]]})
        assert status == 200
        status, stats = get(linear_server.url, "/stats")
        assert status == 200
        assert stats["model"] == "linear"
        series = stats["metrics"]
        # series names render exactly as the offline report's table keys
        # (every request-path metric carries the model-slot label)
        key = 'dmlc_serve_requests_total{model="linear",status="200"}'
        assert series[key] >= 1
        hist = series['dmlc_serve_request_seconds'
                      '{model="linear",status="200"}']
        assert hist["count"] >= 1 and hist["p50"] is not None
        assert hist["p50"] <= hist["p99"]
        # the per-slot identity block rides /stats too
        assert stats["models"]["linear"]["family"] == "linear"
    finally:
        if not was_enabled:
            telemetry.disable()


def test_http_metrics_prometheus_form(linear_server):
    from dmlc_core_tpu import telemetry

    was_enabled = telemetry.enabled()
    telemetry.enable()
    try:
        status, _ = post(linear_server.url, {"instances": [[1, 1, 1, 1]]})
        assert status == 200
        status, text = get(linear_server.url, "/metrics")
        assert status == 200
        assert "dmlc_serve_requests_total" in text
        assert "# TYPE" in text
    finally:
        if not was_enabled:
            telemetry.disable()


def test_http_payload_too_large_is_413(linear_server, monkeypatch):
    from dmlc_core_tpu.serve import server as server_mod

    monkeypatch.setattr(server_mod, "MAX_BODY_BYTES", 64)
    status, body = post(linear_server.url,
                        {"instances": [[0.0, 0.0, 0.0, 0.0]] * 10})
    assert status == 413
    assert body["error"]["code"] == "payload_too_large"


def test_http_negative_content_length_rejected_not_hung(linear_server):
    # a hostile Content-Length must not turn into rfile.read(-1), which
    # would pin the handler thread until the client hangs up
    import http.client

    host, port = linear_server.address
    conn = http.client.HTTPConnection(host, port, timeout=5)
    try:
        conn.putrequest("POST", "/v1/score")
        conn.putheader("Content-Type", "application/json")
        conn.putheader("Content-Length", "-1")
        conn.endheaders()
        resp = conn.getresponse()
        assert resp.status == 400
        assert json.load(resp)["error"]["code"] == "bad_request"
    finally:
        conn.close()


def test_http_keepalive_connection_stays_in_sync(linear_server):
    # two requests down ONE persistent connection: the first response must
    # leave the stream positioned at the second request
    import http.client

    host, port = linear_server.address
    conn = http.client.HTTPConnection(host, port, timeout=10)
    try:
        for i in range(2):
            body = json.dumps({"instances": [[float(i)] * 4]})
            conn.request("POST", "/v1/score", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200
            assert len(json.load(resp)["predictions"]) == 1
    finally:
        conn.close()


def test_http_unknown_model_404_closes_keepalive_connection(linear_server):
    # the route-error path answers WITHOUT reading the body: keeping the
    # keep-alive connection would parse that unread body as the next
    # request line, so the 404 must close the connection
    import http.client

    host, port = linear_server.address
    conn = http.client.HTTPConnection(host, port, timeout=10)
    try:
        body = json.dumps({"instances": [[0.0] * 4]})
        conn.request("POST", "/v1/score/nope", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 404
        assert json.load(resp)["error"]["code"] == "unknown_model"
        with pytest.raises((http.client.HTTPException, ConnectionError,
                            OSError)):
            conn.request("POST", "/v1/score", body=body,
                         headers={"Content-Type": "application/json"})
            conn.getresponse()
    finally:
        conn.close()


def test_http_concurrent_clients_all_answered(linear_server):
    url = linear_server.url
    results = []
    lock = threading.Lock()

    def client(i):
        status, body = post(url, {"instances": [[i, 0.0, 0.0, 0.0]]})
        with lock:
            results.append((i, status, body))

    threads = [threading.Thread(target=client, args=(i,)) for i in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert len(results) == 12
    assert all(status == 200 for _, status, _ in results)
    # scores are per-row correct, not shuffled across the coalesced batch
    w0 = float(np.asarray(linear_server.runtime.params["w"])[0])
    b = float(linear_server.runtime.params["b"])
    for i, _, body in results:
        want = 1.0 / (1.0 + np.exp(-(i * w0 + b)))
        assert body["predictions"][0] == pytest.approx(want, rel=1e-4)


# -- loadgen drift canary ------------------------------------------------------

def test_loadgen_drift_bucketing_and_series():
    """The drift canary's accounting: per-window request counts and mean
    predictions, sorted, empty windows absent (docs/serving.md)."""
    from dmlc_core_tpu.serve.loadgen import _mean_prediction, _Recorder

    # scalar and softmax-row predictions flatten to one mean; junk skipped
    assert _mean_prediction([0.25, 0.75]) == pytest.approx(0.5)
    assert _mean_prediction([[0.2, 0.8], [0.4, 0.6]]) == pytest.approx(0.5)
    assert _mean_prediction(["oops", None]) is None
    assert _mean_prediction([]) is None

    rec = _Recorder()
    rec.record_drift(0, 0.2)
    rec.record_drift(0, 0.4)
    rec.record_drift(2, 0.9)           # window 1 empty: not emitted
    series = rec.drift_series(1.5)
    assert series == [
        {"window": 0, "t_s": 0.0, "n": 2, "mean_prediction": 0.3},
        {"window": 2, "t_s": 3.0, "n": 1, "mean_prediction": 0.9},
    ]


def test_loadgen_report_carries_drift_and_response_check(linear_server):
    """run_load end to end: the report's drift block covers every ok
    response bucketed by scheduled time, and a failing response_check
    turns would-be oks into ``invalid`` (the half-swap detector)."""
    from dmlc_core_tpu.serve.loadgen import run_load

    report = run_load(linear_server.url, qps=40, duration_s=1.0,
                      num_feature=4, seed=3, timeout_s=10.0,
                      drift_window_s=0.25)
    counts = report["counts"]
    assert counts["crashed"] == 0 and counts["ok"] > 0
    drift = report["drift"]
    assert drift["window_s"] == pytest.approx(0.25)
    series = drift["series"]
    assert series, "ok traffic must produce drift windows"
    assert sum(w["n"] for w in series) == counts["ok"]
    assert [w["window"] for w in series] == sorted(
        {w["window"] for w in series})
    for w in series:
        assert w["n"] >= 1 and np.isfinite(w["mean_prediction"])
        assert w["t_s"] == pytest.approx(w["window"] * 0.25)

    # the check sees (payload, rows): reject everything -> all invalid,
    # nothing recorded as drift (wrong scores must not pollute the canary)
    seen_rows = []

    def reject(payload, rows):
        seen_rows.append((payload["num_rows"], len(rows)))
        return False

    report2 = run_load(linear_server.url, qps=30, duration_s=0.5,
                       num_feature=4, seed=4, timeout_s=10.0,
                       response_check=reject)
    assert report2["counts"]["invalid"] > 0
    assert report2["counts"]["ok"] == 0
    assert report2["drift"]["series"] == []
    assert seen_rows and all(n == len_rows for n, len_rows in seen_rows)
