"""Rendezvous conformance against the REFERENCE tracker itself.

The repo's wire-compat claim previously rested on FakeRabitClient
transcripts written by the same author as the server — a shared
misreading of the protocol would pass (r4 VERDICT missing #1).  This
module removes that blind spot: the reference's own pure-stdlib
RabitTracker (/root/reference/tracker/dmlc_tracker/tracker.py:254-320) is
run in-process, the SAME scripted client sessions are driven against it
and against ours, and the recorded wire conversations and assigned
topologies must be identical, op for op.

Determinism notes:
  * clients complete their request header serially, so the reference's
    arrival-order batch assignment (pending.sort by host is stable — all
    clients are 127.0.0.1) maps client i -> deterministic rank;
  * neighbor values stay < 8 for the tested world sizes, where CPython
    small-int set iteration is ascending, so the reference's set-order
    sends are reproducible;
  * OS-assigned listener ports differ run to run, so port VALUES are
    normalized to a placeholder in transcripts (the protocol positions
    they occupy still must match exactly).
"""

import importlib.util
import os
import socket
import sys
import threading

import pytest

from dmlc_core_tpu.tracker.rendezvous import MAGIC, RabitTracker

REFERENCE_TRACKER = "/root/reference/tracker/dmlc_tracker/tracker.py"

pytestmark = pytest.mark.skipif(
    not os.path.exists(REFERENCE_TRACKER),
    reason="reference tracker not present in this image")


def load_reference_tracker():
    spec = importlib.util.spec_from_file_location("ref_tracker",
                                                  REFERENCE_TRACKER)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


PORT = "<PORT>"  # placeholder for OS-assigned (nondeterministic) ports


class RecordingClient:
    """Worker-side protocol driver that records every wire op.

    The transcript is a list of (op, value) tuples: "si"/"ri" for
    sent/received ints, "ss"/"rs" for strings.  Ports (its own advertised
    one and any received in dial triples) are recorded as PORT.
    """

    def __init__(self, tracker_addr, jobid="NULL"):
        self.tracker_addr = tracker_addr
        self.jobid = jobid
        self.transcript = []
        self.rank = -1
        self.listen_sock = socket.socket()
        self.listen_sock.bind(("127.0.0.1", 0))
        self.listen_sock.listen(16)
        self.port = self.listen_sock.getsockname()[1]
        self._accepted = []
        threading.Thread(target=self._acceptor, daemon=True).start()

    # -- wire primitives over a live FramedSocket-alike ---------------------
    def _connect(self):
        import struct

        class _Wire:
            def __init__(w, sock, rec):
                w.sock, w.rec = sock, rec

            def sendint(w, v, tag=None):
                w.sock.sendall(struct.pack("<i", v))
                w.rec.append(("si", tag if tag is not None else v))

            def recvint(w, tag=None):
                buf = b""
                while len(buf) < 4:
                    chunk = w.sock.recv(4 - len(buf))
                    if not chunk:
                        raise ConnectionError("tracker closed mid-int")
                    buf += chunk
                v = struct.unpack("<i", buf)[0]
                w.rec.append(("ri", tag if tag is not None else v))
                return v

            def sendstr(w, s):
                w.sock.sendall(struct.pack("<i", len(s)) + s.encode())
                w.rec.append(("ss", s))

            def recvstr(w):
                buf = b""
                while len(buf) < 4:
                    buf += w.sock.recv(4 - len(buf))
                n = struct.unpack("<i", buf)[0]
                data = b""
                while len(data) < n:
                    data += w.sock.recv(n - len(data))
                s = data.decode()
                w.rec.append(("rs", s))
                return s

        s = socket.create_connection(self.tracker_addr)
        return _Wire(s, self.transcript)

    def _acceptor(self):
        try:
            while True:
                conn, _ = self.listen_sock.accept()
                self._accepted.append(conn)
        except OSError:
            pass

    def _handshake(self, wire, cmd, rank, world=-1):
        wire.sendint(MAGIC)
        got = wire.recvint()
        assert got == MAGIC
        wire.sendint(rank)
        wire.sendint(world)
        wire.sendstr(self.jobid)
        wire.sendstr(cmd)

    def _read_topology(self, wire):
        self.rank = wire.recvint()
        self.parent = wire.recvint()
        self.world = wire.recvint()
        degree = wire.recvint()
        self.tree_neighbors = [wire.recvint() for _ in range(degree)]
        self.ring_prev = wire.recvint()
        self.ring_next = wire.recvint()
        links = set(self.tree_neighbors)
        for r in (self.ring_prev, self.ring_next):
            if r != -1:
                links.add(r)
        self.links = links

    def _broker(self, wire, good=()):
        wire.sendint(len(good))
        for r in sorted(good):
            wire.sendint(r)
        nconn = wire.recvint()
        self.nwait = wire.recvint()
        self.dialed = []
        for _ in range(nconn):
            host = wire.recvstr()
            port = wire.recvint(tag=PORT)
            peer_rank = wire.recvint()
            ps = socket.create_connection((host, port))
            self.dialed.append((peer_rank, ps))
        wire.sendint(0)                      # nerr
        wire.sendint(self.port, tag=PORT)    # our advertised listener

    # -- scripted sessions ---------------------------------------------------
    def begin_start(self, world=-1):
        """Connect and send the full request header NOW (cheap, non-blocking
        writes) so arrival order at the tracker is fixed by call order; the
        blocking response half runs later in :meth:`finish_start`."""
        self._wire = self._connect()
        self._handshake(self._wire, "start", rank=-1, world=world)

    def finish_start(self):
        wire = self._wire
        self._read_topology(wire)
        self._broker(wire)
        wire.sock.close()

    def session_start(self, world=-1):
        self.begin_start(world)
        self.finish_start()

    def session_recover(self, rank):
        """Reconnect as an already-ranked worker whose links all survived
        (ngood = all), so the conversation is one clean round."""
        wire = self._connect()
        self._handshake(wire, "recover", rank=rank)
        self._read_topology(wire)
        self._broker(wire, good=self.links)
        wire.sock.close()

    def session_jobid_restart(self):
        """cmd=start with a known jobid: the tracker must restore the same
        rank without batching."""
        wire = self._connect()
        self._handshake(wire, "start", rank=-1)
        self._read_topology(wire)
        self._broker(wire, good=self.links)
        wire.sock.close()

    def session_print(self, msg):
        wire = self._connect()
        self._handshake(wire, "print", rank=-1)
        wire.sendstr(msg)
        wire.sock.close()

    def session_shutdown(self):
        wire = self._connect()
        self._handshake(wire, "shutdown", rank=self.rank)
        wire.sock.close()

    def close(self):
        self.listen_sock.close()
        for _, s in getattr(self, "dialed", []):
            s.close()
        for s in self._accepted:
            s.close()


def drive_session(tracker_addr, n, jobids=None, with_recover=False,
                  with_print=False):
    """Run one full scripted rendezvous against whatever tracker listens at
    ``tracker_addr``; return (per-client transcripts, topology summary)."""
    clients = [RecordingClient(tracker_addr,
                               jobid=(jobids[i] if jobids else "NULL"))
               for i in range(n)]
    # deterministic arrival by construction: every header is connected and
    # sent from THIS thread in client order (tiny non-blocking writes), so
    # the tracker assigns ranks in exactly that order; only the blocking
    # response halves (topology read + brokering) run in threads.
    for c in clients:
        c.begin_start()
    threads = []
    for c in clients:
        t = threading.Thread(target=c.finish_start, daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "rendezvous hung"
    if with_print:
        clients[0].session_print("hello from conformance")
    if with_recover:
        clients[-1].session_recover(clients[-1].rank)
    for c in clients:
        c.session_shutdown()
    transcripts = [list(c.transcript) for c in clients]
    topology = sorted(
        (c.rank, c.parent, sorted(c.tree_neighbors), c.ring_prev,
         c.ring_next, c.world) for c in clients)
    for c in clients:
        c.close()
    return transcripts, topology


def run_reference(n, **kw):
    ref = load_reference_tracker()
    tracker = ref.RabitTracker("127.0.0.1", n, port=19500, port_end=19599)
    th = threading.Thread(target=tracker.accept_slaves, args=(n,),
                          daemon=True)
    th.start()
    out = drive_session(("127.0.0.1", tracker.port), n, **kw)
    th.join(timeout=30)
    assert not th.is_alive(), "reference tracker did not finish"
    tracker.sock.close()
    return out


def run_ours(n, **kw):
    tracker = RabitTracker("127.0.0.1", n, port=19600, port_end=19699)
    tracker.start(n)
    out = drive_session(("127.0.0.1", tracker.port), n, **kw)
    tracker.join(timeout=30)
    assert not tracker.alive(), "our tracker did not finish"
    return out


@pytest.mark.parametrize("n", [1, 2, 3, 5, 8])
def test_wire_conversation_matches_reference(n):
    ref_tr, ref_topo = run_reference(n)
    our_tr, our_topo = run_ours(n)
    assert our_topo == ref_topo
    for i, (a, b) in enumerate(zip(ref_tr, our_tr)):
        assert a == b, f"client {i} transcript diverges: ref={a} ours={b}"


def test_recover_conversation_matches_reference():
    ref_tr, ref_topo = run_reference(3, with_recover=True)
    our_tr, our_topo = run_ours(3, with_recover=True)
    assert our_topo == ref_topo
    assert our_tr == ref_tr


def test_print_accepted_by_both():
    ref_tr, _ = run_reference(2, with_print=True)
    our_tr, _ = run_ours(2, with_print=True)
    assert our_tr == ref_tr


def test_jobid_restart_matches_reference():
    """A worker restarting with a known jobid gets its old rank back from
    both trackers, with identical conversations."""

    def scripted(addr, n):
        jobids = [f"job-{i}" for i in range(n)]
        clients = [RecordingClient(addr, jobid=jobids[i]) for i in range(n)]
        for c in clients:
            c.begin_start()
        threads = []
        for c in clients:
            t = threading.Thread(target=c.finish_start, daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive()
        # worker 1 dies and comes back under the same jobid
        old_rank = clients[1].rank
        revived = RecordingClient(addr, jobid=clients[1].jobid)
        revived.links = clients[1].links
        revived.session_jobid_restart()
        assert revived.rank == old_rank
        revived.session_shutdown()
        clients[0].session_shutdown()
        clients[2].session_shutdown()
        out = ([list(c.transcript) for c in clients] +
               [list(revived.transcript)])
        for c in clients + [revived]:
            c.close()
        return out

    ref = load_reference_tracker()
    tracker = ref.RabitTracker("127.0.0.1", 3, port=19700, port_end=19799)
    th = threading.Thread(target=tracker.accept_slaves, args=(3,),
                          daemon=True)
    th.start()
    ref_out = scripted(("127.0.0.1", tracker.port), 3)
    # note: the reference counts shutdowns by unique rank, so the revived
    # worker's shutdown (same rank) plus the other two reach nslave=3
    th.join(timeout=30)
    tracker.sock.close()

    ours = RabitTracker("127.0.0.1", 3, port=19800, port_end=19899)
    ours.start(3)
    our_out = scripted(("127.0.0.1", ours.port), 3)
    ours.join(timeout=30)

    assert our_out == ref_out
