"""Smoke tests running the example entry points end-to-end (CPU)."""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_example(script, args, env_extra=None):
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    env.update(env_extra or {})
    # examples force cpu themselves only via env; patch through jax config
    code = (f"import jax; jax.config.update('jax_platforms','cpu'); "
            f"import runpy, sys; sys.argv = {[script] + args!r}; "
            f"runpy.run_path({script!r}, run_name='__main__')")
    return subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                          capture_output=True, text=True, timeout=300)


@pytest.mark.slow
def test_train_logreg_example(tmp_path):
    rng = np.random.RandomState(0)
    lines = []
    for i in range(400):
        x = rng.randn(8)
        y = int(x[0] + x[1] > 0)
        feats = " ".join(f"{j}:{x[j]:.4f}" for j in range(8))
        lines.append(f"{y} {feats}")
    data = tmp_path / "train.libsvm"
    data.write_text("\n".join(lines) + "\n")
    proc = run_example(os.path.join(REPO, "examples", "train_logreg.py"),
                       ["--data", str(data), "--num-feature", "8",
                        "--batch-size", "64", "--epochs", "1"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "loss=" in proc.stderr or "loss=" in proc.stdout


@pytest.mark.slow
def test_train_gbdt_example(tmp_path):
    rng = np.random.RandomState(1)
    rows = []
    for i in range(600):
        x = rng.randn(4)
        y = int(x[0] * x[1] > 0)
        rows.append(",".join([str(y)] + [f"{v:.4f}" for v in x]))
    data = tmp_path / "train.csv"
    data.write_text("\n".join(rows) + "\n")
    ckpt = tmp_path / "model.bin"
    proc = run_example(os.path.join(REPO, "examples", "train_gbdt.py"),
                       ["--data", f"{data}?format=csv&label_column=0",
                        "--num-feature", "4", "--rounds", "5",
                        "--max-depth", "3", "--num-bins", "16",
                        "--checkpoint", str(ckpt)])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "rows/sec" in proc.stdout
    assert ckpt.exists()


def test_bench_pipeline_infeed_roundtrip(tmp_path, capsys):
    """genrec -> infeed harness: every record lands on the device batches."""
    import sys

    sys.path.insert(0, os.path.join(REPO, "benchmarks"))
    try:
        import bench_pipeline
    finally:
        sys.path.pop(0)

    rec = str(tmp_path / "t.rec")
    bench_pipeline.genrec(rec, records=1000, nbytes=64)
    bench_pipeline.bench_infeed(rec, record_bytes=64, batch=128)
    out = capsys.readouterr().out
    assert "1000 records" in out


@pytest.mark.slow
def test_train_mlp_example(tmp_path):
    rng = np.random.RandomState(1)
    lines = []
    for i in range(300):
        x = rng.randn(6)
        y = int(x[0] - x[1] > 0)
        feats = " ".join(f"{j}:{x[j]:.4f}" for j in range(6))
        lines.append(f"{y} {feats}")
    data = tmp_path / "train.libsvm"
    data.write_text("\n".join(lines) + "\n")
    proc = run_example(os.path.join(REPO, "examples", "train_mlp.py"),
                       ["--data", str(data), "--num-feature", "6",
                        "--hidden", "16", "--batch-size", "64",
                        "--epochs", "1"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "epoch 0: loss=" in proc.stderr + proc.stdout


@pytest.mark.slow
def test_train_gbdt_example_with_eval(tmp_path):
    rng = np.random.RandomState(9)
    for name, n in (("tr", 900), ("ev", 300)):
        lines = []
        for i in range(n):
            x = rng.randn(4)
            y = int(x[0] + x[1] > 0)
            feats = " ".join(f"{j}:{x[j]:.4f}" for j in range(4))
            lines.append(f"{y} {feats}")
        (tmp_path / f"{name}.libsvm").write_text("\n".join(lines) + "\n")
    proc = run_example(os.path.join(REPO, "examples", "train_gbdt.py"),
                       ["--data", str(tmp_path / "tr.libsvm"),
                        "--eval-data", str(tmp_path / "ev.libsvm"),
                        "--num-feature", "4", "--rounds", "20",
                        "--max-depth", "3", "--num-bins", "16",
                        "--early-stopping-rounds", "3"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "eval: first" in proc.stdout
    assert "trees kept" in proc.stdout


@pytest.mark.slow
def test_train_gbdt_resumable_checkpoints(tmp_path):
    """--checkpoint-dir: a fresh run writes step checkpoints; a rerun with
    more rounds resumes from the latest instead of starting over."""
    rng = np.random.RandomState(3)
    lines = []
    for i in range(600):
        x = rng.randn(6)
        y = int(x[0] - x[2] > 0)
        feats = " ".join(f"{j}:{x[j]:.4f}" for j in range(6))
        lines.append(f"{y} {feats}")
    data = tmp_path / "train.libsvm"
    data.write_text("\n".join(lines) + "\n")
    ckpt = tmp_path / "ckpts"
    script = os.path.join(REPO, "examples", "train_gbdt.py")
    base_args = ["--data", str(data), "--num-feature", "6",
                 "--max-depth", "3", "--hist-method", "scatter",
                 "--checkpoint-dir", str(ckpt), "--checkpoint-every", "2"]
    proc = run_example(script, base_args + ["--rounds", "4"])
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert (ckpt / "ckpt-00000002").exists()
    proc = run_example(script, base_args + ["--rounds", "6"])
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "resuming from checkpoint step 2" in proc.stdout
    # throughput honesty: the resumed run reports only the rounds IT trained
    assert "trained 4 rounds" in proc.stdout


@pytest.mark.slow
def test_train_mlp_resumable_checkpoints(tmp_path):
    """--checkpoint-dir on the MLP example: params + optimizer state
    round-trip through CheckpointManager's template restore; a rerun with
    more epochs resumes rather than restarting."""
    rng = np.random.RandomState(5)
    lines = []
    for i in range(512):
        x = rng.randn(8)
        y = int(x[0] + x[3] > 0)
        feats = " ".join(f"{j}:{x[j]:.4f}" for j in range(8))
        lines.append(f"{y} {feats}")
    data = tmp_path / "train.libsvm"
    data.write_text("\n".join(lines) + "\n")
    ckpt = tmp_path / "ckpts"
    script = os.path.join(REPO, "examples", "train_mlp.py")
    base = ["--data", str(data), "--num-feature", "8", "--batch-size",
            "128", "--checkpoint-dir", str(ckpt)]
    proc = run_example(script, base + ["--epochs", "2"])
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert (ckpt / "ckpt-00000001").exists()
    proc = run_example(script, base + ["--epochs", "3"])
    assert proc.returncode == 0, proc.stderr[-3000:]
    out = proc.stdout + proc.stderr
    assert "resuming from checkpoint epoch 1" in out


@pytest.mark.slow
def test_train_gbdt_distributed_cli(tmp_path):
    """Under a multi-worker launch the GBDT CLI trains ONE global
    data-parallel model (not N per-shard models) and reports the global
    row count; rank 0 writes the final checkpoint."""
    rng = np.random.RandomState(11)
    lines = []
    for i in range(1000):
        x = rng.randn(6)
        y = int(x[0] + x[1] > 0)
        feats = " ".join(f"{j}:{x[j]:.4f}" for j in range(6))
        lines.append(f"{y} {feats}")
    data = tmp_path / "train.libsvm"
    data.write_text("\n".join(lines) + "\n")
    ckpt = tmp_path / "model.bin"
    from tests.conftest import run_tracker_workers

    proc = run_tracker_workers(
        tmp_path, None, 2,
        script_path=os.path.join(REPO, "examples", "train_gbdt.py"),
        script_args=["--data", str(data), "--num-feature", "6", "--rounds",
                     "4", "--max-depth", "3", "--num-bins", "16",
                     "--hist-method", "scatter", "--checkpoint", str(ckpt)])
    assert proc.returncode == 0, proc.stderr[-4000:]
    out = proc.stdout + proc.stderr
    # both ranks print the SAME global summary (one SPMD program)
    assert out.count("over 2 workers") == 2, out[-2000:]
    assert "on 1000 rows" in out
    assert ckpt.exists()
