"""InputSplit tests: all-(part, npart) coverage against the source bytes
(reference: test/split_test.cc, split_read_test.cc, split_repeat_read_test.cc —
partition-coverage testing = run over all parts and diff concatenation)."""

import os
import random
import struct

import pytest

from dmlc_core_tpu.io.input_split import (
    CachedInputSplit,
    InputSplitShuffle,
    LineSplitter,
    RecordIOSplitter,
    SingleFileSplit,
    ThreadedInputSplit,
    create_input_split,
)
from dmlc_core_tpu.io import filesys as fsys
from dmlc_core_tpu.io.memory_io import MemoryStringStream
from dmlc_core_tpu.io.recordio import RecordIOWriter
from dmlc_core_tpu.io.uri_spec import URISpec


def write_lines(path, lines):
    with open(path, "wb") as f:
        for line in lines:
            f.write(line + b"\n")


def make_text_files(tmp_path, nfiles=3, nlines=200, seed=0):
    rng = random.Random(seed)
    all_lines = []
    paths = []
    for i in range(nfiles):
        lines = [
            b"%d %s" % (rng.randint(0, 10**6),
                        bytes(rng.choice(b"abcdefghij") for _ in range(rng.randint(0, 40))))
            for _ in range(nlines)
        ]
        p = tmp_path / f"part{i}.txt"
        write_lines(p, lines)
        all_lines.extend(lines)
        paths.append(str(p))
    return ";".join(paths), all_lines


def collect_records(split):
    return [bytes(r) for r in split]


def test_uri_spec():
    spec = URISpec("hdfs:///data/x?format=libsvm&clabel=0#cache", 2, 4)
    assert spec.uri == "hdfs:///data/x"
    assert spec.args == {"format": "libsvm", "clabel": "0"}
    assert spec.cache_file == "cache.split4.part2"
    assert URISpec("a/b.txt", 0, 1).cache_file == ""


@pytest.mark.parametrize("num_parts", [1, 2, 3, 5, 8, 16])
def test_line_split_all_parts_coverage(tmp_path, num_parts):
    uri, all_lines = make_text_files(tmp_path)
    collected = []
    for part in range(num_parts):
        split = create_input_split(uri, part, num_parts, "text", threaded=False)
        collected.extend(collect_records(split))
        split.close()
    assert collected == all_lines, f"coverage broken for num_parts={num_parts}"


def test_line_split_threaded_matches_plain(tmp_path):
    from dmlc_core_tpu.io.input_split import NativeLineSplitter

    uri, all_lines = make_text_files(tmp_path)
    collected = []
    for part in range(4):
        split = create_input_split(uri, part, 4, "text")
        # prefetching default path: native engine when built, else the
        # ThreadedInputSplit decorator over the Python engine
        assert isinstance(split, (ThreadedInputSplit, NativeLineSplitter))
        collected.extend(collect_records(split))
        split.close()
    assert collected == all_lines


def test_line_split_before_first_repeats(tmp_path):
    uri, all_lines = make_text_files(tmp_path, nfiles=1, nlines=50)
    split = create_input_split(uri, 0, 2, "text")
    first = collect_records(split)
    split.before_first()
    second = collect_records(split)
    assert first == second
    split.close()


def test_reset_partition_walks_all_parts(tmp_path):
    uri, all_lines = make_text_files(tmp_path, nfiles=2, nlines=80)
    split = create_input_split(uri, 0, 4, "text")
    collected = collect_records(split)
    for part in range(1, 4):
        split.reset_partition(part, 4)
        collected.extend(collect_records(split))
    split.close()
    assert collected == all_lines


def make_recordio_files(tmp_path, nfiles=2, nrec=300, seed=5):
    rng = random.Random(seed)
    magic = struct.pack("<I", 0xCED7230A)
    paths, records = [], []
    for i in range(nfiles):
        stream = MemoryStringStream()
        writer = RecordIOWriter(stream)
        recs = []
        for _ in range(nrec):
            body = b"".join(
                magic if rng.random() < 0.3 else struct.pack("<I", rng.getrandbits(32))
                for _ in range(rng.randint(0, 20)))
            recs.append(body)
            writer.write_record(body)
        p = tmp_path / f"data{i}.rec"
        with open(p, "wb") as f:
            f.write(bytes(stream.data))
        paths.append(str(p))
        records.extend(recs)
    return ";".join(paths), records


@pytest.mark.parametrize("num_parts", [1, 2, 3, 7])
def test_recordio_split_all_parts_coverage(tmp_path, num_parts):
    uri, records = make_recordio_files(tmp_path)
    collected = []
    for part in range(num_parts):
        split = create_input_split(uri, part, num_parts, "recordio", threaded=False)
        collected.extend(collect_records(split))
        split.close()
    assert collected == records


def test_recordio_split_small_chunks(tmp_path):
    """Tiny buffers force the overflow-carry path (ReadChunk boundary logic)."""
    uri, records = make_recordio_files(tmp_path, nfiles=1, nrec=100)
    path = fsys.URI(uri)
    split = RecordIOSplitter(fsys.get_filesystem(path), uri, 0, 1)
    split._buffer_size = 64  # force many chunk reloads + growth
    assert collect_records(split) == records


def test_line_split_small_chunks(tmp_path):
    uri, all_lines = make_text_files(tmp_path, nfiles=1, nlines=100)
    path = fsys.URI(uri)
    split = LineSplitter(fsys.get_filesystem(path), uri, 0, 1)
    split._buffer_size = 32
    assert collect_records(split) == all_lines


def test_indexed_recordio(tmp_path):
    # build a .rec + .idx pair (index lines: "<record-index> <byte-offset>")
    stream = MemoryStringStream()
    writer = RecordIOWriter(stream)
    offsets, records = [], []
    for i in range(100):
        offsets.append(writer.tell() if hasattr(writer, "tell") else len(stream.data))
        body = f"record-{i}".encode() * (i % 5 + 1)
        records.append(body)
        writer.write_record(body)
    rec_path = tmp_path / "data.rec"
    rec_path.write_bytes(bytes(stream.data))
    idx_path = tmp_path / "data.idx"
    idx_path.write_text("".join(f"{i} {off}\n" for i, off in enumerate(offsets)))

    collected = []
    for part in range(3):
        split = create_input_split(str(rec_path), part, 3, "indexed_recordio",
                                   index_uri=str(idx_path), batch_size=7,
                                   threaded=False)
        collected.extend(collect_records(split))
        split.close()
    assert collected == records

    # shuffled variant is a permutation of this part's records
    split = create_input_split(str(rec_path), 0, 1, "indexed_recordio",
                               index_uri=str(idx_path), batch_size=7,
                               shuffle=True, seed=3, threaded=False)
    got = collect_records(split)
    assert sorted(got) == sorted(records) and got != records
    # second epoch reshuffles
    split.before_first()
    got2 = collect_records(split)
    assert sorted(got2) == sorted(records) and got2 != got
    split.close()


def test_cached_split(tmp_path):
    uri, all_lines = make_text_files(tmp_path, nfiles=1, nlines=60)
    cache = tmp_path / "cache.bin"
    split = create_input_split(f"{uri}#{cache}", 0, 1, "text")
    from dmlc_core_tpu.io.input_split import NativeCachedSplitter

    # native cached split when the C++ core is built, Python fallback else
    assert isinstance(split, (CachedInputSplit, NativeCachedSplitter))
    first = collect_records(split)
    assert first == all_lines
    assert cache.exists() and cache.stat().st_size > 0
    split.before_first()
    second = collect_records(split)
    assert second == all_lines
    split.before_first()
    assert collect_records(split) == all_lines
    split.close()


def test_shuffle_split_covers_all(tmp_path):
    uri, all_lines = make_text_files(tmp_path, nfiles=2, nlines=100)
    split = InputSplitShuffle.create(uri, 0, 1, "text", num_shuffle_parts=5,
                                     shuffle_seed=1)
    got = collect_records(split)
    assert sorted(got) == sorted(all_lines)
    assert got != all_lines  # visits sub-parts out of order
    split.before_first()
    got2 = collect_records(split)
    assert sorted(got2) == sorted(all_lines)
    split.close()


def test_single_file_split(tmp_path):
    lines = [b"alpha", b"beta", b"gamma"]
    p = tmp_path / "single.txt"
    write_lines(p, lines)
    split = SingleFileSplit(str(p))
    assert collect_records(split) == lines
    split.before_first()
    assert collect_records(split) == lines
    split.close()


def test_empty_part_when_more_parts_than_bytes(tmp_path):
    p = tmp_path / "tiny.txt"
    p.write_bytes(b"a\nb\n")
    collected = []
    for part in range(8):
        split = create_input_split(str(p), part, 8, "text", threaded=False)
        collected.extend(collect_records(split))
    assert collected == [b"a", b"b"]


def test_directory_uri(tmp_path):
    d = tmp_path / "dir"
    d.mkdir()
    write_lines(d / "a.txt", [b"1", b"2"])
    write_lines(d / "b.txt", [b"3"])
    split = create_input_split(str(d), 0, 1, "text", threaded=False)
    assert collect_records(split) == [b"1", b"2", b"3"]


def test_regex_uri(tmp_path):
    write_lines(tmp_path / "x1.txt", [b"one"])
    write_lines(tmp_path / "x2.txt", [b"two"])
    write_lines(tmp_path / "other.dat", [b"no"])
    split = create_input_split(str(tmp_path / "x.*\\.txt"), 0, 1, "text",
                               threaded=False)
    assert collect_records(split) == [b"one", b"two"]


# ------------------------------------------ constructor escape regression ---

def test_cached_split_init_failure_closes_cache_file(tmp_path, monkeypatch):
    """dmlclint `escape-leak-on-raise`: a failed ThreadedIter bring-up in
    CachedInputSplit.__init__ must close the just-opened cache fd (no
    caller ever holds the instance to close it)."""
    import builtins

    from dmlc_core_tpu.io import input_split as isplit

    data = tmp_path / "d.txt"
    data.write_text("a\nb\nc\n")
    cache = str(tmp_path / "d.cache")

    opened = []
    real_open = builtins.open

    def recording_open(*args, **kwargs):
        fo = real_open(*args, **kwargs)
        opened.append((args[0] if args else kwargs.get("file"), fo))
        return fo

    def exploding_iter(*args, **kwargs):
        raise RuntimeError("injected producer bring-up failure")

    monkeypatch.setattr(builtins, "open", recording_open)
    monkeypatch.setattr(isplit, "ThreadedIter", exploding_iter)
    base = LineSplitter(fsys.LocalFileSystem(), str(data), 0, 1)
    with pytest.raises(RuntimeError, match="injected producer"):
        CachedInputSplit(base, cache)
    cache_fos = [fo for name, fo in opened if str(name) == cache]
    assert cache_fos and all(fo.closed for fo in cache_fos)
    base.close()
